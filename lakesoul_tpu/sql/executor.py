"""SQL execution over catalog scans.

Role parity with rust/lakesoul-datafusion's embedded engine: the WHERE tree
becomes the framework's portable Filter (predicate pushdown + bucket pruning
for free), projections push into the scan, aggregates/sorts run on Arrow
compute kernels.  INSERT/CREATE/DROP route through the ACID catalog paths."""

from __future__ import annotations

import time

import pyarrow as pa
import pyarrow.compute as pc

from lakesoul_tpu.io.filters import Filter
from lakesoul_tpu.obs import registry, span
from lakesoul_tpu.sql import parser as ast
from lakesoul_tpu.sql.parser import SqlError, parse


def _stage_observe(stage: str, started: float) -> None:
    """Per-stage executor latency: lakesoul_sql_stage_seconds{stage=...}."""
    registry().histogram("lakesoul_sql_stage_seconds", stage=stage).observe(
        time.perf_counter() - started
    )

# date-part function → Arrow kernel (parser.EXTRACT_PARTS mirrors the keys)
_DATE_PARTS = {
    "year": pc.year, "month": pc.month, "day": pc.day,
    "hour": pc.hour, "minute": pc.minute, "second": pc.second,
}

_TYPE_MAP = {
    "bigint": pa.int64(),
    "long": pa.int64(),
    "int": pa.int32(),
    "integer": pa.int32(),
    "smallint": pa.int16(),
    "tinyint": pa.int8(),
    "double": pa.float64(),
    "float": pa.float32(),
    "real": pa.float32(),
    "string": pa.string(),
    "varchar": pa.string(),
    "text": pa.string(),
    "bool": pa.bool_(),
    "boolean": pa.bool_(),
    "timestamp": pa.timestamp("us"),
    "date": pa.date32(),
    "binary": pa.binary(),
}


def _walk_case(case, on_value, on_bool) -> None:
    """THE place that knows which CASE parts are VALUE expressions and
    which are boolean trees: simple-form whens (``CASE x WHEN v``) hold
    value expressions and the operand is a value; searched-form whens are
    boolean conditions.  Every COLLECTING walker traverses CASE through
    this helper so the distinction cannot drift per-walker (three walkers
    got it independently wrong before it existed); the REBUILDING
    rewriters (_subst_aggs, _map_node_cols) encode the same form
    dispatch inline because they return new nodes."""
    if case.operand is not None:
        on_value(case.operand)
    for cond, val in case.whens:
        (on_value if case.operand is not None else on_bool)(cond)
        on_value(val)
    if case.default is not None:
        on_value(case.default)


def _expr_columns(expr) -> set[str]:
    """Columns a value expression references (does NOT descend into
    subqueries — those resolve against their own tables)."""
    if isinstance(expr, ast.Column):
        return {expr.name}
    if isinstance(expr, ast.Arith):
        return _expr_columns(expr.left) | _expr_columns(expr.right)
    if isinstance(expr, ast.Agg):
        return _expr_columns(expr.arg) if expr.arg is not None else set()
    if isinstance(expr, ast.Case):
        cols: set[str] = set()
        _walk_case(
            expr,
            lambda e: cols.update(_expr_columns(e)),
            lambda n: cols.update(_node_columns(n)),
        )
        return cols
    if isinstance(expr, ast.Func):
        cols = set()
        for a in expr.args:
            if a is not None:
                cols |= _expr_columns(a)
        return cols
    if isinstance(expr, ast.WindowFn):
        cols = set(expr.partition_by) | {c for c, _ in expr.order_by}
        cols |= _expr_columns(expr.fn)
        return cols
    return set()


def _node_columns(node) -> set[str]:
    """Columns a boolean tree references on the CURRENT table."""
    if isinstance(node, ast.Compare):
        if node.simple:
            return {node.col}
        return _expr_columns(node.left) | _expr_columns(node.right)
    if isinstance(node, (ast.InList, ast.IsNull, ast.Like, ast.Between)):
        return {node.col}
    if isinstance(node, ast.InSubquery):
        return {node.col}
    if isinstance(node, ast.Exists):
        return set()
    if isinstance(node, ast.BoolOp):
        cols = set()
        for a in node.args:
            cols |= _node_columns(a)
        return cols
    if isinstance(node, ast.NotOp):
        return _node_columns(node.arg)
    return set()


def _flatten_and(node) -> list:
    """AND tree → conjunct list (single node when not an AND)."""
    if isinstance(node, ast.BoolOp) and node.op == "and":
        out: list = []
        for a in node.args:
            out.extend(_flatten_and(a))
        return out
    return [node]


def _subquery_outer_candidates(node) -> set[str]:
    """Every column name referenced anywhere inside subqueries of a boolean
    tree OR value expression (any depth).  Correlated subqueries resolve
    some of these against the OUTER table, so scan projection must keep any
    that match the base schema — over-collection only retains a column the
    planner could have dropped, never changes results."""
    subs: list = []

    def walk(n):
        if isinstance(n, (ast.Exists, ast.InSubquery)):
            subs.append(n.select)
        elif isinstance(n, ast.Compare) and not n.simple:
            walk_expr(n.left)
            walk_expr(n.right)
        elif isinstance(n, ast.BoolOp):
            for a in n.args:
                walk(a)
        elif isinstance(n, ast.NotOp):
            walk(n.arg)

    def walk_expr(e):
        if isinstance(e, ast.ScalarSubquery):
            subs.append(e.select)
        elif isinstance(e, ast.Arith):
            walk_expr(e.left)
            walk_expr(e.right)
        elif isinstance(e, ast.Agg):
            if e.arg is not None:
                walk_expr(e.arg)
        elif isinstance(e, ast.Func):
            for a in e.args:
                if a is not None:
                    walk_expr(a)
        elif isinstance(e, ast.Case):
            _walk_case(e, walk_expr, walk)

    # accept either a boolean node or a bare value expression
    if isinstance(e := node, (ast.ScalarSubquery, ast.Arith, ast.Agg, ast.Func,
                              ast.Case, ast.Column)):
        walk_expr(e)
    else:
        walk(node)
    cols: set[str] = set()
    while subs:
        sel = subs.pop()
        if isinstance(sel, ast.SetOp):
            subs.extend([sel.left, sel.right])
            continue
        if sel.where is not None:
            cols |= _node_columns(sel.where)
            walk(sel.where)
    return cols


def _node_column_refs(node) -> list:
    """(qualifier, name) pairs a boolean tree references on the CURRENT
    table — like _node_columns but keeping qualifiers for scope resolution;
    does not descend into nested subqueries."""
    refs: list = []

    def expr_refs(e):
        if isinstance(e, ast.Column):
            refs.append((e.qual, e.name))
        elif isinstance(e, ast.Arith):
            expr_refs(e.left)
            expr_refs(e.right)
        elif isinstance(e, ast.Agg):
            if e.arg is not None:
                expr_refs(e.arg)
        elif isinstance(e, ast.Func):
            for a in e.args:
                if a is not None:
                    expr_refs(a)
        elif isinstance(e, ast.Case):
            _walk_case(e, expr_refs, walk)

    def walk(n):
        if isinstance(n, ast.Compare):
            if n.simple:
                refs.append((n.col_qual, n.col))
            else:
                expr_refs(n.left)
                expr_refs(n.right)
        elif isinstance(n, (ast.InList, ast.IsNull, ast.Like, ast.Between,
                            ast.InSubquery)):
            refs.append((n.col_qual, n.col))
        elif isinstance(n, ast.BoolOp):
            for a in n.args:
                walk(a)
        elif isinstance(n, ast.NotOp):
            walk(n.arg)

    walk(node)
    return refs


def _rewrite_outer_refs(node, resolve, prefix: str = "__o_", inner_renames=None):
    """Rename column references in a boolean tree for evaluation on the
    semi-joined frame: outer-resolved refs get the ``__o_`` prefix (the join
    renamed outer columns to avoid inner-name collisions), and inner refs in
    ``inner_renames`` map to their coalesced key column (pyarrow joins drop
    right-key columns; on matched rows the values are equal by the join)."""
    inner_renames = inner_renames or {}

    def map_col(qual, name):
        if resolve(qual, name) == "outer":
            return None, prefix + name
        return None, inner_renames.get(name, name)

    return _map_node_cols(node, map_col)


def _contains_agg(expr) -> bool:
    return any(True for _ in _walk_aggs(expr))


def _walk_aggs(expr):
    if isinstance(expr, ast.Agg):
        yield expr
        return
    if isinstance(expr, ast.Arith):
        yield from _walk_aggs(expr.left)
        yield from _walk_aggs(expr.right)
    elif isinstance(expr, ast.Case):
        found: list = []
        _walk_case(
            expr,
            lambda e: found.extend(_walk_aggs(e)),
            lambda n: found.extend(
                a for sub in _bool_exprs(n) for a in _walk_aggs(sub)
            ),
        )
        yield from found
    elif isinstance(expr, ast.Func):
        for a in expr.args:
            if a is not None:
                yield from _walk_aggs(a)


def _bool_exprs(node):
    """Value expressions embedded in a boolean tree (for agg collection)."""
    if isinstance(node, ast.Compare) and not node.simple:
        yield node.left
        yield node.right
    elif isinstance(node, ast.BoolOp):
        for a in node.args:
            yield from _bool_exprs(a)
    elif isinstance(node, ast.NotOp):
        yield from _bool_exprs(node.arg)


def _agg_key(a: ast.Agg) -> tuple:
    # repr of the arg AST: labels are too lossy (every CASE stringifies to
    # "case", which would merge distinct CASE aggregates)
    return (a.fn, a.distinct, repr(a.arg) if a.arg is not None else "*")


def _subst_aggs(expr, agg_col: dict):
    """Replace Agg nodes with Column references into the aggregated table."""
    if isinstance(expr, ast.Agg):
        return ast.Column(agg_col[_agg_key(expr)])
    if isinstance(expr, ast.Arith):
        return ast.Arith(
            expr.op, _subst_aggs(expr.left, agg_col), _subst_aggs(expr.right, agg_col)
        )
    if isinstance(expr, ast.Case):
        # conds carry aggregates too: searched CASE WHEN count(*) > 2 ...,
        # simple CASE sum(x) WHEN ... — substitute per the form
        subst_cond = (
            (lambda c: _subst_aggs(c, agg_col)) if expr.operand is not None
            else (lambda c: _subst_aggs_bool(c, agg_col))
        )
        return ast.Case(
            [(subst_cond(c), _subst_aggs(v, agg_col)) for c, v in expr.whens],
            _subst_aggs(expr.default, agg_col) if expr.default is not None else None,
            _subst_aggs(expr.operand, agg_col) if expr.operand is not None else None,
        )
    if isinstance(expr, ast.Func):
        return ast.Func(
            expr.name,
            [_subst_aggs(a, agg_col) if a is not None else None for a in expr.args],
        )
    return expr


def _subst_aggs_bool(node, agg_col: dict):
    if isinstance(node, ast.Compare) and not node.simple:
        return ast.Compare(
            node.op, "", None,
            left=_subst_aggs(node.left, agg_col),
            right=_subst_aggs(node.right, agg_col),
        )
    if isinstance(node, ast.BoolOp):
        return ast.BoolOp(node.op, [_subst_aggs_bool(a, agg_col) for a in node.args])
    if isinstance(node, ast.NotOp):
        return ast.NotOp(_subst_aggs_bool(node.arg, agg_col))
    return node


def _resolve_aliases_bool(node, alias_map: dict):
    """HAVING may reference select aliases (``HAVING n > 5``); rewrite those
    columns to the aliased expressions before aggregate collection."""

    def resolve_expr(expr):
        if isinstance(expr, ast.Column) and expr.name in alias_map:
            return alias_map[expr.name]
        if isinstance(expr, ast.Arith):
            return ast.Arith(expr.op, resolve_expr(expr.left), resolve_expr(expr.right))
        return expr

    if isinstance(node, ast.Compare):
        if node.simple and node.col in alias_map:
            return ast.Compare(
                node.op, "", None,
                left=alias_map[node.col], right=ast.Literal(node.value),
            )
        if not node.simple:
            return ast.Compare(
                node.op, "", None,
                left=resolve_expr(node.left), right=resolve_expr(node.right),
            )
        return node
    if isinstance(node, ast.BoolOp):
        return ast.BoolOp(node.op, [_resolve_aliases_bool(a, alias_map) for a in node.args])
    if isinstance(node, ast.NotOp):
        return ast.NotOp(_resolve_aliases_bool(node.arg, alias_map))
    return node


def _map_node_cols(node, map_col, map_sel=None):
    """Generic boolean-tree rewriter — the ONE walker behind join-key
    renames, semi-join outer-prefix rewrites, and subquery-descending
    correlation renames.  ``map_col(qual, name) -> (qual, name)`` rewrites
    every column reference (including inside Func/Case/Agg expressions);
    ``map_sel(select)`` transforms nested subquery Selects (identity when
    None — nested scopes resolve their own names)."""
    import copy as _copy

    sel = map_sel if map_sel is not None else (lambda s: s)

    def ren_expr(e):
        if isinstance(e, ast.Column):
            q, n = map_col(e.qual, e.name)
            return ast.Column(n, qual=q)
        if isinstance(e, ast.Arith):
            return ast.Arith(e.op, ren_expr(e.left), ren_expr(e.right))
        if isinstance(e, ast.Agg):
            if e.arg is None:
                return e
            return ast.Agg(e.fn, ren_expr(e.arg), e.alias, e.distinct)
        if isinstance(e, ast.Func):
            return ast.Func(
                e.name, [None if a is None else ren_expr(a) for a in e.args]
            )
        if isinstance(e, ast.Case):
            return ast.Case(
                # simple-CASE whens hold VALUE expressions, not bool trees
                [
                    ((walk(c) if e.operand is None else ren_expr(c)), ren_expr(v))
                    for c, v in e.whens
                ],
                None if e.default is None else ren_expr(e.default),
                None if e.operand is None else ren_expr(e.operand),
            )
        if isinstance(e, ast.ScalarSubquery):
            return ast.ScalarSubquery(sel(e.select))
        return e

    def walk(n):
        if isinstance(n, ast.Compare):
            if n.simple:
                q, name = map_col(n.col_qual, n.col)
                return ast.Compare(n.op, name, n.value, col_qual=q)
            return ast.Compare(
                n.op, "", None, left=ren_expr(n.left), right=ren_expr(n.right)
            )
        if isinstance(n, (ast.InList, ast.IsNull, ast.Like, ast.Between,
                          ast.InSubquery)):
            out = _copy.copy(n)
            out.col_qual, out.col = map_col(n.col_qual, n.col)
            if isinstance(out, ast.InSubquery):
                out.select = sel(out.select)
            return out
        if isinstance(n, ast.Exists):
            out = _copy.copy(n)
            out.select = sel(out.select)
            return out
        if isinstance(n, ast.BoolOp):
            return ast.BoolOp(n.op, [walk(a) for a in n.args])
        if isinstance(n, ast.NotOp):
            return ast.NotOp(walk(n.arg))
        return n

    return walk(node)


def _rename_node_cols(node, mapping: dict):
    """Rewrite column names in a boolean tree (join key renames)."""
    return _map_node_cols(
        node, lambda q, n: (q, mapping.get(n, n))
    )


def _select_rebinds(sel, qual: str) -> bool:
    """Does this (sub)query's own FROM/JOIN bind ``qual`` as a table name
    or alias?  If so, the qualifier is re-scoped inside it."""
    if sel.table == qual or sel.from_alias == qual:
        return True
    return any(j.table == qual or j.alias == qual for j in sel.joins)


def _unqualified(node):
    """Copy of an expression tree with every qualifier dropped — GROUP BY
    key matching is structural (``upper(t.s)`` groups by ``upper(s)``)."""
    import copy as _copy
    import dataclasses

    if not dataclasses.is_dataclass(node) or isinstance(node, ast.Token):
        return node
    out = _copy.copy(node)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if f.name in ("qual", "col_qual"):
            setattr(out, f.name, None)
        elif isinstance(v, list):
            setattr(out, f.name, [
                tuple(_unqualified(y) for y in x) if isinstance(x, tuple)
                else _unqualified(x)
                for x in v
            ])
        elif dataclasses.is_dataclass(v) and not isinstance(v, ast.Token):
            setattr(out, f.name, _unqualified(v))
    return out


def _norm_repr(node) -> str:
    return repr(_unqualified(node))


def _subst_group_keys(node, by_norm: dict):
    """Rebuild an expression/boolean tree replacing every subtree that is
    STRUCTURALLY one of the GROUP BY key expressions (qualifier-insensitive)
    with its synthesized key column — items AND HAVING both resolve
    ``upper(s)`` onto ``__grp_0`` after aggregation drops ``s``.  Nested
    sub-Selects keep their own scope untouched."""
    import copy as _copy
    import dataclasses

    if not dataclasses.is_dataclass(node) or isinstance(
        node, (ast.Token, ast.Select, ast.SetOp, ast.Literal, ast.Agg)
    ):
        return node
    key = _norm_repr(node)
    if key in by_norm:
        return ast.Column(by_norm[key])
    out = _copy.copy(node)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, list):
            setattr(out, f.name, [
                tuple(_subst_group_keys(y, by_norm) for y in x)
                if isinstance(x, tuple) else _subst_group_keys(x, by_norm)
                for x in v
            ])
        elif dataclasses.is_dataclass(v) and not isinstance(v, ast.Token):
            setattr(out, f.name, _subst_group_keys(v, by_norm))
    return out


def _rename_qualified_refs(node, qual: str, name: str, new: str,
                           _seen: set | None = None) -> None:
    """IN-PLACE: every reference written ``<qual>.<name>`` becomes the bare
    column ``new`` — items, WHERE/HAVING trees, later-join ON keys, and
    subqueries alike.  Used when a RIGHT/FULL join keeps BOTH same-named
    key columns and the right one survives under a suffix (the statement
    AST is parsed per-execution, so mutation is safe)."""
    import dataclasses

    seen = _seen if _seen is not None else set()
    if node is None or not dataclasses.is_dataclass(node) \
            or isinstance(node, ast.Token) or id(node) in seen:
        return
    if isinstance(node, ast.Select) and seen and _select_rebinds(node, qual):
        # a nested subquery whose OWN FROM/JOIN binds the same qualifier
        # re-scopes it: its inner references must stay untouched
        return
    seen.add(id(node))
    if isinstance(node, ast.Column):
        if node.qual == qual and node.name == name:
            node.name, node.qual = new, None
        return
    if getattr(node, "col_qual", None) == qual and getattr(node, "col", None) == name:
        node.col, node.col_qual = new, None
    if isinstance(node, ast.Join):
        # EITHER operand of a later ON may reference the renamed key (the
        # executor swap-binds by qualifier, so both sides are candidates)
        if node.left_qual == qual and node.left_on == name:
            node.left_on, node.left_qual = new, None
        if node.right_qual == qual and node.right_on == name:
            node.right_on, node.right_qual = new, None
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            if isinstance(item, tuple):
                for sub in item:
                    _rename_qualified_refs(sub, qual, name, new, seen)
            else:
                _rename_qualified_refs(item, qual, name, new, seen)


def _slice_limit_offset(out: pa.Table, stmt) -> pa.Table:
    """Apply the statement's OFFSET/LIMIT tail (shared by every result
    path so the sites cannot drift)."""
    if stmt.offset or stmt.limit is not None:
        out = out.slice(stmt.offset or 0, stmt.limit)
    return out


def _broadcast(val, n: int):
    """Expression results may be scalars (column-free expressions); broadcast
    them to the table's row count.  The scalar's TYPE is preserved — on a
    zero-row table an untyped pa.array([]) would come out null-typed and
    break downstream kernels (coalesce, comparisons)."""
    if isinstance(val, pa.Scalar):
        return pa.chunked_array([pa.array([val.as_py()] * n, type=val.type)])
    if isinstance(val, pa.Array):
        return pa.chunked_array([val])
    return val


def _expr_label(expr) -> str:
    if isinstance(expr, ast.Column):
        return expr.name
    if isinstance(expr, ast.Literal):
        return str(expr.value)
    if isinstance(expr, ast.Arith):
        return f"{_expr_label(expr.left)}{expr.op}{_expr_label(expr.right)}"
    if isinstance(expr, ast.Agg):
        arg = _expr_label(expr.arg) if expr.arg is not None else "*"
        d = "distinct " if expr.distinct else ""
        return f"{expr.fn}({d}{arg})"
    if isinstance(expr, ast.Case):
        return "case"
    if isinstance(expr, ast.Func):
        return expr.name
    if isinstance(expr, ast.WindowFn):
        return _expr_label(expr.fn)
    return "expr"


def _pushable(node) -> bool:
    """Can this predicate push into the scan as a portable Filter?"""
    if isinstance(node, ast.Compare):
        return node.simple
    if isinstance(node, (ast.InList, ast.IsNull, ast.Between)):
        return True
    if isinstance(node, ast.BoolOp):
        return all(_pushable(a) for a in node.args)
    if isinstance(node, ast.NotOp):
        return _pushable(node.arg)
    return False  # LIKE, subqueries, general comparisons stay residual


def _split_where(node) -> tuple[list, list]:
    """Split a WHERE tree into pushdown-eligible conjuncts and residual
    conjuncts (evaluated post-scan with the general evaluator)."""
    conjuncts = (
        list(node.args) if isinstance(node, ast.BoolOp) and node.op == "and" else [node]
    )
    push = [c for c in conjuncts if _pushable(c)]
    resid = [c for c in conjuncts if not _pushable(c)]
    return push, resid


def _where_to_filter(node) -> Filter:
    if isinstance(node, ast.Compare):
        if not node.simple:
            raise SqlError("general comparison cannot push down")
        return Filter(op=node.op, col=node.col, value=node.value)
    if isinstance(node, ast.InList):
        return Filter(op="in", col=node.col, value=list(node.values))
    if isinstance(node, ast.Between):
        return Filter(
            op="and",
            args=(
                Filter(op="ge", col=node.col, value=node.low),
                Filter(op="le", col=node.col, value=node.high),
            ),
        )
    if isinstance(node, ast.IsNull):
        return Filter(op="not_null" if node.negated else "is_null", col=node.col)
    if isinstance(node, ast.BoolOp):
        args = tuple(_where_to_filter(a) for a in node.args)
        return Filter(op=node.op, args=args)
    if isinstance(node, ast.NotOp):
        return Filter(op="not", args=(_where_to_filter(node.arg),))
    raise SqlError(f"unsupported WHERE node {node!r}")


class SqlSession:
    """Execute SQL statements against a catalog; results are Arrow tables."""

    def __init__(self, catalog, namespace: str = "default"):
        self.catalog = catalog
        self.namespace = namespace
        self._externals: dict[str, object] = {}

    # ----------------------------------------------------------- federation
    def register_external(self, name: str, source) -> None:
        """Register a READ-ONLY external table for federation — the role of
        the reference's ADBC federation in lakesoul-datafusion (SURVEY §2.5:
        querying a mysql catalog from the same SQL session).  ``source`` is
        an Arrow table, a data-file path (any format the registry reads —
        parquet/LSF/IPC — on any fsspec store), or a zero-arg callable
        returning an Arrow table (e.g. an ADBC/DB-API fetch).  External
        names shadow catalog tables inside THIS session and join/subquery
        freely against lakehouse tables; DML against them is rejected."""
        self._externals[name] = source

    def _prefetch_join_scans(self, stmt: "ast.Select") -> dict:
        """Start scanning plain-table join right sides on the runtime pool
        (overlapping the base-table scan).  Derived/external right sides
        stay lazy — they may recurse into this executor.  Returns
        {join_index: Future}; errors surface where the serial code would
        have raised (the join's ``.result()``)."""
        from lakesoul_tpu.runtime import get_pool

        pool = get_pool()
        futs: dict = {}
        if pool.in_worker():  # nested query on a pool thread: stay serial
            return futs
        for ji, j in enumerate(stmt.joins):
            if j.subquery is not None or self._external_table(j.table) is not None:
                continue

            def scan_one(name=j.table):
                return self.catalog.table(name, self.namespace).to_arrow()

            futs[ji] = pool.submit(scan_one)
        return futs

    def _external_table(self, name: str) -> "pa.Table | None":
        source = self._externals.get(name)
        if source is None:
            return None
        memo = getattr(self, "_ext_memo", None)
        if memo is None:
            memo = {}  # outside a statement: discarded temporary
        if name in memo:
            return memo[name]
        if isinstance(source, pa.Table):
            out = source
        elif callable(source):
            out = source()
            if not isinstance(out, pa.Table):
                raise SqlError(
                    f"external source {name!r} returned {type(out).__name__},"
                    " expected pyarrow.Table"
                )
        else:
            from lakesoul_tpu.io.formats import format_for

            out = format_for(str(source)).read_table(str(source))
        # one fetch per STATEMENT: a query referencing the external several
        # times (join + subquery) sees one consistent snapshot; outside a
        # statement the memo is a discarded temporary (nothing stays pinned)
        memo[name] = out
        return out

    def execute(self, sql: str) -> pa.Table:
        started = time.perf_counter()
        stmt = parse(sql)
        _stage_observe("parse", started)
        target = getattr(stmt, "table", None)
        if target in self._externals and isinstance(
            stmt,
            (ast.Insert, ast.Update, ast.Delete, ast.DropTable,
             ast.AlterAddColumn, ast.AlterSetProperties),
        ):
            raise SqlError(f"external table {target!r} is read-only")
        self._ext_memo: dict[str, pa.Table] = {}
        started = time.perf_counter()
        try:
            # the statement span carries any client-propagated trace id down
            # into io/meta spans opened underneath
            with span("sql.execute", statement=type(stmt).__name__):
                return self._execute_stmt(stmt)
        finally:
            _stage_observe("execute", started)
            # a fetched external snapshot must not stay pinned past the
            # statement on a long-lived session
            self._ext_memo = None

    def _execute_stmt(self, stmt) -> pa.Table:
        if isinstance(stmt, ast.Explain):
            return self._explain(stmt.stmt)
        if isinstance(stmt, ast.Select):
            return self._select(stmt)
        if isinstance(stmt, ast.SetOp):
            return self._set_op(stmt)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt)
        if isinstance(stmt, ast.CreateTable):
            return self._create(stmt)
        if isinstance(stmt, ast.DropTable):
            return self._drop(stmt)
        if isinstance(stmt, ast.ShowTables):
            return pa.table({"table_name": sorted(self.catalog.list_tables(self.namespace))})
        if isinstance(stmt, ast.AlterAddColumn):
            if stmt.type_name not in _TYPE_MAP:
                raise SqlError(f"unknown type {stmt.type_name!r}")
            self.catalog.table(stmt.table, self.namespace).add_columns(
                pa.field(stmt.column, _TYPE_MAP[stmt.type_name])
            )
            return pa.table({"status": ["ok"]})
        if isinstance(stmt, ast.AlterSetProperties):
            self.catalog.table(stmt.table, self.namespace).set_properties(
                stmt.properties
            )
            return pa.table({"status": ["ok"]})
        if isinstance(stmt, ast.Call):
            return self._call(stmt)
        if isinstance(stmt, ast.Update):
            flt, mask_fn = self._dml_predicate(stmt.where)
            literals: dict = {}
            exprs: dict = {}
            for col, val in stmt.assignments.items():
                if isinstance(val, ast.Literal):
                    literals[col] = val.value
                else:
                    # evaluated over the MATCHED rows at rewrite time
                    exprs[col] = (
                        lambda tbl, e=val: _broadcast(
                            self._eval_expr(e, tbl), len(tbl)
                        )
                    )
            try:
                # arm the per-statement subquery memo UP FRONT: SET-expression
                # subqueries must see the pre-statement snapshot even when the
                # WHERE is pushdown-expressible (mask_fn is None then and
                # would never arm it)
                self._stmt_query_memo = {}
                n = self.catalog.table(stmt.table, self.namespace).update_where(
                    flt, literals, mask_fn=mask_fn, expr_assignments=exprs
                )
            finally:
                self._stmt_query_memo = None
            return pa.table({"updated": pa.array([n], pa.int64())})
        if isinstance(stmt, ast.Delete):
            flt, mask_fn = self._dml_predicate(stmt.where)
            try:
                self._stmt_query_memo = {}
                n = self.catalog.table(stmt.table, self.namespace).delete_where(
                    flt, mask_fn=mask_fn
                )
            finally:
                self._stmt_query_memo = None
            return pa.table({"deleted": pa.array([n], pa.int64())})
        if isinstance(stmt, ast.Describe):
            t = self.catalog.table(stmt.table, self.namespace)
            return pa.table(
                {
                    "column": [f.name for f in t.schema],
                    "type": [str(f.type) for f in t.schema],
                    "primary_key": [f.name in t.primary_keys for f in t.schema],
                }
            )
        raise SqlError(f"unsupported statement {type(stmt).__name__}")

    @staticmethod
    def _apply_fn(name: str, fn, *args):
        """Apply an Arrow kernel for a SQL function, surfacing type
        mismatches as SqlError (never a raw Arrow traceback)."""
        try:
            return fn(*args)
        except (pa.lib.ArrowNotImplementedError, pa.lib.ArrowInvalid) as e:
            raise SqlError(f"{name}(): {e}")

    def _dml_predicate(self, where):
        """UPDATE/DELETE WHERE → (pushdown Filter, mask_fn).

        Fully pushdown-expressible predicates keep the Filter fast path
        (partition pruning + vectorized match, no general evaluator).
        Otherwise the GENERAL predicate — functions, CASE, subqueries —
        evaluates through the full boolean evaluator per partition, while
        any pushable AND-conjuncts still ride along as a Filter so
        partition pruning survives mixed predicates.  Uncorrelated
        subqueries are memoized for the STATEMENT, so every partition sees
        the same pre-statement snapshot of any table the subquery reads
        (partition 1's committed rewrite must not change partition 2's
        predicate)."""
        import numpy as np

        try:
            return _where_to_filter(where), None
        except SqlError:
            pass
        push_nodes, _residual = _split_where(where)
        flt = None
        if push_nodes:
            flt = _where_to_filter(push_nodes[0])
            for n in push_nodes[1:]:
                flt = flt & _where_to_filter(n)

        def mask_fn(table: pa.Table):
            # arm the statement-scoped subquery memo (cleared by the
            # Update/Delete branch once the whole statement commits)
            if getattr(self, "_stmt_query_memo", None) is None:
                self._stmt_query_memo = {}
            mask = pc.fill_null(
                _broadcast(self._eval_bool(where, table), len(table)), False
            )
            if isinstance(mask, pa.ChunkedArray):
                mask = mask.combine_chunks()
            return np.asarray(mask.to_numpy(zero_copy_only=False), dtype=bool)

        return flt, mask_fn

    _CALL_ARITY = {"compact": 1, "rollback": 2, "build_vector_index": 2, "clean": 0}

    def _call(self, stmt) -> pa.Table:
        """Maintenance procedures (reference: Spark CALL commands)."""
        args = list(stmt.args)
        want = self._CALL_ARITY.get(stmt.procedure)
        if want is not None and len(args) != want:
            raise SqlError(
                f"CALL {stmt.procedure} expects {want} argument(s), got {len(args)}"
            )
        if stmt.procedure == "compact":
            n = self.catalog.table(str(args[0]), self.namespace).compact()
            return pa.table({"compacted_partitions": pa.array([n], pa.int64())})
        if stmt.procedure == "rollback":
            t = self.catalog.table(str(args[0]), self.namespace)
            n = t.rollback(to_version=int(args[1]))
            return pa.table({"rolled_back_partitions": pa.array([n], pa.int64())})
        if stmt.procedure == "build_vector_index":
            t = self.catalog.table(str(args[0]), self.namespace)
            n = t.build_vector_index(str(args[1]))
            return pa.table({"indexed_vectors": pa.array([n], pa.int64())})
        if stmt.procedure == "clean":
            from lakesoul_tpu.compaction import Cleaner

            result = Cleaner(self.catalog).clean_all()
            return pa.table({k: pa.array([v], pa.int64()) for k, v in result.items()})
        raise SqlError(f"unknown procedure {stmt.procedure!r}")

    # ------------------------------------------------------------------- DQL
    def _query(self, stmt) -> pa.Table:
        """Select or set-op subtree (derived tables / CTE bodies).

        During a general-predicate DML statement, results are memoized per
        AST node (the statement is parsed once, so each subquery node is
        stable): every partition's mask evaluation then reads the SAME
        pre-statement snapshot instead of re-scanning tables this very
        statement may already have rewritten."""
        memo = getattr(self, "_stmt_query_memo", None)
        if memo is not None and id(stmt) in memo:
            return memo[id(stmt)]
        if isinstance(stmt, ast.SetOp):
            out = self._set_op(stmt)
        else:
            out = self._select(stmt)
        if memo is not None:
            memo[id(stmt)] = out
        return out

    def _set_op(self, stmt: ast.SetOp) -> pa.Table:
        """UNION [ALL] / INTERSECT / EXCEPT with SQL set semantics (distinct
        rows unless ALL; NULLs compare equal for dedup, like DISTINCT)."""
        left = self._query(stmt.left)
        right = self._query(stmt.right)
        if left.num_columns != right.num_columns:
            raise SqlError(
                f"set operation arity mismatch: {left.num_columns} vs "
                f"{right.num_columns} columns"
            )
        right = right.rename_columns(left.column_names)
        if stmt.op == "union":
            # permissive: unify types across branches (int + double → double)
            out = pa.concat_tables([left, right], promote_options="permissive")
            if not stmt.all:
                # same dedup the SELECT DISTINCT path uses (NULLs group equal)
                out = out.group_by(out.column_names).aggregate([])
        else:
            import pandas as pd

            lf = left.to_pandas()
            rf = right.to_pandas()
            if stmt.op == "intersect":
                merged = lf.drop_duplicates().merge(rf.drop_duplicates(), how="inner")
            else:  # except
                probe = lf.drop_duplicates().merge(
                    rf.drop_duplicates(), how="left", indicator=True
                )
                merged = probe[probe["_merge"] == "left_only"].drop(columns="_merge")
            out = pa.Table.from_pandas(merged, preserve_index=False)
            # pandas may widen types (e.g. int64 → float64 when NaNs appear)
            try:
                out = out.cast(left.schema)
            except (pa.lib.ArrowInvalid, pa.lib.ArrowNotImplementedError):
                pass
        if stmt.order_by:
            out = out.sort_by(
                [(c, "descending" if d else "ascending") for c, d in stmt.order_by]
            )
        return _slice_limit_offset(out, stmt)

    def _base_scan(self, stmt: ast.Select):
        """Scan of the FROM table, positioned at AS OF when time-traveling."""
        scan = self.catalog.table(stmt.table, self.namespace).scan()
        if stmt.as_of_ms is not None:
            scan = scan.snapshot_at(stmt.as_of_ms)
        return scan

    def _plan_base(self, stmt: ast.Select, has_aggs: bool):
        """Base-table scan with every pushdown decision applied — filter
        split, projection, early-stop LIMIT.  Shared by execution and
        EXPLAIN so the plan shown IS the plan run.  → (scan, residual)."""
        base_schema = set(
            self.catalog.table(stmt.table, self.namespace).schema.names
        )
        scan = self._base_scan(stmt)
        residual_nodes: list = []
        push_nodes: list = []
        if stmt.where is not None:
            push_nodes, residual_nodes = _split_where(stmt.where)
            if any(j.kind in ("right", "full") for j in stmt.joins):
                # RIGHT/FULL OUTER preserve unmatched rows from the other
                # side, whose base columns surface as NULL: a base-table
                # predicate does NOT commute below the join (it would drop
                # the NULL-extended rows' match partners) — everything
                # evaluates post-join
                residual_nodes = residual_nodes + push_nodes
                push_nodes = []
            elif stmt.joins:
                # only base-table conjuncts may push below the join
                spill = [
                    n for n in push_nodes if not _node_columns(n) <= base_schema
                ]
                push_nodes = [n for n in push_nodes if _node_columns(n) <= base_schema]
                residual_nodes = residual_nodes + spill
        if push_nodes:
            flt = _where_to_filter(push_nodes[0])
            for n in push_nodes[1:]:
                flt = flt & _where_to_filter(n)
            scan = scan.filter(flt)
        if not stmt.joins and not stmt.star:
            needed = self._needed_columns(stmt, residual_nodes)
            refs = sorted(needed & base_schema)
            if refs:
                scan = scan.select(refs)
            # no refs → full scan keeps the row count for literal selects
        if (
            stmt.limit is not None
            and not stmt.joins
            and not residual_nodes
            and not stmt.order_by
            and not has_aggs
            and not stmt.distinct
        ):
            # LIMIT without ORDER BY returns arbitrary rows, so the scan
            # can stop early (unread units are skipped entirely); with an
            # OFFSET the prefix rows must still be delivered for the slice
            scan = scan.limit(stmt.limit + (stmt.offset or 0))
        return scan, residual_nodes

    def _explain(self, stmt) -> pa.Table:
        """EXPLAIN: the plan as text lines, nothing executed.  For base-table
        selects the scan line comes from the SAME _plan_base/scan.explain
        decisions execution uses; other statements get a structural sketch."""
        import json as _json

        lines: list[str] = []

        def describe(s, indent=""):
            if isinstance(s, ast.SetOp):
                lines.append(f"{indent}SetOp: {s.op}{' all' if s.all else ''}")
                describe(s.left, indent + "  ")
                describe(s.right, indent + "  ")
                if s.order_by or s.limit is not None or s.offset:
                    tail_bits = []
                    if s.order_by:
                        tail_bits.append(f"order_by={s.order_by}")
                    if s.limit is not None:
                        tail_bits.append(f"limit={s.limit}")
                    if s.offset:
                        tail_bits.append(f"offset={s.offset}")
                    lines.append(f"{indent}  " + " ".join(tail_bits))
                return
            if not isinstance(s, ast.Select):
                lines.append(f"{indent}{type(s).__name__}")
                return
            if s.from_subquery is not None:
                lines.append(f"{indent}DerivedTable{f' {s.from_alias}' if s.from_alias else ''}:")
                describe(s.from_subquery, indent + "  ")
                if s.where is not None:
                    # derived tables take no pushdown: the whole WHERE is a
                    # post-materialization filter (same as _select)
                    lines.append(f"{indent}Filter (post-materialization): WHERE clause")
                has_aggs = bool(s.group_by) or s.having is not None or any(
                    _contains_agg(it.expr) for it in s.items
                )
            elif s.table in self._externals:
                lines.append(
                    f"{indent}ExternalScan: {s.table} (federated source; no"
                    " pushdown — whole WHERE filters post-materialization)"
                )
                has_aggs = bool(s.group_by) or s.having is not None or any(
                    _contains_agg(it.expr) for it in s.items
                )
            elif not s.table:
                lines.append(f"{indent}OneRow: FROM-less SELECT")
                return
            elif self._count_shortcut_applies(s):
                lines.append(
                    f"{indent}MetadataCount: table={s.table} — row count from"
                    " file metadata, no data files read"
                )
                return
            else:
                has_aggs = bool(s.group_by) or s.having is not None or any(
                    _contains_agg(it.expr) for it in s.items
                )
                scan, residual = self._plan_base(s, has_aggs)
                d = scan.explain()
                lines.append(
                    f"{indent}Scan: table={d['table']}"
                    + (f" columns={d['columns']}" if d["columns"] is not None else " columns=*")
                    + (f" snapshot_ts={d['snapshot_ts']}" if d["snapshot_ts"] else "")
                )
                if d["filter"] is not None:
                    lines.append(f"{indent}  pushdown: {_json.dumps(d['filter'])}")
                if d.get("zone_predicates"):
                    lines.append(
                        f"{indent}  zone-map conjuncts: {len(d['zone_predicates'])}"
                    )
                if d["partitions"]:
                    lines.append(f"{indent}  partition filter: {d['partitions']}")
                lines.append(
                    f"{indent}  units={d['units']} (merge-on-read {d['merge_units']},"
                    f" unit-pruned {d['units_pruned']} of"
                    f" {d['units_before_bucket_prune']}) files={d['files']}"
                    + (f" bytes={d['bytes_known']}" if d["bytes_known"] else "")
                    + (f" formats={d['file_formats']}" if d["file_formats"] else "")
                )
                if d["limit"] is not None:
                    lines.append(f"{indent}  early-stop limit: {d['limit']}")
                if residual:
                    lines.append(f"{indent}Residual filter: {len(residual)} predicate(s) post-scan")
            for j in s.joins:
                target = j.alias or j.table or "(subquery)"
                lines.append(f"{indent}Join: {j.kind} {target} ON {j.left_on} = {j.right_on}")
                if j.subquery is not None:
                    describe(j.subquery, indent + "  ")
            if has_aggs:
                n_sets = len(s.grouping_sets) if s.grouping_sets is not None else 1
                lines.append(
                    f"{indent}Aggregate: group_by={s.group_by} sets={n_sets}"
                    + (" having" if s.having is not None else "")
                )
            if s.distinct:
                lines.append(f"{indent}Distinct")
            if s.order_by:
                lines.append(f"{indent}Sort: {s.order_by}")
            if s.limit is not None or s.offset:
                bits = []
                if s.limit is not None:
                    bits.append(f"Limit: {s.limit}")
                if s.offset:
                    bits.append(f"offset={s.offset}" if bits else f"Offset: {s.offset}")
                lines.append(f"{indent}" + " ".join(bits))

        describe(stmt)
        return pa.table({"plan": lines})

    def _count_shortcut_applies(self, stmt: ast.Select) -> bool:
        """Bare ``SELECT count(*) FROM t``: metadata-only count, no decode
        (reference: EmptyScanCountExec shortcut).  Shared with EXPLAIN so the
        plan shown is the plan run."""
        return (
            stmt.table not in self._externals
            and len(stmt.items) == 1
            and isinstance(stmt.items[0].expr, ast.Agg)
            and stmt.items[0].expr.fn == "count"
            and stmt.items[0].expr.arg is None
            and stmt.where is None
            and not stmt.joins
            and not stmt.group_by
            and stmt.having is None
            and stmt.from_subquery is None
            and not stmt.distinct
            and not stmt.star
            and (stmt.limit is None or stmt.limit >= 1)  # LIMIT 0 drops the row
            and not stmt.offset  # OFFSET 1+ drops the single result row
        )

    def _select(self, stmt: ast.Select) -> pa.Table:
        if not stmt.table and stmt.from_subquery is None and not stmt.joins:
            # FROM-less SELECT: evaluate items over one anonymous row
            one = pa.table({"__r__": pa.array([0])})
            if stmt.where is not None:
                mask = self._eval_bool(stmt.where, one)
                one = one.filter(pc.fill_null(_broadcast(mask, 1), False))
            out, hidden = self._project(stmt, one)
            if hidden:
                out = out.drop_columns(hidden)
            return _slice_limit_offset(out, stmt)
        if self._count_shortcut_applies(stmt):
            n = self._base_scan(stmt).count_rows()
            label = stmt.items[0].alias or "count(*)"
            return pa.table({label: pa.array([n], type=pa.int64())})

        has_aggs = bool(stmt.group_by) or stmt.having is not None or any(
            _contains_agg(it.expr) for it in stmt.items
        )

        # ---- source: scan with pushdown, or a derived table
        residual_nodes: list = []
        key_renames: dict[str, str] = {}
        join_tables: dict = {}
        if stmt.from_subquery is not None:
            if stmt.as_of_ms is not None:
                raise SqlError("AS OF time travel requires a base table")
            table = self._query(stmt.from_subquery)
            if stmt.where is not None:
                residual_nodes = [stmt.where]
        elif (ext := self._external_table(stmt.table)) is not None:
            if stmt.as_of_ms is not None:
                raise SqlError("AS OF time travel requires a lakehouse table")
            table = ext
            if stmt.where is not None:
                residual_nodes = [stmt.where]
        else:
            started = time.perf_counter()
            scan, residual_nodes = self._plan_base(stmt, has_aggs)
            _stage_observe("plan", started)
            started = time.perf_counter()
            # parallel scan stage on the shared runtime: join right-side
            # base tables start scanning on the pool WHILE the base table
            # scans here (each scan's own units also fan out on the pool).
            # Every future resolves HERE — a failure anywhere cancels the
            # rest, so no background scan outlives a failed statement
            join_futs = self._prefetch_join_scans(stmt)
            try:
                table = scan.to_arrow()  # MOR timings land in lakesoul_io_*
                join_tables = {ji: f.result() for ji, f in sorted(join_futs.items())}
            except BaseException:
                import concurrent.futures

                for f in join_futs.values():
                    f.cancel()
                # cancel() can't stop an already-RUNNING scan: wait it out
                # (bounded by that scan's own duration) so no background
                # scan outlives the failed statement and races a retry or
                # a DROP TABLE issued right after
                concurrent.futures.wait(list(join_futs.values()))
                raise
            _stage_observe("scan", started)

        emit_started = time.perf_counter()
        # ---- joins (hash joins on Arrow compute; right side may be derived)
        for ji, j in enumerate(stmt.joins):
            if j.subquery is not None:
                right = self._query(j.subquery)
            elif (jext := self._external_table(j.table)) is not None:
                right = jext
            elif (pre := join_tables.get(ji)) is not None:
                right = pre
            else:
                right = self.catalog.table(j.table, self.namespace).to_arrow()
            rname = j.alias or j.table
            join_type = {
                "inner": "inner",
                "left": "left outer",
                "right": "right outer",
                "full": "full outer",
            }[j.kind]
            left_key, right_key = j.left_on, j.right_on
            # bind keys by their written qualifier (ON b.x = a.y works in
            # either order); bare names fall back to column membership
            if (j.left_qual is not None and j.left_qual in (j.table, j.alias)) or (
                j.left_qual is None
                and left_key not in table.column_names
                and left_key in right.column_names
            ):
                left_key, right_key = right_key, left_key
            if j.kind in ("right", "full"):
                # ON semantics under outer extension: keep BOTH key columns
                # (pyarrow's default key coalescing would make the
                # NULL-extended side's key read the other side's value,
                # silently breaking `a.k IS NULL` anti-joins)
                clashes = set(table.column_names) & set(right.column_names)
                suffix = f"_{rname}" if clashes else None
                table = table.join(
                    right, keys=left_key, right_keys=right_key,
                    join_type=join_type, right_suffix=suffix,
                    coalesce_keys=False,
                )
                if left_key == right_key and suffix:
                    # the right key survives suffixed: qualified references
                    # to it resolve there (bare ones stay on the left key)
                    new = right_key + suffix
                    _rename_qualified_refs(stmt, rname, right_key, new)
                    for n2 in residual_nodes:
                        _rename_qualified_refs(n2, rname, right_key, new)
                    # ORDER BY / GROUP BY store bare names; their recorded
                    # qualifiers rebind `b.k` onto the suffixed right key
                    # (silently sorting the NULL-extended left key instead
                    # would return wrong orderings)
                    oq = stmt.order_by_quals
                    stmt.order_by = [
                        (new, d)
                        if i < len(oq) and oq[i] == rname and c == right_key
                        else (c, d)
                        for i, (c, d) in enumerate(stmt.order_by)
                    ]
                    gq = stmt.group_by_quals
                    stmt.group_by = [
                        new
                        if i < len(gq) and gq[i] == rname and c == right_key
                        else c
                        for i, c in enumerate(stmt.group_by)
                    ]
                continue
            # non-key name collisions: suffix the right side (documented,
            # deterministic; a bare reference resolves to the left table)
            clashes = (set(table.column_names) & set(right.column_names)) - {right_key}
            suffix = f"_{rname}" if clashes else None
            table = table.join(
                right, keys=left_key, right_keys=right_key, join_type=join_type,
                right_suffix=suffix,
            )
            if right_key != left_key:
                # the right key column is dropped by the join; predicates
                # on it rewrite to the surviving left key
                key_renames[right_key] = left_key

        # ---- residual WHERE (general predicates, subqueries, post-join)
        if residual_nodes:
            node = (
                residual_nodes[0]
                if len(residual_nodes) == 1
                else ast.BoolOp("and", list(residual_nodes))
            )
            if key_renames:
                node = _rename_node_cols(node, key_renames)
                node = self._rename_correlated_outer_refs(node, key_renames)
            mask = self._eval_bool(node, table)
            table = table.filter(pc.fill_null(_broadcast(mask, len(table)), False))

        # ---- aggregate / project
        if has_aggs:
            out, hidden = self._aggregate(stmt, table)
        elif stmt.star:
            out, hidden = table, []
        else:
            out, hidden = self._project(stmt, table)

        # ---- DISTINCT (on the visible projection)
        if stmt.distinct:
            if hidden:
                out = out.drop_columns(hidden)
                hidden = []
            out = out.group_by(out.column_names).aggregate([])

        # ---- ORDER BY (one multi-key sort; hidden columns carry unprojected
        # sort keys) / LIMIT
        if stmt.order_by:
            keys = []
            for c, desc in stmt.order_by:
                name = c if c in out.column_names else f"__ord_{c}"
                if name not in out.column_names:
                    raise SqlError(f"ORDER BY column {c!r} not available")
                keys.append((name, "descending" if desc else "ascending"))
            out = out.sort_by(keys)
        if hidden:
            out = out.drop_columns(hidden)
        out = _slice_limit_offset(out, stmt)
        _stage_observe("emit", emit_started)
        return out

    def _needed_columns(self, stmt: ast.Select, residual_nodes: list) -> set[str]:
        cols: set[str] = set(stmt.group_by)
        for name, e in stmt.group_exprs:
            cols.discard(name)  # synthesized, not a base column
            cols |= _expr_columns(e)
        for it in stmt.items:
            cols |= _expr_columns(it.expr)
            cols |= _subquery_outer_candidates(it.expr)
        for c, _ in stmt.order_by:
            cols.add(c)
        if stmt.having is not None:
            cols |= _node_columns(stmt.having)
            cols |= _subquery_outer_candidates(stmt.having)
        for n in residual_nodes:
            cols |= _node_columns(n)
            cols |= _subquery_outer_candidates(n)  # correlation columns
        return cols

    def _project(self, stmt: ast.Select, table: pa.Table) -> tuple[pa.Table, list[str]]:
        """Evaluate non-aggregate select items; append hidden ``__ord_*``
        columns for ORDER BY keys that are not projected."""
        cols, labels = [], []
        for it in stmt.items:
            cols.append(_broadcast(self._eval_expr(it.expr, table), len(table)))
            labels.append(it.alias or _expr_label(it.expr))
        hidden: list[str] = []
        for c, _ in stmt.order_by:
            if c not in labels and c in table.column_names:
                h = f"__ord_{c}"
                cols.append(table.column(c))
                labels.append(h)
                hidden.append(h)
        return pa.table(cols, names=labels), hidden  # list form keeps dup labels

    _AGG_FN = {"count": "count", "sum": "sum", "min": "min", "max": "max", "avg": "mean"}

    def _aggregate(self, stmt: ast.Select, table: pa.Table) -> tuple[pa.Table, list[str]]:
        """GROUP BY / global aggregation with HAVING and expressions over
        aggregates (e.g. ``100 * sum(a) / sum(b)``)."""
        # GROUP BY <expr>: materialize each synthesized key column over the
        # pre-aggregation table, then rewrite every STRUCTURAL occurrence of
        # a key expression (qualifier-insensitive, as a subexpression) in
        # the select items and HAVING onto the key column — after
        # aggregation the base columns are gone
        if stmt.group_exprs:
            by_norm = {}
            for name, e in stmt.group_exprs:
                table = table.append_column(
                    name, _broadcast(self._eval_expr(e, table), len(table))
                )
                by_norm[_norm_repr(e)] = name
            new_items = []
            for it in stmt.items:
                sub = _subst_group_keys(it.expr, by_norm)
                alias = it.alias
                if sub is not it.expr and alias is None:
                    alias = _expr_label(it.expr)
                new_items.append(ast.SelectItem(sub, alias))
            stmt.items = new_items
            if stmt.having is not None:
                stmt.having = _subst_group_keys(stmt.having, by_norm)
        # alias resolution for HAVING/expressions: alias → item expression
        alias_map = {it.alias: it.expr for it in stmt.items if it.alias}

        # collect every distinct aggregate across select items + HAVING
        agg_nodes: dict[tuple, ast.Agg] = {}

        def collect(expr):
            for a in _walk_aggs(expr):
                agg_nodes.setdefault(_agg_key(a), a)

        for it in stmt.items:
            collect(it.expr)
        having = stmt.having
        if having is not None:
            having = _resolve_aliases_bool(having, alias_map)
            for sub in _bool_exprs(having):
                collect(sub)

        # materialize expression arguments, build one spec per distinct agg
        work = table
        specs: list = []
        agg_col: dict[tuple, str] = {}
        for i, (key, agg) in enumerate(agg_nodes.items()):
            if agg.arg is None:
                specs.append(([], "count_all"))
                agg_col[key] = "count_all"
                continue
            if isinstance(agg.arg, ast.Column):
                target = agg.arg.name
            else:
                target = f"__agg_in_{i}"
                arr = _broadcast(self._eval_expr(agg.arg, work), len(work))
                work = work.append_column(target, arr)
            if agg.distinct and agg.fn != "count":
                raise SqlError(
                    f"DISTINCT is only supported for count, not {agg.fn}"
                )
            fn = "count_distinct" if agg.distinct else self._AGG_FN[agg.fn]
            specs.append((target, fn))
            agg_col[key] = f"{target}_{fn}"
        # dedup identical specs (repeated aggregates share one output column)
        call_specs, seen = [], set()
        for target, fn in specs:
            k = (tuple(target) if isinstance(target, list) else target, fn)
            if k not in seen:
                seen.add(k)
                call_specs.append((target, fn))

        # ROLLUP/CUBE/GROUPING SETS: aggregate once per set; grouping columns
        # absent from a set surface as NULL in its (subtotal) rows
        sets = (
            stmt.grouping_sets if stmt.grouping_sets is not None else [list(stmt.group_by)]
        )
        agg_names = [
            "count_all" if not target else f"{target}_{fn}" for target, fn in call_specs
        ]
        parts = []
        for s in sets:
            g = work.group_by(list(s)).aggregate(call_specs)
            for c in stmt.group_by:
                if c not in s:
                    g = g.append_column(c, pa.nulls(len(g), type=work.schema.field(c).type))
            parts.append(g.select(agg_names + list(stmt.group_by)))
        grouped = parts[0] if len(parts) == 1 else pa.concat_tables(parts)

        if having is not None:
            try:
                mask = self._eval_bool(_subst_aggs_bool(having, agg_col), grouped)
            except KeyError as e:
                raise SqlError(
                    f"HAVING references {e} which is neither grouped nor"
                    " inside an aggregate"
                )
            grouped = grouped.filter(pc.fill_null(_broadcast(mask, len(grouped)), False))

        # project select items over the aggregated table
        cols, labels = [], []
        for it in stmt.items:
            if isinstance(it.expr, ast.Column):
                if it.expr.name not in stmt.group_by:
                    raise SqlError(f"column {it.expr.name} must appear in GROUP BY")
                cols.append(grouped.column(it.expr.name))
                labels.append(it.alias or it.expr.name)
            else:
                expr = _subst_aggs(it.expr, agg_col)
                try:
                    cols.append(
                        _broadcast(self._eval_expr(expr, grouped), len(grouped))
                    )
                except KeyError as e:
                    # a non-grouped base column survived substitution: the
                    # aggregated frame no longer carries it
                    raise SqlError(
                        f"select expression references {e} which is neither"
                        " grouped (column or GROUP BY expression) nor inside"
                        " an aggregate"
                    )
                labels.append(it.alias or _expr_label(it.expr))
        out = pa.table(cols, names=labels)
        # unprojected ORDER BY keys that are group keys ride along hidden
        hidden: list[str] = []
        for c, _ in stmt.order_by:
            if c not in labels and c in grouped.column_names:
                h = f"__ord_{c}"
                out = out.append_column(h, grouped.column(c))
                hidden.append(h)
        return out, hidden

    # ------------------------------------------------------- expression eval
    # ---------------------------------------------- correlated subqueries
    #
    # Correlated EXISTS / IN / scalar-aggregate subqueries are decorrelated
    # mechanically (VERDICT r3 item 9) — the classic transforms DataFusion
    # applies in the reference:
    #   EXISTS (… WHERE inner.k = outer.k AND p)   → hash semi-join on k
    #   col IN (SELECT c FROM … WHERE corr)        → EXISTS with c = col
    #   (SELECT agg(x) FROM … WHERE inner.k = outer.k AND p)
    #                                              → GROUP BY k + left join
    # Column references resolve QUALIFIER-FIRST (Column.qual survives
    # parsing): a qualifier naming the subquery's own table/alias is inner,
    # any other qualifier is outer; bare names resolve by scope membership,
    # innermost-first.  That covers aliased self-correlation too — Q21's
    # ``l2.l_suppkey <> l1.l_suppkey`` runs natively, the inner/outer sides
    # disambiguated by the l1/l2 aliases even though the names collide.

    def _projection_names(self, sel) -> set[str]:
        if isinstance(sel, ast.SetOp):
            return self._projection_names(sel.left)
        if sel.star:
            return self._scope_columns(sel)
        names: set[str] = set()
        for it in sel.items:
            if it.alias:
                names.add(it.alias)
            elif isinstance(it.expr, ast.Column):
                names.add(it.expr.name)
        return names

    def _table_schema_names(self, name: str) -> set[str]:
        ext = self._external_table(name)
        if ext is not None:
            return set(ext.schema.names)
        return set(self.catalog.table(name, self.namespace).schema.names)

    def _scope_columns(self, sel) -> set[str]:
        """Names visible inside a Select's FROM scope, without executing it."""
        cols: set[str] = set()
        if sel.from_subquery is not None:
            cols |= self._projection_names(sel.from_subquery)
        elif sel.table:
            cols |= self._table_schema_names(sel.table)
        for j in sel.joins:
            if j.subquery is not None:
                cols |= self._projection_names(j.subquery)
            elif j.table:
                cols |= self._table_schema_names(j.table)
        return cols

    @staticmethod
    def _inner_quals(sel) -> set[str]:
        quals = {sel.table, sel.from_alias}
        for j in sel.joins:
            quals.add(j.table)
            quals.add(j.alias)
        quals.discard(None)
        quals.discard("")
        return quals

    def _make_scope_resolver(self, sel, outer_cols: set[str]):
        """→ resolve(qual, name) ∈ {"inner", "outer"}.  Qualifiers win
        (``orders.orderkey`` is outer even when lineitem also has
        ``orderkey``); bare names resolve innermost-scope-first."""
        inner_cols = self._scope_columns(sel)
        inner_quals = self._inner_quals(sel)

        def resolve(qual, name):
            if qual == "__outer__":
                # marker left by _rename_correlated_outer_refs: this ref was
                # a join-key column the outer join coalesced away, already
                # rewritten to the surviving left-key name
                if name not in outer_cols:
                    raise SqlError(f"unknown outer column {name!r} in subquery")
                return "outer"
            if qual:
                if qual in inner_quals:
                    if name not in inner_cols:
                        raise SqlError(f"unknown column {qual}.{name} in subquery")
                    return "inner"
                if name not in outer_cols:
                    raise SqlError(
                        f"unknown column {qual}.{name} (outer scope has no {name!r})"
                    )
                return "outer"
            if name in inner_cols:
                return "inner"
            if name in outer_cols:
                return "outer"
            raise SqlError(f"unknown column {name!r} in subquery")

        return resolve

    def _split_correlated(self, sel, outer_cols: set[str]):
        """Classify a subquery's WHERE conjuncts against (inner, outer)
        scopes → (inner_only_node, eq_pairs [(outer_col, inner_col)],
        mixed_conjuncts, outer_only_conjuncts, resolve)."""
        if sel.where is None:
            return None, [], [], [], None
        resolve = self._make_scope_resolver(sel, outer_cols)
        inner, eq_pairs, mixed, outer_only = [], [], [], []
        for c in _flatten_and(sel.where):
            refs = _node_column_refs(c)
            if not refs:
                inner.append(c)
                continue
            scopes = {resolve(q, n) for q, n in refs}
            if scopes == {"inner"}:
                inner.append(c)
            elif scopes == {"outer"}:
                outer_only.append(c)
            else:
                pair = self._as_eq_pair(c, resolve)
                if pair is not None:
                    eq_pairs.append(pair)
                else:
                    mixed.append(c)
        node = (
            inner[0] if len(inner) == 1
            else (ast.BoolOp("and", inner) if inner else None)
        )
        return node, eq_pairs, mixed, outer_only, resolve

    @staticmethod
    def _as_eq_pair(c, resolve):
        if (
            isinstance(c, ast.Compare) and c.op == "eq" and not c.simple
            and isinstance(c.left, ast.Column) and isinstance(c.right, ast.Column)
        ):
            ls = resolve(c.left.qual, c.left.name)
            rs = resolve(c.right.qual, c.right.name)
            if ls == "inner" and rs == "outer":
                return (c.right.name, c.left.name)
            if rs == "inner" and ls == "outer":
                return (c.left.name, c.right.name)
        return None

    def _rename_correlated_outer_refs(self, node, mapping: dict):
        """Join-key renames must reach OUTER references inside subqueries:
        ``JOIN part ON l_partkey = partkey`` drops ``partkey`` from the
        outer frame, so a correlated ``l2.l_partkey = part.partkey`` must
        rewrite to the surviving ``l_partkey`` — marked with the reserved
        ``__outer__`` qualifier so scope resolution still reads it as outer
        even when the inner scope has a column of the same name."""
        from dataclasses import replace as _dc_replace

        def fix_sel(sel):
            if not isinstance(sel, ast.Select) or sel.where is None:
                return sel
            inner_cols = self._scope_columns(sel)
            inner_quals = self._inner_quals(sel)

            def map_col(qual, name):
                if qual and qual in inner_quals:
                    return qual, name
                if not qual and name in inner_cols:
                    return qual, name
                if name in mapping:
                    return "__outer__", mapping[name]
                return qual, name

            return _dc_replace(
                sel, where=_map_node_cols(sel.where, map_col, map_sel=fix_sel)
            )

        # top level: only descend into subqueries — top-level refs were
        # already renamed by _rename_node_cols
        return _map_node_cols(node, lambda q, n: (q, n), map_sel=fix_sel)

    def _decorrelated_inner(self, sel, inner_node, needed: set | None = None) -> pa.Table:
        from dataclasses import replace as _dc_replace

        if sel.group_by or sel.having is not None:
            raise SqlError(
                "correlated EXISTS/IN with GROUP BY is not supported"
            )
        if sel.limit is not None or sel.offset:
            # decorrelation evaluates the inner ONCE over all groups; a
            # per-outer-row LIMIT/OFFSET cannot be expressed there — reject
            # loudly rather than silently dropping it (wrong answers)
            raise SqlError(
                "correlated subqueries do not support LIMIT/OFFSET"
            )
        if needed:
            # project to the correlation keys + mixed-predicate columns:
            # EXISTS over a wide fact table must not materialize every column
            items = [ast.SelectItem(ast.Column(c)) for c in sorted(needed)]
            inner_sel = _dc_replace(
                sel, items=items, star=False, where=inner_node,
                order_by=[], limit=None, offset=None, distinct=True,
            )
        else:
            inner_sel = _dc_replace(
                sel, items=[], star=True, where=inner_node, order_by=[],
                limit=None, offset=None,
            )
        return self._query(inner_sel)

    def _semi_join_mask(self, outer, inner, eq_pairs, mixed, resolve):
        """Per-outer-row EXISTS mask: hash semi-join on the equality
        correlation keys, remaining mixed-reference conjuncts evaluated on
        the joined pairs.  Null keys never match (SQL semantics).  Outer
        columns are renamed ``__o_<name>`` on the joined frame so inner
        columns with the SAME name (self-correlation) stay unambiguous."""
        import numpy as np

        n = len(outer)
        idx = pa.array(np.arange(n, dtype=np.int64))
        keys_o = list(dict.fromkeys(p[0] for p in eq_pairs))
        keys_i = [p[1] for p in eq_pairs]
        if mixed:
            need = set(keys_o)
            for c in mixed:
                need |= {nm for q, nm in _node_column_refs(c)
                         if resolve(q, nm) == "outer"}
            osel = outer.select(sorted(need)).rename_columns(
                ["__o_" + c for c in sorted(need)]
            ).append_column("__cidx__", idx)
            if eq_pairs:
                joined = osel.join(
                    inner,
                    keys=["__o_" + p[0] for p in eq_pairs],
                    right_keys=keys_i,
                    join_type="inner",
                )
            else:
                one = pa.array(np.ones(len(osel), np.int8))
                joined = osel.append_column("__one__", one).join(
                    inner.append_column(
                        "__one__", pa.array(np.ones(len(inner), np.int8))
                    ),
                    keys="__one__",
                    join_type="inner",
                )
            # inner join-key columns are dropped (coalesced) by the join;
            # mixed refs to them read the surviving outer-side key instead
            inner_renames = {i: "__o_" + o for o, i in eq_pairs}
            rewritten = [
                _rewrite_outer_refs(c, resolve, inner_renames=inner_renames)
                for c in mixed
            ]
            node = (
                rewritten[0] if len(rewritten) == 1
                else ast.BoolOp("and", rewritten)
            )
            m = self._eval_bool(node, joined)
            joined = joined.filter(pc.fill_null(_broadcast(m, len(joined)), False))
            matched = joined.column("__cidx__")
        else:
            distinct = inner.select(keys_i).group_by(keys_i).aggregate([])
            joined = (
                outer.select(keys_o)
                .rename_columns(["__o_" + c for c in keys_o])
                .append_column("__cidx__", idx)
                .join(
                    distinct,
                    keys=["__o_" + p[0] for p in eq_pairs],
                    right_keys=keys_i,
                    join_type="inner",
                )
            )
            matched = joined.column("__cidx__")
        mask = np.zeros(n, dtype=bool)
        mi = matched.combine_chunks().to_numpy(zero_copy_only=False)
        mask[mi] = True
        return pa.array(mask)

    def _eval_exists(self, node, table):
        sel = node.select
        if isinstance(sel, ast.SetOp):
            exists = len(self._query(sel)) > 0
            return pa.scalar(exists != node.negated)
        inner_node, eq_pairs, mixed, outer_only, resolve = self._split_correlated(
            sel, set(table.column_names)
        )
        if not eq_pairs and not mixed and not outer_only:
            exists = len(self._query(sel)) > 0
            return pa.scalar(exists != node.negated)
        needed = {i for _, i in eq_pairs}
        for c in mixed:
            needed |= {nm for q, nm in _node_column_refs(c)
                       if resolve(q, nm) == "inner"}
        inner = self._decorrelated_inner(sel, inner_node, needed or None)
        if eq_pairs or mixed:
            mask = self._semi_join_mask(table, inner, eq_pairs, mixed, resolve)
        else:
            mask = pa.array([len(inner) > 0] * len(table))
        for c in outer_only:
            mask = pc.and_kleene(
                pc.fill_null(mask, False),
                pc.fill_null(_broadcast(self._eval_bool(c, table), len(table)), False),
            )
        return pc.invert(mask) if node.negated else mask

    def _eval_in_subquery(self, node, table):
        sel = node.select
        if isinstance(sel, ast.Select) and sel.where is not None:
            inner_node, eq_pairs, mixed, outer_only, resolve = self._split_correlated(
                sel, set(table.column_names)
            )
        else:
            inner_node, eq_pairs, mixed, outer_only, resolve = (
                None, [], [], [], None,
            )
        if not eq_pairs and not mixed and not outer_only:
            sub = self._query(sel)
            if sub.num_columns != 1:
                raise SqlError("IN (SELECT ...) must produce one column")
            values = sub.column(0).combine_chunks()
            col = table.column(node.col)
            mask = pc.fill_null(
                pc.is_in(col, value_set=values, skip_nulls=True), False
            )
            # SQL three-valued logic: an UNMATCHED probe is UNKNOWN (null),
            # not FALSE, when the probe is NULL or the set contains NULLs —
            # so `x NOT IN (... NULL ...)` filters the row instead of
            # keeping it (Kleene invert maps null → null)
            if len(values) and (col.null_count or values.null_count):
                unknown = pc.and_(
                    pc.invert(mask),
                    pc.or_(
                        pc.is_null(col), pa.scalar(bool(values.null_count))
                    ),
                )
                mask = pc.if_else(unknown, pa.scalar(None, pa.bool_()), mask)
            return pc.invert(mask) if node.negated else mask
        # correlated IN: col IN (SELECT c …) ≡ EXISTS(… AND c = col)
        if isinstance(sel, ast.SetOp) or sel.star or len(sel.items) != 1 \
                or not isinstance(sel.items[0].expr, ast.Column):
            raise SqlError(
                "correlated IN subquery must select a single plain column"
            )
        inner_item = sel.items[0].expr.name
        needed = {i for _, i in eq_pairs} | {inner_item}
        for c in mixed:
            needed |= {nm for q, nm in _node_column_refs(c)
                       if resolve(q, nm) == "inner"}
        inner = self._decorrelated_inner(sel, inner_node, needed)
        mask = self._semi_join_mask(
            table, inner, eq_pairs + [(node.col, inner_item)], mixed, resolve
        )
        # three-valued logic: unmatched is UNKNOWN (not FALSE) when the outer
        # value is NULL and the correlated group is non-empty, or the group
        # itself contains a NULL — `NOT IN` must filter such rows.  Joins
        # never match NULL keys, so `mask` alone would claim definite FALSE.
        outer_col = table.column(node.col)
        inner_vals = inner.column(inner_item)
        if outer_col.null_count or inner_vals.null_count:
            def _group_mask(group: pa.Table):
                if eq_pairs or mixed:
                    return self._semi_join_mask(
                        table, group, eq_pairs, mixed, resolve
                    )
                return pa.array([len(group) > 0] * len(table))

            unknown = None
            if inner_vals.null_count:
                unknown = _group_mask(inner.filter(pc.is_null(inner_vals)))
            if outer_col.null_count:
                probe_null = pc.and_(
                    pc.is_null(outer_col), _group_mask(inner)
                )
                unknown = probe_null if unknown is None \
                    else pc.or_(unknown, probe_null)
            unknown = pc.and_(
                pc.fill_null(_broadcast(unknown, len(table)), False),
                pc.invert(pc.fill_null(_broadcast(mask, len(table)), False)),
            )
            mask = pc.if_else(
                unknown, pa.scalar(None, pa.bool_()),
                _broadcast(mask, len(table)),
            )
        for c in outer_only:
            # the outer-only predicate gates the whole subquery: where it is
            # FALSE or UNKNOWN the group is empty → IN is definite FALSE
            mask = pc.and_kleene(
                _broadcast(mask, len(table)),
                pc.fill_null(_broadcast(self._eval_bool(c, table), len(table)), False),
            )
        return pc.invert(mask) if node.negated else mask

    def _eval_scalar_correlated(self, sel, inner_node, eq_pairs, table):
        """(SELECT agg(x) FROM … WHERE k = outer.k AND p) → GROUP BY k,
        left-joined back per outer row; groupless rows yield NULL (0 for a
        bare count, matching SQL)."""
        import numpy as np
        from dataclasses import replace as _dc_replace

        if len(sel.items) != 1 or not _contains_agg(sel.items[0].expr) \
                or sel.group_by:
            raise SqlError(
                "correlated scalar subquery must be a single aggregate"
            )
        if sel.limit is not None or sel.offset:
            raise SqlError(
                "correlated subqueries do not support LIMIT/OFFSET"
            )
        keys_o = [p[0] for p in eq_pairs]
        keys_i = [p[1] for p in eq_pairs]
        dec = _dc_replace(
            sel,
            items=[ast.SelectItem(ast.Column(k)) for k in keys_i]
            + [ast.SelectItem(sel.items[0].expr, "__scalar__")],
            star=False,
            where=inner_node,
            group_by=list(keys_i),
            order_by=[],
            limit=None,
            offset=None,
        )
        grouped = self._select(dec)
        n = len(table)
        idx = pa.array(np.arange(n, dtype=np.int64))
        joined = (
            table.select(keys_o)
            .append_column("__cidx__", idx)
            .join(grouped, keys=keys_o, right_keys=keys_i, join_type="left outer")
            .sort_by("__cidx__")
        )
        vals = joined.column("__scalar__")
        fill = self._agg_expr_empty_value(sel.items[0].expr)
        if fill is not None:
            # SQL evaluates the aggregate expression over the EMPTY set for
            # outer rows with no matching group: count(*) → 0, so
            # count(*)+1 → 1; sum/avg/min/max → NULL keeps the join NULL
            vals = pc.fill_null(vals, fill)
        return vals

    def _agg_expr_empty_value(self, expr):
        """Value of an aggregate expression over zero rows, or None when it
        is NULL (any NULL-yielding aggregate poisons the expression)."""

        def sub(e):
            if isinstance(e, ast.Agg):
                return ast.Literal(0) if e.fn == "count" else ast.Literal(None)
            if isinstance(e, ast.Arith):
                return ast.Arith(e.op, sub(e.left), sub(e.right))
            if isinstance(e, ast.Func):
                return ast.Func(e.name, [None if a is None else sub(a) for a in e.args])
            return e

        one_row = pa.table({"__d__": pa.array([0])})
        try:
            v = self._eval_expr(sub(expr), one_row)
        except (SqlError, pa.ArrowInvalid, TypeError, KeyError):
            # KeyError: the expression also references a (correlation) column
            # — no constant empty-set value exists, keep the NULL
            return None
        if isinstance(v, pa.ChunkedArray):
            v = v.combine_chunks()
        if isinstance(v, (pa.Array, pa.ChunkedArray)):
            v = v[0]
        py = v.as_py() if isinstance(v, pa.Scalar) else v
        return py if py is not None else None

    def _eval_expr(self, expr, table: pa.Table):
        """Evaluate a value expression against a table → Arrow array/scalar."""
        if isinstance(expr, ast.Column):
            return table.column(expr.name)
        if isinstance(expr, ast.Literal):
            return pa.scalar(expr.value)
        if isinstance(expr, ast.Arith):
            left = self._eval_expr(expr.left, table)
            right = self._eval_expr(expr.right, table)
            fn = {"+": pc.add, "-": pc.subtract, "*": pc.multiply, "/": pc.divide}[expr.op]
            return fn(left, right)
        if isinstance(expr, ast.WindowFn):
            return self._eval_window(expr, table)
        if isinstance(expr, ast.Case):
            return self._eval_case(expr, table)
        if isinstance(expr, ast.Func):
            if expr.name == "substring":
                arr, start, length = expr.args
                s = self._eval_expr(start, table)
                s0 = (s.as_py() if isinstance(s, pa.Scalar) else s) - 1  # SQL is 1-based
                stop = None
                if length is not None:
                    ln = self._eval_expr(length, table)
                    stop = s0 + (ln.as_py() if isinstance(ln, pa.Scalar) else ln)
                return pc.utf8_slice_codeunits(
                    self._eval_expr(arr, table), start=s0, stop=stop
                )
            if expr.name == "cast":
                val, spec = expr.args
                tname, params = spec.value
                if tname == "decimal":
                    if params:
                        precision = params[0]
                        scale = params[1] if len(params) > 1 else 0
                    else:
                        precision, scale = 38, 10
                    try:
                        target = pa.decimal128(precision, scale)
                    except ValueError as e:  # precision out of [1, 38]
                        raise SqlError(f"CAST failed: {e}")
                elif tname in ("varchar", "char"):
                    target = pa.string()  # length is advisory in SQL
                else:
                    target = _TYPE_MAP.get(tname)
                if target is None:
                    raise SqlError(f"unknown type {tname!r} in CAST")
                try:
                    # float→int TRUNCATES (standard SQL / Spark / DuckDB);
                    # malformed strings and overflows still error
                    opts = pc.CastOptions(
                        target_type=target, allow_float_truncate=True
                    )
                    return pc.cast(self._eval_expr(val, table), options=opts)
                except (pa.lib.ArrowInvalid, pa.lib.ArrowNotImplementedError) as e:
                    raise SqlError(f"CAST failed: {e}")
            if expr.name == "coalesce":
                vals = [
                    _broadcast(self._eval_expr(a, table), len(table))
                    for a in expr.args
                ]
                return pc.coalesce(*vals)
            if expr.name == "nullif":
                if len(expr.args) != 2:
                    raise SqlError("nullif takes exactly two arguments")
                a = _broadcast(self._eval_expr(expr.args[0], table), len(table))
                b = _broadcast(self._eval_expr(expr.args[1], table), len(table))
                eq = pc.fill_null(pc.equal(a, b), False)
                return pc.if_else(eq, pa.scalar(None, a.type), a)
            if expr.name in _DATE_PARTS:
                if len(expr.args) != 1:
                    raise SqlError(f"{expr.name} takes exactly one argument")
                fn = _DATE_PARTS[expr.name]
                # evaluate the argument OUTSIDE the guard: a failure inside
                # a nested expression is that expression's error, not a
                # date-typing complaint from this function
                arg = self._eval_expr(expr.args[0], table)
                arg_type = arg.type if hasattr(arg, "type") else None
                if arg_type is not None and pa.types.is_null(arg_type):
                    # bare NULL literal: date_part(NULL) is NULL, not an error
                    return pa.scalar(None, pa.int64())
                if (
                    arg_type is not None and pa.types.is_date(arg_type)
                    and expr.name in ("hour", "minute", "second")
                ):
                    # DataFusion semantics: time parts of a DATE are 0
                    arg = pc.cast(arg, pa.timestamp("us"))
                try:
                    out = fn(arg)
                except (pa.lib.ArrowNotImplementedError, pa.lib.ArrowInvalid) as e:
                    raise SqlError(f"{expr.name}() needs a date/timestamp: {e}")
                return pc.cast(out, pa.int64())  # BI tools expect plain ints
            if expr.name in ("trim", "ltrim", "rtrim"):
                if len(expr.args) != 1:
                    raise SqlError(f"{expr.name} takes exactly one argument")
                fn = {
                    "trim": pc.utf8_trim_whitespace,
                    "ltrim": pc.utf8_ltrim_whitespace,
                    "rtrim": pc.utf8_rtrim_whitespace,
                }[expr.name]
                return self._apply_fn(expr.name, fn, self._eval_expr(expr.args[0], table))
            if expr.name == "replace":
                if len(expr.args) != 3:
                    raise SqlError("replace takes exactly three arguments")
                pat, rep = expr.args[1], expr.args[2]
                if not isinstance(pat, ast.Literal) or not isinstance(rep, ast.Literal):
                    raise SqlError("replace pattern and replacement must be literals")
                if pat.value is None or rep.value is None:
                    # SQL: any NULL argument nulls the result — never the
                    # text "None"
                    return pa.nulls(len(table), pa.string())
                return pc.replace_substring(
                    self._eval_expr(expr.args[0], table),
                    pattern=str(pat.value), replacement=str(rep.value),
                )
            if expr.name == "concat":
                if not expr.args:
                    raise SqlError("concat takes at least one argument")
                parts = [
                    pc.cast(
                        _broadcast(self._eval_expr(a, table), len(table)),
                        pa.string(),
                    )
                    for a in expr.args
                ]
                # NULL arguments are SKIPPED (Postgres/DataFusion concat
                # semantics — the engine this dialect claims parity with;
                # Spark/MySQL instead null the whole result).  That holds
                # for ONE argument too: concat(NULL) is '' — skipping the
                # sole NULL leaves the empty string, never NULL
                if len(parts) == 1:
                    return pc.fill_null(parts[0], "")
                return pc.binary_join_element_wise(
                    *parts, "", null_handling="skip"
                )
            if expr.name in ("abs", "upper", "lower", "length", "round"):
                if expr.name == "round":
                    if not 1 <= len(expr.args) <= 2:
                        raise SqlError("round takes one or two arguments")
                    nd = 0
                    if len(expr.args) == 2:
                        ndv = self._eval_expr(expr.args[1], table)
                        if not isinstance(ndv, pa.Scalar):
                            raise SqlError("round digits must be a literal")
                        nd = int(ndv.as_py())
                    # SQL rounds half away from zero, not banker's rounding
                    return pc.round(
                        self._eval_expr(expr.args[0], table),
                        ndigits=nd, round_mode="half_towards_infinity",
                    )
                if len(expr.args) != 1:
                    raise SqlError(f"{expr.name} takes exactly one argument")
                arg = self._eval_expr(expr.args[0], table)
                fn = {
                    "abs": pc.abs,
                    "upper": pc.utf8_upper,
                    "lower": pc.utf8_lower,
                    "length": pc.utf8_length,
                }[expr.name]
                return self._apply_fn(expr.name, fn, arg)
            raise SqlError(f"unknown function {expr.name!r}")
        if isinstance(expr, ast.ScalarSubquery):
            sel = expr.select
            if isinstance(sel, ast.Select) and sel.where is not None:
                inner_node, eq_pairs, mixed, outer_only, _rs = self._split_correlated(
                    sel, set(table.column_names)
                )
                if eq_pairs or mixed or outer_only:
                    if mixed or outer_only:
                        raise SqlError(
                            "correlated scalar subquery supports equality"
                            " correlation predicates only"
                        )
                    return self._eval_scalar_correlated(
                        sel, inner_node, eq_pairs, table
                    )
            sub = self._query(sel)
            if sub.num_columns != 1 or len(sub) > 1:
                raise SqlError("scalar subquery must produce one value")
            return sub.column(0)[0] if len(sub) else pa.scalar(None)
        if isinstance(expr, ast.Agg):
            raise SqlError("aggregate not allowed here (missing GROUP BY context?)")
        raise SqlError(f"unsupported expression {expr!r}")

    def _eval_window(self, wf: ast.WindowFn, table: pa.Table):
        """Window functions: ONE stable multi-key sort (partition + order
        keys + row tiebreaker), vectorized rank/offset/aggregate computation
        in the sorted domain, scatter back to row order.  Aggregates with an
        ORDER BY are running with RANGE semantics (peer rows share the value
        at the last peer); without one they broadcast the partition value —
        standard SQL defaults (the reference gets these from DataFusion's
        window planner)."""
        import numpy as np

        fn = wf.fn
        n = len(table)
        is_rank = isinstance(fn, ast.Func) and fn.name in (
            "row_number", "rank", "dense_rank"
        )
        if n == 0:
            return pa.nulls(0, type=pa.int64() if is_rank else pa.float64())

        aug = table.append_column("__rn", pa.array(np.arange(n, dtype=np.int64)))
        sort_keys = (
            [(c, "ascending") for c in wf.partition_by]
            + [(c, "descending" if d else "ascending") for c, d in wf.order_by]
            + [("__rn", "ascending")]  # determinism among peers
        )
        order = pc.sort_indices(aug, sort_keys=sort_keys).to_numpy()
        idx = np.arange(n, dtype=np.int64)
        inv = np.empty(n, dtype=np.int64)
        inv[order] = idx

        def sorted_codes(cname: str) -> np.ndarray:
            # dictionary codes make run detection null-safe and type-agnostic
            arr = table.column(cname).combine_chunks()
            enc = arr if pa.types.is_dictionary(arr.type) else pc.dictionary_encode(arr)
            codes = pc.fill_null(enc.indices.cast(pa.int64()), -1).to_numpy()
            return codes[order]

        part_new = np.zeros(n, dtype=bool)
        part_new[0] = True
        for c in wf.partition_by:
            cs = sorted_codes(c)
            part_new[1:] |= cs[1:] != cs[:-1]
        peer_new = part_new.copy()
        for c, _ in wf.order_by:
            cs = sorted_codes(c)
            peer_new[1:] |= cs[1:] != cs[:-1]
        part_first = np.maximum.accumulate(np.where(part_new, idx, 0))

        if isinstance(fn, ast.Func) and fn.name in ("row_number", "rank", "dense_rank"):
            if fn.name == "row_number":
                out_sorted = idx - part_first + 1
            elif fn.name == "rank":
                peer_first = np.maximum.accumulate(np.where(peer_new, idx, 0))
                out_sorted = peer_first - part_first + 1
            else:  # dense_rank
                dr = np.cumsum(peer_new)
                dr_start = np.maximum.accumulate(np.where(part_new, dr, 0))
                out_sorted = dr - dr_start + 1
            res = np.empty(n, dtype=np.int64)
            res[order] = out_sorted
            return pa.array(res)

        if isinstance(fn, ast.Func):  # lag / lead
            k = fn.args[1].value if len(fn.args) > 1 else 1
            default = fn.args[2].value if len(fn.args) > 2 else None
            vals = _broadcast(self._eval_expr(fn.args[0], table), n)
            if isinstance(vals, pa.ChunkedArray):
                vals = vals.combine_chunks()
            sorted_vals = vals.take(pa.array(order))
            shift = k if fn.name == "lag" else -k
            src = idx - shift
            part_id = np.cumsum(part_new)
            valid = (src >= 0) & (src < n)
            src_c = np.clip(src, 0, n - 1)
            valid &= part_id[src_c] == part_id
            taken = sorted_vals.take(pa.array(np.where(valid, src_c, 0)))
            fallback = (
                pa.nulls(n, type=sorted_vals.type)
                if default is None
                else pa.array([default] * n).cast(sorted_vals.type)
            )
            out = pc.if_else(pa.array(valid), taken, fallback)
            return out.take(pa.array(inv))

        # aggregate window (Agg)
        import pandas as pd

        part_id = np.cumsum(part_new)
        if fn.arg is None:
            ser = pd.Series(np.ones(n))
            counts_star = True
        else:
            vals = _broadcast(self._eval_expr(fn.arg, table), n)
            if isinstance(vals, pa.ChunkedArray):
                vals = vals.combine_chunks()
            ser = vals.take(pa.array(order)).to_pandas()
            counts_star = False
        g = ser.groupby(part_id)
        if not wf.order_by:  # whole-partition broadcast
            if fn.fn == "count":
                out = g.transform("size") if counts_star else g.transform("count")
            else:
                out = g.transform({"sum": "sum", "min": "min", "max": "max",
                                   "avg": "mean"}[fn.fn])
                if fn.fn == "sum":
                    # SQL: sum over zero non-null inputs is NULL, not 0
                    nn = ser.notna().groupby(part_id).transform("sum")
                    out = out.where(nn > 0)
            out_sorted = out.to_numpy()
        else:  # running (RANGE: peers share the last peer row's value)
            # SQL frame semantics: NULL inputs are SKIPPED — the running
            # value carries forward through them (pandas cum* would leave
            # NaN at NaN positions instead)
            nn = ser.notna().groupby(part_id).cumsum()
            if fn.fn == "count":
                out = g.cumcount() + 1 if counts_star else nn
            elif fn.fn == "sum":
                out = ser.fillna(0).groupby(part_id).cumsum().where(nn > 0)
            elif fn.fn == "min":
                out = g.cummin().groupby(part_id).ffill()
            elif fn.fn == "max":
                out = g.cummax().groupby(part_id).ffill()
            else:  # avg
                out = (ser.fillna(0).groupby(part_id).cumsum() / nn).where(nn > 0)
            starts = np.flatnonzero(peer_new)
            ends = np.append(starts[1:], n) - 1
            peer_last = np.repeat(ends, np.diff(np.append(starts, n)))
            out_sorted = out.to_numpy()[peer_last]
        res = np.empty(n, dtype=np.asarray(out_sorted).dtype)
        res[order] = out_sorted
        return pa.array(res, from_pandas=True)  # NaN → null

    def _eval_case(self, expr: ast.Case, table: pa.Table):
        """CASE with SQL's lazy-branch guarantee: each THEN/ELSE evaluates
        only over the rows its condition selects (``CASE WHEN b != 0 THEN
        a / b ...`` must not divide by zero on guarded rows), then results
        scatter back into row order."""
        import numpy as np

        n = len(table)
        remaining = np.ones(n, dtype=bool)
        parts: list[tuple[np.ndarray, pa.Table]] = []
        # simple CASE: the operand evaluates ONCE, each WHEN compares to it
        op_val = (
            _broadcast(self._eval_expr(expr.operand, table), n)
            if expr.operand is not None else None
        )
        for cond, value in expr.whens:
            if op_val is not None:
                raw = pc.equal(
                    op_val, _broadcast(self._eval_expr(cond, table), n)
                )
            else:
                raw = _broadcast(self._eval_bool(cond, table), n)
            mask = pc.fill_null(raw, False)
            m = np.asarray(mask) & remaining
            rows = np.nonzero(m)[0]
            if rows.size:
                sub = table.take(pa.array(rows))
                vals = _broadcast(self._eval_expr(value, sub), len(sub))
                parts.append((rows, pa.table({"v": vals})))
            remaining &= ~m
        rest = np.nonzero(remaining)[0]
        if rest.size:
            if expr.default is not None:
                sub = table.take(pa.array(rest))
                vals = _broadcast(self._eval_expr(expr.default, sub), len(sub))
            else:
                vals = pa.nulls(rest.size)
            parts.append((rest, pa.table({"v": vals})))
        if not parts:
            return pa.nulls(0)
        merged = pa.concat_tables(
            [p for _, p in parts], promote_options="permissive"
        ).column("v")
        order = np.concatenate([r for r, _ in parts])
        inverse = np.empty(n, dtype=np.int64)
        inverse[order] = np.arange(n, dtype=np.int64)
        return merged.take(pa.array(inverse))

    def _eval_bool(self, node, table: pa.Table):
        """Evaluate a boolean tree to an Arrow mask (Kleene semantics)."""
        if isinstance(node, ast.Compare):
            ops = {"eq": pc.equal, "ne": pc.not_equal, "lt": pc.less,
                   "le": pc.less_equal, "gt": pc.greater, "ge": pc.greater_equal}
            if node.simple:
                return ops[node.op](table.column(node.col), pa.scalar(node.value))
            return ops[node.op](
                self._eval_expr(node.left, table), self._eval_expr(node.right, table)
            )
        if isinstance(node, ast.InList):
            return pc.is_in(table.column(node.col), value_set=pa.array(node.values))
        if isinstance(node, ast.InSubquery):
            return self._eval_in_subquery(node, table)
        if isinstance(node, ast.Exists):
            return self._eval_exists(node, table)
        if isinstance(node, ast.Like):
            mask = pc.match_like(table.column(node.col), node.pattern)
            return pc.invert(mask) if node.negated else mask
        if isinstance(node, ast.Between):
            col = table.column(node.col)
            return pc.and_kleene(
                pc.greater_equal(col, pa.scalar(node.low)),
                pc.less_equal(col, pa.scalar(node.high)),
            )
        if isinstance(node, ast.IsNull):
            col = table.column(node.col)
            return col.is_valid() if node.negated else pc.is_null(col)
        if isinstance(node, ast.BoolOp):
            fold = pc.and_kleene if node.op == "and" else pc.or_kleene
            masks = [
                _broadcast(self._eval_bool(a, table), len(table)) for a in node.args
            ]
            out = masks[0]
            for m in masks[1:]:
                out = fold(out, m)
            return out
        if isinstance(node, ast.NotOp):
            return pc.invert(
                _broadcast(self._eval_bool(node.arg, table), len(table))
            )
        raise SqlError(f"unsupported predicate {node!r}")

    # ------------------------------------------------------------------- DML
    def _insert(self, stmt: ast.Insert) -> pa.Table:
        t = self.catalog.table(stmt.table, self.namespace)
        schema = t.schema
        if stmt.select is not None:
            src = self._query(stmt.select)
            names = stmt.columns or list(src.column_names)
            if len(names) != src.num_columns:
                raise SqlError(
                    f"INSERT column list has {len(names)} names but the"
                    f" SELECT produces {src.num_columns} columns"
                )
            cols = {}
            for i, name in enumerate(names):
                if name not in schema.names:
                    raise SqlError(f"unknown column {name!r} in INSERT target")
                cols[name] = src.column(i).cast(schema.field(name).type)
            t.write_arrow(
                pa.table(cols, schema=pa.schema([schema.field(n) for n in names]))
            )
            return pa.table({"inserted": pa.array([len(src)], type=pa.int64())})
        columns = stmt.columns or [f.name for f in schema]
        if any(len(r) != len(columns) for r in stmt.rows):
            raise SqlError("VALUES row arity does not match column list")
        data = {}
        for i, name in enumerate(columns):
            fld = schema.field(name)
            data[name] = pa.array([r[i] for r in stmt.rows], type=fld.type)
        t.write_arrow(pa.table(data, schema=pa.schema([schema.field(c) for c in columns])))
        return pa.table({"inserted": pa.array([len(stmt.rows)], type=pa.int64())})

    # ------------------------------------------------------------------- DDL
    def _create(self, stmt: ast.CreateTable) -> pa.Table:
        if stmt.if_not_exists and self.catalog.table_exists(stmt.table, self.namespace):
            return pa.table({"status": ["exists"]})
        fields = []
        pks = []
        for c in stmt.columns:
            if c.type_name not in _TYPE_MAP:
                raise SqlError(f"unknown type {c.type_name!r}")
            fields.append(pa.field(c.name, _TYPE_MAP[c.type_name]))
            if c.primary_key:
                pks.append(c.name)
        props = {str(k): str(v) for k, v in stmt.properties.items()}
        hash_bucket_num = props.pop("hashBucketNum", None)
        self.catalog.create_table(
            stmt.table,
            pa.schema(fields),
            primary_keys=pks or None,
            range_partitions=stmt.range_partitions or None,
            hash_bucket_num=int(hash_bucket_num) if hash_bucket_num else None,
            properties=props or None,
            namespace=self.namespace,
        )
        return pa.table({"status": ["ok"]})

    def _drop(self, stmt: ast.DropTable) -> pa.Table:
        if stmt.if_exists and not self.catalog.table_exists(stmt.table, self.namespace):
            return pa.table({"status": ["absent"]})
        self.catalog.drop_table(stmt.table, self.namespace)
        return pa.table({"status": ["ok"]})
