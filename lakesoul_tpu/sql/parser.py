"""Minimal SQL parser: tokenizer + recursive descent → dataclass AST.

Covers the statement surface the reference exposes through its embedded SQL
engines (rust/lakesoul-datafusion catalog/TableProvider + console):
SELECT (projection, WHERE, GROUP BY, ORDER BY, LIMIT, aggregates), INSERT
INTO … VALUES, CREATE TABLE (with PRIMARY KEY / PARTITIONED BY / WITH
properties), DROP TABLE, SHOW TABLES, DESCRIBE.  WHERE trees compile to the
framework's portable Filter AST so predicate pushdown works unchanged."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

from lakesoul_tpu.errors import LakeSoulError


class SqlError(LakeSoulError):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*|\.|\+|-|/)
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "order", "by", "limit", "as", "and",
    "or", "not", "in", "is", "null", "asc", "desc", "insert", "into",
    "values", "create", "table", "drop", "show", "tables", "describe",
    "primary", "key", "partitioned", "with", "if", "exists", "distinct",
    "count", "sum", "min", "max", "avg", "true", "false", "alter", "add",
    "column", "call", "update", "set", "delete", "join", "inner", "left", "on",
    "right", "full", "outer",
    "case", "when", "then", "else", "end", "having", "between", "like",
    "substring", "for", "union", "intersect", "except", "all", "over",
    "partition",
}

# window-only functions (idents, not keywords: usable as column names)
WINDOW_FUNCTIONS = ("row_number", "rank", "dense_rank", "lag", "lead")

# generic scalar functions parsed as ``name(arg, ...)`` (idents, not
# keywords — still usable as column names when not followed by "(")
# EXTRACT(part FROM expr) parts; each is also callable as a function of
# the same name (the executor owns the part → Arrow-kernel mapping)
EXTRACT_PARTS = ("year", "month", "day", "hour", "minute", "second")

SCALAR_FUNCTIONS = (
    "coalesce", "nullif", "abs", "round", "upper", "lower", "length",
    "trim", "ltrim", "rtrim", "replace", "concat",
) + EXTRACT_PARTS


@dataclass
class Token:
    kind: str  # number | string | op | ident | kw
    value: str


def tokenize(sql: str) -> list[Token]:
    tokens = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"cannot tokenize SQL at: {sql[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        value = m.group()
        if kind == "ident" and value.lower() in KEYWORDS:
            tokens.append(Token("kw", value.lower()))
        else:
            tokens.append(Token(kind, value))
    return tokens


# ----------------------------------------------------------------- AST nodes
@dataclass
class Column:
    name: str
    # table/alias qualifier as written (``o.col``); evaluation resolves by
    # bare name, correlated-subquery classification resolves scope by it
    qual: str | None = None


@dataclass
class Literal:
    value: Any


@dataclass
class Agg:
    fn: str  # count | sum | min | max | avg
    arg: object | None  # Column/Literal/Arith expression; None = count(*)
    alias: str | None = None
    distinct: bool = False  # count(DISTINCT x)


@dataclass
class Arith:
    op: str  # + - * /
    left: object
    right: object


@dataclass
class Case:
    """CASE WHEN cond THEN expr [...] [ELSE expr] END.

    Simple form (``CASE x WHEN v THEN r``): ``operand`` holds ``x`` and
    each when's first element is the comparison VALUE expression — the
    evaluator computes the operand once, not once per branch."""

    whens: list  # [(bool_node | value_expr, value_expr), ...]
    default: object | None = None
    operand: object | None = None


@dataclass
class Func:
    """Scalar function call (substring, ...)."""

    name: str
    args: list


@dataclass
class ScalarSubquery:
    """Uncorrelated (SELECT ...) used as a value."""

    select: "Select"


@dataclass
class WindowFn:
    """``fn OVER (PARTITION BY ... ORDER BY ...)``: fn is an Agg (sum/avg/
    min/max/count) or a Func for row_number/rank/dense_rank/lag/lead.
    Aggregates with an ORDER BY are running (RANGE semantics: peers share
    the value at the last peer row), without one they broadcast the whole-
    partition value — standard SQL defaults."""

    fn: object
    partition_by: list[str] = field(default_factory=list)
    order_by: list[tuple[str, bool]] = field(default_factory=list)  # (col, desc)


@dataclass
class SelectItem:
    expr: Column | Agg
    alias: str | None = None


@dataclass
class Compare:
    op: str
    col: str  # simple column name when the LHS is a bare column, else ""
    value: Any  # literal when the RHS is a literal, else None
    left: Any = None  # general expressions (col-col / arith comparisons)
    right: Any = None
    col_qual: str | None = None  # qualifier of `col` as written (o.total)

    @property
    def simple(self) -> bool:
        """Pushdown-eligible: bare column vs literal."""
        return bool(self.col) and self.left is None


@dataclass
class InList:
    col: str
    values: list
    col_qual: str | None = None


@dataclass
class InSubquery:
    col: str
    select: "Select"
    negated: bool = False
    col_qual: str | None = None


@dataclass
class Exists:
    select: "Select"
    negated: bool = False


@dataclass
class Like:
    col: str
    pattern: str
    negated: bool = False
    col_qual: str | None = None


@dataclass
class Between:
    col: str
    low: Any
    high: Any
    col_qual: str | None = None


@dataclass
class IsNull:
    col: str
    negated: bool
    col_qual: str | None = None


@dataclass
class BoolOp:
    op: str  # and | or
    args: list


@dataclass
class NotOp:
    arg: Any


@dataclass
class Join:
    table: str  # name, or "" when right is a derived table
    kind: str  # inner | left
    left_on: str
    right_on: str
    left_qual: str | None = None  # table qualifier as written (a.col)
    right_qual: str | None = None
    subquery: "Select | None" = None  # JOIN (SELECT ...) alias
    alias: str | None = None


@dataclass
class Select:
    items: list[SelectItem]
    star: bool
    table: str  # name, or "" when from_subquery is set
    from_subquery: "Select | None" = None  # FROM (SELECT ...) alias
    from_alias: str | None = None
    distinct: bool = False
    joins: list = field(default_factory=list)
    where: Any = None
    group_by: list[str] = field(default_factory=list)
    # GROUP BY <expr> entries: [(synthesized column name, expr AST)] — the
    # name also appears in group_by; the executor materializes the column
    # before aggregation and rewrites matching select items onto it
    group_exprs: list = field(default_factory=list)
    # ROLLUP/CUBE/GROUPING SETS: the list of grouping sets (each a subset of
    # group_by); None = plain GROUP BY (one set = group_by itself)
    grouping_sets: list | None = None
    # time travel: epoch ms of `FROM t TIMESTAMP AS OF ...` (Spark style) or
    # `FOR SYSTEM_TIME AS OF ...` (SQL:2011/Flink); None = latest snapshot
    as_of_ms: int | None = None
    having: Any = None
    order_by: list[tuple[str, bool]] = field(default_factory=list)  # (col, desc)
    # qualifiers as written for order_by/group_by entries (aligned by index;
    # may be shorter — ROLLUP/CUBE paths don't record them).  Needed so a
    # RIGHT/FULL join's suffixed right key can rebind `ORDER BY b.k`.
    order_by_quals: list = field(default_factory=list)
    group_by_quals: list = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None


@dataclass
class Explain:
    """EXPLAIN <statement>: plan description, nothing executed."""

    stmt: object


@dataclass
class SetOp:
    """UNION [ALL] / INTERSECT / EXCEPT over two selects (or nested set
    ops).  ORDER BY / LIMIT written after the chain bind to the whole."""

    op: str  # union | intersect | except
    left: "Select | SetOp"
    right: "Select | SetOp"
    all: bool = False
    order_by: list[tuple[str, bool]] = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None


@dataclass
class Insert:
    table: str
    columns: list[str]
    rows: list[list]
    select: "Select | None" = None  # INSERT INTO ... SELECT ...


@dataclass
class ColumnDef:
    name: str
    type_name: str
    primary_key: bool = False


@dataclass
class CreateTable:
    table: str
    columns: list[ColumnDef]
    range_partitions: list[str] = field(default_factory=list)
    properties: dict = field(default_factory=dict)
    if_not_exists: bool = False


@dataclass
class DropTable:
    table: str
    if_exists: bool = False


@dataclass
class ShowTables:
    pass


@dataclass
class Describe:
    table: str


@dataclass
class AlterAddColumn:
    table: str
    column: str
    type_name: str


@dataclass
class AlterSetProperties:
    table: str
    properties: dict


@dataclass
class Call:
    procedure: str  # compact | rollback | clean | build_vector_index
    args: list


@dataclass
class Update:
    table: str
    assignments: dict
    where: Any


@dataclass
class Delete:
    table: str
    where: Any


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.pos = 0

    # ------------------------------------------------------------- plumbing
    def peek(self) -> Token | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Token:
        tok = self.peek()
        if tok is None:
            raise SqlError("unexpected end of statement")
        self.pos += 1
        return tok

    def accept(self, kind: str, value: str | None = None) -> Token | None:
        tok = self.peek()
        if tok and tok.kind == kind and (value is None or tok.value == value):
            self.pos += 1
            return tok
        return None

    def expect(self, kind: str, value: str | None = None) -> Token:
        tok = self.accept(kind, value)
        if tok is None:
            have = self.peek()
            raise SqlError(f"expected {value or kind}, got {have.value if have else 'EOF'!r}")
        return tok

    def ident(self) -> str:
        tok = self.next()
        if tok.kind not in ("ident", "kw"):
            raise SqlError(f"expected identifier, got {tok.value!r}")
        return tok.value

    # ------------------------------------------------------------ statements
    def parse(self):
        tok = self.peek()
        if tok is None:
            raise SqlError("empty statement")
        if tok.kind == "ident" and tok.value.lower() == "explain":
            self.next()
            return Explain(self.parse())
        dispatch = {
            "select": self.parse_query,
            "with": self.parse_with,
            "insert": self.parse_insert,
            "create": self.parse_create,
            "drop": self.parse_drop,
            "show": self.parse_show,
            "describe": self.parse_describe,
            "alter": self.parse_alter,
            "call": self.parse_call,
            "update": self.parse_update,
            "delete": self.parse_delete,
        }
        if tok.kind != "kw" or tok.value not in dispatch:
            raise SqlError(f"unsupported statement start {tok.value!r}")
        stmt = dispatch[tok.value]()
        if self.peek() is not None and not self.accept("op", ";"):
            extra = self.peek()
            if extra is not None:
                raise SqlError(f"unexpected trailing token {extra.value!r}")
        return stmt

    def parse_with(self):
        """``WITH name AS (query), ... <query>``: non-recursive CTEs, inlined
        as derived tables (each reference becomes a from_subquery — the way
        lightweight planners lower WITH).  Earlier CTEs are visible to later
        ones and to the main query, including inside subqueries and joins."""
        self.expect("kw", "with")
        ctes: dict[str, object] = {}
        while True:
            name = self.ident()
            self.expect("kw", "as")
            self.expect("op", "(")
            body = self.parse_query()
            self.expect("op", ")")
            inline_ctes(body, ctes)
            ctes[name] = body
            if not self.accept("op", ","):
                break
        stmt = self.parse_query()
        inline_ctes(stmt, ctes)
        return stmt

    def parse_query(self):
        """One query: a SELECT, optionally chained with UNION [ALL] /
        INTERSECT / EXCEPT.  Standard precedence: INTERSECT binds tighter
        than UNION/EXCEPT; same-level operators are left-associative."""
        left = self._parse_intersect_chain()
        while True:
            tok = self.peek()
            if tok is None or tok.kind != "kw" or tok.value not in ("union", "except"):
                break
            op = self.next().value
            all_ = bool(self.accept("kw", "all"))
            right = self._parse_intersect_chain()
            left = SetOp(op, left, right, all_)
        return self._hoist_trailing_order(left)

    def _parse_intersect_chain(self):
        left = self.parse_select()
        while self.peek() is not None and self.peek().kind == "kw" \
                and self.peek().value == "intersect":
            self.next()
            all_ = bool(self.accept("kw", "all"))
            left = SetOp("intersect", left, self.parse_select(), all_)
        return left

    @staticmethod
    def _hoist_trailing_order(node):
        """ORDER BY / LIMIT written after a set-op chain were consumed by the
        rightmost SELECT's parse — per SQL they bind to the whole query."""
        if not isinstance(node, SetOp):
            return node
        rightmost = node
        while isinstance(rightmost.right, SetOp):
            rightmost = rightmost.right
        tail = rightmost.right
        if tail.order_by or tail.limit is not None or tail.offset is not None:
            node.order_by, node.limit = tail.order_by, tail.limit
            node.offset = tail.offset
            tail.order_by, tail.limit, tail.offset = [], None, None
            tail.order_by_quals = []
        return node

    def parse_select(self) -> Select:
        self.expect("kw", "select")
        distinct = bool(self.accept("kw", "distinct"))
        star = False
        items: list[SelectItem] = []
        if self.accept("op", "*"):
            star = True
        else:
            while True:
                items.append(self._select_item())
                if not self.accept("op", ","):
                    break
        sel = Select(items=items, star=star, table="", distinct=distinct)
        has_from = bool(self.accept("kw", "from"))
        if not has_from and star:
            # FROM-less SELECT (`SELECT 1`, `SELECT 1 LIMIT 1`) — the probe
            # statement ADBC/JDBC drivers open connections with; evaluates
            # the items over one anonymous row.  Trailing clauses (WHERE,
            # ORDER BY, LIMIT) parse the same as with a FROM.
            raise SqlError("SELECT * requires a FROM clause")
        if has_from and self.accept("op", "("):
            sel.from_subquery = self.parse_query()
            self.expect("op", ")")
            explicit_as = bool(self.accept("kw", "as"))
            if self.peek() is not None and self.peek().kind == "ident" \
                    and (explicit_as or self.peek().value.lower() != "offset"):
                # same soft-keyword rule as the base-table alias: a bare
                # OFFSET after the derived table starts the OFFSET clause
                sel.from_alias = self.ident()
        elif has_from:
            sel.table = self.ident()
            self._maybe_time_travel(sel)
            # optional table alias (FROM lineitem l) — ignored for resolution,
            # accepted so qualified queries parse.  "offset" stays a soft
            # keyword here: `FROM t OFFSET 1` must not read it as an alias.
            nxt = self.peek()
            if nxt is not None and nxt.kind == "ident" \
                    and nxt.value.lower() != "offset":
                sel.from_alias = self.ident()
        while has_from:
            kind = None
            if self.accept("kw", "inner"):
                kind = "inner"
                self.expect("kw", "join")
            elif self.accept("kw", "left"):
                kind = "left"
                self.accept("kw", "outer")
                self.expect("kw", "join")
            elif self.accept("kw", "right"):
                kind = "right"
                self.accept("kw", "outer")
                self.expect("kw", "join")
            elif self.accept("kw", "full"):
                kind = "full"
                self.accept("kw", "outer")
                self.expect("kw", "join")
            elif self.accept("kw", "join"):
                kind = "inner"
            else:
                break
            sub = None
            jt = ""
            alias = None
            if self.accept("op", "("):
                sub = self.parse_query()
                self.expect("op", ")")
                self.accept("kw", "as")
                alias = self.ident()
            else:
                jt = self.ident()
                nxt = self.peek()
                if nxt is not None and nxt.kind == "ident":
                    alias = self.ident()
            self.expect("kw", "on")
            # ON a.col = b.col  (qualified or bare column names)
            lq, left_on = self._qualified_ident()
            self.expect("op", "=")
            rq, right_on = self._qualified_ident()
            sel.joins.append(Join(jt, kind, left_on, right_on, lq, rq, sub, alias))
        if self.accept("kw", "where"):
            sel.where = self._bool_expr()
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            self._group_by_clause(sel)
        if self.accept("kw", "having"):
            sel.having = self._bool_expr()
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                qual, col = self._qualified_ident()
                desc = False
                if self.accept("kw", "desc"):
                    desc = True
                else:
                    self.accept("kw", "asc")
                sel.order_by.append((col, desc))
                sel.order_by_quals.append(qual)
                if not self.accept("op", ","):
                    break
        if self.accept("kw", "limit"):
            sel.limit = int(self.expect("number").value)
        # OFFSET is a soft ident (columns named offset keep working); it
        # composes with or without LIMIT, per standard SQL
        nxt = self.peek()
        if nxt is not None and nxt.kind == "ident" and nxt.value.lower() == "offset":
            self.next()
            sel.offset = int(self.expect("number").value)
        return sel

    def _maybe_time_travel(self, sel: Select) -> None:
        """``FROM t TIMESTAMP AS OF <ts>`` (Spark) or ``FROM t FOR
        SYSTEM_TIME AS OF <ts>`` (SQL:2011/Flink) → snapshot read at that
        instant via the scan's snapshot_at (the reference's Spark time-travel
        read, SnapshotManagement readEndTime).  <ts> is a TIMESTAMP literal,
        an ISO string, or an epoch-milliseconds number."""

        def _nth_is(n, kind, value=None):
            i = self.pos + n
            return i < len(self.tokens) and self.tokens[i].kind == kind and (
                value is None or self.tokens[i].value.lower() == value
            )

        if _nth_is(0, "kw", "for") and _nth_is(1, "ident", "system_time") \
                and _nth_is(2, "kw", "as") and _nth_is(3, "ident", "of"):
            self.next()
            self.next()
        elif _nth_is(0, "ident", "timestamp") and _nth_is(1, "kw", "as") \
                and _nth_is(2, "ident", "of"):
            self.next()
        else:
            return
        self.expect("kw", "as")
        self.next()  # 'of' (checked above)
        val = self._arith_factor()
        if not isinstance(val, Literal):
            raise SqlError("AS OF requires a literal timestamp")
        import datetime as _dt

        v = val.value
        if isinstance(v, str):
            try:
                v = _dt.datetime.fromisoformat(v)
            except ValueError as e:
                raise SqlError(f"invalid AS OF timestamp {val.value!r}: {e}")
        if isinstance(v, _dt.date) and not isinstance(v, _dt.datetime):
            v = _dt.datetime.combine(v, _dt.time())  # DATE '...' = midnight
        if isinstance(v, _dt.datetime):
            if v.tzinfo is None:
                # naive literals are UTC: commit timestamps are UTC epoch ms,
                # and .timestamp() on a naive datetime would bake in the
                # server host's local zone — same query, host-dependent
                # snapshot (ADVICE r2).  Explicit offsets still win.
                v = v.replace(tzinfo=_dt.timezone.utc)
            sel.as_of_ms = int(v.timestamp() * 1000)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            sel.as_of_ms = int(v)
        else:
            raise SqlError(f"invalid AS OF timestamp {val.value!r}")

    def _group_by_clause(self, sel: Select) -> None:
        """Plain column list, or ROLLUP(...) / CUBE(...) / GROUPING SETS
        ((...), ...).  The analytic forms expand to explicit grouping sets
        here, like DataFusion's planner; missing grouping columns surface as
        NULL in the subtotal rows.  The words are soft (idents) so columns
        named rollup/cube/grouping still work in plain GROUP BY."""
        tok = self.peek()
        word = tok.value.lower() if tok is not None and tok.kind == "ident" else None

        def _nth_is(n, kind, value=None):
            i = self.pos + n
            return i < len(self.tokens) and self.tokens[i].kind == kind and (
                value is None or self.tokens[i].value.lower() == value
            )

        if word in ("rollup", "cube") and _nth_is(1, "op", "("):
            self.next()
            self.expect("op", "(")
            cols = [self._qualified_ident()[1]]
            while self.accept("op", ","):
                cols.append(self._qualified_ident()[1])
            self.expect("op", ")")
            sel.group_by = cols
            if word == "rollup":
                sel.grouping_sets = [cols[:i] for i in range(len(cols), -1, -1)]
            else:
                from itertools import combinations

                sel.grouping_sets = [
                    list(c)
                    for r in range(len(cols), -1, -1)
                    for c in combinations(cols, r)
                ]
            return
        if word == "grouping" and _nth_is(1, "ident", "sets") and _nth_is(2, "op", "("):
            self.next()
            self.next()
            self.expect("op", "(")
            sets: list[list[str]] = []
            while True:
                if self.accept("op", "("):
                    s: list[str] = []
                    if not self.accept("op", ")"):
                        s.append(self._qualified_ident()[1])
                        while self.accept("op", ","):
                            s.append(self._qualified_ident()[1])
                        self.expect("op", ")")
                    sets.append(s)
                else:
                    sets.append([self._qualified_ident()[1]])
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
            seen: list[str] = []
            for s in sets:
                for c in s:
                    if c not in seen:
                        seen.append(c)
            sel.group_by = seen
            sel.grouping_sets = sets
            return
        self._group_by_entry(sel)
        while self.accept("op", ","):
            self._group_by_entry(sel)

    def _group_by_entry(self, sel: Select) -> None:
        """One plain GROUP BY entry: a bare column keeps its name; an
        integer literal is a select-item ORDINAL (GROUP BY 1, the
        Postgres/Spark convention); any other expression (upper(s),
        CASE ..., k / 10) gets a synthesized key column the executor
        materializes pre-aggregation."""
        expr = self._arith_expr()
        if isinstance(expr, Literal):
            if not isinstance(expr.value, int) or isinstance(expr.value, bool):
                raise SqlError(
                    "cannot GROUP BY a literal; use a column, an expression,"
                    " or a select-item ordinal"
                )
            if not 1 <= expr.value <= len(sel.items):
                raise SqlError(f"GROUP BY ordinal {expr.value} is out of range")
            expr = sel.items[expr.value - 1].expr
        if isinstance(expr, Column):
            sel.group_by.append(expr.name)
            sel.group_by_quals.append(expr.qual)
            return
        name = f"__grp_{len(sel.group_exprs)}"
        sel.group_exprs.append((name, expr))
        sel.group_by.append(name)
        sel.group_by_quals.append(None)

    def _qualified_ident(self) -> tuple[str | None, str]:
        """→ (qualifier or None, column)."""
        name = self.ident()
        if self.accept("op", "."):
            return name, self.ident()
        return None, name

    def _select_item(self) -> SelectItem:
        # aggregates are ordinary factors, so `sum(a) / sum(b)` parses whole
        expr = self._arith_expr()
        alias = self.ident() if self.accept("kw", "as") else None
        return SelectItem(expr, alias)

    def _maybe_agg(self) -> Agg | None:
        tok = self.peek()
        if not (tok and tok.kind == "kw" and tok.value in ("count", "sum", "min", "max", "avg")):
            return None
        fn = self.next().value
        self.expect("op", "(")
        distinct = bool(self.accept("kw", "distinct"))
        if self.accept("op", "*"):
            arg = None
            if fn != "count":
                raise SqlError(f"{fn}(*) not supported")
        else:
            arg = self._arith_expr()
        self.expect("op", ")")
        return Agg(fn, arg, distinct=distinct)

    # arithmetic value expressions: expr := term (±term)*; term := factor (*/factor)*
    @staticmethod
    def _fold(op: str, left, right):
        """Constant-fold literal arithmetic so negative numbers and literal
        math stay pushdown-eligible literals."""
        if isinstance(left, Literal) and isinstance(right, Literal):
            if isinstance(left.value, str) or isinstance(right.value, str):
                raise SqlError("arithmetic requires numeric operands")
            if left.value is None or right.value is None:
                return Literal(None)
            if op == "/":
                if right.value == 0:
                    raise SqlError("division by zero in literal expression")
                if isinstance(left.value, int) and isinstance(right.value, int):
                    # match the runtime's pc.divide: integer division
                    # truncating toward zero, not Python floor/true division
                    return Literal(int(left.value / right.value))
                return Literal(left.value / right.value)
            py = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                  "*": lambda a, b: a * b}[op]
            try:
                return Literal(py(left.value, right.value))
            except TypeError as e:  # e.g. DATE '...' + 1
                raise SqlError(f"invalid literal arithmetic: {e}")
        return Arith(op, left, right)

    def _arith_expr(self):
        left = self._arith_term()
        while True:
            if self.accept("op", "+"):
                left = self._fold("+", left, self._arith_term())
            elif self.accept("op", "-"):
                left = self._fold("-", left, self._arith_term())
            else:
                return left

    def _arith_term(self):
        left = self._arith_factor()
        while True:
            if self.accept("op", "*"):
                left = self._fold("*", left, self._arith_factor())
            elif self.accept("op", "/"):
                left = self._fold("/", left, self._arith_factor())
            else:
                return left

    def _arith_factor(self):
        if self.accept("op", "("):
            # (SELECT ...) scalar subquery or parenthesized expression
            nxt = self.peek()
            if nxt is not None and nxt.kind == "kw" and nxt.value == "select":
                sub = self.parse_query()
                self.expect("op", ")")
                return ScalarSubquery(sub)
            e = self._arith_expr()
            self.expect("op", ")")
            return e
        if self.accept("op", "-"):
            return self._fold("-", Literal(0), self._arith_factor())
        tok = self.peek()
        if tok is None:
            raise SqlError("unexpected end of statement in expression")
        if tok.kind == "kw" and tok.value == "case":
            return self._case_expr()
        if tok.kind == "kw" and tok.value == "substring":
            return self._substring_expr()
        agg = self._maybe_agg()
        if agg is not None:
            # OVER turns the aggregate into a window function
            if self.peek() is not None and self.peek().kind == "kw" \
                    and self.peek().value == "over":
                part, order = self._over_clause()
                return WindowFn(agg, part, order)
            return agg  # aggregates inside expressions (HAVING, agg arith)
        if tok.kind == "number" or tok.kind == "string" or (
            tok.kind == "kw" and tok.value in ("true", "false", "null")
        ):
            return Literal(self._value())
        if tok.kind == "ident" and tok.value.lower() in WINDOW_FUNCTIONS \
                and self.pos + 1 < len(self.tokens) \
                and self.tokens[self.pos + 1].kind == "op" \
                and self.tokens[self.pos + 1].value == "(":
            return self._window_call()
        if tok.kind == "ident" and tok.value.lower() in SCALAR_FUNCTIONS \
                and self.pos + 1 < len(self.tokens) \
                and self.tokens[self.pos + 1].kind == "op" \
                and self.tokens[self.pos + 1].value == "(":
            name = self.next().value.lower()
            self.expect("op", "(")
            args = [self._arith_expr()]
            while self.accept("op", ","):
                args.append(self._arith_expr())
            self.expect("op", ")")
            return Func(name, args)
        if tok.kind == "ident" and tok.value.lower() == "cast" \
                and self.pos + 1 < len(self.tokens) \
                and self.tokens[self.pos + 1].kind == "op" \
                and self.tokens[self.pos + 1].value == "(":
            # CAST(expr AS type) — the standard spelling every ADBC/BI
            # client emits; the type vocabulary is CREATE TABLE's, plus
            # parameterized forms (varchar(n) length is advisory-ignored,
            # decimal(p,s) maps to a real decimal type)
            self.next()
            self.expect("op", "(")
            e = self._arith_expr()
            self.expect("kw", "as")
            tname = self.ident().lower()
            params: list[int] = []
            if self.accept("op", "("):
                params.append(int(self.expect("number").value))
                while self.accept("op", ","):
                    params.append(int(self.expect("number").value))
                self.expect("op", ")")
            self.expect("op", ")")
            return Func("cast", [e, Literal((tname, tuple(params)))])
        if self._at_temporal_literal():
            # typed temporal literals: TIMESTAMP '2026-07-02 00:00:00',
            # DATE '2026-07-02' (standard SQL; DataFusion accepts the same)
            return Literal(self._temporal_literal())
        if tok.kind == "ident" and tok.value.lower() == "extract" \
                and self.pos + 1 < len(self.tokens) \
                and self.tokens[self.pos + 1].kind == "op" \
                and self.tokens[self.pos + 1].value == "(":
            # EXTRACT(part FROM expr) — the standard spelling; sugar for
            # the part-named scalar function
            self.next()
            self.expect("op", "(")
            part = self.ident().lower()
            if part not in EXTRACT_PARTS:
                raise SqlError(f"EXTRACT part {part!r} not supported")
            self.expect("kw", "from")
            e = self._arith_expr()
            self.expect("op", ")")
            return Func(part, [e])
        qual, name = self._qualified_ident()
        # the qualifier is kept for scope resolution (correlated subqueries
        # decide inner-vs-outer by it); plain evaluation ignores it — names
        # are unique within a working table
        return Column(name, qual=qual)

    def _window_call(self) -> WindowFn:
        name = self.next().value.lower()
        self.expect("op", "(")
        args: list = []
        if name in ("lag", "lead"):
            args.append(self._arith_expr())
            if self.accept("op", ","):
                off = self._value()
                args.append(Literal(int(off)))
                if self.accept("op", ","):
                    args.append(Literal(self._value()))
        self.expect("op", ")")
        part, order = self._over_clause()
        if not order and name != "row_number":
            raise SqlError(f"{name}() requires ORDER BY in its OVER clause")
        return WindowFn(Func(name, args), part, order)

    def _over_clause(self) -> tuple[list[str], list[tuple[str, bool]]]:
        self.expect("kw", "over")
        self.expect("op", "(")
        part: list[str] = []
        order: list[tuple[str, bool]] = []
        if self.accept("kw", "partition"):
            self.expect("kw", "by")
            part.append(self._qualified_ident()[1])
            while self.accept("op", ","):
                part.append(self._qualified_ident()[1])
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                col = self._qualified_ident()[1]
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                order.append((col, desc))
                if not self.accept("op", ","):
                    break
        self.expect("op", ")")
        return part, order

    def _case_expr(self) -> Case:
        self.expect("kw", "case")
        operand = None
        nxt = self.peek()
        if nxt is not None and not (nxt.kind == "kw" and nxt.value == "when"):
            # simple CASE (`CASE x WHEN v THEN r ...`): desugars to the
            # searched form with equality tests — a NULL operand matches
            # no WHEN (standard SQL equality semantics)
            operand = self._arith_expr()
        whens = []
        default = None
        while self.accept("kw", "when"):
            cond = self._arith_expr() if operand is not None else self._bool_expr()
            self.expect("kw", "then")
            whens.append((cond, self._arith_expr()))
        if self.accept("kw", "else"):
            default = self._arith_expr()
        self.expect("kw", "end")
        if not whens:
            raise SqlError("CASE requires at least one WHEN")
        return Case(whens, default, operand)

    def _substring_expr(self) -> Func:
        self.expect("kw", "substring")
        self.expect("op", "(")
        arg = self._arith_expr()
        # substring(x FROM a FOR b) or substring(x, a, b)
        if self.accept("kw", "from"):
            start = self._arith_expr()
            length = self._arith_expr() if self.accept("kw", "for") else None
        else:
            self.expect("op", ",")
            start = self._arith_expr()
            length = self._arith_expr() if self.accept("op", ",") else None
        self.expect("op", ")")
        return Func("substring", [arg, start, length])

    # ------------------------------------------------------------- where expr
    def _bool_expr(self):
        left = self._bool_term()
        while self.accept("kw", "or"):
            right = self._bool_term()
            if isinstance(left, BoolOp) and left.op == "or":
                left.args.append(right)
            else:
                left = BoolOp("or", [left, right])
        return left

    def _bool_term(self):
        left = self._bool_factor()
        while self.accept("kw", "and"):
            right = self._bool_factor()
            if isinstance(left, BoolOp) and left.op == "and":
                left.args.append(right)
            else:
                left = BoolOp("and", [left, right])
        return left

    def _bool_factor(self):
        if self.accept("kw", "not"):
            return NotOp(self._bool_factor())
        if self.accept("kw", "exists"):
            self.expect("op", "(")
            sub = self.parse_query()
            self.expect("op", ")")
            return Exists(sub)
        if self.peek() and self.peek().kind == "op" and self.peek().value == "(":
            # lookahead: "(bool expr)" vs a parenthesized arith LHS like
            # "(a + b) > c" — try bool first, rewind on failure
            mark = self.pos
            self.next()
            try:
                e = self._bool_expr()
                self.expect("op", ")")
                nxt = self.peek()
                # "(x)" followed by a comparison means x was an arith LHS
                if not (nxt and nxt.kind == "op" and nxt.value in ("<", "<=", ">", ">=", "=", "!=", "<>")):
                    return e
            except SqlError:
                pass
            self.pos = mark
        return self._predicate()

    _OP_MAP = {"=": "eq", "!=": "ne", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}

    def _predicate(self):
        left = self._arith_expr()
        simple_col = left.name if isinstance(left, Column) else None
        # the written qualifier rides along: correlated-subquery scope
        # resolution needs `o.total` to resolve OUTER even when the inner
        # scope has a same-named column (evaluation still uses bare names)
        simple_qual = left.qual if isinstance(left, Column) else None
        if simple_col is not None and self.accept("kw", "is"):
            negated = bool(self.accept("kw", "not"))
            self.expect("kw", "null")
            return IsNull(simple_col, negated, col_qual=simple_qual)
        if simple_col is not None and self.accept("kw", "between"):
            low = self._arith_expr()
            self.expect("kw", "and")
            high = self._arith_expr()
            if not (isinstance(low, Literal) and isinstance(high, Literal)):
                raise SqlError("BETWEEN bounds must be literals")
            return Between(simple_col, low.value, high.value, col_qual=simple_qual)
        if self.peek() and self.peek().kind == "kw" and self.peek().value == "not":
            self.next()
            if self.accept("kw", "like"):
                if simple_col is None:
                    raise SqlError("LIKE requires a plain column")
                return Like(simple_col, self._string_value(), negated=True,
                            col_qual=simple_qual)
            self.expect("kw", "in")
            node = self._in_tail(simple_col, simple_qual)
            if isinstance(node, InSubquery):
                node.negated = True
                return node
            return NotOp(node)
        if simple_col is not None and self.accept("kw", "like"):
            return Like(simple_col, self._string_value(), col_qual=simple_qual)
        if self.accept("kw", "in"):
            return self._in_tail(simple_col, simple_qual)
        op_tok = self.next()
        if op_tok.kind != "op" or op_tok.value not in self._OP_MAP:
            raise SqlError(f"expected comparison operator, got {op_tok.value!r}")
        op = self._OP_MAP[op_tok.value]
        right = self._arith_expr()
        if simple_col is not None and isinstance(right, Literal):
            return Compare(op, simple_col, right.value, col_qual=simple_qual)
        return Compare(op, "", None, left=left, right=right)

    def _in_tail(self, simple_col: str | None, simple_qual: str | None = None):
        """After IN: either a literal list or a subquery."""
        self.expect("op", "(")
        nxt = self.peek()
        if nxt is not None and nxt.kind == "kw" and nxt.value == "select":
            sub = self.parse_query()
            self.expect("op", ")")
            if simple_col is None:
                raise SqlError("IN (SELECT ...) requires a plain column")
            return InSubquery(simple_col, sub, col_qual=simple_qual)
        vals = [self._value()]
        while self.accept("op", ","):
            vals.append(self._value())
        self.expect("op", ")")
        if simple_col is None:
            raise SqlError("IN list requires a plain column")
        return InList(simple_col, vals, col_qual=simple_qual)

    def _string_value(self) -> str:
        v = self._value()
        if not isinstance(v, str):
            raise SqlError("LIKE pattern must be a string literal")
        return v

    def _at_temporal_literal(self) -> bool:
        nxt = self.peek()
        return (
            nxt is not None and nxt.kind == "ident"
            and nxt.value.lower() in ("timestamp", "date")
            and self.pos + 1 < len(self.tokens)
            and self.tokens[self.pos + 1].kind == "string"
        )

    def _temporal_literal(self):
        """``TIMESTAMP '...'`` / ``DATE '...'`` → datetime/date value — the
        ONE parser for typed temporal literals, shared by expressions and
        INSERT VALUES so the two paths cannot drift."""
        import datetime as _dt

        kind = self.next().value.lower()
        raw = self._value()
        try:
            if kind == "date":
                return _dt.date.fromisoformat(raw)
            return _dt.datetime.fromisoformat(raw)
        except ValueError as e:
            raise SqlError(f"invalid {kind.upper()} literal {raw!r}: {e}")

    def _value_list(self) -> list:
        self.expect("op", "(")
        vals = [self._value()]
        while self.accept("op", ","):
            vals.append(self._value())
        self.expect("op", ")")
        return vals

    def _value(self):
        if self.accept("op", "-"):
            v = self._value()
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SqlError("unary minus requires a numeric literal")
            return -v
        if self._at_temporal_literal():
            # typed temporal literals in VALUES, same as in expressions
            return self._temporal_literal()
        tok = self.next()
        if tok.kind == "number":
            return float(tok.value) if "." in tok.value else int(tok.value)
        if tok.kind == "string":
            return tok.value[1:-1].replace("''", "'")
        if tok.kind == "kw" and tok.value in ("true", "false"):
            return tok.value == "true"
        if tok.kind == "kw" and tok.value == "null":
            return None
        raise SqlError(f"expected literal, got {tok.value!r}")

    # ---------------------------------------------------------------- others
    def parse_insert(self) -> Insert:
        self.expect("kw", "insert")
        self.expect("kw", "into")
        table = self.ident()
        columns: list[str] = []
        if self.accept("op", "("):
            columns.append(self.ident())
            while self.accept("op", ","):
                columns.append(self.ident())
            self.expect("op", ")")
        nxt = self.peek()
        if nxt is not None and nxt.kind == "kw" and nxt.value == "select":
            return Insert(table, columns, [], select=self.parse_query())
        self.expect("kw", "values")
        rows = [self._value_list()]
        while self.accept("op", ","):
            rows.append(self._value_list())
        return Insert(table, columns, rows)

    def parse_create(self) -> CreateTable:
        self.expect("kw", "create")
        self.expect("kw", "table")
        if_not_exists = False
        if self.accept("kw", "if"):
            self.expect("kw", "not")
            self.expect("kw", "exists")
            if_not_exists = True
        table = self.ident()
        self.expect("op", "(")
        cols = []
        while True:
            name = self.ident()
            type_name = self.ident()
            pk = False
            if self.accept("kw", "primary"):
                self.expect("kw", "key")
                pk = True
            cols.append(ColumnDef(name, type_name.lower(), pk))
            if not self.accept("op", ","):
                break
        self.expect("op", ")")
        stmt = CreateTable(table, cols, if_not_exists=if_not_exists)
        if self.accept("kw", "partitioned"):
            self.expect("kw", "by")
            self.expect("op", "(")
            stmt.range_partitions.append(self.ident())
            while self.accept("op", ","):
                stmt.range_partitions.append(self.ident())
            self.expect("op", ")")
        if self.accept("kw", "with"):
            self.expect("op", "(")
            while True:
                key = self._value() if self.peek().kind == "string" else self.ident()
                self.expect("op", "=")
                stmt.properties[str(key)] = self._value()
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return stmt

    def parse_drop(self) -> DropTable:
        self.expect("kw", "drop")
        self.expect("kw", "table")
        if_exists = False
        if self.accept("kw", "if"):
            self.expect("kw", "exists")
            if_exists = True
        return DropTable(self.ident(), if_exists)

    def parse_alter(self):
        self.expect("kw", "alter")
        self.expect("kw", "table")
        table = self.ident()
        if self.accept("kw", "set"):
            # ALTER TABLE t SET ('k' = 'v', ...) — TBLPROPERTIES role
            self.expect("op", "(")
            props = {}
            while True:
                key = self._value() if self.peek().kind == "string" else self.ident()
                self.expect("op", "=")
                props[str(key)] = self._value()
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
            return AlterSetProperties(table, props)
        self.expect("kw", "add")
        self.expect("kw", "column")
        name = self.ident()
        type_name = self.ident()
        return AlterAddColumn(table, name, type_name.lower())

    def parse_call(self) -> Call:
        self.expect("kw", "call")
        proc = self.ident()
        args: list = []
        if self.accept("op", "("):
            if not self.accept("op", ")"):
                while True:
                    tok = self.peek()
                    if tok is None:
                        raise SqlError("unexpected end of statement in CALL arguments")
                    if tok.kind in ("number", "string") or (
                        tok.kind == "kw" and tok.value in ("true", "false", "null")
                    ):
                        args.append(self._value())
                    else:
                        args.append(self.ident())
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
        return Call(proc.lower(), args)

    def parse_update(self) -> Update:
        self.expect("kw", "update")
        table = self.ident()
        self.expect("kw", "set")
        assignments = {}
        while True:
            col = self.ident()
            self.expect("op", "=")
            # full value expressions (SET v = abs(v) + 1), not just literals
            assignments[col] = self._arith_expr()
            if not self.accept("op", ","):
                break
        self.expect("kw", "where")  # whole-table updates must be explicit
        return Update(table, assignments, self._bool_expr())

    def parse_delete(self) -> Delete:
        self.expect("kw", "delete")
        self.expect("kw", "from")
        table = self.ident()
        self.expect("kw", "where")  # whole-table deletes go through DROP/delete_partitions
        return Delete(table, self._bool_expr())

    def parse_show(self) -> ShowTables:
        self.expect("kw", "show")
        self.expect("kw", "tables")
        return ShowTables()

    def parse_describe(self) -> Describe:
        self.expect("kw", "describe")
        return Describe(self.ident())


def inline_ctes(node, ctes: dict, _seen: set | None = None) -> None:
    """Substitute CTE references throughout a query AST: any Select/Join
    whose source name matches a CTE becomes a derived table over the CTE
    body.  Walks every dataclass field (subqueries in WHERE/HAVING/items
    included); shared CTE bodies are visited once."""
    import dataclasses

    if not ctes or not dataclasses.is_dataclass(node) or isinstance(node, Token):
        return
    seen = _seen if _seen is not None else set()
    if id(node) in seen:
        return
    seen.add(id(node))
    if isinstance(node, Select) and node.from_subquery is None and node.table in ctes:
        node.from_subquery = ctes[node.table]
        node.from_alias = node.from_alias or node.table
        node.table = ""
    if isinstance(node, Join) and node.subquery is None and node.table in ctes:
        node.subquery = ctes[node.table]
        node.alias = node.alias or node.table
        node.table = ""
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        for item in (v if isinstance(v, list) else [v]):
            if dataclasses.is_dataclass(item) and not isinstance(item, Token):
                inline_ctes(item, ctes, seen)


def referenced_tables(stmt) -> set[str]:
    """Every catalog table name a parsed statement reads or writes —
    primary FROM tables, JOINed tables, and tables inside derived tables,
    EXISTS / IN / scalar subqueries, set operations, INSERT ... SELECT,
    EXPLAIN bodies, and maintenance CALLs, recursively.

    This is the per-statement RBAC surface: a gateway must check ALL of
    these, not just the primary FROM table, or ``SELECT ... FROM allowed
    JOIN secret`` reads ``secret`` unchecked.  CREATE TABLE targets are
    excluded (the table does not exist yet); CTE names never appear (they
    are inlined into derived tables at parse time); derived tables carry
    ``table == ""``."""
    import dataclasses

    out: set[str] = set()
    seen: set[int] = set()

    def walk(node) -> None:
        if node is None or isinstance(node, (str, bytes, int, float, bool)):
            return
        if isinstance(node, (list, tuple, set, frozenset)):
            for item in node:
                walk(item)
            return
        if isinstance(node, dict):
            for item in node.values():
                walk(item)
            return
        if not dataclasses.is_dataclass(node) or isinstance(node, Token):
            return
        if id(node) in seen:
            return
        seen.add(id(node))
        if isinstance(node, CreateTable):
            return
        if isinstance(node, Call):
            # compact/rollback/build_vector_index address a table by name in
            # their first argument; clean is warehouse-wide and so has NO
            # per-table surface — gateways must gate it explicitly
            # (LakeSoulFlightServer._check_statement), an empty set here is
            # NOT a grant
            if node.procedure in ("compact", "rollback", "build_vector_index") \
                    and node.args:
                out.add(str(node.args[0]))
            return
        target = getattr(node, "table", None)
        if isinstance(target, str) and target:
            out.add(target)
        for f in dataclasses.fields(node):
            walk(getattr(node, f.name))

    walk(stmt)
    return out


def parse(sql: str):
    return Parser(sql).parse()


def parse_predicate(text: str):
    """Parse a bare WHERE-style boolean expression (``"f > 100 AND id IN
    (1, 2)"``) into the pushdown Filter AST — the string form of
    ``LakeSoulScan.filter``.  Only pushdown-eligible predicates are accepted
    (simple comparisons, IN, BETWEEN, IS NULL, AND/OR/NOT); anything needing
    the general SQL evaluator must go through ``SqlSession``."""
    from lakesoul_tpu.sql.executor import _where_to_filter

    p = Parser(text)
    node = p._bool_expr()
    tok = p.peek()
    if tok is not None:
        raise SqlError(f"trailing input in predicate: {tok.value!r}")
    return _where_to_filter(node)
