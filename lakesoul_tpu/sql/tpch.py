"""TPC-H-lite harness: all 22 query shapes over the 8-table schema.

The reference ships a TPC-H module as a harness (schemas + queries, no
committed numbers — rust/lakesoul-datafusion/src/tpch/, tests/benchmarks/
tpch/).  This is the same idea sized to this framework's SQL dialect: a
scaled generator for all eight TPC-H tables and adaptations of Q1–Q22 that
keep each query's *shape* (joins, grouping, expression aggregates, CASE,
HAVING, sub-queries) while mapping constructs the dialect does not have:

- dates are ISO strings (lexicographic order == date order; EXTRACT(year)
  becomes ``substring(col, 1, 4)``)
- correlated sub-queries run NATIVELY (Q2/Q4/Q17/Q20/Q21/Q22 keep their
  real correlated shapes; the executor decorrelates them mechanically to
  hash semi-joins / grouped left joins, with alias qualifiers resolving
  self-correlation like Q21's ``l2.l_suppkey <> l1.l_suppkey``)
- partsupp's composite key joins through a synthetic ``ps_key``
  (partkey * 1e6 + suppkey) mirrored on lineitem
- multi-role dimension joins (Q7/Q8's two nations) use column-renaming
  derived tables

Every query is result-checked against an independent pandas implementation
(``verify(name)`` / tests/test_tpch.py), matching the reference's
"correctness harness, not committed numbers" stance.

    t = TpchLite(catalog, scale_rows=20_000)
    t.generate()
    seconds, table = t.run("q01")
    assert t.verify("q01")
"""

from __future__ import annotations

import time

import numpy as np
import pyarrow as pa

from lakesoul_tpu.sql import SqlSession

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
NATIONS = ["FRANCE", "GERMANY", "KENYA", "PERU", "JAPAN", "CANADA", "BRAZIL", "INDIA"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE"]
NATION_REGION = [3, 3, 0, 1, 2, 1, 1, 2]
TYPES = ["PROMO STEEL", "PROMO BRASS", "ECONOMY STEEL", "STANDARD BRASS", "SMALL COPPER"]
BRANDS = ["Brand#11", "Brand#22", "Brand#33", "Brand#44"]
CONTAINERS = ["SM CASE", "MED BOX", "LG JAR", "WRAP BAG"]
MODES = ["MAIL", "SHIP", "AIR", "TRUCK", "RAIL"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]

QUERIES = {
    # Q1 pricing summary report: expression aggregates over a date filter
    "q01": (
        "SELECT returnflag, linestatus, sum(quantity) AS sum_qty,"
        " sum(extendedprice) AS sum_base,"
        " sum(extendedprice * (1 - discount)) AS sum_disc,"
        " sum(extendedprice * (1 - discount) * (1 + tax)) AS sum_charge,"
        " avg(quantity) AS avg_qty, avg(extendedprice) AS avg_price,"
        " avg(discount) AS avg_disc, count(*) AS count_order"
        " FROM lineitem WHERE shipdate <= '1998-09-02'"
        " GROUP BY returnflag, linestatus ORDER BY returnflag, linestatus"
    ),
    "q02": (
        # native correlated scalar subquery (min cost per part), the real
        # Q2 shape — decorrelated automatically by the executor
        "SELECT s_acctbal, s_name, n_name, ps_partkey, ps_supplycost"
        " FROM partsupp ps0"
        " JOIN supplier ON ps_suppkey = suppkey"
        " JOIN nation ON s_nationkey = nationkey"
        " JOIN region ON n_regionkey = regionkey"
        " WHERE r_name = 'EUROPE' AND ps_supplycost ="
        " (SELECT min(ps_supplycost) FROM partsupp p2"
        "  WHERE p2.ps_partkey = ps0.ps_partkey)"
        " ORDER BY s_acctbal DESC, n_name, s_name, ps_partkey LIMIT 100"
    ),
    # Q3 shipping priority: 3-way join, grouped revenue
    "q03": (
        "SELECT orderkey, sum(extendedprice * (1 - discount)) AS revenue,"
        " orderdate, o_shippriority"
        " FROM lineitem"
        " JOIN orders ON lineitem.orderkey = orders.orderkey"
        " JOIN customer ON orders.custkey = customer.custkey"
        " WHERE mktsegment = 'BUILDING' AND orderdate < '1995-03-15'"
        " AND shipdate > '1995-03-15'"
        " GROUP BY orderkey, orderdate, o_shippriority"
        " ORDER BY revenue DESC, orderdate LIMIT 10"
    ),
    # Q4 order priority checking — native correlated EXISTS (the real Q4
    # shape; the executor decorrelates it to a hash semi-join)
    "q04": (
        "SELECT o_priority, count(*) AS order_count FROM orders"
        " WHERE orderdate >= '1993-07-01' AND orderdate < '1993-10-01'"
        " AND EXISTS (SELECT * FROM lineitem"
        "             WHERE lineitem.orderkey = orders.orderkey"
        "             AND commitdate < receiptdate)"
        " GROUP BY o_priority ORDER BY o_priority"
    ),
    # Q5 local supplier volume: 6-way join + col-col residual predicate
    "q05": (
        "SELECT n_name, sum(extendedprice * (1 - discount)) AS revenue"
        " FROM lineitem"
        " JOIN orders ON lineitem.orderkey = orders.orderkey"
        " JOIN customer ON orders.custkey = customer.custkey"
        " JOIN supplier ON lineitem.l_suppkey = supplier.suppkey"
        " JOIN nation ON s_nationkey = nationkey"
        " JOIN region ON n_regionkey = regionkey"
        " WHERE r_name = 'ASIA' AND orderdate >= '1994-01-01'"
        " AND orderdate < '1995-01-01' AND c_nationkey = s_nationkey"
        " GROUP BY n_name ORDER BY revenue DESC"
    ),
    # Q6 forecast revenue change: pure filtered aggregate with BETWEEN
    "q06": (
        "SELECT sum(extendedprice * discount) AS revenue FROM lineitem"
        " WHERE shipdate >= '1994-01-01' AND shipdate < '1995-01-01'"
        " AND discount BETWEEN 0.05 AND 0.07 AND quantity < 24"
    ),
    # Q7 volume shipping: two nation roles via renaming derived tables,
    # year via substring
    "q07": (
        "SELECT supp_nation, cust_nation, l_year,"
        " sum(extendedprice * (1 - discount)) AS revenue"
        " FROM (SELECT orderkey AS lo_key, l_suppkey, extendedprice, discount,"
        "              substring(shipdate, 1, 4) AS l_year, shipdate FROM lineitem) l"
        " JOIN orders ON lo_key = orderkey"
        " JOIN customer ON orders.custkey = customer.custkey"
        " JOIN supplier ON l_suppkey = suppkey"
        " JOIN (SELECT nationkey AS s_nkey, n_name AS supp_nation FROM nation) sn"
        " ON s_nationkey = s_nkey"
        " JOIN (SELECT nationkey AS c_nkey, n_name AS cust_nation FROM nation) cn"
        " ON c_nationkey = c_nkey"
        " WHERE shipdate >= '1995-01-01' AND shipdate <= '1996-12-31'"
        " AND supp_nation = 'FRANCE' AND cust_nation = 'GERMANY'"
        " GROUP BY supp_nation, cust_nation, l_year"
        " ORDER BY supp_nation, cust_nation, l_year"
    ),
    # Q8 national market share: CASE-sum ratio, year substring
    "q08": (
        "SELECT o_year, sum(CASE WHEN supp_nation = 'BRAZIL' THEN volume"
        " ELSE 0 END) / sum(volume) AS mkt_share"
        " FROM (SELECT orderkey AS lo_key, l_suppkey, l_partkey,"
        "              extendedprice * (1 - discount) AS volume FROM lineitem) l"
        " JOIN (SELECT orderkey AS ok2, orderdate,"
        "              substring(orderdate, 1, 4) AS o_year FROM orders) o2"
        " ON lo_key = ok2"
        " JOIN part ON l_partkey = partkey"
        " JOIN supplier ON l_suppkey = suppkey"
        " JOIN (SELECT nationkey AS s_nkey, n_name AS supp_nation FROM nation) sn"
        " ON s_nationkey = s_nkey"
        " WHERE p_type = 'ECONOMY STEEL'"
        " AND orderdate >= '1995-01-01' AND orderdate <= '1996-12-31'"
        " GROUP BY o_year ORDER BY o_year"
    ),
    # Q9 product type profit: partsupp composite key via ps_key, LIKE filter
    "q09": (
        "SELECT n_name, o_year, sum(gross - ps_supplycost * quantity) AS sum_profit"
        " FROM (SELECT l_ps_key, l_suppkey, orderkey AS lo_key, l_partkey,"
        "       extendedprice * (1 - discount) AS gross, quantity FROM lineitem) l"
        " JOIN partsupp ON l_ps_key = ps_key"
        " JOIN part ON l_partkey = partkey"
        " JOIN supplier ON l_suppkey = suppkey"
        " JOIN nation ON s_nationkey = nationkey"
        " JOIN (SELECT orderkey AS ok2, substring(orderdate, 1, 4) AS o_year"
        "       FROM orders) o2 ON lo_key = ok2"
        " WHERE p_name LIKE 'PROMO%'"
        " GROUP BY n_name, o_year ORDER BY n_name, o_year DESC"
    ),
    # Q10 returned item reporting
    "q10": (
        "SELECT customer.custkey, c_name,"
        " sum(extendedprice * (1 - discount)) AS revenue, c_acctbal, n_name"
        " FROM lineitem"
        " JOIN orders ON lineitem.orderkey = orders.orderkey"
        " JOIN customer ON orders.custkey = customer.custkey"
        " JOIN nation ON c_nationkey = nationkey"
        " WHERE returnflag = 'R' AND orderdate >= '1993-10-01'"
        " AND orderdate < '1994-01-01'"
        " GROUP BY custkey, c_name, c_acctbal, n_name"
        " ORDER BY revenue DESC LIMIT 20"
    ),
    # Q11 important stock: HAVING against a scalar subquery
    "q11": (
        "SELECT ps_partkey, sum(ps_supplycost * ps_availqty) AS value"
        " FROM partsupp"
        " JOIN supplier ON ps_suppkey = suppkey"
        " JOIN nation ON s_nationkey = nationkey"
        " WHERE n_name = 'GERMANY'"
        " GROUP BY ps_partkey"
        " HAVING sum(ps_supplycost * ps_availqty) >"
        " (SELECT sum(ps_supplycost * ps_availqty) * 0.01 FROM partsupp"
        "  JOIN supplier ON ps_suppkey = suppkey"
        "  JOIN nation ON s_nationkey = nationkey WHERE n_name = 'GERMANY')"
        " ORDER BY value DESC"
    ),
    # Q12 shipping modes: CASE-sums over a two-mode filter
    "q12": (
        "SELECT shipmode,"
        " sum(CASE WHEN o_priority = '1-URGENT' OR o_priority = '2-HIGH'"
        "     THEN 1 ELSE 0 END) AS high_line_count,"
        " sum(CASE WHEN o_priority <> '1-URGENT' AND o_priority <> '2-HIGH'"
        "     THEN 1 ELSE 0 END) AS low_line_count"
        " FROM lineitem JOIN orders ON lineitem.orderkey = orders.orderkey"
        " WHERE shipmode IN ('MAIL', 'SHIP') AND commitdate < receiptdate"
        " AND shipdate < commitdate AND receiptdate >= '1994-01-01'"
        " AND receiptdate < '1995-01-01'"
        " GROUP BY shipmode ORDER BY shipmode"
    ),
    # Q13 customer order-count distribution: LEFT JOIN + nested grouping
    "q13": (
        "SELECT c_count, count(*) AS custdist FROM"
        " (SELECT customer.custkey, count(orderkey) AS c_count"
        "  FROM customer LEFT JOIN orders ON customer.custkey = orders.custkey"
        "  GROUP BY custkey) c_orders"
        " GROUP BY c_count ORDER BY custdist DESC, c_count DESC"
    ),
    # Q14 promotion effect: CASE-LIKE ratio
    "q14": (
        "SELECT 100.0 * sum(CASE WHEN p_type LIKE 'PROMO%'"
        " THEN extendedprice * (1 - discount) ELSE 0 END)"
        " / sum(extendedprice * (1 - discount)) AS promo_revenue"
        " FROM lineitem JOIN part ON l_partkey = partkey"
        " WHERE shipdate >= '1995-09-01' AND shipdate < '1995-10-01'"
    ),
    # Q15 top supplier: derived revenue view + scalar-subquery equality
    "q15": (
        "SELECT suppkey, s_name, total_revenue FROM supplier"
        " JOIN (SELECT l_suppkey AS rk,"
        "       sum(extendedprice * (1 - discount)) AS total_revenue"
        "       FROM lineitem WHERE shipdate >= '1996-01-01'"
        "       AND shipdate < '1996-04-01' GROUP BY l_suppkey) revenue"
        " ON suppkey = rk"
        " WHERE total_revenue ="
        " (SELECT max(total_revenue) FROM"
        "  (SELECT l_suppkey, sum(extendedprice * (1 - discount)) AS total_revenue"
        "   FROM lineitem WHERE shipdate >= '1996-01-01'"
        "   AND shipdate < '1996-04-01' GROUP BY l_suppkey) r2)"
        " ORDER BY suppkey"
    ),
    # Q16 parts/supplier relationship: count(distinct) + NOT IN subquery
    "q16": (
        "SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS supplier_cnt"
        " FROM partsupp JOIN part ON ps_partkey = partkey"
        " WHERE p_brand <> 'Brand#11' AND p_type NOT LIKE 'PROMO%'"
        " AND p_size IN (1, 2, 3, 4, 5)"
        " AND ps_suppkey NOT IN (SELECT suppkey FROM supplier WHERE s_acctbal < 0)"
        " GROUP BY p_brand, p_type, p_size"
        " ORDER BY supplier_cnt DESC, p_brand, p_type, p_size"
    ),
    # Q17 small-quantity-order revenue — native correlated scalar avg (the
    # real Q17 shape; decorrelated to GROUP BY + left join automatically)
    "q17": (
        "SELECT sum(extendedprice) / 7.0 AS avg_yearly FROM lineitem"
        " JOIN part ON l_partkey = partkey"
        " WHERE p_brand = 'Brand#22' AND p_container = 'MED BOX'"
        " AND quantity < (SELECT 0.5 * avg(quantity) FROM lineitem l2"
        "                 WHERE l2.l_partkey = part.partkey)"
    ),
    # Q18 large-volume customers: IN over a HAVING subquery
    "q18": (
        "SELECT c_name, customer.custkey, orders.orderkey, orderdate, totalprice,"
        " sum(quantity) AS total_qty"
        " FROM lineitem"
        " JOIN orders ON lineitem.orderkey = orders.orderkey"
        " JOIN customer ON orders.custkey = customer.custkey"
        " WHERE orders.orderkey IN"
        " (SELECT orderkey FROM lineitem GROUP BY orderkey"
        "  HAVING sum(quantity) > 120)"
        " GROUP BY c_name, custkey, orderkey, orderdate, totalprice"
        " ORDER BY totalprice DESC, orderdate LIMIT 100"
    ),
    # Q19 discounted revenue: OR of AND-groups (fully pushable predicate)
    "q19": (
        "SELECT sum(extendedprice * (1 - discount)) AS revenue"
        " FROM lineitem JOIN part ON l_partkey = partkey"
        " WHERE (p_brand = 'Brand#11' AND p_container = 'SM CASE'"
        "        AND quantity BETWEEN 1 AND 11 AND p_size BETWEEN 1 AND 5)"
        " OR (p_brand = 'Brand#22' AND p_container = 'MED BOX'"
        "     AND quantity BETWEEN 10 AND 20 AND p_size BETWEEN 1 AND 10)"
        " OR (p_brand = 'Brand#33' AND p_container = 'LG JAR'"
        "     AND quantity BETWEEN 20 AND 30 AND p_size BETWEEN 1 AND 15)"
    ),
    # Q20 potential part promotion — the real nested shape: IN over a
    # subquery whose availqty threshold is a CORRELATED scalar sum over
    # lineitem (correlates to the middle partsupp scope)
    "q20": (
        "SELECT s_name FROM supplier"
        " JOIN nation ON s_nationkey = nationkey"
        " WHERE n_name = 'CANADA' AND suppkey IN"
        " (SELECT ps_suppkey FROM partsupp"
        "  WHERE ps_partkey IN (SELECT partkey FROM part WHERE p_name LIKE 'PROMO%')"
        "  AND ps_availqty > (SELECT 0.5 * sum(quantity) FROM lineitem"
        "                     WHERE l_partkey = ps_partkey"
        "                     AND l_suppkey = ps_suppkey))"
        " ORDER BY s_name"
    ),
    # Q21 suppliers who kept orders waiting — the REAL self-correlated
    # shape: alias qualifiers (l1/l2/l3) resolve the same-named columns
    # across scopes; the executor decorrelates both EXISTS legs to
    # semi-joins with the <> predicate evaluated on the joined pairs
    "q21": (
        "SELECT s_name, count(*) AS numwait FROM lineitem l1"
        " JOIN supplier ON l1.l_suppkey = suppkey"
        " JOIN orders ON l1.orderkey = orders.orderkey"
        " JOIN nation ON s_nationkey = nationkey"
        " WHERE o_status = 'F' AND receiptdate > commitdate"
        " AND n_name = 'KENYA'"
        " AND EXISTS (SELECT * FROM lineitem l2 WHERE l2.orderkey = l1.orderkey"
        "             AND l2.l_suppkey <> l1.l_suppkey)"
        " AND NOT EXISTS (SELECT * FROM lineitem l3"
        "                 WHERE l3.orderkey = l1.orderkey"
        "                 AND l3.l_suppkey <> l1.l_suppkey"
        "                 AND l3.receiptdate > l3.commitdate)"
        " GROUP BY s_name ORDER BY numwait DESC, s_name LIMIT 100"
    ),
    # Q22 global sales opportunity: substring country codes, scalar-subquery
    # threshold, and the real correlated NOT EXISTS anti-join
    "q22": (
        "SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal FROM"
        " (SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal, custkey"
        "  FROM customer) c"
        " WHERE cntrycode IN ('13', '31', '23', '29', '30')"
        " AND c_acctbal > (SELECT avg(c_acctbal) FROM customer"
        "                  WHERE c_acctbal > 0.0)"
        " AND NOT EXISTS (SELECT * FROM orders WHERE orders.custkey = c.custkey)"
        " GROUP BY cntrycode ORDER BY cntrycode"
    ),
}


class TpchLite:
    def __init__(self, catalog, *, scale_rows: int = 20_000, seed: int = 0):
        self.catalog = catalog
        self.sql = SqlSession(catalog)
        self.scale_rows = scale_rows
        self.seed = seed
        self._frames: dict[str, "object"] = {}

    # --------------------------------------------------------------- schema
    def generate(self) -> None:
        rng = np.random.default_rng(self.seed)
        n_li = self.scale_rows
        n_ord = max(4, n_li // 4)
        n_cust = max(4, n_ord // 10)
        n_part = max(4, n_li // 20)
        n_supp = max(4, n_li // 100)
        n_nation = len(NATIONS)

        ddl = [
            "CREATE TABLE IF NOT EXISTS region (regionkey bigint PRIMARY KEY,"
            " r_name string)",
            "CREATE TABLE IF NOT EXISTS nation (nationkey bigint PRIMARY KEY,"
            " n_name string, n_regionkey bigint)",
            "CREATE TABLE IF NOT EXISTS supplier (suppkey bigint PRIMARY KEY,"
            " s_name string, s_nationkey bigint, s_acctbal double)",
            "CREATE TABLE IF NOT EXISTS customer (custkey bigint PRIMARY KEY,"
            " c_name string, c_nationkey bigint, c_acctbal double,"
            " mktsegment string, c_phone string)",
            "CREATE TABLE IF NOT EXISTS part (partkey bigint PRIMARY KEY,"
            " p_name string, p_brand string, p_type string, p_size int,"
            " p_container string, p_retailprice double)",
            "CREATE TABLE IF NOT EXISTS partsupp (ps_key bigint PRIMARY KEY,"
            " ps_partkey bigint, ps_suppkey bigint, ps_availqty int,"
            " ps_supplycost double) WITH (hashBucketNum = '2')",
            "CREATE TABLE IF NOT EXISTS orders (orderkey bigint PRIMARY KEY,"
            " custkey bigint, o_status string, totalprice double,"
            " orderdate string, o_priority string, o_shippriority int)"
            " WITH (hashBucketNum = '4')",
            "CREATE TABLE IF NOT EXISTS lineitem (linekey bigint PRIMARY KEY,"
            " orderkey bigint, l_partkey bigint, l_suppkey bigint,"
            " l_ps_key bigint, quantity double, extendedprice double,"
            " discount double, tax double, returnflag string,"
            " linestatus string, shipdate string, commitdate string,"
            " receiptdate string, shipmode string)"
            " WITH (hashBucketNum = '4')",
        ]
        for stmt in ddl:
            self.sql.execute(stmt)

        def dates(base: str, spread: int, n: int):
            return (np.datetime64(base) + rng.integers(0, spread, n)).astype(str)

        region = pa.table(
            {"regionkey": np.arange(4, dtype=np.int64), "r_name": REGIONS}
        )
        nation = pa.table(
            {
                "nationkey": np.arange(n_nation, dtype=np.int64),
                "n_name": NATIONS,
                "n_regionkey": np.array(NATION_REGION, dtype=np.int64),
            }
        )
        supplier = pa.table(
            {
                "suppkey": np.arange(n_supp, dtype=np.int64),
                "s_name": [f"Supplier#{i:05d}" for i in range(n_supp)],
                "s_nationkey": rng.integers(0, n_nation, n_supp).astype(np.int64),
                "s_acctbal": (rng.random(n_supp) * 12_000 - 1_000).round(2),
            }
        )
        customer = pa.table(
            {
                "custkey": np.arange(n_cust, dtype=np.int64),
                "c_name": [f"Customer#{i:06d}" for i in range(n_cust)],
                "c_nationkey": rng.integers(0, n_nation, n_cust).astype(np.int64),
                "c_acctbal": (rng.random(n_cust) * 10_000 - 1_000).round(2),
                "mktsegment": rng.choice(SEGMENTS, n_cust),
                "c_phone": [
                    f"{rng.integers(10, 35)}-{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
                    for _ in range(n_cust)
                ],
            }
        )
        part = pa.table(
            {
                "partkey": np.arange(n_part, dtype=np.int64),
                "p_name": rng.choice(
                    ["PROMO tin", "PROMO lace", "LARGE plated", "SMALL brushed"], n_part
                ),
                "p_brand": rng.choice(BRANDS, n_part),
                "p_type": rng.choice(TYPES, n_part),
                "p_size": rng.integers(1, 21, n_part).astype(np.int32),
                "p_container": rng.choice(CONTAINERS, n_part),
                "p_retailprice": (rng.random(n_part) * 2_000).round(2),
            }
        )
        ps_part = rng.integers(0, n_part, n_li // 5 + 4).astype(np.int64)
        ps_supp = rng.integers(0, n_supp, n_li // 5 + 4).astype(np.int64)
        ps_key = ps_part * 1_000_000 + ps_supp
        _, uniq_idx = np.unique(ps_key, return_index=True)
        partsupp = pa.table(
            {
                "ps_key": ps_key[uniq_idx],
                "ps_partkey": ps_part[uniq_idx],
                "ps_suppkey": ps_supp[uniq_idx],
                "ps_availqty": rng.integers(1, 10_000, len(uniq_idx)).astype(np.int32),
                "ps_supplycost": (rng.random(len(uniq_idx)) * 1_000).round(2),
            }
        )
        orders = pa.table(
            {
                "orderkey": np.arange(n_ord, dtype=np.int64),
                "custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
                "o_status": rng.choice(["O", "F", "P"], n_ord),
                "totalprice": (rng.random(n_ord) * 100_000).round(2),
                "orderdate": dates("1992-01-01", 2500, n_ord),
                "o_priority": rng.choice(PRIORITIES, n_ord),
                "o_shippriority": np.zeros(n_ord, dtype=np.int32),
            }
        )
        # lineitem draws its partsupp pair from existing partsupp rows so the
        # synthetic ps_key join always matches
        pick = rng.integers(0, len(partsupp), n_li)
        l_part = partsupp.column("ps_partkey").to_numpy()[pick]
        l_supp = partsupp.column("ps_suppkey").to_numpy()[pick]
        ship = np.datetime64("1992-01-02") + rng.integers(0, 2500, n_li)
        commit = ship + rng.integers(-30, 60, n_li)
        receipt = commit + rng.integers(-10, 45, n_li)
        lineitem = pa.table(
            {
                "linekey": np.arange(n_li, dtype=np.int64),
                "orderkey": rng.integers(0, n_ord, n_li).astype(np.int64),
                "l_partkey": l_part,
                "l_suppkey": l_supp,
                "l_ps_key": l_part * 1_000_000 + l_supp,
                "quantity": rng.integers(1, 51, n_li).astype(np.float64),
                "extendedprice": (rng.random(n_li) * 10_000).round(2),
                "discount": rng.integers(0, 11, n_li).astype(np.float64) / 100.0,
                "tax": rng.integers(0, 9, n_li).astype(np.float64) / 100.0,
                "returnflag": rng.choice(["A", "N", "R"], n_li),
                "linestatus": rng.choice(["O", "F"], n_li),
                "shipdate": ship.astype(str),
                "commitdate": commit.astype(str),
                "receiptdate": receipt.astype(str),
                "shipmode": rng.choice(MODES, n_li),
            }
        )
        tables = {
            "region": region, "nation": nation, "supplier": supplier,
            "customer": customer, "part": part, "partsupp": partsupp,
            "orders": orders, "lineitem": lineitem,
        }
        for name, tbl in tables.items():
            self.catalog.table(name).write_arrow(tbl)
        self._frames = {k: v.to_pandas() for k, v in tables.items()}

    # ---------------------------------------------------------------- runs
    def run(self, name: str) -> tuple[float, pa.Table]:
        sql = QUERIES[name]
        start = time.perf_counter()
        out = self.sql.execute(sql)
        return time.perf_counter() - start, out

    def run_all(self) -> dict[str, tuple[float, pa.Table]]:
        return {name: self.run(name) for name in QUERIES}

    # ---------------------------------------------------------------- verify
    def verify(self, name: str, *, atol: float = 1e-6) -> bool:
        """Execute + compare against the independent pandas reference."""
        _, got = self.run(name)
        expected = pandas_reference(name, self.frames())
        return _tables_match(got, expected, atol=atol)

    def frames(self) -> dict:
        if not self._frames:
            self._frames = {
                n: self.catalog.table(n).to_arrow().to_pandas()
                for n in ("region", "nation", "supplier", "customer", "part",
                          "partsupp", "orders", "lineitem")
            }
        return self._frames


def _tables_match(got: pa.Table, expected, *, atol: float) -> bool:
    import pandas as pd

    gdf = got.to_pandas().reset_index(drop=True)
    edf = expected.reset_index(drop=True)
    if list(gdf.columns) != list(edf.columns):
        raise AssertionError(f"column mismatch: {list(gdf.columns)} vs {list(edf.columns)}")
    if len(gdf) != len(edf):
        raise AssertionError(f"row count mismatch: {len(gdf)} vs {len(edf)}")
    for col in gdf.columns:
        g, e = gdf[col], edf[col]
        if pd.api.types.is_numeric_dtype(e):
            if not np.allclose(
                g.astype(float).fillna(np.nan),
                e.astype(float).fillna(np.nan),
                atol=atol, rtol=1e-9, equal_nan=True,
            ):
                raise AssertionError(f"numeric mismatch in {col}")
        else:
            if not (g.fillna("<null>").astype(str) == e.fillna("<null>").astype(str)).all():
                raise AssertionError(f"value mismatch in {col}")
    return True


def pandas_reference(name: str, f: dict):
    """Independent pandas implementation of each adapted query."""
    import pandas as pd

    li, od, cu = f["lineitem"], f["orders"], f["customer"]
    su, na, re_, pt, ps = f["supplier"], f["nation"], f["region"], f["part"], f["partsupp"]

    def rev(df):
        return df["extendedprice"] * (1 - df["discount"])

    if name == "q01":
        d = li[li.shipdate <= "1998-09-02"].copy()
        d["sum_disc"] = rev(d)
        d["sum_charge"] = rev(d) * (1 + d["tax"])
        g = d.groupby(["returnflag", "linestatus"], as_index=False).agg(
            sum_qty=("quantity", "sum"), sum_base=("extendedprice", "sum"),
            sum_disc=("sum_disc", "sum"), sum_charge=("sum_charge", "sum"),
            avg_qty=("quantity", "mean"), avg_price=("extendedprice", "mean"),
            avg_disc=("discount", "mean"), count_order=("quantity", "size"),
        )
        return g.sort_values(["returnflag", "linestatus"])

    if name == "q02":
        m = ps.groupby("ps_partkey", as_index=False)["ps_supplycost"].min()
        m.columns = ["ps_partkey", "mincost"]
        d = (
            ps.merge(su, left_on="ps_suppkey", right_on="suppkey")
            .merge(na, left_on="s_nationkey", right_on="nationkey")
            .merge(re_, left_on="n_regionkey", right_on="regionkey")
            .merge(m, on="ps_partkey")
        )
        d = d[(d.r_name == "EUROPE") & (d.ps_supplycost == d.mincost)]
        d = d.sort_values(
            ["s_acctbal", "n_name", "s_name", "ps_partkey"],
            ascending=[False, True, True, True],
        ).head(100)
        return d[["s_acctbal", "s_name", "n_name", "ps_partkey", "ps_supplycost"]]

    if name == "q03":
        d = li.merge(od, on="orderkey").merge(cu, on="custkey")
        d = d[(d.mktsegment == "BUILDING") & (d.orderdate < "1995-03-15") & (d.shipdate > "1995-03-15")]
        d = d.assign(revenue=rev(d))
        g = d.groupby(["orderkey", "orderdate", "o_shippriority"], as_index=False)["revenue"].sum()
        g = g.sort_values(["revenue", "orderdate"], ascending=[False, True]).head(10)
        return g[["orderkey", "revenue", "orderdate", "o_shippriority"]]

    if name == "q04":
        late = set(li[li.commitdate < li.receiptdate]["orderkey"])
        d = od[
            (od.orderdate >= "1993-07-01") & (od.orderdate < "1993-10-01")
            & od.orderkey.isin(late)
        ]
        g = d.groupby("o_priority", as_index=False).agg(order_count=("orderkey", "size"))
        return g.sort_values("o_priority")

    if name == "q05":
        d = (
            li.merge(od, on="orderkey").merge(cu, on="custkey")
            .merge(su, left_on="l_suppkey", right_on="suppkey")
            .merge(na, left_on="s_nationkey", right_on="nationkey")
            .merge(re_, left_on="n_regionkey", right_on="regionkey")
        )
        d = d[
            (d.r_name == "ASIA") & (d.orderdate >= "1994-01-01")
            & (d.orderdate < "1995-01-01") & (d.c_nationkey == d.s_nationkey)
        ]
        d = d.assign(revenue=rev(d))
        g = d.groupby("n_name", as_index=False)["revenue"].sum()
        return g.sort_values("revenue", ascending=False)

    if name == "q06":
        d = li[
            (li.shipdate >= "1994-01-01") & (li.shipdate < "1995-01-01")
            & (li.discount >= 0.05) & (li.discount <= 0.07) & (li.quantity < 24)
        ]
        return pd.DataFrame({"revenue": [(d["extendedprice"] * d["discount"]).sum()]})

    if name == "q07":
        d = (
            li.merge(od, on="orderkey").merge(cu, on="custkey")
            .merge(su, left_on="l_suppkey", right_on="suppkey")
            .merge(na.rename(columns={"n_name": "supp_nation"}),
                   left_on="s_nationkey", right_on="nationkey")
            .merge(na.rename(columns={"n_name": "cust_nation"}),
                   left_on="c_nationkey", right_on="nationkey")
        )
        d = d[
            (d.shipdate >= "1995-01-01") & (d.shipdate <= "1996-12-31")
            & (d.supp_nation == "FRANCE") & (d.cust_nation == "GERMANY")
        ]
        d = d.assign(l_year=d.shipdate.str[:4], revenue=rev(d))
        g = d.groupby(["supp_nation", "cust_nation", "l_year"], as_index=False)["revenue"].sum()
        return g.sort_values(["supp_nation", "cust_nation", "l_year"])

    if name == "q08":
        d = (
            li.merge(od, on="orderkey").merge(pt, left_on="l_partkey", right_on="partkey")
            .merge(su, left_on="l_suppkey", right_on="suppkey")
            .merge(na.rename(columns={"n_name": "supp_nation"}),
                   left_on="s_nationkey", right_on="nationkey")
        )
        d = d[
            (d.p_type == "ECONOMY STEEL")
            & (d.orderdate >= "1995-01-01") & (d.orderdate <= "1996-12-31")
        ]
        d = d.assign(o_year=d.orderdate.str[:4], volume=rev(d))
        d["brazil"] = np.where(d.supp_nation == "BRAZIL", d.volume, 0.0)
        g = d.groupby("o_year", as_index=False).agg(
            b=("brazil", "sum"), v=("volume", "sum")
        )
        g["mkt_share"] = g.b / g.v
        return g.sort_values("o_year")[["o_year", "mkt_share"]]

    if name == "q09":
        d = (
            li.merge(ps, left_on="l_ps_key", right_on="ps_key")
            .merge(pt, left_on="l_partkey", right_on="partkey")
            .merge(su, left_on="l_suppkey", right_on="suppkey")
            .merge(na, left_on="s_nationkey", right_on="nationkey")
            .merge(od, on="orderkey")
        )
        d = d[d.p_name.str.startswith("PROMO")]
        d = d.assign(
            o_year=d.orderdate.str[:4],
            amount=rev(d) - d.ps_supplycost * d.quantity,
        )
        g = d.groupby(["n_name", "o_year"], as_index=False)["amount"].sum()
        g.columns = ["n_name", "o_year", "sum_profit"]
        return g.sort_values(["n_name", "o_year"], ascending=[True, False])

    if name == "q10":
        d = (
            li.merge(od, on="orderkey").merge(cu, on="custkey")
            .merge(na, left_on="c_nationkey", right_on="nationkey")
        )
        d = d[
            (d.returnflag == "R") & (d.orderdate >= "1993-10-01")
            & (d.orderdate < "1994-01-01")
        ]
        d = d.assign(revenue=rev(d))
        g = d.groupby(["custkey", "c_name", "c_acctbal", "n_name"], as_index=False)["revenue"].sum()
        g = g.sort_values("revenue", ascending=False).head(20)
        return g[["custkey", "c_name", "revenue", "c_acctbal", "n_name"]]

    if name == "q11":
        d = (
            ps.merge(su, left_on="ps_suppkey", right_on="suppkey")
            .merge(na, left_on="s_nationkey", right_on="nationkey")
        )
        d = d[d.n_name == "GERMANY"]
        d = d.assign(value=d.ps_supplycost * d.ps_availqty)
        threshold = d["value"].sum() * 0.01
        g = d.groupby("ps_partkey", as_index=False)["value"].sum()
        g = g[g["value"] > threshold]
        return g.sort_values("value", ascending=False)

    if name == "q12":
        d = li.merge(od, on="orderkey")
        d = d[
            d.shipmode.isin(["MAIL", "SHIP"]) & (d.commitdate < d.receiptdate)
            & (d.shipdate < d.commitdate) & (d.receiptdate >= "1994-01-01")
            & (d.receiptdate < "1995-01-01")
        ]
        high = d.o_priority.isin(["1-URGENT", "2-HIGH"])
        d = d.assign(high_line_count=high.astype(int), low_line_count=(~high).astype(int))
        g = d.groupby("shipmode", as_index=False)[["high_line_count", "low_line_count"]].sum()
        return g.sort_values("shipmode")

    if name == "q13":
        merged = cu.merge(od, on="custkey", how="left")
        counts = merged.groupby("custkey", as_index=False).agg(
            c_count=("orderkey", "count")
        )
        g = counts.groupby("c_count", as_index=False).agg(custdist=("c_count", "size"))
        return g.sort_values(["custdist", "c_count"], ascending=[False, False])

    if name == "q14":
        d = li.merge(pt, left_on="l_partkey", right_on="partkey")
        d = d[(d.shipdate >= "1995-09-01") & (d.shipdate < "1995-10-01")]
        promo = np.where(d.p_type.str.startswith("PROMO"), rev(d), 0.0)
        return pd.DataFrame({"promo_revenue": [100.0 * promo.sum() / rev(d).sum()]})

    if name == "q15":
        d = li[(li.shipdate >= "1996-01-01") & (li.shipdate < "1996-04-01")]
        r = d.assign(revenue=rev(d)).groupby("l_suppkey", as_index=False)["revenue"].sum()
        r.columns = ["l_suppkey", "total_revenue"]
        top = r[r.total_revenue == r.total_revenue.max()]
        out = su.merge(top, left_on="suppkey", right_on="l_suppkey")
        return out.sort_values("suppkey")[["suppkey", "s_name", "total_revenue"]]

    if name == "q16":
        bad = set(su[su.s_acctbal < 0]["suppkey"])
        d = ps.merge(pt, left_on="ps_partkey", right_on="partkey")
        d = d[
            (d.p_brand != "Brand#11") & ~d.p_type.str.startswith("PROMO")
            & d.p_size.isin([1, 2, 3, 4, 5]) & ~d.ps_suppkey.isin(bad)
        ]
        g = d.groupby(["p_brand", "p_type", "p_size"], as_index=False).agg(
            supplier_cnt=("ps_suppkey", "nunique")
        )
        return g.sort_values(
            ["supplier_cnt", "p_brand", "p_type", "p_size"],
            ascending=[False, True, True, True],
        )

    if name == "q17":
        avg_qty = li.groupby("l_partkey", as_index=False)["quantity"].mean()
        avg_qty.columns = ["l_partkey", "avg_qty"]
        d = li.merge(pt, left_on="l_partkey", right_on="partkey").merge(avg_qty, on="l_partkey")
        d = d[
            (d.p_brand == "Brand#22") & (d.p_container == "MED BOX")
            & (d.quantity < 0.5 * d.avg_qty)
        ]
        return pd.DataFrame({"avg_yearly": [d["extendedprice"].sum() / 7.0]})

    if name == "q18":
        big = li.groupby("orderkey", as_index=False)["quantity"].sum()
        big = set(big[big.quantity > 120]["orderkey"])
        d = li.merge(od, on="orderkey").merge(cu, on="custkey")
        d = d[d.orderkey.isin(big)]
        g = d.groupby(
            ["c_name", "custkey", "orderkey", "orderdate", "totalprice"], as_index=False
        )["quantity"].sum()
        g.columns = ["c_name", "custkey", "orderkey", "orderdate", "totalprice", "total_qty"]
        g = g.sort_values(["totalprice", "orderdate"], ascending=[False, True]).head(100)
        return g

    if name == "q19":
        d = li.merge(pt, left_on="l_partkey", right_on="partkey")
        m1 = (
            (d.p_brand == "Brand#11") & (d.p_container == "SM CASE")
            & d.quantity.between(1, 11) & d.p_size.between(1, 5)
        )
        m2 = (
            (d.p_brand == "Brand#22") & (d.p_container == "MED BOX")
            & d.quantity.between(10, 20) & d.p_size.between(1, 10)
        )
        m3 = (
            (d.p_brand == "Brand#33") & (d.p_container == "LG JAR")
            & d.quantity.between(20, 30) & d.p_size.between(1, 15)
        )
        d = d[m1 | m2 | m3]
        return pd.DataFrame({"revenue": [rev(d).sum()]})

    if name == "q20":
        promo_parts = set(pt[pt.p_name.str.startswith("PROMO")]["partkey"])
        qty = li.groupby(["l_partkey", "l_suppkey"])["quantity"].sum()
        cand = ps[ps.ps_partkey.isin(promo_parts)].copy()
        thresh = cand.apply(
            lambda r: 0.5 * qty.get((r.ps_partkey, r.ps_suppkey), float("nan")),
            axis=1,
        )
        # NaN threshold (no lineitem rows) never passes — SQL NULL semantics
        supp = set(cand[cand.ps_availqty > thresh]["ps_suppkey"])
        d = su.merge(na, left_on="s_nationkey", right_on="nationkey")
        d = d[(d.n_name == "CANADA") & d.suppkey.isin(supp)]
        return d.sort_values("s_name")[["s_name"]]

    if name == "q21":
        # real Q21 semantics: l1 late; ANOTHER supplier has a lineitem in
        # the same order; and NO other supplier's lineitem in it is late
        supp_by_order = li.groupby("orderkey")["l_suppkey"].agg(lambda s: set(s))
        late = li[li.receiptdate > li.commitdate]
        late_by_order = late.groupby("orderkey")["l_suppkey"].agg(lambda s: set(s))
        d = (
            li.merge(su, left_on="l_suppkey", right_on="suppkey")
            .merge(od, on="orderkey")
            .merge(na, left_on="s_nationkey", right_on="nationkey")
        )
        d = d[
            (d.o_status == "F") & (d.receiptdate > d.commitdate)
            & (d.n_name == "KENYA")
        ]
        keep = d.apply(
            lambda r: bool(supp_by_order.get(r.orderkey, set()) - {r.l_suppkey})
            and not (late_by_order.get(r.orderkey, set()) - {r.l_suppkey}),
            axis=1,
        )
        d = d[keep] if len(d) else d
        g = d.groupby("s_name", as_index=False).agg(numwait=("orderkey", "size"))
        return g.sort_values(["numwait", "s_name"], ascending=[False, True]).head(100)

    if name == "q22":
        avg_bal = cu[cu.c_acctbal > 0.0]["c_acctbal"].mean()
        has_orders = set(od["custkey"])
        d = cu.assign(cntrycode=cu.c_phone.str[:2])
        d = d[
            d.cntrycode.isin(["13", "31", "23", "29", "30"])
            & (d.c_acctbal > avg_bal) & ~d.custkey.isin(has_orders)
        ]
        g = d.groupby("cntrycode", as_index=False).agg(
            numcust=("custkey", "size"), totacctbal=("c_acctbal", "sum")
        )
        return g.sort_values("cntrycode")

    raise KeyError(name)
