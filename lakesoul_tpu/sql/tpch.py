"""TPC-H-lite harness.

The reference ships a TPC-H module as a harness (schemas + queries, no
committed numbers — rust/lakesoul-datafusion/src/tpch/).  This is the same
idea sized to this framework's SQL subset: a scaled generator for the
lineitem/orders/customer core, and adapted queries exercising expression
aggregates, joins, group-by and DML — runnable as a correctness harness or a
timing loop.

    from lakesoul_tpu.sql.tpch import TpchLite
    t = TpchLite(catalog, scale_rows=100_000)
    t.generate()
    results = t.run_all()      # {name: (seconds, arrow table)}
"""

from __future__ import annotations

import time

import numpy as np
import pyarrow as pa

from lakesoul_tpu.sql import SqlSession

QUERIES = {
    # Q1-style pricing summary: expression aggregates + group by
    "q1_pricing_summary": (
        "SELECT returnflag, count(*) AS cnt,"
        " sum(extendedprice) AS sum_base,"
        " sum(extendedprice * (1 - discount)) AS sum_disc,"
        " avg(quantity) AS avg_qty"
        " FROM lineitem WHERE shipdate <= '1998-09-02'"
        " GROUP BY returnflag ORDER BY returnflag"
    ),
    # Q3-style shipping priority: join + filter + grouped revenue
    "q3_shipping_priority": (
        "SELECT orderkey, sum(extendedprice * (1 - discount)) AS revenue"
        " FROM lineitem JOIN orders ON lineitem.orderkey = orders.orderkey"
        " WHERE orderdate < '1995-03-15'"
        " GROUP BY orderkey ORDER BY revenue DESC LIMIT 10"
    ),
    # Q6-style forecast revenue change: pure expression aggregate
    "q6_forecast_revenue": (
        "SELECT sum(extendedprice * discount) AS revenue FROM lineitem"
        " WHERE shipdate >= '1994-01-01' AND shipdate < '1995-01-01'"
        " AND discount >= 0.05 AND discount <= 0.07 AND quantity < 24"
    ),
    # customer rollup across a join
    "q_customer_revenue": (
        "SELECT mktsegment, count(*) AS orders, sum(totalprice) AS total"
        " FROM orders JOIN customer ON orders.custkey = customer.custkey"
        " GROUP BY mktsegment ORDER BY total DESC"
    ),
}


class TpchLite:
    def __init__(self, catalog, *, scale_rows: int = 100_000, seed: int = 0):
        self.catalog = catalog
        self.sql = SqlSession(catalog)
        self.scale_rows = scale_rows
        self.seed = seed

    # --------------------------------------------------------------- schema
    def generate(self) -> None:
        rng = np.random.default_rng(self.seed)
        n_li = self.scale_rows
        n_ord = max(1, n_li // 4)
        n_cust = max(1, n_ord // 10)

        self.sql.execute(
            "CREATE TABLE IF NOT EXISTS lineitem (linekey bigint PRIMARY KEY,"
            " orderkey bigint, quantity double, extendedprice double,"
            " discount double, returnflag string, shipdate string)"
            " WITH (hashBucketNum = '4')"
        )
        self.sql.execute(
            "CREATE TABLE IF NOT EXISTS orders (orderkey bigint PRIMARY KEY,"
            " custkey bigint, totalprice double, orderdate string)"
            " WITH (hashBucketNum = '4')"
        )
        self.sql.execute(
            "CREATE TABLE IF NOT EXISTS customer (custkey bigint PRIMARY KEY,"
            " mktsegment string)"
        )

        days = np.datetime64("1992-01-01") + rng.integers(0, 2500, n_li)
        lineitem = pa.table(
            {
                "linekey": np.arange(n_li, dtype=np.int64),
                "orderkey": rng.integers(0, n_ord, n_li).astype(np.int64),
                "quantity": rng.integers(1, 51, n_li).astype(np.float64),
                "extendedprice": (rng.random(n_li) * 10_000).round(2),
                "discount": rng.integers(0, 11, n_li).astype(np.float64) / 100.0,
                "returnflag": rng.choice(["A", "N", "R"], n_li),
                "shipdate": days.astype(str),
            }
        )
        odays = np.datetime64("1992-01-01") + rng.integers(0, 2500, n_ord)
        orders = pa.table(
            {
                "orderkey": np.arange(n_ord, dtype=np.int64),
                "custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
                "totalprice": (rng.random(n_ord) * 100_000).round(2),
                "orderdate": odays.astype(str),
            }
        )
        customer = pa.table(
            {
                "custkey": np.arange(n_cust, dtype=np.int64),
                "mktsegment": rng.choice(
                    ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"],
                    n_cust,
                ),
            }
        )
        self.catalog.table("lineitem").write_arrow(lineitem)
        self.catalog.table("orders").write_arrow(orders)
        self.catalog.table("customer").write_arrow(customer)

    # ---------------------------------------------------------------- runs
    def run(self, name: str) -> tuple[float, pa.Table]:
        sql = QUERIES[name]
        start = time.perf_counter()
        out = self.sql.execute(sql)
        return time.perf_counter() - start, out

    def run_all(self) -> dict[str, tuple[float, pa.Table]]:
        return {name: self.run(name) for name in QUERIES}
