from lakesoul_tpu.streaming.cdc import CdcIngestor, CheckpointedWriter
from lakesoul_tpu.streaming.db_sync import DatabaseSyncer, DebeziumJsonConsumer

__all__ = [
    "CdcIngestor",
    "CheckpointedWriter",
    "DatabaseSyncer",
    "DebeziumJsonConsumer",
]
