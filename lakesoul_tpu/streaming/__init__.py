from lakesoul_tpu.streaming.cdc import CdcIngestor, CheckpointedWriter

__all__ = ["CdcIngestor", "CheckpointedWriter"]
