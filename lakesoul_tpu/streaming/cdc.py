"""Exactly-once streaming / CDC ingest.

Role parity with the reference's Flink sink stack (LakeSoulMultiTablesSink →
NativeParquetWriter → LakeSoulSinkGlobalCommitter.java:128): files are staged
per *checkpoint epoch*, and the epoch commit uses **deterministic commit ids**
(UUIDv5 of table/partition/checkpoint) so a replay after failure is an
idempotent no-op — the same mechanism the Flink committer gets from its
checkpointed commit_id UUIDs (:95 filterRecoveredCommittables), without the
Flink runtime.

CDC rows carry a row-kind column (``rowKinds``: insert/update/delete) like
LakeSoulRecordConvert; deletes materialize at read time through the normal
merge + CDC filter path."""

from __future__ import annotations

import uuid
from typing import Iterable

import pyarrow as pa

from lakesoul_tpu.errors import ConfigError
from lakesoul_tpu.io.writer import TableWriter
from lakesoul_tpu.meta.entity import CommitOp
from lakesoul_tpu.meta import DataFileOp

_CHECKPOINT_NS = uuid.UUID("6ba7b811-9dad-11d1-80b4-00c04fd430c8")


def checkpoint_commit_id(table_id: str, partition_desc: str, checkpoint_id: int | str) -> str:
    """Deterministic commit id for (table, partition, checkpoint epoch)."""
    return str(uuid.uuid5(_CHECKPOINT_NS, f"{table_id}/{partition_desc}/{checkpoint_id}"))


class CheckpointedWriter:
    """Stage batches, commit atomically per checkpoint epoch.

    ::

        w = CheckpointedWriter(table)
        w.write(batch); w.write(batch)
        w.checkpoint(7)        # commits everything staged since the last one
        w.checkpoint(7)        # replay → no-op (same deterministic ids)
    """

    def __init__(self, table, *, commit_op: CommitOp | None = None):
        self.table = table
        self.commit_op = commit_op or (
            CommitOp.MERGE if table.info.primary_keys else CommitOp.APPEND
        )
        self._writer: TableWriter | None = None

    def _ensure_writer(self) -> TableWriter:
        if self._writer is None:
            self._writer = TableWriter(self.table.io_config(), self.table.info.table_path)
        return self._writer

    def write(self, batch: pa.RecordBatch | pa.Table) -> None:
        self._ensure_writer().write_batch(batch)

    def _staged_files_by_partition(self) -> dict[str, list[DataFileOp]]:
        """Flush and group this epoch's staged files per partition.
        take_staged, not flush()'s return: write_batch may have auto-flushed
        earlier files of this epoch on the row budget."""
        if self._writer is None:
            return {}
        self._writer.flush()
        files_by_partition: dict[str, list[DataFileOp]] = {}
        for out in self._writer.take_staged():
            files_by_partition.setdefault(out.partition_desc, []).append(
                DataFileOp(path=out.path, file_op="add", size=out.size,
                           file_exist_cols=out.file_exist_cols)
            )
        return files_by_partition

    def checkpoint(self, checkpoint_id: int | str) -> int:
        """Flush staged data and commit with checkpoint-derived commit ids.
        Returns the number of partitions committed (0 on replay/no data).

        The commit runs under the shared
        :class:`~lakesoul_tpu.runtime.resilience.RetryPolicy`: a transient
        store/meta fault retries on the seeded schedule, and because the
        commit ids derive from the checkpoint id, a retry after a
        half-landed attempt is the same idempotent replay a crashed
        process gets — a continuously-ingesting writer (the freshness
        chaos harness's writer role) survives injected flaky faults
        without double-committing an epoch."""
        from lakesoul_tpu.runtime.resilience import RetryPolicy

        files_by_partition = self._staged_files_by_partition()
        if not files_by_partition:
            return 0
        commit_ids = {
            desc: checkpoint_commit_id(self.table.info.table_id, desc, checkpoint_id)
            for desc in files_by_partition
        }

        def attempt():
            return self.table.catalog.client.commit_data_files(
                self.table.info,
                files_by_partition,
                self.commit_op,
                commit_id_by_partition=commit_ids,
                storage_options=self.table.io_config().object_store_options,
            )

        committed = RetryPolicy.from_env().run(attempt, op="cdc.checkpoint")
        return len(committed)

    def checkpoint_replace(self, checkpoint_id: int | str) -> int:
        """REPLACE-mode checkpoint: swap the table's ENTIRE content for this
        epoch's staged files without ever dropping the table.

        Partitions that received data get an UPDATE commit (whole-snapshot
        replace with read-version conflict detection); pre-existing
        partitions that did not are emptied with a DELETE commit.  The
        table_id never changes and every commit id derives from the
        checkpoint id, so replaying the same id after a success is an
        idempotent no-op (re-staged duplicate files are dropped as replay
        orphans) — unlike a drop+recreate, a client disconnect mid-stream
        leaves the old data fully intact, and a crash between the two commit
        waves is healed by the replay.  Returns partitions committed."""
        files_by_partition = self._staged_files_by_partition()
        from lakesoul_tpu.errors import CommitConflictError
        from lakesoul_tpu.runtime.resilience import RetryPolicy

        client = self.table.catalog.client
        info = self.table.info
        opts = self.table.io_config().object_store_options

        # a concurrent writer advancing a partition between our head read
        # and the commit raises CommitConflictError; each attempt re-reads
        # fresh heads and re-applies the whole replace
        def attempt() -> int:
            heads = {
                h.partition_desc: h
                for h in client._select_partitions(info, None)
            }
            committed = 0
            if files_by_partition:
                committed += len(client.commit_data_files(
                    info,
                    files_by_partition,
                    CommitOp.UPDATE,
                    commit_id_by_partition={
                        desc: checkpoint_commit_id(info.table_id, desc, checkpoint_id)
                        for desc in files_by_partition
                    },
                    read_partition_info=[
                        heads[d] for d in files_by_partition if d in heads
                    ],
                    storage_options=opts,
                ))
            stale = [
                d for d, h in heads.items()
                if d not in files_by_partition and h.snapshot
            ]
            if stale:
                committed += len(client.commit_data_files(
                    info,
                    {d: [] for d in stale},
                    CommitOp.DELETE,
                    commit_id_by_partition={
                        d: checkpoint_commit_id(
                            info.table_id, d, f"{checkpoint_id}:truncate"
                        )
                        for d in stale
                    },
                    # conflict detection on the DELETE wave too: a
                    # concurrent writer advancing one of these
                    # partitions between our head read and this commit
                    # must raise CommitConflictError (and re-run the
                    # replace against fresh heads) instead of being
                    # silently wiped by the truncate
                    read_partition_info=[heads[d] for d in stale],
                    storage_options=opts,
                ))
            return committed

        return RetryPolicy.from_env(
            max_attempts=5,
            base_delay_s=0.01,
            max_delay_s=0.25,
            classify=lambda e: isinstance(e, CommitConflictError),
        ).run(attempt, op="cdc.checkpoint_replace")

    def adopt_staged(self, other: "CheckpointedWriter | None") -> None:
        """Take over another checkpointed writer's staged-but-uncommitted
        files (schema-evolution handoff: the old writer is retired, this one
        commits its files at the next checkpoint).  The donor is closed and
        must not be written to again."""
        if other is None or other._writer is None:
            return
        donor = other._writer
        donor.flush()
        self._ensure_writer()._staged.extend(donor.take_staged())
        donor._closed = True
        other._writer = None

    def abort(self) -> None:
        if self._writer is not None:
            self._writer.abort()
            self._writer = None

    def close(self) -> None:
        if self._writer is not None:
            self._writer._closed = True
            self._writer = None


class CdcIngestor:
    """Apply CDC change events to a CDC-enabled PK table.

    Events are (op, row_dict) with op ∈ {insert, update, delete} — the shape
    a Debezium-style source produces (reference: entry/JdbcCDC.java →
    LakeSoulRecordConvert).  Deletes only need the primary key columns."""

    def __init__(self, table, *, buffer_rows: int = 10_000):
        info = table.info
        if not info.cdc_column:
            raise ConfigError(
                f"table {info.table_name} is not CDC-enabled (create with cdc=True)"
            )
        if not info.primary_keys:
            raise ConfigError("CDC ingest requires a primary-key table")
        self.table = table
        self.cdc_column = info.cdc_column
        self.buffer_rows = buffer_rows
        self._writer = CheckpointedWriter(table)
        self._pending: list[dict] = []

    def apply(self, op: str, row: dict) -> None:
        if op not in ("insert", "update", "delete"):
            raise ConfigError(f"unknown CDC op {op!r}")
        event = dict(row)
        event[self.cdc_column] = op
        self._pending.append(event)
        if len(self._pending) >= self.buffer_rows:
            self._flush_buffer()

    def apply_many(self, events: Iterable[tuple[str, dict]]) -> None:
        for op, row in events:
            self.apply(op, row)

    def _flush_buffer(self) -> None:
        if not self._pending:
            return
        schema = self.table.schema
        cols = {}
        for fld in schema:
            cols[fld.name] = pa.array(
                [r.get(fld.name) for r in self._pending], type=fld.type
            )
        self._writer.write(pa.table(cols, schema=schema))
        self._pending.clear()

    def checkpoint(self, checkpoint_id: int | str) -> int:
        """Flush buffered events and commit exactly-once for this epoch."""
        self._flush_buffer()
        return self._writer.checkpoint(checkpoint_id)
