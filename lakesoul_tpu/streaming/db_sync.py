"""Whole-database ingest: snapshot sync + Debezium-format CDC consumption.

Role parity with the reference's Flink entry points
(lakesoul-flink/…/entry/JdbcCDC.java — Debezium CDC from MySQL/Oracle/PG
into per-table exactly-once sinks with automatic DDL sync — and
entry/SyncDatabase.java — batch whole-DB copy).  The TPU build has no Flink
or Debezium runtime, so the two halves are:

- :class:`DatabaseSyncer` — snapshot-sync every table of a DB-API source
  connection (schema introspection → auto CREATE TABLE with source primary
  keys → bulk copy).  Works against sqlite out of the box and any DB-API
  driver with ``information_schema``-style introspection via the hook
  methods.
- :class:`DebeziumJsonConsumer` — consume Debezium change-event dicts (the
  wire format every Debezium connector emits: ``payload.op`` c/r/u/d with
  ``before``/``after`` row images and ``source.table``), routing each event
  to a per-table :class:`~lakesoul_tpu.streaming.cdc.CdcIngestor`.  Tables
  are auto-created on first sight and auto-evolved when events carry new
  columns (the role of LakeSoulSinkGlobalCommitter's DDL sync,
  LakeSoulSinkGlobalCommitter.java:176); ``checkpoint(epoch)`` commits every
  table exactly-once (deterministic commit ids, replay-safe).
"""

from __future__ import annotations

import logging
from typing import Iterable

import pyarrow as pa

from lakesoul_tpu.errors import ConfigError

logger = logging.getLogger(__name__)

# declared-type → arrow mapping for DB-API sources (sqlite's loose typing
# resolves through affinity prefixes; richer engines hit exact names first)
_SQL_TYPE_MAP = [
    ("BIGINT", pa.int64()),
    ("INT", pa.int64()),
    ("SERIAL", pa.int64()),
    ("DOUBLE", pa.float64()),
    ("FLOAT", pa.float64()),
    ("REAL", pa.float64()),
    ("NUMERIC", pa.float64()),
    ("DECIMAL", pa.float64()),
    ("BOOL", pa.bool_()),
    ("CHAR", pa.string()),
    ("TEXT", pa.string()),
    ("CLOB", pa.string()),
    ("DATE", pa.string()),
    ("TIME", pa.string()),
    ("BLOB", pa.binary()),
    ("BYTEA", pa.binary()),
]


def _arrow_type_for(declared: str) -> pa.DataType:
    up = (declared or "").upper()
    for token, typ in _SQL_TYPE_MAP:
        if token in up:
            return typ
    return pa.string()  # safest fallback: everything casts to string


class DatabaseSyncer:
    """Snapshot-sync a whole source database into the lakehouse
    (reference: entry/SyncDatabase.java)."""

    def __init__(self, catalog, *, namespace: str = "default", hash_bucket_num: int = 4):
        self.catalog = catalog
        self.namespace = namespace
        self.hash_bucket_num = hash_bucket_num

    # ------------------------------------------------------- introspection
    def list_source_tables(self, conn) -> list[str]:
        cur = conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table'"
            " AND name NOT LIKE 'sqlite_%'"
        )
        return [r[0] for r in cur.fetchall()]

    def source_schema(self, conn, table: str) -> tuple[pa.Schema, list[str]]:
        """→ (arrow schema, primary key columns) from table_info."""
        cur = conn.execute(f'PRAGMA table_info("{table}")')
        fields, pks = [], []
        for _cid, name, declared, _notnull, _default, pk in cur.fetchall():
            fields.append(pa.field(name, _arrow_type_for(declared)))
            if pk:
                pks.append((pk, name))
        pks.sort()
        return pa.schema(fields), [name for _, name in pks]

    # --------------------------------------------------------------- sync
    def sync_table(self, conn, table: str, *, batch_rows: int = 50_000) -> int:
        """Copy one source table (auto-creating the lakehouse table); returns
        rows copied."""
        schema, pks = self.source_schema(conn, table)
        if not self.catalog.table_exists(table, self.namespace):
            self.catalog.create_table(
                table,
                schema,
                primary_keys=pks,
                hash_bucket_num=self.hash_bucket_num if pks else 1,
                namespace=self.namespace,
            )
        dest = self.catalog.table(table, self.namespace)
        cols_sql = ", ".join(f'"{c}"' for c in schema.names)
        cur = conn.execute(f'SELECT {cols_sql} FROM "{table}"')
        total = 0
        while True:
            rows = cur.fetchmany(batch_rows)
            if not rows:
                break
            cols = {
                f.name: pa.array([r[i] for r in rows]).cast(f.type)
                for i, f in enumerate(schema)
            }
            batch = pa.table(cols, schema=schema)
            if pks:
                dest.upsert(batch)  # re-sync converges instead of duplicating
            else:
                dest.write_arrow(batch)
            total += len(rows)
        logger.info("synced table %s: %d rows", table, total)
        return total

    def sync(self, conn, *, tables: list[str] | None = None) -> dict[str, int]:
        """Whole-DB sync; returns {table: rows_copied}."""
        names = tables if tables is not None else self.list_source_tables(conn)
        return {name: self.sync_table(conn, name) for name in names}


class DebeziumJsonConsumer:
    """Route Debezium change events into per-table exactly-once CDC ingest
    (reference: entry/JdbcCDC.java → LakeSoulRecordConvert → multi-table
    sink).  Accepts both the enveloped form ({"payload": {...}}) and the
    flattened form Debezium emits with schemas disabled."""

    _OPS = {"c": "insert", "r": "insert", "u": "update", "d": "delete"}

    def __init__(self, catalog, *, namespace: str = "default",
                 hash_bucket_num: int = 4, primary_keys: dict[str, list[str]] | None = None):
        self.catalog = catalog
        self.namespace = namespace
        self.hash_bucket_num = hash_bucket_num
        # Debezium events don't carry PK metadata; the source's key columns
        # arrive out of band (reference: JdbcCDC gets them from JDBC metadata)
        self.primary_keys = dict(primary_keys or {})
        self._ingestors: dict[str, "object"] = {}
        # known column names per table: the per-event evolution check must
        # not cost a metadata-store query per event
        self._known_cols: dict[str, set[str]] = {}

    # -------------------------------------------------------------- events
    def consume(self, event: dict) -> None:
        payload = event.get("payload", event)
        op = payload.get("op")
        if op not in self._OPS:
            raise ConfigError(f"unknown Debezium op {op!r}")
        row = payload.get("after") if op != "d" else payload.get("before")
        if row is None:
            raise ConfigError(f"Debezium event missing row image for op {op!r}")
        source = payload.get("source", {})
        table = source.get("table")
        if not table:
            raise ConfigError("Debezium event missing source.table")
        self._ingestor_for(table, row)  # ensures table + ingestor exist
        self._evolve_if_needed(table, row)  # may swap in a rebuilt ingestor
        self._ingestors[table].apply(self._OPS[op], row)

    def consume_many(self, events: Iterable[dict]) -> int:
        n = 0
        for e in events:
            self.consume(e)
            n += 1
        return n

    def checkpoint(self, checkpoint_id: int | str) -> int:
        """Commit every table's staged changes exactly-once for this epoch;
        returns the number of partition commits."""
        total = 0
        for ing in self._ingestors.values():
            total += ing.checkpoint(checkpoint_id)
        return total

    # ------------------------------------------------------------- plumbing
    def _infer_schema(self, row: dict) -> pa.Schema:
        fields = []
        for k, v in row.items():
            if isinstance(v, bool):
                t = pa.bool_()
            elif isinstance(v, int):
                t = pa.int64()
            elif isinstance(v, float):
                t = pa.float64()
            elif isinstance(v, bytes):
                t = pa.binary()
            else:
                t = pa.string()
            fields.append(pa.field(k, t))
        return pa.schema(fields)

    def _ingestor_for(self, table: str, row: dict):
        ing = self._ingestors.get(table)
        if ing is not None:
            return ing
        from lakesoul_tpu.streaming.cdc import CdcIngestor

        if not self.catalog.table_exists(table, self.namespace):
            pks = self.primary_keys.get(table)
            if not pks:
                raise ConfigError(
                    f"first event for unknown table {table!r}: pass its primary"
                    " keys via DebeziumJsonConsumer(primary_keys={...})"
                )
            self.catalog.create_table(
                table,
                self._infer_schema(row),
                primary_keys=pks,
                hash_bucket_num=self.hash_bucket_num,
                cdc=True,
                namespace=self.namespace,
            )
            logger.info("auto-created CDC table %s from first event", table)
        ing = CdcIngestor(self.catalog.table(table, self.namespace))
        self._ingestors[table] = ing
        return ing

    def _evolve_if_needed(self, table: str, row: dict) -> None:
        """Auto schema evolution: a new column in an event adds a nullable
        column to the table (committer DDL-sync role).  The fast path is a
        cached set check — no metadata query per event."""
        known = self._known_cols.get(table)
        if known is None:
            known = set(self.catalog.table(table, self.namespace).schema.names)
            self._known_cols[table] = known
        if all(k in known for k in row):
            return
        t = self.catalog.table(table, self.namespace)
        known = set(t.schema.names)  # authoritative re-check
        new = [k for k in row.keys() if k not in known]
        if not new:
            self._known_cols[table] = known
            return
        inferred = self._infer_schema(row)
        old = self._ingestors.get(table)
        if old is not None:
            # stage everything buffered under the OLD schema first — the old
            # writer must not see new-column rows (it would silently align
            # them down to the old schema)
            old._flush_buffer()
        t.add_columns([inferred.field(k) for k in new])
        logger.info("auto-evolved table %s: added columns %s", table, new)
        # rebuild the ingestor against the evolved schema, carrying any
        # staged-but-uncommitted files across so checkpoint() commits them
        from lakesoul_tpu.streaming.cdc import CdcIngestor

        fresh = CdcIngestor(self.catalog.table(table, self.namespace))
        fresh._writer.adopt_staged(old._writer if old is not None else None)
        self._ingestors[table] = fresh
        self._known_cols[table] = known | set(new)
