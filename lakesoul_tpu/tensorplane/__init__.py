"""Tensor plane — the device-first data plane (ROADMAP item 5).

Four pieces, one goal: rows that are *tensors* (embeddings, token blocks,
image patches) should travel from LSF buffers into a JAX training step
without being re-discovered, re-collated, or re-copied every epoch:

- :mod:`columns` — first-class fixed-shape tensor column declarations:
  ``tensor_field("emb", (16, 16), "float32")`` builds a
  ``fixed_size_list`` field carrying its logical shape in field metadata
  (full-fidelity through the IPC schema the catalog stores; the Spark-JSON
  mirror spells it as an array with ``fixedLength`` — see
  ``meta/entity.py``).  The writer validates every declared column on
  write with typed :class:`~lakesoul_tpu.errors.TensorColumnError`\\ s, so
  a malformed batch dies at the table boundary, not three stages into a
  training run; the collate layer reshapes to the declared shape from a
  spec computed ONCE per loader instead of probing Arrow types per batch.
- :mod:`dlpack` — zero-copy hand-off from collated host buffers into jax:
  ``deliver()`` rides the DLPack protocol (``jax.dlpack.from_dlpack``)
  when the dtype survives unchanged, and the empirical
  :func:`~lakesoul_tpu.tensorplane.dlpack.delivery_copies` probe tells the
  loader whether ``device_put`` on THIS backend actually copies — the
  PR-9 ring-disarm rule now keys on measured aliasing, not a platform
  guess.
- :mod:`replay` — :class:`~lakesoul_tpu.tensorplane.replay.
  DeviceReplayCache`: an HBM-budgeted residency manager
  (``LAKESOUL_REPLAY_BUDGET_BYTES``) that pins epoch-1's collated,
  device-put shards per device and serves every later epoch straight from
  device memory — zero storage/host/link traffic — with an optional
  seeded on-device permutation per epoch.  Past the budget it spills
  *gracefully*: the typed, metered spill record marks the cache hybrid,
  and epoch ≥ 2 replays the resident prefix then re-streams only the
  tail.
- :mod:`smoke` — the one-command TPU re-validation registry behind
  ``tools/tpu_smoke.py``: every Pallas kernel in the repo (enumerated
  from lakelint's device index, so the registry provably covers 100%),
  the multichip shapes, and the tensorplane delivery/replay paths compile
  and run on-chip when a device is reachable; on CPU fallback the report
  carries the complete ``untested_on_tpu`` list so ONE live-tunnel
  session re-validates every on-chip claim with zero hand work.
"""

from lakesoul_tpu.tensorplane.columns import (
    TensorSpec,
    tensor_field,
    tensor_shape_of,
    tensor_specs,
    validate_tensor_batch,
)
from lakesoul_tpu.tensorplane.dlpack import (
    aligned_empty,
    deliver,
    delivery_copies,
    device_put_copies,
)
from lakesoul_tpu.tensorplane.replay import DeviceReplayCache, ReplaySpill

__all__ = [
    "TensorSpec",
    "tensor_field",
    "tensor_shape_of",
    "tensor_specs",
    "validate_tensor_batch",
    "aligned_empty",
    "deliver",
    "delivery_copies",
    "device_put_copies",
    "DeviceReplayCache",
    "ReplaySpill",
]
