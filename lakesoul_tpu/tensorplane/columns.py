"""First-class fixed-shape tensor columns for LSF tables.

A tensor column is a ``fixed_size_list<element: T not null>[prod(shape)]``
field whose *logical* shape rides in field metadata under
``lakesoul:tensor`` — the declaration the Delta-Tensor / Deep-Lake line of
work makes the differentiator for training-loop ingest: the storage layer
knows rows are ``(16, 16)`` float32 patches, so the writer can verify them
once at the table boundary and the collate layer can reshape straight to
``(batch, 16, 16)`` from a spec computed ONCE per loader, instead of
probing Arrow types per batch and flattening every epoch.

Fidelity: the catalog stores the Arrow schema as IPC bytes, which carry
field metadata verbatim, so declarations survive every metadata round
trip.  The Spark-JSON mirror (``meta/entity.py``) spells the same field's
type as ``{"type": "array", ..., "fixedLength": N}`` and carries the
logical shape in the field's Spark ``metadata`` map
(``{"lakesoul:tensor": {"shape": [...]}}``, restored to Arrow field
metadata on parse) so the JSON column stays fully interoperable instead
of degrading to a raw Arrow type string.

Element types are restricted to fixed-width numerics (what a TPU can eat);
the LSF ``fsl`` encoding stores the flat child values verbatim, so a
declared column decodes to a zero-copy 2-D-ready buffer.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass

import pyarrow as pa

from lakesoul_tpu.errors import ConfigError, TensorColumnError

# field-metadata key carrying the logical shape (JSON: {"shape": [...]})
TENSOR_META_KEY = b"lakesoul:tensor"

_ELEMENT_TYPES: dict[str, pa.DataType] = {
    "float16": pa.float16(),
    "float32": pa.float32(),
    "float64": pa.float64(),
    "int8": pa.int8(),
    "int16": pa.int16(),
    "int32": pa.int32(),
    "int64": pa.int64(),
    "uint8": pa.uint8(),
    "uint16": pa.uint16(),
    "uint32": pa.uint32(),
    "uint64": pa.uint64(),
}


@dataclass(frozen=True)
class TensorSpec:
    """One declared tensor column: logical shape + element dtype."""

    name: str
    shape: tuple[int, ...]
    dtype: pa.DataType

    @property
    def width(self) -> int:
        """Flattened row width (the fixed_size_list size)."""
        return math.prod(self.shape)


def _normalize_shape(shape) -> tuple[int, ...]:
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(int(d) for d in shape)
    if not shape or any(d < 1 for d in shape):
        raise ConfigError(f"tensor shape must be positive dims, got {shape}")
    return shape


def _element_type(dtype) -> pa.DataType:
    if isinstance(dtype, pa.DataType):
        t = dtype
    else:
        t = _ELEMENT_TYPES.get(str(dtype))
        if t is None:
            raise ConfigError(
                f"unsupported tensor element dtype {dtype!r}; expected one of"
                f" {sorted(_ELEMENT_TYPES)}"
            )
    if not (pa.types.is_integer(t) or pa.types.is_floating(t)):
        raise ConfigError(
            f"tensor element type must be fixed-width numeric, got {t}"
        )
    return t


def tensor_field(name: str, shape, dtype="float32") -> pa.Field:
    """Declare one tensor column: ``tensor_field("emb", (16, 16))`` →
    a non-nullable ``fixed_size_list<element: float not null>[256]`` field
    with the logical shape in ``lakesoul:tensor`` metadata."""
    shape = _normalize_shape(shape)
    elem = _element_type(dtype)
    t = pa.list_(pa.field("element", elem, nullable=False), math.prod(shape))
    meta = {TENSOR_META_KEY: json.dumps({"shape": list(shape)}).encode()}
    return pa.field(name, t, nullable=False, metadata=meta)


def tensor_shape_of(field: pa.Field) -> tuple[int, ...] | None:
    """The declared logical shape of ``field``, or None when it is not a
    declared tensor column.  A ``fixed_size_list`` without metadata still
    counts as a 1-D tensor of its list size — the pre-declaration collate
    contract — so legacy embedding columns keep collating to 2-D."""
    if not pa.types.is_fixed_size_list(field.type):
        return None
    meta = field.metadata or {}
    raw = meta.get(TENSOR_META_KEY)
    if raw is None:
        return (field.type.list_size,)
    try:
        shape = tuple(int(d) for d in json.loads(raw)["shape"])
    except (ValueError, KeyError, TypeError) as e:
        raise ConfigError(
            f"column {field.name!r} carries unparseable tensor metadata"
            f" {raw!r}"
        ) from e
    if math.prod(shape) != field.type.list_size:
        raise ConfigError(
            f"column {field.name!r}: declared tensor shape {shape} does not"
            f" flatten to the fixed_size_list width {field.type.list_size}"
        )
    return shape


def tensor_specs(schema: pa.Schema | None) -> dict[str, TensorSpec]:
    """Every *declared* tensor column of ``schema`` (metadata-carrying
    fields only — plain ``fixed_size_list`` columns are not validated, they
    predate declarations), keyed by column name.  Computed once per
    writer/loader; empty for schemas with no declarations."""
    if schema is None:
        return {}
    out: dict[str, TensorSpec] = {}
    for field in schema:
        if not pa.types.is_fixed_size_list(field.type):
            continue
        if not (field.metadata or {}).get(TENSOR_META_KEY):
            continue
        shape = tensor_shape_of(field)
        out[field.name] = TensorSpec(field.name, shape, field.type.value_type)
    return out


def validate_tensor_batch(
    table: pa.Table | pa.RecordBatch, specs: dict[str, TensorSpec]
) -> None:
    """Verify every declared tensor column of ``table`` against its spec;
    raises :class:`TensorColumnError` naming the first offending column.

    Checked per write batch (cheap: type identity + null counts, no data
    pass): the column must be present, a ``fixed_size_list`` of exactly the
    declared width and element dtype, and free of nulls at both the list
    and element level — a null row would silently misalign the flat child
    buffer against the row count in the zero-copy collate."""
    if not specs:
        return
    schema = table.schema
    for name, spec in specs.items():
        idx = schema.get_field_index(name)
        if idx < 0:
            raise TensorColumnError(
                f"tensor column {name!r} (shape {spec.shape},"
                f" {spec.dtype}) missing from the written batch"
            )
        col = table.column(idx)
        t = schema.field(idx).type
        if not pa.types.is_fixed_size_list(t):
            raise TensorColumnError(
                f"tensor column {name!r} must be fixed_size_list"
                f"[{spec.width}] of {spec.dtype}, got {t}"
            )
        if t.list_size != spec.width or t.value_type != spec.dtype:
            raise TensorColumnError(
                f"tensor column {name!r} declared shape {spec.shape}"
                f" ({spec.dtype}, width {spec.width}) but the batch carries"
                f" fixed_size_list[{t.list_size}] of {t.value_type}"
            )
        chunks = col.chunks if isinstance(col, pa.ChunkedArray) else [col]
        for chunk in chunks:
            if chunk.null_count:
                raise TensorColumnError(
                    f"tensor column {name!r} has {chunk.null_count} null"
                    " row(s) — tensor rows must be dense"
                )
            flat = chunk.flatten()
            if flat.null_count:
                raise TensorColumnError(
                    f"tensor column {name!r} has {flat.null_count} null"
                    " element(s) inside its rows — tensor elements must be"
                    " dense"
                )
