"""Zero-copy DLPack delivery + the empirical device-put aliasing probe.

Two exports, both about the same question — *where does the copy happen
when a collated host buffer becomes a jax.Array?*

- :func:`deliver` moves a collated pytree to device.  When the dtype
  survives jax's canonicalization unchanged, each leaf rides the DLPack
  protocol (``jax.dlpack.from_dlpack``) so the host-side import is
  zero-copy — on TPU the only copy left is the H2D DMA itself, on CPU
  there is no copy at all.  Leaves whose dtype jax would demote
  (int64/float64 under disabled x64) take plain ``device_put`` — the cast
  IS a real copy, there is nothing to save.
- :func:`device_put_copies` / :func:`delivery_copies` measure, per
  (dtype, target backend), whether ``jax.device_put`` of a host array is
  a REAL copy or an alias of the host buffer.  PR 9 found the collate
  reuse ring corrupting live device data because host-backed
  ``device_put`` aliases dtype-matching buffers; the disarm rule it
  shipped keyed on the *platform* ("host-backed ⇒ disarm").  The probe
  replaces the guess with a measurement: an int64/float64-only table on a
  CPU backend gets its ring back (the demotion cast copies), while a
  float32 table still disarms.  The loader and the device-resident replay
  cache both key on it — the lifetime rules (``ring-aliasing``) accept a
  probe-guarded ring as sanctioned.

Probe results are cached per (dtype, device kind) for the process — the
answer is a property of the backend, not of the call site.
"""

from __future__ import annotations

import numpy as np

# (np dtype str, device platform) -> device_put makes a real copy
_COPY_CACHE: dict[tuple[str, str], bool] = {}

# XLA's CPU client only zero-copies host buffers aligned to this; anything
# less falls back to a silent staging copy.  Collate output buffers are
# allocated through aligned_empty so the zero-copy delivery claim holds
# deterministically instead of depending on where malloc happened to land —
# and the probe below uses it so "can this dtype alias?" is answered for
# the aligned case (the conservative one: an unaligned probe would report
# "copies" while a real, aligned collate buffer aliased).
ALIGNMENT = 64


def aligned_empty(shape, dtype) -> np.ndarray:
    """``np.empty`` with the buffer start aligned to :data:`ALIGNMENT`
    bytes (the backing allocation stays alive via ``.base``)."""
    dt = np.dtype(dtype)
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    nbytes = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
    raw = np.empty(nbytes + ALIGNMENT, dtype=np.uint8)
    off = (-raw.ctypes.data) % ALIGNMENT
    return raw[off:off + nbytes].view(dt).reshape(shape)


def _probe_device(sharding=None):
    """The single device a probe targets: aliasing is a per-backend
    property, so one device of the sharding's set stands for all of it."""
    import jax

    if sharding is not None:
        devices = getattr(sharding, "device_set", None)
        if devices:
            return sorted(devices, key=lambda d: d.id)[0]
    return jax.devices()[0]


def device_put_copies(dtype, sharding=None) -> bool:
    """True when ``jax.device_put`` of a host numpy array of ``dtype``
    onto the delivery target is a REAL copy (the produced jax.Array owns
    bytes disjoint from the source buffer); False when it aliases.  Any
    probe failure reports False — "assume aliasing" is the safe answer
    for every caller (the ring stays down, the replay cache makes a
    defensive copy)."""
    import jax

    dt = np.dtype(dtype)
    try:
        device = _probe_device(sharding)
    except Exception:
        return False
    key = (dt.str, getattr(device, "platform", "unknown"))
    hit = _COPY_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        probe = aligned_empty((8,), dt)
        probe[:] = 0
        arr = jax.device_put(probe, device)
        arr.block_until_ready()
        try:
            dst = arr.unsafe_buffer_pointer()
        except Exception:
            # no single addressable buffer (or API absent): prove the copy
            # behaviorally — mutate the source and check the device value
            probe[0] = 1
            copied = bool(int(arr[0]) == 0)
            _COPY_CACHE[key] = copied
            return copied
        src = probe.ctypes.data
        copied = not (src <= dst < src + probe.nbytes)
    except Exception:
        copied = False
    _COPY_CACHE[key] = copied
    return copied


def delivery_copies(dtypes, sharding=None) -> bool:
    """True only when EVERY dtype's device_put is a real copy — the
    condition under which a collate output buffer can be reused the moment
    ``device_put`` returns.  ``dtypes`` None/empty means the caller could
    not resolve the schema: report False (assume aliasing, stay safe)."""
    if not dtypes:
        return False
    return all(device_put_copies(dt, sharding) for dt in dtypes)


def _canonical_dtype(dt: np.dtype):
    """What jax will store for a host array of ``dt`` (x64 demotion)."""
    import jax.numpy as jnp

    return jnp.asarray(np.zeros(0, dtype=dt)).dtype


def deliver(batch, sharding=None):
    """Collated host pytree → device pytree, avoiding every avoidable host
    copy.

    Dtype-preserved leaves are imported through DLPack first — a zero-copy
    view of the collate buffer — then placed with ``device_put``: on CPU
    placement is the identity (no copy anywhere), on TPU/GPU it is the H2D
    DMA and nothing else.  Demoted dtypes skip the import (the cast is the
    copy).  The caller owns the lifetime question: an aliased delivery
    borrows the collate buffer, which is exactly what
    :func:`delivery_copies` lets it check."""
    import jax

    def put_leaf(x):
        if isinstance(x, np.ndarray) and x.flags.c_contiguous:
            try:
                if _canonical_dtype(x.dtype) == x.dtype:
                    imported = jax.dlpack.from_dlpack(x)
                    # placement still runs: on CPU it is the identity (the
                    # imported alias passes through), on TPU/GPU it is the
                    # H2D transfer — from_dlpack alone would leave the
                    # leaf committed to the host backend
                    if sharding is None:
                        return jax.device_put(imported)
                    return jax.device_put(imported, sharding)
            except Exception:
                pass  # protocol/backend gap: plain device_put is correct
        return jax.device_put(x, sharding) if sharding is not None else jax.device_put(x)

    return jax.tree_util.tree_map(put_leaf, batch)
