"""Device-resident dataset replay: the HBM-budgeted residency manager.

Epoch 1 of a training run streams the table — decode, merge, collate,
``device_put`` — and *offers* every delivered device batch to a
:class:`DeviceReplayCache`.  The cache pins offered batches (per device:
a sharded batch costs each chip only its shard) until the declared HBM
budget (``LAKESOUL_REPLAY_BUDGET_BYTES``, per device) is reached.  From
epoch 2 on, the loader serves the pinned shards straight from device
memory — zero storage, host, and link traffic; the ``train_hbm`` role
grown into a subsystem — optionally re-permuted on device each epoch
under a pinned seed.

Budget overflow is not an error: the first offer that would cross the
budget flips the cache into *spilled* mode — a typed
:class:`ReplaySpill` record, metered in
``lakesoul_replay_spilled_batches_total`` /
``lakesoul_replay_spilled_bytes_total`` — after which later epochs
replay the resident prefix from HBM and re-stream only the tail through
the normal streaming path (the offers stop at the first rejection, so
the resident set is always a contiguous prefix and the tail resume
position is exactly ``resident_rows``).

State machine::

    filling --offer() within budget--> filling (batch pinned)
    filling --offer() over budget----> filling/spilled (typed + metered)
    filling --seal()  (epoch done)---> ready          (replay serves)
    filling --abandon() (epoch broken)-> empty        (partial replay
                                                       would drop data)

Residency accounting is *per device*: each leaf bills
``nbytes / |sharding.device_set|`` — eight chips holding one batch-
sharded epoch each pay an eighth of it.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from lakesoul_tpu.errors import ConfigError
from lakesoul_tpu.obs import registry

ENV_BUDGET = "LAKESOUL_REPLAY_BUDGET_BYTES"

_PERMUTE_FN = None  # lazily-jitted on-device row permutation


@dataclass(frozen=True)
class ReplaySpill:
    """The typed record of one cache's budget overflow: which offer
    crossed the line and what stayed resident.  Carried by
    :attr:`DeviceReplayCache.spill` (and logged once); later epochs keep
    working — resident prefix from HBM, tail from the stream."""

    budget_bytes: int
    batch_rows: int
    batch_bytes: int
    resident_batches: int
    resident_bytes: int


def _batch_device_bytes(batch) -> int:
    """Per-device residency cost of one delivered device batch: each leaf
    bills the bytes ONE device actually holds — its shard shape, which for
    a replicated leaf (``P()``) is the full array, not ``nbytes / ndev``
    (dividing by the device count would under-bill replication by the
    replication factor and turn the budget's graceful spill into an HBM
    OOM on a real pod)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        nbytes = getattr(leaf, "nbytes", 0)
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            try:
                import math

                shard = sharding.shard_shape(leaf.shape)
                total += math.prod(shard) * leaf.dtype.itemsize
                continue
            except Exception:
                pass  # fall through to the whole-leaf conservative bill
        total += nbytes
    return total


def _permute_on_device(batch, key):
    """Row-permute every leading-dim leaf of ``batch`` on device (jitted
    once per pytree shape): the permutation index is drawn and applied by
    the backend — no host traffic, which is the whole point of replay."""
    global _PERMUTE_FN
    import jax

    if _PERMUTE_FN is None:
        def _permute(b, k):
            leaves = jax.tree_util.tree_leaves(b)
            n = leaves[0].shape[0] if leaves and leaves[0].ndim else 0
            idx = jax.random.permutation(k, n)
            return jax.tree_util.tree_map(
                lambda x: x[idx] if x.ndim and x.shape[0] == n else x, b
            )

        _PERMUTE_FN = jax.jit(_permute)
    return _PERMUTE_FN(batch, key)


class DeviceReplayCache:
    """Sharded, HBM-budgeted residency manager for one loader's epochs.

    Args:
        budget_bytes: per-device pin budget; default from
            ``LAKESOUL_REPLAY_BUDGET_BYTES``; ``None``/unset = unbounded
            (the caller opted into whole-epoch residency knowing
            rows × bytes/row).
        permute: re-permute rows *within* each resident batch on device
            every replay epoch (seeded, deterministic); batch order is
            shuffled too.  Only honoured while fully resident — a spilled
            cache replays its prefix in stream order so the hybrid epoch
            stays position-exact against the streamed tail.
        seed: permutation seed; the (seed, epoch, batch) triple fully
            determines every draw, so two runs under one seed deliver
            identical epochs.
    """

    def __init__(self, *, budget_bytes: int | None = None,
                 permute: bool = False, seed: int = 0):
        if budget_bytes is None:
            raw = os.environ.get(ENV_BUDGET)
            if raw is not None:
                try:
                    budget_bytes = int(raw)
                except ValueError:
                    raise ConfigError(
                        f"{ENV_BUDGET} must be an integer byte count, got"
                        f" {raw!r}"
                    )
        if budget_bytes is not None and budget_bytes <= 0:
            raise ConfigError(
                f"replay budget must be positive, got {budget_bytes}"
            )
        self.budget_bytes = budget_bytes
        self.permute = permute
        self.seed = seed
        self.ready = False
        self.spill: ReplaySpill | None = None
        self._batches: list[tuple[int, object]] = []  # (rows, device pytree)
        self._resident_bytes = 0
        self._resident_rows = 0
        self._epochs_served = 0
        reg = registry()
        self._g_bytes = reg.gauge("lakesoul_replay_resident_bytes")
        self._g_batches = reg.gauge("lakesoul_replay_resident_batches")
        self._c_spill_b = reg.counter("lakesoul_replay_spilled_batches_total")
        self._c_spill_bytes = reg.counter("lakesoul_replay_spilled_bytes_total")
        self._c_epochs = reg.counter("lakesoul_replay_epochs_total")
        self._c_rows = reg.counter("lakesoul_replay_served_rows_total")

    # ------------------------------------------------------------- filling
    @property
    def spilled(self) -> bool:
        return self.spill is not None

    @property
    def resident_rows(self) -> int:
        """Rows covered by the pinned prefix — the streamed-tail resume
        position of a spilled cache (the scan's deterministic unit order
        makes a row count a complete position, same as the loader
        checkpoint)."""
        return self._resident_rows

    @property
    def resident_bytes(self) -> int:
        return self._resident_bytes

    @property
    def resident_batches(self) -> int:
        return len(self._batches)

    def offer(self, rows: int, batch) -> bool:
        """Offer one delivered device batch for pinning during the filling
        epoch.  Returns True when pinned (the cache now holds a reference;
        the caller must hand its consumer fresh containers).  The first
        offer past the budget records the typed spill and every later
        offer is refused without accounting — the resident set stays a
        contiguous prefix."""
        if self.ready:
            raise ConfigError("offer() after seal(): the cache is serving")
        cost = _batch_device_bytes(batch)
        if self.spilled:
            # EVERY refused batch is metered, not just the one that crossed
            # the budget: the spilled_* counters are what an operator sizes
            # LAKESOUL_REPLAY_BUDGET_BYTES from, and counting one batch
            # when half the epoch re-streams would read as negligible
            self._c_spill_b.inc()
            self._c_spill_bytes.inc(cost)
            return False
        if self.budget_bytes is not None and \
                self._resident_bytes + cost > self.budget_bytes:
            self.spill = ReplaySpill(
                budget_bytes=self.budget_bytes,
                batch_rows=rows,
                batch_bytes=cost,
                resident_batches=len(self._batches),
                resident_bytes=self._resident_bytes,
            )
            self._c_spill_b.inc()
            self._c_spill_bytes.inc(cost)
            import logging

            logging.getLogger(__name__).info(
                "replay cache spilled: batch of %d rows (%d B/device) would"
                " cross the %d B budget; %d batches / %d B stay resident,"
                " later epochs re-stream the tail",
                rows, cost, self.budget_bytes, len(self._batches),
                self._resident_bytes,
            )
            return False
        self._batches.append((rows, batch))
        self._resident_bytes += cost
        self._resident_rows += rows
        self._g_bytes.set(self._resident_bytes)
        self._g_batches.set(len(self._batches))
        return True

    def seal(self) -> None:
        """The filling epoch completed: the cache starts serving.  A
        spilled cache seals too — it serves its prefix; only an *abandoned*
        epoch (consumer break) discards, partial replay would silently
        drop data."""
        self.ready = True

    def abandon(self) -> None:
        """The filling epoch did not complete: drop every pin (the device
        memory comes back) and stay in streaming mode."""
        if self.ready:
            return
        self._batches.clear()
        self._resident_bytes = 0
        self._resident_rows = 0
        self.spill = None
        self._g_bytes.set(0)
        self._g_batches.set(0)

    # ------------------------------------------------------------- serving
    def replay(self):
        """Yield ``(rows, device_batch)`` for one replay epoch, entirely
        from device memory.  With ``permute`` on a fully-resident cache:
        batch order is shuffled and each batch's rows are permuted on
        device, both drawn from (seed, epoch) so replays are
        deterministic per epoch and different across epochs."""
        if not self.ready:
            raise ConfigError("replay() before seal(): the cache is filling")
        epoch = self._epochs_served
        self._epochs_served += 1
        self._c_epochs.inc()
        order = range(len(self._batches))
        do_permute = self.permute and not self.spilled
        if do_permute:
            import numpy as np

            order = np.random.default_rng((self.seed, epoch)).permutation(
                len(self._batches)
            )
        for pos in order:
            rows, batch = self._batches[pos]
            if do_permute:
                import jax

                key = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch),
                    int(pos),
                )
                batch = _permute_on_device(batch, key)
            self._c_rows.inc(rows)
            yield rows, batch

    def stats(self) -> dict:
        return {
            "ready": self.ready,
            "spilled": self.spilled,
            "resident_batches": len(self._batches),
            "resident_rows": self._resident_rows,
            "resident_bytes": self._resident_bytes,
            "budget_bytes": self.budget_bytes,
            "epochs_served": self._epochs_served,
            "permute": self.permute,
        }
