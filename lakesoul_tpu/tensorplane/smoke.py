"""One-command TPU re-validation: the on-chip claim registry.

Every Pallas kernel and ``parallel/`` leg since round 1 has only ever run
on CPU fallback, and PR 9's ring-aliasing find is exactly the class of
claim only a real device settles.  This module makes re-validating all of
it a single command (``python tools/tpu_smoke.py``):

- a **registry** of :class:`SmokeCase`\\ s — one per Pallas kernel (each
  case names the kernel functions it compiles, by lakelint device-index
  qname), one per multichip shape (the annplane cross-chip top-k merge
  and the parallel mesh/pipeline/moe dryrun), and one per tensorplane
  delivery/replay path;
- :func:`enumerate_pallas_kernels` — the ground truth: lakelint's device
  index re-parses the package and lists every ``pl.pallas_call`` kernel,
  so the "registry covers 100% of Pallas kernels" claim is machine-checked
  (``kernel_enumeration.uncovered`` must be empty; a new kernel that
  forgets to register FAILS the smoke run and its CI test);
- :func:`run_smoke` — on a reachable TPU, compile and run every case
  on-chip with per-case pass/fail + wall seconds; on CPU fallback, run
  each kernel in Pallas interpret mode against its jnp twin (the
  differential contract still holds) and record the complete
  ``untested_on_tpu: [...]`` list, so ONE live-tunnel session replays the
  whole register with zero hand work.

Host readbacks below exist to *verify* device results — that is the one
sanctioned reason to round-trip device memory in this package, and each
site carries its ``replay-host-roundtrip`` pragma saying so.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np


@dataclass(frozen=True)
class SmokeCase:
    """One on-chip claim: ``run(on_tpu)`` must raise on any divergence and
    may return a detail dict for the record.  ``kernels`` are the lakelint
    device-index qnames this case compiles (empty for non-Pallas shapes);
    ``min_devices`` gates collective shapes; ``heavy`` cases (model
    training dryruns) run on TPU but are skipped — and recorded — on CPU
    unless forced."""

    name: str
    kind: str  # "pallas" | "multichip" | "tensorplane"
    run: Callable[[bool], dict | None]
    kernels: tuple[str, ...] = ()
    min_devices: int = 1
    heavy: bool = False


# ------------------------------------------------------------------ pallas


def _rng(seed: int = 0):
    return np.random.default_rng(seed)


def _packed_inputs(n: int = 600, d: int = 64, seed: int = 0):
    rng = _rng(seed)
    codes = rng.integers(0, 256, (n, d // 8)).astype(np.uint8)
    norms = rng.random(n).astype(np.float32) + 0.1
    factors = rng.random(n).astype(np.float32) + 0.5
    q_rot = rng.normal(size=d).astype(np.float32)
    return codes, norms, factors, q_rot


def _run_packed_scan(on_tpu: bool) -> dict:
    import jax.numpy as jnp

    from lakesoul_tpu.vector.kernels import packed_scan_pallas
    from lakesoul_tpu.vector.rabitq import estimate_distances

    codes, norms, factors, q_rot = _packed_inputs()
    d = q_rot.shape[0]
    got = packed_scan_pallas(
        jnp.asarray(codes), jnp.asarray(norms), jnp.asarray(factors),
        jnp.asarray(q_rot), d=d, interpret=not on_tpu,
    )
    want = estimate_distances(
        jnp.asarray(codes), jnp.asarray(norms), jnp.asarray(factors),
        jnp.asarray(q_rot), d=d,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4  # lakelint: ignore[replay-host-roundtrip] verification readback: differential-test the on-chip result against the jnp twin
    )
    return {"rows": len(codes), "d": d}


def _run_packed_dot(on_tpu: bool) -> dict:
    import jax.numpy as jnp

    from lakesoul_tpu.vector.kernels import _packed_dot_jnp, packed_dot_pallas

    codes, _, _, q_rot = _packed_inputs(seed=1)
    got = packed_dot_pallas(
        jnp.asarray(codes), jnp.asarray(q_rot), interpret=not on_tpu
    )
    want = _packed_dot_jnp(jnp.asarray(codes), jnp.asarray(q_rot))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4  # lakelint: ignore[replay-host-roundtrip] verification readback: differential-test the on-chip result against the jnp twin
    )
    return {"rows": len(codes)}


def _run_packed_dot_batch(on_tpu: bool) -> dict:
    import jax.numpy as jnp

    from lakesoul_tpu.vector.kernels import packed_dot_batch_pallas
    from lakesoul_tpu.vector.rabitq import unpack_bits_jnp

    codes, _, _, _ = _packed_inputs(seed=2)
    d = codes.shape[1] * 8
    queries = _rng(3).normal(size=(4, d)).astype(np.float32)
    got = packed_dot_batch_pallas(
        jnp.asarray(codes), jnp.asarray(queries), interpret=not on_tpu
    )
    bits = unpack_bits_jnp(jnp.asarray(codes), d)
    want = bits @ jnp.asarray(queries).T
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4  # lakelint: ignore[replay-host-roundtrip] verification readback: differential-test the on-chip result against the jnp twin
    )
    return {"rows": len(codes), "queries": len(queries)}


def _run_bruteforce(on_tpu: bool) -> dict:
    import jax.numpy as jnp

    from lakesoul_tpu.vector.kernels import (
        _bruteforce_jnp,
        bruteforce_distances_pallas,
    )

    rng = _rng(4)
    vectors = rng.normal(size=(700, 32)).astype(np.float32)
    query = rng.normal(size=32).astype(np.float32)
    got = bruteforce_distances_pallas(
        jnp.asarray(np.pad(vectors, ((0, 1024 - 700), (0, 0)))),
        jnp.asarray(query), interpret=not on_tpu,
    )[:700]
    want = _bruteforce_jnp(jnp.asarray(vectors), jnp.asarray(query))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4  # lakelint: ignore[replay-host-roundtrip] verification readback: differential-test the on-chip result against the jnp twin
    )
    return {"rows": 700}


def _run_ragged(on_tpu: bool) -> dict:
    from lakesoul_tpu.annplane.ragged import (
        TILE,
        ragged_score_jnp,
        ragged_score_pallas,
    )

    rng = _rng(5)
    d, ntiles, nq = 32, 3, 2
    codes = rng.normal(size=(ntiles * TILE, d)).astype(np.float32)
    a = rng.random(ntiles * TILE).astype(np.float32)
    b = rng.random(ntiles * TILE).astype(np.float32)
    h = rng.random(ntiles * TILE).astype(np.float32)
    q_glob = rng.normal(size=(nq, d)).astype(np.float32)
    item_q = np.array([0, 0, 1, 1, 1], np.int32)
    item_tile = np.array([0, 2, 0, 1, 2], np.int32)
    csq = rng.random(len(item_q)).astype(np.float32)
    csum = rng.random(len(item_q)).astype(np.float32)
    got = ragged_score_pallas(
        item_q, item_tile, csq, csum, q_glob, codes, a, b, h,
        interpret=not on_tpu,
    )
    want = ragged_score_jnp(item_q, item_tile, csq, csum, q_glob, codes, a, b, h)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    return {"items": len(item_q), "tile": TILE}


# --------------------------------------------------------------- multichip


def _run_cross_chip_topk(on_tpu: bool) -> dict:
    import jax

    from lakesoul_tpu.annplane.collective import dryrun_multichip

    n = len(jax.devices())
    return {"devices": n, "k": 10, **{"ok": bool(dryrun_multichip(n))}}


def _run_parallel_dryrun(on_tpu: bool) -> dict:
    """The three parallel multichip shapes (mesh scan→train, pipeline,
    moe) via the repo's dryrun entry — heavy (tiny-model train steps), so
    CPU runs skip it unless forced."""
    import importlib.util
    import pathlib

    import jax

    root = pathlib.Path(__file__).resolve().parents[2]
    spec = importlib.util.spec_from_file_location(
        "_lakesoul_graft_entry", root / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    n = len(jax.devices())
    mod.dryrun_multichip(n)
    return {"devices": n}


# -------------------------------------------------------------- tensorplane


def _run_dlpack_delivery(on_tpu: bool) -> dict:
    """The zero-copy delivery claim, measured where it can be: on a host
    backend the delivered float32 leaf must ALIAS the collate buffer (no
    host copy anywhere); on TPU ``delivery_copies(float32)`` must be True
    — the H2D link copy is real, which is precisely the condition that
    keeps the collate ring armed on-chip (the PR-9 disarm rule's other
    half, checkable only here)."""
    from lakesoul_tpu.tensorplane.dlpack import (
        aligned_empty,
        deliver,
        device_put_copies,
    )

    rng = _rng(6)
    batch = {
        "x": aligned_empty((256, 8), np.float32),
        "y": aligned_empty((256,), np.int32),
    }
    batch["x"][:] = rng.normal(size=(256, 8)).astype(np.float32)
    batch["y"][:] = rng.integers(0, 100, 256).astype(np.int32)
    out = deliver(batch)
    for k in batch:
        np.testing.assert_array_equal(
            np.asarray(out[k]), batch[k]  # lakelint: ignore[replay-host-roundtrip] verification readback: delivered values must round-trip exactly
        )
    f32_copies = device_put_copies(np.float32)
    if on_tpu:
        assert f32_copies, (
            "device_put(float32) on TPU must be a REAL copy across the"
            " link — the collate ring's stay-armed condition"
        )
    else:
        try:
            aliased = out["x"].unsafe_buffer_pointer() == batch["x"].ctypes.data
        except Exception:
            aliased = not f32_copies
        assert aliased, (
            "DLPack delivery on a host backend must alias the collate"
            " buffer (zero host copies)"
        )
    return {"f32_device_put_copies": bool(f32_copies)}


def _run_replay_cache(on_tpu: bool) -> dict:
    """Pin a four-batch epoch, replay it twice from device memory, and
    check byte-exact equality plus the permutation contract under a pinned
    seed."""
    from lakesoul_tpu.tensorplane.dlpack import deliver
    from lakesoul_tpu.tensorplane.replay import DeviceReplayCache

    rng = _rng(7)
    host = [
        {"x": rng.normal(size=(64, 4)).astype(np.float32)} for _ in range(4)
    ]
    cache = DeviceReplayCache(budget_bytes=1 << 20)
    for hb in host:
        assert cache.offer(64, deliver(hb))
    cache.seal()
    for _ in range(2):
        got = [b for _, b in cache.replay()]
        assert len(got) == len(host)
        for dev, hb in zip(got, host):
            np.testing.assert_array_equal(
                np.asarray(dev["x"]), hb["x"]  # lakelint: ignore[replay-host-roundtrip] verification readback: replayed shards must be byte-identical to the pinned epoch
            )
    perm = DeviceReplayCache(budget_bytes=1 << 20, permute=True, seed=3)
    for hb in host:
        assert perm.offer(64, deliver(hb))
    perm.seal()
    seen = [b for _, b in perm.replay()]
    flat_in = np.sort(np.concatenate([hb["x"].ravel() for hb in host]))
    flat_out = np.sort(
        np.concatenate([np.asarray(b["x"]).ravel() for b in seen])  # lakelint: ignore[replay-host-roundtrip] verification readback: permutation must preserve the multiset
    )
    np.testing.assert_array_equal(flat_out, flat_in)
    return {"batches": len(host), "epochs": 2}


# ------------------------------------------------------------ the register


def smoke_cases() -> list[SmokeCase]:
    return [
        SmokeCase(
            "vector.packed_scan", "pallas", _run_packed_scan,
            kernels=("lakesoul_tpu/vector/kernels.py::_packed_scan_kernel",),
        ),
        SmokeCase(
            "vector.packed_dot", "pallas", _run_packed_dot,
            kernels=("lakesoul_tpu/vector/kernels.py::_packed_dot_kernel",),
        ),
        SmokeCase(
            "vector.packed_dot_batch", "pallas", _run_packed_dot_batch,
            kernels=(
                "lakesoul_tpu/vector/kernels.py::_packed_dot_batch_kernel",
            ),
        ),
        SmokeCase(
            "vector.bruteforce", "pallas", _run_bruteforce,
            kernels=("lakesoul_tpu/vector/kernels.py::_bruteforce_kernel",),
        ),
        SmokeCase(
            "annplane.ragged_score", "pallas", _run_ragged,
            kernels=("lakesoul_tpu/annplane/ragged.py::_ragged_score_kernel",),
        ),
        SmokeCase(
            "annplane.cross_chip_topk", "multichip", _run_cross_chip_topk,
            min_devices=2,
        ),
        SmokeCase(
            "parallel.mesh_pipeline_moe", "multichip", _run_parallel_dryrun,
            min_devices=2, heavy=True,
        ),
        SmokeCase(
            "tensorplane.dlpack_delivery", "tensorplane", _run_dlpack_delivery,
        ),
        SmokeCase(
            "tensorplane.replay_cache", "tensorplane", _run_replay_cache,
        ),
    ]


def enumerate_pallas_kernels() -> list[str]:
    """Ground truth for the 100%-coverage claim: lakelint's device index
    re-parses the package and returns every ``pl.pallas_call`` kernel
    qname.  The registry is checked against THIS, not against a hand list
    that rots."""
    from lakesoul_tpu.analysis.engine import Module, Project, package_root
    from lakesoul_tpu.analysis.rules.jaxtpu import device_index

    pkg = package_root()
    project = Project(root=pkg.parent)
    for path in sorted(pkg.rglob("*.py")):
        mod = Module.load(path, pkg.parent)
        if mod is not None:
            project.modules.append(mod)
    return sorted(device_index(project).pallas_kernels)


def run_smoke(*, force_heavy: bool = False) -> dict:
    """Run the register and return the report dict (see module docstring).

    ``report["ok"]`` is False when any case failed OR the enumeration
    found a kernel no case covers — a new Pallas kernel cannot land
    without joining the register."""
    import jax

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    n_devices = len(jax.devices())
    cases = smoke_cases()
    results = []
    failed = False
    for case in cases:
        entry = {"name": case.name, "kind": case.kind,
                 "kernels": list(case.kernels)}
        if case.min_devices > n_devices:
            entry["status"] = "skipped"
            entry["detail"] = (
                f"needs >= {case.min_devices} devices, have {n_devices}"
            )
        elif case.heavy and not on_tpu and not force_heavy:
            entry["status"] = "skipped"
            entry["detail"] = "heavy case: runs on TPU (or with --heavy)"
        else:
            t0 = time.perf_counter()
            try:
                detail = case.run(on_tpu)
                entry["status"] = "pass" if on_tpu else "cpu_fallback_pass"
                if detail:
                    entry["detail"] = detail
            except Exception as e:  # record, keep going: one bad kernel
                entry["status"] = "fail"  # must not hide the rest
                entry["error"] = f"{type(e).__name__}: {e}"
                failed = True
            entry["seconds"] = round(time.perf_counter() - t0, 3)
        results.append(entry)

    enumerated = enumerate_pallas_kernels()
    covered = sorted({k for c in cases for k in c.kernels})
    uncovered = sorted(set(enumerated) - set(covered))
    # the untested record must stay COMPLETE on a TPU run too: a case the
    # run skipped (mesh too narrow for a multichip shape) has NOT been
    # validated on-chip, and dropping it from the list would make a
    # single-chip tunnel session read as a full re-validation
    if on_tpu:
        untested = [e["name"] for e in results if e["status"] == "skipped"]
    else:
        untested = [c.name for c in cases]
    report = {
        "platform": platform,
        "device_count": n_devices,
        "on_tpu": on_tpu,
        "jax": jax.__version__,
        "cases": results,
        "kernel_enumeration": {
            "enumerated": enumerated,
            "covered": covered,
            "uncovered": uncovered,
        },
        "untested_on_tpu": untested,
        "ok": not failed and not uncovered,
    }
    return report
