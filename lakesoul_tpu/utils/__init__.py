from lakesoul_tpu.utils.spark_hash import HASH_SEED, hash_columns, hash_scalar, bucket_ids

__all__ = ["HASH_SEED", "hash_columns", "hash_scalar", "bucket_ids"]


def honor_platform_env() -> None:
    """Make JAX_PLATFORMS env authoritative before backend init.

    The axon boot hook (sitecustomize) pins ``jax.config.jax_platforms`` to
    "axon,cpu", which silently overrides a caller-set ``JAX_PLATFORMS=cpu``
    env var — and a wedged TPU tunnel then hangs backend init.  Call this
    before the first jax array op in scripts that honor the env var."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass  # jax already initialized; too late to switch
