from lakesoul_tpu.utils.spark_hash import HASH_SEED, hash_columns, hash_scalar, bucket_ids

__all__ = ["HASH_SEED", "hash_columns", "hash_scalar", "bucket_ids"]
