"""Process memory accounting helpers."""

from __future__ import annotations


def current_rss_mb() -> float:
    """This process's CURRENT resident set, in MiB (``/proc/self/statm``).

    Unlike the high-water counters (``ru_maxrss``, ``VmHWM``), the current
    RSS can never leak a forked parent's footprint through ``execve`` —
    see :func:`peak_rss_mb` for why that matters — so a subprocess that
    samples this at its own cadence (e.g. once per consumed batch) gets a
    peak that is genuinely ITS OWN on every kernel, emulated or not.
    Returns 0.0 where /proc is absent."""
    import os

    try:
        with open("/proc/self/statm") as f:
            resident_pages = int(f.read().split()[1])
        return resident_pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)
    except (OSError, ValueError, IndexError):
        return 0.0


def peak_rss_mb() -> float:
    """This process's peak resident set, in MiB — with a caveat.

    ``getrusage(RUSAGE_SELF).ru_maxrss`` is the obvious API but carries a
    Linux quirk that poisons subprocess measurements: ``maxrss`` lives on
    the signal struct, which SURVIVES ``execve`` — a worker forked from a
    large parent (pytest after a long session, a bench driver that just
    built a 100M-row table) reports the PARENT's high-water mark, not its
    own.  ``VmHWM`` in ``/proc/self/status`` is per-``mm`` and resets at
    exec on mainline Linux, so it is preferred; ru_maxrss remains the
    fallback where /proc is absent.

    CAVEAT (proven in tests/test_stream_ceiling.py's history): sandboxed
    kernels that emulate /proc (gVisor reports "Linux 4.4.0") serve VmHWM
    from the same exec-surviving usage counter as ru_maxrss, so under
    those a fresh child still reports max(parent peak, own peak).  A
    subprocess asserting a ceiling on ITSELF must sample
    :func:`current_rss_mb` instead of trusting any high-water counter."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    import resource
    import sys

    maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # linux reports KiB; macOS reports BYTES (the only common /proc-less host)
    return maxrss / (1 << 20) if sys.platform == "darwin" else maxrss / 1024.0
