"""Process memory accounting helpers."""

from __future__ import annotations


def peak_rss_mb() -> float:
    """This process's OWN peak resident set, in MiB.

    ``getrusage(RUSAGE_SELF).ru_maxrss`` is the obvious API but carries a
    Linux quirk that poisons subprocess measurements: ``maxrss`` lives on
    the signal struct, which SURVIVES ``execve`` — a worker forked from a
    large parent (pytest after a long session, a bench driver that just
    built a 100M-row table) reports the PARENT's high-water mark, not its
    own.  ``VmHWM`` in ``/proc/self/status`` is per-``mm`` and resets at
    exec, so it measures the process itself; ru_maxrss remains the
    fallback where /proc is absent."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    import resource
    import sys

    maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # linux reports KiB; macOS reports BYTES (the only common /proc-less host)
    return maxrss / (1 << 20) if sys.platform == "darwin" else maxrss / 1024.0
