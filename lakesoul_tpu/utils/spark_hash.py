"""Spark-compatible Murmur3 (x86_32, seed 42) row hashing, vectorized with numpy.

Byte-compatibility with the reference implementation
(rust/lakesoul-io/src/utils/hash/{mod.rs,spark_murmur3.rs}) is a hard
requirement: hash-bucket assignment decides which file a primary key lives in,
so a framework that hashes differently cannot read reference-written tables and
its bucket pruning (reader.rs:164-225) would be wrong.

Semantics reproduced (verified against the reference's behavior):

- Core is Murmur3 x86 32-bit, but the tail (< 4 remaining bytes) is processed
  **one byte at a time, each byte as a full mixed block** (Spark's
  ``hashUnsafeBytes`` quirk), with the total byte count in the finalizer.
- Integer types up to 32 bits (bool, i8, i16, i32, u8, u16, u32) hash as the
  value **sign-extended to u32**, little-endian, one block.
- 64-bit ints hash as 8 LE bytes (two blocks); 128-bit as 16 bytes.
- Floats bitcast to their unsigned int of the same width, except ``-0.0``
  which hashes as ``0``; f32 → one block, f64 → two blocks.
- Strings/binary hash their raw bytes (UTF-8 for strings).
- Null rows do **not** update the hash buffer (first column → hash 0).
- Multi-column hashing chains: column *i*'s per-row hash value seeds column
  *i+1* (``rehash`` in the reference).

The vectorized numpy implementation processes whole columns at once; an
optional C++ kernel (lakesoul_tpu/native) accelerates string columns.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

HASH_SEED = 42

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_FMIX1 = np.uint32(0x85EBCA6B)
_FMIX2 = np.uint32(0xC2B2AE35)
_M = np.uint32(5)
_N = np.uint32(0xE6546B64)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k(k: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        k = (k * _C1).astype(np.uint32)
        k = _rotl32(k, 15)
        return (k * _C2).astype(np.uint32)


def _mix_h(h: np.ndarray, k: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = h ^ _mix_k(k)
        h = _rotl32(h, 13)
        return (h * _M + _N).astype(np.uint32)


def _fmix(h: np.ndarray, length: np.ndarray | int) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = h ^ np.uint32(length) if np.isscalar(length) else h ^ length.astype(np.uint32)
        h = h ^ (h >> np.uint32(16))
        h = (h * _FMIX1).astype(np.uint32)
        h = h ^ (h >> np.uint32(13))
        h = (h * _FMIX2).astype(np.uint32)
        return h ^ (h >> np.uint32(16))


def murmur3_bytes(data: bytes, seed: int = HASH_SEED) -> int:
    """Scalar Spark-variant Murmur3 over raw bytes (byte-wise tail)."""
    h = np.uint32(seed)
    n = len(data)
    nblocks = n // 4
    if nblocks:
        blocks = np.frombuffer(data[: nblocks * 4], dtype="<u4")
        for k in blocks:
            h = _mix_h(h, np.uint32(k))
    for b in data[nblocks * 4 :]:
        h = _mix_h(h, np.uint32(b))
    return int(_fmix(h, n))


def _hash_u32_blocks(blocks: np.ndarray, seeds: np.ndarray, nbytes: int) -> np.ndarray:
    """Vectorized hash of fixed-width rows. blocks: (n, nblocks) uint32 LE."""
    h = seeds.astype(np.uint32, copy=True)
    for j in range(blocks.shape[1]):
        h = _mix_h(h, blocks[:, j])
    return _fmix(h, nbytes)


def _seed_array(n: int, seeds) -> np.ndarray:
    if seeds is None:
        return np.full(n, HASH_SEED, dtype=np.uint32)
    return np.asarray(seeds, dtype=np.uint32)


def hash_int_array(values: np.ndarray, seeds=None) -> np.ndarray:
    """Hash ≤32-bit integers / bools: sign-extend to u32, one LE block."""
    v = np.asarray(values)
    if v.dtype == np.bool_:
        v = v.astype(np.int32)
    from lakesoul_tpu import native

    if native.available() and len(v):
        # int32 cast sign-extends smaller ints; the kernel wraps to u32 —
        # identical to the numpy sign-extend-then-wrap below
        out = np.empty(len(v), dtype=np.uint32)
        seeds_arr = None if seeds is None else np.ascontiguousarray(seeds, np.uint32)
        native.hash_i32(v.astype(np.int32, copy=False), seeds_arr, None, out, HASH_SEED)
        return out
    u = v.astype(np.int64).astype(np.uint32).reshape(-1, 1)  # sign-extend then wrap
    return _hash_u32_blocks(u, _seed_array(len(u), seeds), 4)


def hash_long_array(values: np.ndarray, seeds=None) -> np.ndarray:
    """Hash 64-bit integers: 8 LE bytes = two u32 blocks (low then high)."""
    raw = np.asarray(values)
    from lakesoul_tpu import native

    if native.available() and len(raw):
        i64 = raw.view(np.int64) if raw.dtype == np.uint64 else np.ascontiguousarray(raw, np.int64)
        out = np.empty(len(raw), dtype=np.uint32)
        seeds_arr = None if seeds is None else np.ascontiguousarray(seeds, np.uint32)
        native.hash_i64(i64, seeds_arr, None, out, HASH_SEED)
        return out
    u = raw.astype(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    return _hash_u32_blocks(np.stack([lo, hi], axis=1), _seed_array(len(u), seeds), 8)


def hash_float_array(values: np.ndarray, seeds=None) -> np.ndarray:
    v = np.asarray(values)
    if v.dtype == np.float32:
        # -0.0 hashes as integer 0 in the reference
        neg_zero = np.signbit(v) & (v == 0)
        bits = np.where(neg_zero, np.uint32(0), v.view(np.uint32))
        return _hash_u32_blocks(bits.reshape(-1, 1), _seed_array(len(v), seeds), 4)
    elif v.dtype == np.float64:
        neg_zero = np.signbit(v) & (v == 0)
        bits = np.where(neg_zero, np.uint64(0), v.view(np.uint64))
        lo = (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (bits >> np.uint64(32)).astype(np.uint32)
        return _hash_u32_blocks(np.stack([lo, hi], axis=1), _seed_array(len(v), seeds), 8)
    raise TypeError(f"unsupported float dtype {v.dtype}")


def hash_bytes_list(values, seeds=None) -> np.ndarray:
    """Hash variable-length byte strings.  Rows are grouped by length so each
    group vectorizes (full LE words, then byte-wise tail)."""
    n = len(values)
    seeds = _seed_array(n, seeds)
    out = np.zeros(n, dtype=np.uint32)
    lengths = np.fromiter((len(v) for v in values), dtype=np.int64, count=n)
    for length in np.unique(lengths):
        idx = np.nonzero(lengths == length)[0]
        L = int(length)
        if L == 0:
            out[idx] = _fmix(seeds[idx].copy(), 0)
            continue
        buf = np.empty((len(idx), L), dtype=np.uint8)
        for row, i in enumerate(idx):
            buf[row] = np.frombuffer(values[i], dtype=np.uint8)
        h = seeds[idx].astype(np.uint32, copy=True)
        nblocks = L // 4
        if nblocks:
            words = buf[:, : nblocks * 4].view("<u4")
            for j in range(nblocks):
                h = _mix_h(h, words[:, j])
        for j in range(nblocks * 4, L):
            h = _mix_h(h, buf[:, j].astype(np.uint32))
        out[idx] = _fmix(h, L)
    return out


def hash_array(arr: pa.Array, seeds=None, *, null_values: np.ndarray | None = None) -> np.ndarray:
    """Hash one Arrow array; null rows keep their hash-buffer value unchanged
    (0 for the first column — the reference zero-initializes the buffer,
    repartition/mod.rs:246), matching hash_array_primitive in the reference.
    ``seeds`` seeds the hash of valid rows; ``null_values`` supplies the
    passthrough value for null rows (defaults to ``seeds``)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    seeds_arr = _seed_array(n, seeds)
    null_arr = seeds_arr if null_values is None else np.asarray(null_values, dtype=np.uint32)
    t = arr.type
    valid = np.ones(n, dtype=bool)
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
        # hash only valid rows; null rows pass their incoming buffer through
        filled = arr.drop_null()
    else:
        filled = arr

    if pa.types.is_dictionary(t):
        # hash the decoded values (same logical value → same hash)
        return hash_array(arr.cast(t.value_type), seeds, null_values=null_values)

    def _dispatch(a: pa.Array, s: np.ndarray) -> np.ndarray:
        ty = a.type
        if pa.types.is_boolean(ty):
            return hash_int_array(np.asarray(a.cast(pa.int32())), s)
        if pa.types.is_integer(ty):
            if ty.bit_width <= 32:
                return hash_int_array(np.asarray(a), s)
            return hash_long_array(np.asarray(a), s)
        if pa.types.is_floating(ty):
            if ty.bit_width == 16:
                v16 = np.asarray(a).astype(np.float16)
                neg_zero = np.signbit(v16) & (v16 == 0)
                bits = np.where(neg_zero, np.uint16(0), v16.view(np.uint16))
                return hash_int_array(bits.astype(np.uint32), s)
            return hash_float_array(np.asarray(a), s)
        if pa.types.is_decimal(ty):
            # hash the raw unscaled storage (i128/i256 LE bytes), like the
            # reference's Decimal128/256 HashValue impls — NOT the rounded
            # Python value
            width = ty.byte_width  # 16 for decimal128, 32 for decimal256
            raw = np.frombuffer(a.buffers()[1], dtype=np.uint8)
            start = a.offset * width
            bufs = [
                raw[start + i * width : start + (i + 1) * width].tobytes()
                for i in range(len(a))
            ]
            return hash_bytes_list(bufs, s)
        if (
            pa.types.is_string(ty)
            or pa.types.is_large_string(ty)
            or pa.types.is_binary(ty)
            or pa.types.is_large_binary(ty)
        ):
            from lakesoul_tpu import native

            if native.available() and len(a) > 0:
                # zero-copy over the Arrow buffers (validity handled upstream)
                bufs = a.buffers()
                off_dtype = np.int64 if (
                    pa.types.is_large_string(ty) or pa.types.is_large_binary(ty)
                ) else np.int32
                offsets = np.frombuffer(bufs[1], dtype=off_dtype)[
                    a.offset : a.offset + len(a) + 1
                ].copy()
                data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] else np.zeros(0, np.uint8)
                out = np.empty(len(a), dtype=np.uint32)
                native.hash_string_array(data, offsets, s, None, out, HASH_SEED)
                return out
            pylist = a.to_pylist()
            bufs = [v.encode("utf-8") if isinstance(v, str) else v for v in pylist]
            return hash_bytes_list(bufs, s)
        if pa.types.is_fixed_size_binary(ty):
            return hash_bytes_list(a.to_pylist(), s)
        if pa.types.is_date(ty) or pa.types.is_time(ty) or pa.types.is_timestamp(ty):
            # 32-bit storage (date32/time32) hashes as one 4-byte block, like
            # the reference's i32-native Date32/Time32 arrays; 64-bit storage
            # as two blocks
            if ty.bit_width == 32:
                return hash_int_array(np.asarray(a.view(pa.int32())), s)
            return hash_long_array(np.asarray(a.view(pa.int64())), s)
        raise TypeError(f"Unsupported data type in hasher: {ty}")

    if arr.null_count:
        out = null_arr.copy()
        out[valid] = _dispatch(filled, seeds_arr[valid])
        return out
    return _dispatch(filled, seeds_arr)


def hash_columns(columns, num_rows: int | None = None) -> np.ndarray:
    """Hash one row-hash per row across columns, chaining like the reference's
    create_hashes (utils/hash/mod.rs:304): column 0 seeds valid rows with 42,
    column i>0 seeds each row with the running hash.  Null rows pass the
    buffer through unchanged, so a first-column null hashes to 0 (the
    reference zero-initializes the buffer)."""
    cols = list(columns)
    if not cols:
        raise ValueError("hash_columns needs at least one column")
    n = num_rows if num_rows is not None else len(cols[0])
    h = hash_array(cols[0], None, null_values=np.zeros(n, dtype=np.uint32))
    for col in cols[1:]:
        h = hash_array(col, h)
    return h


def hash_scalar(value, dtype: pa.DataType | None = None) -> int:
    """Hash a single Python scalar the way compute_scalar_hash does
    (helpers/mod.rs:1059) — used for bucket pruning on PK equality filters."""
    if value is None:
        return HASH_SEED
    if isinstance(value, bool):
        return int(hash_int_array(np.array([value]))[0])
    if isinstance(value, int):
        if dtype is not None and pa.types.is_integer(dtype) and dtype.bit_width <= 32:
            return int(hash_int_array(np.array([value], dtype=np.int64))[0])
        if dtype is None and -(2**31) <= value < 2**31:
            return int(hash_int_array(np.array([value], dtype=np.int64))[0])
        return int(hash_long_array(np.array([value], dtype=np.int64))[0])
    if isinstance(value, float):
        if dtype is not None and pa.types.is_float32(dtype):
            return int(hash_float_array(np.array([value], dtype=np.float32))[0])
        return int(hash_float_array(np.array([value], dtype=np.float64))[0])
    if isinstance(value, str):
        return murmur3_bytes(value.encode("utf-8"))
    if isinstance(value, (bytes, bytearray)):
        return murmur3_bytes(bytes(value))
    raise TypeError(f"unsupported scalar type {type(value)}")


def bucket_ids(hashes: np.ndarray, num_buckets: int) -> np.ndarray:
    """Bucket assignment: unsigned u32 hash % num_buckets
    (repartition/mod.rs:259 uses `*hash % *partitions as u32`)."""
    return (hashes.astype(np.uint32) % np.uint32(num_buckets)).astype(np.int64)


def bucket_id_for_scalar(value, num_buckets: int, dtype: pa.DataType | None = None) -> int:
    return int(np.uint32(hash_scalar(value, dtype)) % np.uint32(num_buckets))
