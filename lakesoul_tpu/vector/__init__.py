from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams

__all__ = ["VectorIndexConfig", "IvfRabitqIndex", "SearchParams"]
