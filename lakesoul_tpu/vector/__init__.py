from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams
from lakesoul_tpu.vector.serving import AnnEndpoint

__all__ = ["VectorIndexConfig", "IvfRabitqIndex", "SearchParams", "AnnEndpoint"]
