"""Per-bucket vector-index shard builder + table-level build/search.

Layout parity with the reference (VectorShardIndexBuilder,
lakesoul-io/src/vector/builder.rs:20; python vector_index.py:96-263): one
index shard per (range partition, hash bucket) at
``{table_path}/_vector_index/{column}/{partition_desc}/{bucket}/``, vector
row ids are the table's primary keys (u64), search unions per-shard
candidates and re-ranks by exact distance."""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.io.reader import iter_scan_unit_batches, read_scan_unit
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams
from lakesoul_tpu.vector.manifest import ManifestStore

# k-means needs a sample, not the corpus: shards up to this many rows train
# on everything in one pass; larger shards reservoir-sample for training and
# take a second streaming pass to insert
DEFAULT_TRAIN_SAMPLE_ROWS = 200_000


def _shard_root(table_path: str, column: str, partition_desc: str, bucket_id: int) -> str:
    part = partition_desc if partition_desc else "-5"
    return f"{table_path}/_vector_index/{column}/{part}/{max(bucket_id, 0)}"


def extract_vectors(
    table: pa.Table, column: str, id_column: str, dim: int
) -> tuple[np.ndarray, np.ndarray]:
    """FixedSizeList<f32>/List<f32> column + integer PK column → (vectors, ids)
    (reference: extract_vector_batch, vector/reader.rs:25)."""
    col = table.column(column).combine_chunks()
    if col.null_count:
        # a null row contributes no child values (variable lists) or garbage
        # slots (fixed), so col.values would silently misalign against ids —
        # fail typed instead of returning a corrupted index
        raise VectorIndexError(
            f"vector column {column!r} contains {col.null_count} null row(s);"
            " null vectors cannot be indexed — filter or fill them first"
        )
    t = col.type
    if pa.types.is_fixed_size_list(t):
        if t.list_size != dim:
            raise VectorIndexError(f"vector column dim {t.list_size} != config dim {dim}")
        values = np.asarray(col.values, dtype=np.float32).reshape(-1, dim)
    elif pa.types.is_list(t) or pa.types.is_large_list(t):
        values = np.asarray(col.values, dtype=np.float32).reshape(len(col), -1)
        if values.shape[1] != dim:
            raise VectorIndexError(f"vector column dim {values.shape[1]} != config dim {dim}")
    else:
        raise VectorIndexError(f"column {column} is not a vector (list<float>) column")
    ids = np.asarray(table.column(id_column).cast(pa.uint64()), dtype=np.uint64)
    return values, ids


class VectorShardIndexBuilder:
    """Build/refresh the index shard of one scan unit."""

    def __init__(
        self,
        table_path: str,
        config: VectorIndexConfig,
        id_column: str,
        *,
        storage_options: dict | None = None,
        batch_size: int = 65_536,
        memory_budget_bytes: int | None = None,
        train_sample_rows: int = DEFAULT_TRAIN_SAMPLE_ROWS,
    ):
        self.table_path = table_path
        self.config = config
        self.id_column = id_column
        self.storage_options = storage_options or {}
        self.batch_size = batch_size
        from lakesoul_tpu.io.config import DEFAULT_MEMORY_BUDGET

        self.memory_budget_bytes = (
            memory_budget_bytes if memory_budget_bytes is not None else DEFAULT_MEMORY_BUDGET
        )
        self.train_sample_rows = train_sample_rows

    def build(self, unit, schema: pa.Schema, *, keep_raw: bool = True,
              incremental: bool = False) -> int:
        """Scan the unit's files (merged), train a shard index, persist it.

        ``incremental=True`` and an existing shard: only files not yet covered
        by the manifest are read and inserted as delta segments (reference:
        insert_batch → delta segments; note updated PKs keep their stale
        entry too until a full rebuild — exact re-rank resolves ordering, the
        same contract the reference has).  Returns vectors (newly) indexed."""
        store = ManifestStore(
            _shard_root(self.table_path, self.config.column, unit.partition_desc, unit.bucket_id),
            self.storage_options,
        )
        if incremental and store.exists():
            manifest = store.read_manifest()
            # a compaction/rollback rewrote the file set: indexed files no
            # longer exist, so the "new" files are rewrites of already-indexed
            # rows — delta-inserting them would duplicate every id.  Rebuild.
            current = set(unit.data_files)
            already = set(manifest.get("indexed_files", []))
            if manifest.get("config") == self.config.encode() and already <= current:
                new_files = [f for f in unit.data_files if f not in already]
                if not new_files:
                    return 0
                table = read_scan_unit(
                    new_files,
                    [],  # raw appended rows; dedup resolved at re-rank/rebuild
                    schema=schema,
                    partition_values=unit.partition_values,
                    columns=[self.config.column, self.id_column],
                    storage_options=self.storage_options,
                )
                if len(table) == 0:
                    return 0
                vectors, ids = extract_vectors(
                    table, self.config.column, self.id_column, self.config.dim
                )
                index = store.read_latest()
                index.insert_batch(vectors, ids)
                store.write_index(index, indexed_files=sorted(already | set(new_files)))
                return len(ids)
        # full (re)build with bounded memory.  Pass 1 streams the unit,
        # buffering everything up to train_sample_rows and RESERVOIR-sampling
        # beyond it (an unbiased training sample — first-N would bias
        # centroids toward PK-ordered drift).  Small shards finish in that
        # single pass; oversized shards train on the reservoir and take a
        # second streaming pass to insert every vector.
        cap = self.train_sample_rows
        rng = np.random.default_rng(0xC0FFEE)
        reservoir_v: np.ndarray | None = None
        reservoir_i: np.ndarray | None = None
        buffered: list[tuple[np.ndarray, np.ndarray]] = []  # exact rows (small path)
        seen = 0
        for vectors, ids in self._stream_vectors(unit, schema):
            if seen < cap and seen + len(ids) <= cap:
                buffered.append((vectors, ids))
                seen += len(ids)
                continue
            if reservoir_v is None:
                # crossing the cap: seed the reservoir from the exact buffer
                parts_v = [v for v, _ in buffered] or [
                    np.zeros((0, self.config.dim), np.float32)
                ]
                parts_i = [i for _, i in buffered] or [np.zeros(0, np.uint64)]
                reservoir_v = np.concatenate(parts_v)
                reservoir_i = np.concatenate(parts_i)
                buffered = []
                if len(reservoir_v) < cap:  # top up from the current batch
                    take = cap - len(reservoir_v)
                    reservoir_v = np.concatenate([reservoir_v, vectors[:take]])
                    reservoir_i = np.concatenate([reservoir_i, ids[:take]])
                    vectors, ids = vectors[take:], ids[take:]
                    seen = cap
            # algorithm-R style vectorized replacement for the remainder
            m = len(ids)
            if m:
                positions = seen + np.arange(m)
                accept = rng.random(m) < cap / (positions + 1)
                idx = np.nonzero(accept)[0]
                slots = rng.integers(0, cap, len(idx))
                reservoir_v[slots] = vectors[idx]
                reservoir_i[slots] = ids[idx]
                seen += m

        if reservoir_v is None:
            # single pass: the whole shard fit in the sample window
            if not buffered:
                return 0
            vectors = np.concatenate([v for v, _ in buffered])
            ids = np.concatenate([i for _, i in buffered])
            index = IvfRabitqIndex.train(vectors, ids, self.config, keep_raw=keep_raw)
            store.write_index(index, indexed_files=unit.data_files)
            return len(ids)

        # oversized shard: train on the unbiased sample, then pass 2 inserts
        # EVERY vector (the reservoir was for centroids only)
        index = IvfRabitqIndex.train(
            reservoir_v, reservoir_i[: len(reservoir_v)], self.config, keep_raw=keep_raw
        )
        index.clusters = [
            index._make_cluster(
                np.zeros((0, self.config.dim), np.float32),
                np.zeros(0, np.uint64),
                index.centroids[c],
            )
            for c in range(len(index.centroids))
        ]  # drop the sample rows: pass 2 re-inserts them with everything else
        total = 0
        for vectors, ids in self._stream_vectors(unit, schema):
            index.insert_batch(vectors, ids)
            total += len(ids)
        index.merge_deltas()
        store.write_index(index, indexed_files=unit.data_files)
        return total

    def _stream_vectors(self, unit, schema: pa.Schema):
        for batch in iter_scan_unit_batches(
            unit.data_files,
            unit.primary_keys,
            batch_size=self.batch_size,
            memory_budget_bytes=self.memory_budget_bytes,
            file_sizes=getattr(unit, "file_sizes", None),
            schema=schema,
            partition_values=unit.partition_values,
            columns=[self.config.column, self.id_column],
            storage_options=self.storage_options,
        ):
            t = pa.Table.from_batches([batch])
            if len(t) == 0:
                continue
            yield extract_vectors(t, self.config.column, self.id_column, self.config.dim)


def build_table_vector_index(table, column: str, *, config: VectorIndexConfig | None = None,
                             incremental: bool = False, **cfg_kw) -> int:
    """Build one shard per scan unit of the table (reference:
    build_table_vector_index, vector_index.py:215).  With ``incremental=True``
    existing shards only ingest files committed since their last build.
    Returns total (newly) indexed vectors."""
    info = table.info
    if not info.primary_keys:
        raise VectorIndexError("vector index requires a primary-key table")
    if len(info.primary_keys) != 1:
        raise VectorIndexError(
            "vector index requires a single integer primary key (row ids are the"
            f" PK); table has composite PK {info.primary_keys}"
        )
    if config is None:
        field = info.arrow_schema.field(column)
        t = field.type
        if pa.types.is_fixed_size_list(t):
            dim = t.list_size
        elif "dim" in cfg_kw:
            dim = cfg_kw.pop("dim")
        else:
            raise VectorIndexError("dim required for non-fixed-size-list columns")
        config = VectorIndexConfig(column=column, dim=dim, **cfg_kw)
    io_cfg = table.io_config()
    builder = VectorShardIndexBuilder(
        info.table_path, config, info.primary_keys[0],
        storage_options=table.catalog.storage_options,
        batch_size=io_cfg.batch_size,
        memory_budget_bytes=io_cfg.memory_budget_bytes,
    )
    total = 0
    for unit in table.scan().scan_plan():
        total += builder.build(unit, info.arrow_schema, incremental=incremental)
    # record the index config on the table for readers — merged inside the
    # store's locked transaction, so a peer indexing a DIFFERENT column
    # concurrently cannot have its config entry clobbered by this write
    def record(props: dict) -> dict:
        props = dict(props)
        configs = [c for c in props.get("vector_index_columns", "").split(";") if c]
        configs = [c for c in configs if not c.startswith(column + ":")]
        configs.append(config.encode())
        props["vector_index_columns"] = ";".join(configs)
        return props

    table.catalog.client.store.merge_table_properties(info.table_id, record)
    table.refresh()
    return total


def search_table_vector_index(
    table,
    column: str,
    query: np.ndarray,
    *,
    top_k: int = 10,
    nprobe: int = 8,
    partitions: dict[str, str] | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Search every shard matching the (filtered) scan, union candidates and
    re-rank globally (reference: search_matching_shards vector/search.rs:55 +
    rerank_by_distance vector_index.py:263).  Returns (pk ids, distances)."""
    info = table.info
    configs = VectorIndexConfig.parse_multiple(
        info.properties.get("vector_index_columns", "")
    )
    config = next((c for c in configs if c.column == column), None)
    if config is None:
        raise VectorIndexError(f"no vector index built for column {column}")
    params = SearchParams(top_k=top_k, nprobe=nprobe)
    scan = table.scan()
    if partitions:
        scan = scan.partitions(partitions)
    all_ids, all_dists = [], []
    for unit in scan.scan_plan():
        root = _shard_root(info.table_path, column, unit.partition_desc, unit.bucket_id)
        store = ManifestStore(root, table.catalog.storage_options)
        if not store.exists():
            continue
        index = store.read_latest()
        ids, dists = index.search(np.asarray(query, np.float32), params)
        all_ids.append(ids)
        all_dists.append(dists)
    if not all_ids:
        return np.zeros(0, np.uint64), np.zeros(0, np.float32)
    ids = np.concatenate(all_ids)
    dists = np.concatenate(all_dists)
    order = np.argsort(dists)[:top_k]
    return ids[order], dists[order]
