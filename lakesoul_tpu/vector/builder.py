"""Per-bucket vector-index shard builder + table-level build/search.

Layout parity with the reference (VectorShardIndexBuilder,
lakesoul-io/src/vector/builder.rs:20; python vector_index.py:96-263): one
index shard per (range partition, hash bucket) at
``{table_path}/_vector_index/{column}/{partition_desc}/{bucket}/``, vector
row ids are the table's primary keys (u64), search unions per-shard
candidates and re-ranks by exact distance."""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.io.reader import read_scan_unit
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams
from lakesoul_tpu.vector.manifest import ManifestStore


def _shard_root(table_path: str, column: str, partition_desc: str, bucket_id: int) -> str:
    part = partition_desc if partition_desc else "-5"
    return f"{table_path}/_vector_index/{column}/{part}/{max(bucket_id, 0)}"


def extract_vectors(
    table: pa.Table, column: str, id_column: str, dim: int
) -> tuple[np.ndarray, np.ndarray]:
    """FixedSizeList<f32>/List<f32> column + integer PK column → (vectors, ids)
    (reference: extract_vector_batch, vector/reader.rs:25)."""
    col = table.column(column).combine_chunks()
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    t = col.type
    if pa.types.is_fixed_size_list(t):
        if t.list_size != dim:
            raise VectorIndexError(f"vector column dim {t.list_size} != config dim {dim}")
        values = np.asarray(col.values, dtype=np.float32).reshape(-1, dim)
    elif pa.types.is_list(t) or pa.types.is_large_list(t):
        values = np.asarray(col.values, dtype=np.float32).reshape(len(col), -1)
        if values.shape[1] != dim:
            raise VectorIndexError(f"vector column dim {values.shape[1]} != config dim {dim}")
    else:
        raise VectorIndexError(f"column {column} is not a vector (list<float>) column")
    ids = np.asarray(table.column(id_column).cast(pa.uint64()), dtype=np.uint64)
    return values, ids


class VectorShardIndexBuilder:
    """Build/refresh the index shard of one scan unit."""

    def __init__(
        self,
        table_path: str,
        config: VectorIndexConfig,
        id_column: str,
        *,
        storage_options: dict | None = None,
    ):
        self.table_path = table_path
        self.config = config
        self.id_column = id_column
        self.storage_options = storage_options or {}

    def build(self, unit, schema: pa.Schema, *, keep_raw: bool = True,
              incremental: bool = False) -> int:
        """Scan the unit's files (merged), train a shard index, persist it.

        ``incremental=True`` and an existing shard: only files not yet covered
        by the manifest are read and inserted as delta segments (reference:
        insert_batch → delta segments; note updated PKs keep their stale
        entry too until a full rebuild — exact re-rank resolves ordering, the
        same contract the reference has).  Returns vectors (newly) indexed."""
        store = ManifestStore(
            _shard_root(self.table_path, self.config.column, unit.partition_desc, unit.bucket_id),
            self.storage_options,
        )
        if incremental and store.exists():
            manifest = store.read_manifest()
            # a compaction/rollback rewrote the file set: indexed files no
            # longer exist, so the "new" files are rewrites of already-indexed
            # rows — delta-inserting them would duplicate every id.  Rebuild.
            current = set(unit.data_files)
            already = set(manifest.get("indexed_files", []))
            if manifest.get("config") == self.config.encode() and already <= current:
                new_files = [f for f in unit.data_files if f not in already]
                if not new_files:
                    return 0
                table = read_scan_unit(
                    new_files,
                    [],  # raw appended rows; dedup resolved at re-rank/rebuild
                    schema=schema,
                    partition_values=unit.partition_values,
                    columns=[self.config.column, self.id_column],
                )
                if len(table) == 0:
                    return 0
                vectors, ids = extract_vectors(
                    table, self.config.column, self.id_column, self.config.dim
                )
                index = store.read_latest()
                index.insert_batch(vectors, ids)
                store.write_index(index, indexed_files=sorted(already | set(new_files)))
                return len(ids)
        # full (re)build with bounded memory: stream the unit, train
        # centroids on the first TRAIN_SAMPLE_ROWS vectors (standard IVF
        # practice — k-means needs a sample, not the corpus), then insert the
        # remaining batches incrementally and fold the deltas once
        TRAIN_SAMPLE_ROWS = 200_000
        from lakesoul_tpu.io.reader import iter_scan_unit_batches

        batches = iter_scan_unit_batches(
            unit.data_files,
            unit.primary_keys,
            batch_size=65_536,
            file_sizes=getattr(unit, "file_sizes", None),
            schema=schema,
            partition_values=unit.partition_values,
            columns=[self.config.column, self.id_column],
        )
        sample_v: list[np.ndarray] = []
        sample_i: list[np.ndarray] = []
        sampled = 0
        index = None
        total = 0
        for batch in batches:
            t = pa.Table.from_batches([batch])
            if len(t) == 0:
                continue
            vectors, ids = extract_vectors(
                t, self.config.column, self.id_column, self.config.dim
            )
            total += len(ids)
            if index is None:
                sample_v.append(vectors)
                sample_i.append(ids)
                sampled += len(ids)
                if sampled >= TRAIN_SAMPLE_ROWS:
                    index = IvfRabitqIndex.train(
                        np.concatenate(sample_v),
                        np.concatenate(sample_i),
                        self.config,
                        keep_raw=keep_raw,
                    )
                    sample_v, sample_i = [], []
            else:
                index.insert_batch(vectors, ids)
        if index is None:
            if not sample_v:
                return 0
            index = IvfRabitqIndex.train(
                np.concatenate(sample_v),
                np.concatenate(sample_i),
                self.config,
                keep_raw=keep_raw,
            )
        index.merge_deltas()
        store.write_index(index, indexed_files=unit.data_files)
        return total


def build_table_vector_index(table, column: str, *, config: VectorIndexConfig | None = None,
                             incremental: bool = False, **cfg_kw) -> int:
    """Build one shard per scan unit of the table (reference:
    build_table_vector_index, vector_index.py:215).  With ``incremental=True``
    existing shards only ingest files committed since their last build.
    Returns total (newly) indexed vectors."""
    info = table.info
    if not info.primary_keys:
        raise VectorIndexError("vector index requires a primary-key table")
    if len(info.primary_keys) != 1:
        raise VectorIndexError(
            "vector index requires a single integer primary key (row ids are the"
            f" PK); table has composite PK {info.primary_keys}"
        )
    if config is None:
        field = info.arrow_schema.field(column)
        t = field.type
        if pa.types.is_fixed_size_list(t):
            dim = t.list_size
        elif "dim" in cfg_kw:
            dim = cfg_kw.pop("dim")
        else:
            raise VectorIndexError("dim required for non-fixed-size-list columns")
        config = VectorIndexConfig(column=column, dim=dim, **cfg_kw)
    builder = VectorShardIndexBuilder(
        info.table_path, config, info.primary_keys[0],
        storage_options=table.catalog.storage_options,
    )
    total = 0
    for unit in table.scan().scan_plan():
        total += builder.build(unit, info.arrow_schema, incremental=incremental)
    # record the index config on the table for readers
    props = dict(info.properties)
    configs = [c for c in props.get("vector_index_columns", "").split(";") if c]
    configs = [c for c in configs if not c.startswith(column + ":")]
    configs.append(config.encode())
    props["vector_index_columns"] = ";".join(configs)
    table.catalog.client.store.update_table_properties(info.table_id, props)
    table.refresh()
    return total


def search_table_vector_index(
    table,
    column: str,
    query: np.ndarray,
    *,
    top_k: int = 10,
    nprobe: int = 8,
    partitions: dict[str, str] | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Search every shard matching the (filtered) scan, union candidates and
    re-rank globally (reference: search_matching_shards vector/search.rs:55 +
    rerank_by_distance vector_index.py:263).  Returns (pk ids, distances)."""
    info = table.info
    configs = VectorIndexConfig.parse_multiple(
        info.properties.get("vector_index_columns", "")
    )
    config = next((c for c in configs if c.column == column), None)
    if config is None:
        raise VectorIndexError(f"no vector index built for column {column}")
    params = SearchParams(top_k=top_k, nprobe=nprobe)
    scan = table.scan()
    if partitions:
        scan = scan.partitions(partitions)
    all_ids, all_dists = [], []
    for unit in scan.scan_plan():
        root = _shard_root(info.table_path, column, unit.partition_desc, unit.bucket_id)
        store = ManifestStore(root, table.catalog.storage_options)
        if not store.exists():
            continue
        index = store.read_latest()
        ids, dists = index.search(np.asarray(query, np.float32), params)
        all_ids.append(ids)
        all_dists.append(dists)
    if not all_ids:
        return np.zeros(0, np.uint64), np.zeros(0, np.float32)
    ids = np.concatenate(all_ids)
    dists = np.concatenate(all_dists)
    order = np.argsort(dists)[:top_k]
    return ids[order], dists[order]
