"""Vector index configuration.

String format compatible with the reference's
``VectorIndexConfig::parse_multiple`` (rust/lakesoul-vector/src/config.rs:68):
``col:dim:nlist:total_bits:metric:rotator:seed:faster`` with trailing fields
optional, multiple configs separated by ``;``.
"""

from __future__ import annotations

from dataclasses import dataclass

from lakesoul_tpu.errors import VectorIndexError

METRICS = {"l2", "ip"}
ROTATORS = {"fht", "matrix", "identity"}


@dataclass(frozen=True)
class VectorIndexConfig:
    column: str
    dim: int
    nlist: int = 16
    total_bits: int = 1
    metric: str = "l2"
    rotator: str = "fht"
    seed: int = 42
    faster: bool = False

    def __post_init__(self):
        if self.dim <= 0:
            raise VectorIndexError(f"invalid dim {self.dim}")
        if self.nlist <= 0:
            raise VectorIndexError(f"invalid nlist {self.nlist}")
        if not 1 <= self.total_bits <= 16:
            raise VectorIndexError(f"total_bits must be in [1,16], got {self.total_bits}")
        if self.metric not in METRICS:
            raise VectorIndexError(f"unknown metric {self.metric}")
        if self.rotator not in ROTATORS:
            raise VectorIndexError(f"unknown rotator {self.rotator}")

    @classmethod
    def parse(cls, s: str) -> "VectorIndexConfig":
        parts = s.strip().split(":")
        if len(parts) < 2:
            raise VectorIndexError(f"invalid vector index config {s!r}")
        kwargs = {"column": parts[0], "dim": int(parts[1])}
        if len(parts) > 2:
            kwargs["nlist"] = int(parts[2])
        if len(parts) > 3:
            kwargs["total_bits"] = int(parts[3])
        if len(parts) > 4:
            kwargs["metric"] = parts[4]
        if len(parts) > 5:
            kwargs["rotator"] = parts[5]
        if len(parts) > 6:
            kwargs["seed"] = int(parts[6])
        if len(parts) > 7:
            kwargs["faster"] = parts[7].lower() in ("1", "true")
        return cls(**kwargs)

    @classmethod
    def parse_multiple(cls, s: str) -> list["VectorIndexConfig"]:
        return [cls.parse(p) for p in s.split(";") if p.strip()]

    def encode(self) -> str:
        return (
            f"{self.column}:{self.dim}:{self.nlist}:{self.total_bits}:"
            f"{self.metric}:{self.rotator}:{self.seed}:{str(self.faster).lower()}"
        )
