"""IVF + RaBitQ ANN index.

Capability parity with IvfRabitqIndex (rust/lakesoul-vector/src/rabitq/ivf/
mod.rs: train:90, train_from_batches:257, search:1131, search_filtered:1149,
batch_search:1169, insert_batch:1901), redesigned around TPU kernels: cluster
scans are MXU matvecs over packed codes (lakesoul_tpu.vector.kernels), train
is JAX k-means on-device.

Incremental inserts append to per-cluster *delta* arrays, mirroring the
reference's base + delta segments; ``merge_deltas()`` folds them in."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.kernels import bruteforce_topk, packed_scan
from lakesoul_tpu.vector.kmeans import kmeans
from lakesoul_tpu.vector.rabitq import RabitqQuantizer


@dataclass(frozen=True)
class SearchParams:
    """reference: SearchParams{top_k, nprobe} (ivf/mod.rs:29)."""

    top_k: int = 10
    nprobe: int = 8


@dataclass
class _Cluster:
    codes: np.ndarray  # [n, padded/8] uint8
    norms: np.ndarray  # [n] f32
    factors: np.ndarray  # [n] f32
    ids: np.ndarray  # [n] u64 row ids
    code_dot_c: np.ndarray | None = None  # [n] f32: bits · P(centroid)
    raw: np.ndarray | None = None  # [n, dim] f32 (kept for exact re-rank)


class IvfRabitqIndex:
    def __init__(self, config: VectorIndexConfig):
        self.config = config
        self.quantizer = RabitqQuantizer(
            config.dim, rotator=config.rotator, seed=config.seed
        )
        self.centroids: np.ndarray | None = None  # [nlist, dim]
        self._centroids_rot: np.ndarray | None = None  # cache of P(centroids)
        self.clusters: list[_Cluster] = []
        self.deltas: list[list[_Cluster]] = []
        self.keep_raw = True

    # ------------------------------------------------------------------ train
    @classmethod
    def train(
        cls,
        vectors: np.ndarray,
        ids: np.ndarray,
        config: VectorIndexConfig,
        *,
        keep_raw: bool = True,
        kmeans_iters: int = 10,
    ) -> "IvfRabitqIndex":
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.uint64)
        if vectors.ndim != 2 or vectors.shape[1] != config.dim:
            raise VectorIndexError(
                f"expected [N, {config.dim}] vectors, got {vectors.shape}"
            )
        if len(ids) != len(vectors):
            raise VectorIndexError("ids/vectors length mismatch")
        index = cls(config)
        index.keep_raw = keep_raw
        nlist = min(config.nlist, max(1, len(vectors)))
        centroids, assign = kmeans(
            vectors, nlist, iters=kmeans_iters, seed=config.seed
        )
        index.centroids = centroids
        index.clusters = [
            index._make_cluster(vectors[assign == c], ids[assign == c], centroids[c])
            for c in range(nlist)
        ]
        index.deltas = [[] for _ in range(nlist)]
        return index

    @classmethod
    def train_from_batches(cls, batches, config: VectorIndexConfig, **kw) -> "IvfRabitqIndex":
        """batches: iterable of (vectors [n, dim], ids [n])."""
        vs, ds = [], []
        for v, i in batches:
            vs.append(np.asarray(v, dtype=np.float32))
            ds.append(np.asarray(i, dtype=np.uint64))
        if not vs:
            raise VectorIndexError("no vectors to train on")
        return cls.train(np.concatenate(vs), np.concatenate(ds), config, **kw)

    def _make_cluster(self, vectors, ids, centroid) -> _Cluster:
        if len(vectors) == 0:
            d8 = self.quantizer.padded_dim // 8
            return _Cluster(
                codes=np.zeros((0, d8), np.uint8),
                norms=np.zeros(0, np.float32),
                factors=np.ones(0, np.float32),
                ids=np.zeros(0, np.uint64),
                code_dot_c=np.zeros(0, np.float32),
                raw=np.zeros((0, self.config.dim), np.float32) if self.keep_raw else None,
            )
        codes, norms, factors, code_dot_c = self.quantizer.quantize(vectors, centroid)
        return _Cluster(
            codes=codes,
            norms=norms,
            factors=factors,
            ids=ids,
            code_dot_c=code_dot_c,
            raw=vectors.copy() if self.keep_raw else None,
        )

    # ----------------------------------------------------------------- insert
    def insert_batch(self, vectors: np.ndarray, ids: np.ndarray) -> None:
        """Incremental insert: assign to nearest centroid, quantize, append as
        a delta segment (reference: insert_batch → delta segments)."""
        if self.centroids is None:
            raise VectorIndexError("index not trained")
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.uint64)
        d2 = (
            np.sum(vectors**2, axis=1, keepdims=True)
            - 2.0 * vectors @ self.centroids.T
            + np.sum(self.centroids**2, axis=1)[None, :]
        )
        assign = np.argmin(d2, axis=1)
        for c in np.unique(assign):
            m = assign == c
            self.deltas[c].append(
                self._make_cluster(vectors[m], ids[m], self.centroids[c])
            )

    def merge_deltas(self) -> None:
        """Fold delta segments into base clusters (compaction of the index)."""
        for c, deltas in enumerate(self.deltas):
            if not deltas:
                continue
            segs = [self.clusters[c]] + deltas
            self.clusters[c] = _Cluster(
                codes=np.concatenate([s.codes for s in segs]),
                norms=np.concatenate([s.norms for s in segs]),
                factors=np.concatenate([s.factors for s in segs]),
                ids=np.concatenate([s.ids for s in segs]),
                code_dot_c=np.concatenate([np.asarray(s.code_dot_c) for s in segs]),
                raw=(
                    np.concatenate([s.raw for s in segs])
                    if self.keep_raw and all(s.raw is not None for s in segs)
                    else None
                ),
            )
            self.deltas[c] = []

    @property
    def num_vectors(self) -> int:
        return sum(len(c.ids) for c in self.clusters) + sum(
            len(s.ids) for ds in self.deltas for s in ds
        )

    # ----------------------------------------------------------------- search
    def _rotated_centroid(self, c: int) -> np.ndarray:
        if self._centroids_rot is None or len(self._centroids_rot) != len(self.centroids):
            self._centroids_rot = self.quantizer.rotate(self.centroids)
        return self._centroids_rot[c]

    def _cluster_segments(self, c: int):
        yield self.clusters[c]
        yield from self.deltas[c]

    def search(
        self,
        query: np.ndarray,
        params: SearchParams = SearchParams(),
        *,
        allowed_ids: np.ndarray | None = None,
        rerank: bool = True,
    ) -> tuple[np.ndarray, np.ndarray]:
        """→ (ids [k] u64, distances [k] f32), nearest first.

        ``allowed_ids`` implements search_filtered (ivf/mod.rs:1149).
        ``rerank`` re-scores the RaBitQ candidates with exact distances when
        raw vectors are kept (the reference re-ranks caller-side,
        vector_index.py:263)."""
        if self.centroids is None:
            raise VectorIndexError("index not trained")
        query = np.asarray(query, dtype=np.float32)
        nprobe = min(params.nprobe, len(self.centroids))
        cd = np.sum((self.centroids - query[None, :]) ** 2, axis=1)
        probe = np.argsort(cd)[:nprobe]

        # All probed segments are concatenated into ONE fused device call.
        # Rotation is linear, so the estimator works in the *global* query
        # frame: with Q = P(query) and xc = P(c) - Q (per cluster),
        #   dist² ≈ ||r||² + ||xc||² + 2·||r||·<o_bar, xc>/factor,
        # where <o_bar, xc> needs only bits·Q (one MXU scan) plus the
        # build-time per-row constant code_dot_c = bits·P(c) and two
        # per-cluster scalars (||xc||², Σxc) broadcast per row on the host.
        cand = {k: [] for k in ("ids", "codes", "norms", "factors", "cdc", "csq", "csum", "raw")}
        q_glob = self.quantizer.rotate(query)  # P(query), computed once
        for c in probe:
            xc = self._rotated_centroid(c) - q_glob
            xc_sq = np.float32(np.dot(xc, xc))
            xc_sum = np.float32(np.sum(xc))
            for seg in self._cluster_segments(c):
                if len(seg.ids) == 0:
                    continue
                ids = seg.ids
                sel = slice(None)
                if allowed_ids is not None:
                    m = np.isin(ids, allowed_ids)
                    if not m.any():
                        continue
                    sel = m
                    ids = ids[m]
                n_seg = len(ids)
                cand["ids"].append(ids)
                cand["codes"].append(seg.codes[sel])
                cand["norms"].append(seg.norms[sel])
                cand["factors"].append(seg.factors[sel])
                cand["cdc"].append(np.asarray(seg.code_dot_c)[sel])
                cand["csq"].append(np.full(n_seg, xc_sq, np.float32))
                cand["csum"].append(np.full(n_seg, xc_sum, np.float32))
                cand["raw"].append(seg.raw[sel] if seg.raw is not None else None)

        if not cand["ids"]:
            return np.zeros(0, np.uint64), np.zeros(0, np.float32)
        ids = np.concatenate(cand["ids"])

        from lakesoul_tpu.vector.kernels import fused_search

        use_rerank = rerank and self.keep_raw and all(r is not None for r in cand["raw"])
        dists, idx = fused_search(
            np.concatenate(cand["codes"]),
            np.concatenate(cand["norms"]),
            np.concatenate(cand["factors"]),
            np.concatenate(cand["cdc"]),
            np.concatenate(cand["csq"]),
            np.concatenate(cand["csum"]),
            q_glob,
            np.concatenate(cand["raw"]) if use_rerank else None,
            query,
            d=self.quantizer.padded_dim,
            top_k=params.top_k,
            shortlist=max(params.top_k * 4, params.top_k),
        )
        valid = idx < len(ids)
        idx, dists = idx[valid], dists[valid]
        k = min(params.top_k, len(ids))
        return ids[idx[:k]], dists[:k]

    def search_filtered(self, query, allowed_ids, params: SearchParams = SearchParams()):
        return self.search(query, params, allowed_ids=np.asarray(allowed_ids, np.uint64))

    def batch_search(self, queries: np.ndarray, params: SearchParams = SearchParams()):
        out = [self.search(q, params) for q in np.asarray(queries, np.float32)]
        return [o[0] for o in out], [o[1] for o in out]
