"""IVF + RaBitQ ANN index.

Capability parity with IvfRabitqIndex (rust/lakesoul-vector/src/rabitq/ivf/
mod.rs: train:90, train_from_batches:257, search:1131, search_filtered:1149,
batch_search:1169, insert_batch:1901), redesigned around TPU kernels: cluster
scans are MXU matvecs over packed codes (lakesoul_tpu.vector.kernels), train
is JAX k-means on-device.

Incremental inserts append to per-cluster *delta* arrays, mirroring the
reference's base + delta segments; ``merge_deltas()`` folds them in."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.kmeans import kmeans
from lakesoul_tpu.vector.rabitq import RabitqQuantizer


def _finalize_topk(ids: np.ndarray, dists: np.ndarray, idx: np.ndarray, top_k: int):
    """Drop pad rows from a fused-search result and cut to top_k."""
    valid = (idx < len(ids)) & np.isfinite(dists)
    idx, dists = idx[valid], dists[valid]
    k = min(top_k, len(ids))
    return ids[idx[:k]], dists[:k]


@dataclass(frozen=True)
class SearchParams:
    """reference: SearchParams{top_k, nprobe} (ivf/mod.rs:29).

    ``rerank_depth`` sizes the estimator shortlist handed to the exact
    re-rank (None → 4·top_k).  With raw vectors kept, recall is bounded only
    by probe coverage and this depth, so deeper re-rank trades QPS for
    recall without touching the quantizer."""

    top_k: int = 10
    nprobe: int = 8
    rerank_depth: int | None = None

    def shortlist(self) -> int:
        s = self.rerank_depth if self.rerank_depth is not None else self.top_k * 4
        return max(s, self.top_k)


@dataclass
class _Cluster:
    codes: np.ndarray  # 1-bit: [n, padded/8] uint8 packed; ex: [n, padded] int8
    norms: np.ndarray  # [n] f32
    factors: np.ndarray  # [n] f32
    ids: np.ndarray  # [n] u64 row ids
    code_dot_c: np.ndarray | None = None  # [n] f32: u_hat · P(centroid)
    raw: np.ndarray | None = None  # [n, dim] f32 (kept for exact re-rank)
    scales: np.ndarray | None = None  # [n] f32, ex-codes only (u_hat = codes*scales)


class IvfRabitqIndex:
    def __init__(self, config: VectorIndexConfig):
        self.config = config
        self.quantizer = RabitqQuantizer(
            config.dim, rotator=config.rotator, seed=config.seed
        )
        self.centroids: np.ndarray | None = None  # [nlist, dim]
        self._centroids_rot: np.ndarray | None = None  # cache of P(centroids)
        self.clusters: list[_Cluster] = []
        self.deltas: list[list[_Cluster]] = []
        self.keep_raw = True
        self._device_cache_enabled = False
        self._device_bundle = None

    # ------------------------------------------------------------------ train
    @classmethod
    def train(
        cls,
        vectors: np.ndarray,
        ids: np.ndarray,
        config: VectorIndexConfig,
        *,
        keep_raw: bool = True,
        kmeans_iters: int = 10,
    ) -> "IvfRabitqIndex":
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.uint64)
        if vectors.ndim != 2 or vectors.shape[1] != config.dim:
            raise VectorIndexError(
                f"expected [N, {config.dim}] vectors, got {vectors.shape}"
            )
        if len(ids) != len(vectors):
            raise VectorIndexError("ids/vectors length mismatch")
        index = cls(config)
        index.keep_raw = keep_raw
        nlist = min(config.nlist, max(1, len(vectors)))
        centroids, assign = kmeans(
            vectors, nlist, iters=kmeans_iters, seed=config.seed
        )
        index.centroids = centroids
        index.clusters = [
            index._make_cluster(vectors[assign == c], ids[assign == c], centroids[c])
            for c in range(nlist)
        ]
        index.deltas = [[] for _ in range(nlist)]
        return index

    @classmethod
    def train_from_batches(cls, batches, config: VectorIndexConfig, **kw) -> "IvfRabitqIndex":
        """batches: iterable of (vectors [n, dim], ids [n])."""
        vs, ds = [], []
        for v, i in batches:
            vs.append(np.asarray(v, dtype=np.float32))
            ds.append(np.asarray(i, dtype=np.uint64))
        if not vs:
            raise VectorIndexError("no vectors to train on")
        return cls.train(np.concatenate(vs), np.concatenate(ds), config, **kw)

    @property
    def _ex_bits(self) -> bool:
        return self.config.total_bits > 1

    def _make_cluster(self, vectors, ids, centroid) -> _Cluster:
        if len(vectors) == 0:
            if self._ex_bits:
                dt = np.int8 if self.config.total_bits <= 8 else np.int16
                codes0 = np.zeros((0, self.quantizer.padded_dim), dt)
            else:
                codes0 = np.zeros((0, self.quantizer.padded_dim // 8), np.uint8)
            return _Cluster(
                codes=codes0,
                norms=np.zeros(0, np.float32),
                factors=np.ones(0, np.float32),
                ids=np.zeros(0, np.uint64),
                code_dot_c=np.zeros(0, np.float32),
                raw=np.zeros((0, self.config.dim), np.float32) if self.keep_raw else None,
                scales=np.zeros(0, np.float32) if self._ex_bits else None,
            )
        if self._ex_bits:
            codes, scales, norms, factors, code_dot_c = self.quantizer.quantize_ex(
                vectors, centroid, self.config.total_bits
            )
        else:
            codes, norms, factors, code_dot_c = self.quantizer.quantize(vectors, centroid)
            scales = None
        return _Cluster(
            codes=codes,
            norms=norms,
            factors=factors,
            ids=ids,
            code_dot_c=code_dot_c,
            raw=vectors.copy() if self.keep_raw else None,
            scales=scales,
        )

    # ----------------------------------------------------------------- insert
    def insert_batch(self, vectors: np.ndarray, ids: np.ndarray) -> None:
        """Incremental insert: assign to nearest centroid, quantize, append as
        a delta segment (reference: insert_batch → delta segments)."""
        if self.centroids is None:
            raise VectorIndexError("index not trained")
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.uint64)
        d2 = (
            np.sum(vectors**2, axis=1, keepdims=True)
            - 2.0 * vectors @ self.centroids.T
            + np.sum(self.centroids**2, axis=1)[None, :]
        )
        self._invalidate_device_cache()
        assign = np.argmin(d2, axis=1)
        for c in np.unique(assign):
            m = assign == c
            self.deltas[c].append(
                self._make_cluster(vectors[m], ids[m], self.centroids[c])
            )

    def merge_deltas(self) -> None:
        """Fold delta segments into base clusters (compaction of the index)."""
        self._invalidate_device_cache()
        for c, deltas in enumerate(self.deltas):
            if not deltas:
                continue
            segs = [self.clusters[c]] + deltas
            self.clusters[c] = _Cluster(
                codes=np.concatenate([s.codes for s in segs]),
                norms=np.concatenate([s.norms for s in segs]),
                factors=np.concatenate([s.factors for s in segs]),
                ids=np.concatenate([s.ids for s in segs]),
                code_dot_c=np.concatenate([np.asarray(s.code_dot_c) for s in segs]),
                scales=(
                    np.concatenate([np.asarray(s.scales) for s in segs])
                    if all(s.scales is not None for s in segs)
                    else None
                ),
                raw=(
                    np.concatenate([s.raw for s in segs])
                    if self.keep_raw and all(s.raw is not None for s in segs)
                    else None
                ),
            )
            self.deltas[c] = []

    @property
    def num_vectors(self) -> int:
        return sum(len(c.ids) for c in self.clusters) + sum(
            len(s.ids) for ds in self.deltas for s in ds
        )

    # ------------------------------------------------------- device residency
    def enable_device_cache(self) -> None:
        """Pin the shard's arrays in device HBM: subsequent searches upload
        only the query + per-cluster scalars (one device call, no candidate
        re-upload).  Invalidated automatically by insert/merge."""
        self._device_cache_enabled = True

    def _invalidate_device_cache(self) -> None:
        self._device_bundle = None

    def _get_device_bundle(self):
        import jax.numpy as jnp

        from lakesoul_tpu.vector.kernels import _pow2_bucket

        bundle = getattr(self, "_device_bundle", None)
        if bundle is not None:
            return bundle
        segs = [
            (c, seg)
            for c in range(len(self.clusters))
            for seg in self._cluster_segments(c)
            if len(seg.ids)
        ]
        if not segs:
            return None
        codes = np.concatenate([s.codes for _, s in segs])
        n = len(codes)
        n_pad = _pow2_bucket(n)
        pad = n_pad - n

        def padded(a, const=0.0, dtype=np.float32):
            a = np.asarray(a, dtype)
            return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1), constant_values=const)

        from lakesoul_tpu.vector.kernels import PAD_FACTOR, PAD_NORM, PAD_RAW

        bundle = {
            "codes": jnp.asarray(np.pad(codes, ((0, pad), (0, 0)))),
            "norms": jnp.asarray(padded(np.concatenate([s.norms for _, s in segs]), PAD_NORM)),
            "factors": jnp.asarray(padded(np.concatenate([s.factors for _, s in segs]), PAD_FACTOR)),
            "cdc": jnp.asarray(padded(np.concatenate([np.asarray(s.code_dot_c) for _, s in segs]))),
            "cluster_id": jnp.asarray(
                np.pad(
                    np.concatenate(
                        [np.full(len(s.ids), c, np.int32) for c, s in segs]
                    ),
                    (0, pad),
                )
            ),
            "scales": (
                jnp.asarray(
                    padded(np.concatenate([np.asarray(s.scales) for _, s in segs]), 1.0)
                )
                if all(s.scales is not None for _, s in segs)
                else None
            ),
            "raw": (
                jnp.asarray(
                    np.pad(
                        np.concatenate([s.raw for _, s in segs]),
                        ((0, pad), (0, 0)),
                        constant_values=PAD_RAW,
                    )
                )
                if self.keep_raw and all(s.raw is not None for _, s in segs)
                else None
            ),
            "ids": np.concatenate([s.ids for _, s in segs]),  # host side
            "n": n,
        }
        self._device_bundle = bundle
        return bundle

    def _search_device_resident(self, query, params: SearchParams, probe):
        import jax.numpy as jnp

        from lakesoul_tpu.vector.kernels import _fused_search_resident, _on_tpu

        bundle = self._get_device_bundle()
        if bundle is None:
            return np.zeros(0, np.uint64), np.zeros(0, np.float32)
        q_glob = self.quantizer.rotate(query)
        xc = self._rotated_centroids() - q_glob[None, :]
        csq_c = np.sum(xc * xc, axis=1).astype(np.float32)
        csum_c = np.sum(xc, axis=1).astype(np.float32)
        probe_mask = np.zeros(len(self.centroids), dtype=bool)
        probe_mask[probe] = True
        do_rerank = bundle["raw"] is not None
        s = min(params.shortlist(), int(bundle["codes"].shape[0]))
        k = min(params.top_k, int(bundle["codes"].shape[0]))
        dists, idx = _fused_search_resident(
            bundle["codes"], bundle["norms"], bundle["factors"], bundle["cdc"],
            bundle["cluster_id"], jnp.asarray(probe_mask),
            jnp.asarray(csq_c), jnp.asarray(csum_c), jnp.asarray(q_glob),
            bundle["raw"] if do_rerank else jnp.zeros((1, 1), jnp.float32),
            jnp.asarray(query, jnp.float32),
            d=self.quantizer.padded_dim, s=s, k=k,
            use_pallas=_on_tpu(), do_rerank=do_rerank,
        )
        dists, idx = np.asarray(dists), np.asarray(idx)
        valid = (idx < bundle["n"]) & np.isfinite(dists)
        idx, dists = idx[valid], dists[valid]
        kk = min(params.top_k, len(idx))
        return bundle["ids"][idx[:kk]], dists[:kk]

    # ----------------------------------------------------------------- search
    def _rotated_centroids(self) -> np.ndarray:
        if self._centroids_rot is None or len(self._centroids_rot) != len(self.centroids):
            self._centroids_rot = self.quantizer.rotate(self.centroids)
        return self._centroids_rot

    def _rotated_centroid(self, c: int) -> np.ndarray:
        return self._rotated_centroids()[c]

    def _cluster_segments(self, c: int):
        yield self.clusters[c]
        yield from self.deltas[c]

    def search(
        self,
        query: np.ndarray,
        params: SearchParams = SearchParams(),
        *,
        allowed_ids: np.ndarray | None = None,
        rerank: bool = True,
    ) -> tuple[np.ndarray, np.ndarray]:
        """→ (ids [k] u64, distances [k] f32), nearest first.

        ``allowed_ids`` implements search_filtered (ivf/mod.rs:1149).
        ``rerank`` re-scores the RaBitQ candidates with exact distances when
        raw vectors are kept (the reference re-ranks caller-side,
        vector_index.py:263)."""
        if self.centroids is None:
            raise VectorIndexError("index not trained")
        query = np.asarray(query, dtype=np.float32)
        nprobe = min(params.nprobe, len(self.centroids))

        if (
            getattr(self, "_device_cache_enabled", False)
            and allowed_ids is None
            and rerank == self.keep_raw
        ):
            if not self._ex_bits:
                cd = np.sum((self.centroids - query[None, :]) ** 2, axis=1)
                probe = np.argsort(cd)[:nprobe]
                return self._search_device_resident(query, params, probe)
            # ex-codes: the batched resident kernel IS the single-query path
            # (Q=1 column) — same HBM-resident codes, one dispatch; it
            # computes its own probe set, so none is computed here
            out = self._batch_search_device_resident(query[None, :], params)
            if out is not None:
                ids_b, dists_b = out
                return ids_b[0], dists_b[0]

        cd = np.sum((self.centroids - query[None, :]) ** 2, axis=1)
        probe = np.argsort(cd)[:nprobe]

        # All probed segments are concatenated into ONE fused device call.
        # Rotation is linear, so the estimator works in the *global* query
        # frame: with Q = P(query) and xc = P(c) - Q (per cluster),
        #   dist² ≈ ||r||² + ||xc||² + 2·||r||·<o_bar, xc>/factor,
        # where <o_bar, xc> needs only bits·Q (one MXU scan) plus the
        # build-time per-row constant code_dot_c = bits·P(c) and two
        # per-cluster scalars (||xc||², Σxc) broadcast per row on the host.
        cand = {k: [] for k in ("ids", "codes", "norms", "factors", "cdc", "csq", "csum", "raw", "scales")}
        q_glob = self.quantizer.rotate(query)  # P(query), computed once
        ex = self._ex_bits
        for c in probe:
            xc = self._rotated_centroid(c) - q_glob
            xc_sq = np.float32(np.dot(xc, xc))
            xc_sum = np.float32(0.0) if ex else np.float32(np.sum(xc))  # ex path never uses csum
            for seg in self._cluster_segments(c):
                if len(seg.ids) == 0:
                    continue
                ids = seg.ids
                sel = slice(None)
                if allowed_ids is not None:
                    m = np.isin(ids, allowed_ids)
                    if not m.any():
                        continue
                    sel = m
                    ids = ids[m]
                n_seg = len(ids)
                cand["ids"].append(ids)
                cand["codes"].append(seg.codes[sel])
                cand["norms"].append(seg.norms[sel])
                cand["factors"].append(seg.factors[sel])
                cand["cdc"].append(np.asarray(seg.code_dot_c)[sel])
                cand["csq"].append(np.full(n_seg, xc_sq, np.float32))
                cand["csum"].append(np.full(n_seg, xc_sum, np.float32))
                cand["raw"].append(seg.raw[sel] if seg.raw is not None else None)
                if ex and seg.scales is None:
                    raise VectorIndexError(
                        "index config says total_bits > 1 but segment has no scales"
                        " (legacy 1-bit shard?) — rebuild the index"
                    )
                cand["scales"].append(seg.scales[sel] if seg.scales is not None else None)

        if not cand["ids"]:
            return np.zeros(0, np.uint64), np.zeros(0, np.float32)
        ids = np.concatenate(cand["ids"])

        from lakesoul_tpu.vector.kernels import fused_search, fused_search_ex

        use_rerank = rerank and self.keep_raw and all(r is not None for r in cand["raw"])
        if self._ex_bits:
            dists, idx = fused_search_ex(
                np.concatenate(cand["codes"]),
                np.concatenate(cand["scales"]),
                np.concatenate(cand["norms"]),
                np.concatenate(cand["factors"]),
                np.concatenate(cand["cdc"]),
                np.concatenate(cand["csq"]),
                q_glob,
                np.concatenate(cand["raw"]) if use_rerank else None,
                query,
                top_k=params.top_k,
                shortlist=params.shortlist(),
            )
            return _finalize_topk(ids, dists, idx, params.top_k)
        dists, idx = fused_search(
            np.concatenate(cand["codes"]),
            np.concatenate(cand["norms"]),
            np.concatenate(cand["factors"]),
            np.concatenate(cand["cdc"]),
            np.concatenate(cand["csq"]),
            np.concatenate(cand["csum"]),
            q_glob,
            np.concatenate(cand["raw"]) if use_rerank else None,
            query,
            d=self.quantizer.padded_dim,
            top_k=params.top_k,
            shortlist=params.shortlist(),
        )
        return _finalize_topk(ids, dists, idx, params.top_k)

    def search_filtered(self, query, allowed_ids, params: SearchParams = SearchParams()):
        return self.search(query, params, allowed_ids=np.asarray(allowed_ids, np.uint64))

    def tune_nprobe(
        self,
        queries: np.ndarray,
        *,
        target_recall: float = 0.95,
        top_k: int = 10,
        rerank_depth: int | None = None,
        candidates: list[int] | None = None,
        max_queries: int = 128,
    ) -> dict:
        """Pick the smallest ``nprobe`` whose measured recall@top_k on the
        given held-out queries meets ``target_recall`` (the faiss-autotune
        role; the reference picks nprobe by hand in its e2e tests,
        python/tests/vector/test_e2e_glove.py:182).

        Ground truth is exact brute force over the raw vectors, so the
        index must have been built with ``keep_raw=True``.  Returns
        ``{"nprobe", "recall", "target_met", "measured": [(nprobe,
        recall), ...]}`` — ``measured`` records every probed point UP TO
        the chosen one (the sweep stops at the first qualifying nprobe;
        pass explicit ``candidates`` to force a full curve)."""
        from lakesoul_tpu.errors import ConfigError

        raws, id_chunks = [], []
        for c in range(len(self.clusters)):
            for seg in self._cluster_segments(c):
                if seg.raw is None:
                    raise ConfigError(
                        "tune_nprobe needs raw vectors (build with keep_raw=True)"
                    )
                if len(seg.ids):
                    raws.append(seg.raw)
                    id_chunks.append(seg.ids)
        if not raws:
            raise ConfigError("tune_nprobe on an empty index")
        base = np.concatenate(raws)
        base_ids = np.concatenate(id_chunks)
        from lakesoul_tpu.vector.oracle import exact_topk, recall_at_k, subsample_queries

        # exact ground truth: top_k by L2 (matches the search metric) via the
        # shared recall oracle — ONE batched gram matmul for all queries
        queries = subsample_queries(queries, max_queries, self.config.seed)
        truth = exact_topk(base, base_ids, queries, top_k)
        nlist = len(self.clusters)
        if candidates is None:
            candidates, p = [], 1
            while p < nlist:
                candidates.append(p)
                p *= 2
            candidates.append(nlist)
        measured = []
        best = None
        for nprobe in sorted(set(candidates)):
            params = SearchParams(
                top_k=top_k, nprobe=nprobe, rerank_depth=rerank_depth
            )
            got_ids, _ = self.batch_search(queries, params)
            # denominator = achievable hits (a small index or duplicate ids
            # can make the truth sets smaller than top_k; perfect search
            # must be able to reach recall 1.0)
            recall = recall_at_k(truth, got_ids)
            measured.append((nprobe, recall))
            if best is None and recall >= target_recall:
                best = (nprobe, recall)
                break  # smallest qualifying nprobe: stop sweeping
        if best is None:
            best = measured[-1]
        return {
            "nprobe": best[0],
            "recall": best[1],
            "target_met": best[1] >= target_recall,
            "measured": measured,
        }

    def batch_search(self, queries: np.ndarray, params: SearchParams = SearchParams()):
        """Search many queries; with the device cache enabled, all queries run
        in ONE device call (amortizing dispatch/readback latency)."""
        queries = np.asarray(queries, np.float32)
        if getattr(self, "_device_cache_enabled", False):
            out = self._batch_search_device_resident(queries, params)
            if out is not None:
                return out
        results = [self.search(q, params) for q in queries]
        return [o[0] for o in results], [o[1] for o in results]

    def _batch_search_device_resident(self, queries: np.ndarray, params: SearchParams):
        nq = len(queries)
        # chunk oversized batches: the kernel holds the (Q, 8*d8) query block
        # and (tile, Q) output tile in VMEM, so Q is capped per call
        MAX_Q = 256
        if nq > MAX_Q:
            bundle = self._get_device_bundle()
            if bundle is None or (self._ex_bits and bundle["scales"] is None):
                return None  # same guards as _dispatch_resident, pre-chunking
            ids_all, d_all = [], []
            for start in range(0, nq, MAX_Q):
                ids_c, d_c = self._batch_search_device_resident(
                    queries[start : start + MAX_Q], params
                )
                ids_all.extend(ids_c)
                d_all.extend(d_c)
            return ids_all, d_all
        disp = self._dispatch_resident(queries, params)
        if disp is None:
            return None
        return self._resolve_resident(*disp, params)

    def search_async(self, query: np.ndarray, params: SearchParams = SearchParams()):
        """Dispatch ONE query on the device-resident bundle WITHOUT waiting
        and return a zero-arg resolver yielding (ids, dists).

        JAX dispatch is asynchronous, so a serving loop overlaps the chip
        round-trip by dispatching query i+1 before resolving query i — the
        per-call link latency then bounds *latency*, not throughput.  Falls
        back to the synchronous path (resolver returns a precomputed result)
        when no resident bundle applies."""
        query = np.asarray(query, dtype=np.float32)
        disp = None
        if getattr(self, "_device_cache_enabled", False):
            disp = self._dispatch_resident(query[None, :], params)
        if disp is None:
            out = self.search(query, params)
            return lambda: out
        dists, idx, nq, bundle = disp

        def resolve():
            ids_b, d_b = self._resolve_resident(dists, idx, nq, bundle, params)
            return ids_b[0], d_b[0]

        return resolve

    def _dispatch_resident(self, queries: np.ndarray, params: SearchParams):
        """Device dispatch of a ≤MAX_Q query block against the resident
        bundle; returns (device dists, device idx, nq, bundle) or None when
        the resident path doesn't apply.  Does NOT block on the result."""
        import jax.numpy as jnp

        from lakesoul_tpu.vector.kernels import _fused_search_resident_batch, _on_tpu

        bundle = self._get_device_bundle()
        if bundle is None:
            return None
        if self._ex_bits and bundle["scales"] is None:
            return None  # legacy segments without scales: non-resident path
        nq = len(queries)
        # bucket Q to a pow2 so variable batch sizes reuse compiled shapes
        nq_pad = 8
        while nq_pad < nq:
            nq_pad *= 2
        if nq_pad != nq:
            queries = np.pad(queries, ((0, nq_pad - nq), (0, 0)))
        nprobe = min(params.nprobe, len(self.centroids))
        cd = (
            np.sum(queries[:nq] ** 2, axis=1, keepdims=True)
            - 2.0 * queries[:nq] @ self.centroids.T
            + np.sum(self.centroids**2, axis=1)[None, :]
        )  # [Q, nlist]
        probe = np.argsort(cd, axis=1)[:, :nprobe]
        probe_mask = np.zeros((len(self.centroids), nq_pad), dtype=bool)
        for qi in range(nq):  # pad queries stay fully masked → inf distances
            probe_mask[probe[qi], qi] = True
        q_glob = self.quantizer.rotate(queries)  # [Q, d]
        # closed forms — no [nlist, Q, d] intermediate:
        #   ||c - q||² = ||c||² - 2 c·q + ||q||² ;  Σ(c - q) = Σc - Σq
        cent = self._rotated_centroids()
        csq_c = (
            np.sum(cent * cent, axis=1)[:, None]
            - 2.0 * (cent @ q_glob.T)
            + np.sum(q_glob * q_glob, axis=1)[None, :]
        ).astype(np.float32)
        csum_c = (
            np.sum(cent, axis=1)[:, None] - np.sum(q_glob, axis=1)[None, :]
        ).astype(np.float32)
        do_rerank = bundle["raw"] is not None
        n_pad = int(bundle["codes"].shape[0])
        s = min(params.shortlist(), n_pad)
        k = min(params.top_k, n_pad)
        if self._ex_bits:
            from lakesoul_tpu.vector.kernels import _fused_search_resident_ex_batch

            dists, idx = _fused_search_resident_ex_batch(
                bundle["codes"], bundle["scales"], bundle["norms"], bundle["factors"],
                bundle["cdc"], bundle["cluster_id"], jnp.asarray(probe_mask),
                jnp.asarray(csq_c), jnp.asarray(q_glob),
                bundle["raw"] if do_rerank else jnp.zeros((1, 1), jnp.float32),
                jnp.asarray(queries),
                s=s, k=k, do_rerank=do_rerank,
            )
        else:
            dists, idx = _fused_search_resident_batch(
                bundle["codes"], bundle["norms"], bundle["factors"], bundle["cdc"],
                bundle["cluster_id"], jnp.asarray(probe_mask),
                jnp.asarray(csq_c), jnp.asarray(csum_c), jnp.asarray(q_glob),
                bundle["raw"] if do_rerank else jnp.zeros((1, 1), jnp.float32),
                jnp.asarray(queries),
                d=self.quantizer.padded_dim, s=s, k=k,
                use_pallas=_on_tpu(), do_rerank=do_rerank,
            )
        return dists, idx, nq, bundle

    @staticmethod
    def _resolve_resident(dists, idx, nq, bundle, params):
        """Host-side tail of a resident search: blocks on the device values
        (np.asarray) and maps kernel row indices back to caller ids."""
        dists, idx = np.asarray(dists), np.asarray(idx)
        ids_out, d_out = [], []
        for qi in range(nq):
            valid = (idx[qi] < bundle["n"]) & np.isfinite(dists[qi])
            sel = idx[qi][valid][: params.top_k]
            ids_out.append(bundle["ids"][sel])
            d_out.append(dists[qi][valid][: params.top_k])
        return ids_out, d_out
