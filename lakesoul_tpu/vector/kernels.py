"""On-chip ANN scan kernels (Pallas TPU + jnp fallback).

The reference's compute-kernel layer is AVX-512 bit packing + FastScan LUTs
(rust/lakesoul-vector/src/rabitq/simd.rs, fastscan.rs).  On TPU the same
work is reshaped for the MXU/VPU:

- ``packed_scan``: uint8-packed sign codes stay packed in HBM; each grid step
  DMAs a (TILE, D/8) block into VMEM, unpacks with vectorized shift-and-mask
  (VPU), and computes the code·query dot as a (TILE, D) x (D, 1) MXU matvec,
  fused with the RaBitQ affine correction into estimated distances.
- ``bruteforce_topk``: tiled exact-L2 scan (MXU matmul) + top-k.

Both have pure-jnp fallbacks (used on CPU and for differential testing);
``pallas=`` auto-detects the platform.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# --------------------------------------------------------------------------
# packed RaBitQ scan
# --------------------------------------------------------------------------


def _packed_scan_kernel(q_ref, codes_ref, norms_ref, factors_ref, out_ref, *, d: int):
    """One tile: codes [T, d/8] uint8 → estimated squared distances [T].

    Mosaic-friendly unpack: no 3D reshapes — 8 shift-planes, each a 2D
    (T, d8) x (d8, 1) MXU matvec against the byte-strided query layout
    q_ref [8, d8] where q_ref[j, p] = q[8p + j] (bit j of byte p, MSB-first)."""
    packed = codes_ref[:].astype(jnp.int32)  # [T, d8]
    planes = jnp.concatenate(
        [((packed >> (7 - j)) & 1).astype(jnp.float32) for j in range(8)], axis=1
    )  # [T, 8*d8]: bit-plane j of byte p at column j*d8 + p
    q_flat = q_ref[:]  # [1, 8*d8] pre-laid-out on host in plane-concat order
    bq = jnp.dot(planes, q_flat.T, preferred_element_type=jnp.float32)  # [T, 1] MXU
    qsum = jnp.sum(q_flat)
    qsq = jnp.sum(q_flat * q_flat)
    dot_obar_q = (2.0 * bq[:, 0] - qsum) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    norms = norms_ref[0, :]
    factors = factors_ref[0, :]
    est_rq = norms * dot_obar_q / factors
    out_ref[0, :] = norms * norms + qsq - 2.0 * est_rq


@functools.partial(jax.jit, static_argnames=("d", "tile", "interpret"))
def packed_scan_pallas(
    packed_codes, norms, factors, q_rot, *, d: int, tile: int = 512,
    interpret: bool = False,
):
    """Pallas packed-code scan over one cluster: returns estimated sq-dists
    [N].  ``interpret=True`` runs the kernel in the Pallas interpreter — the
    pinned JAX has no ``force_tpu_interpret_mode``, so differential tests on
    CPU opt in per call."""
    n, d8 = packed_codes.shape
    n_pad = ((n + tile - 1) // tile) * tile
    if n_pad != n:
        packed_codes = jnp.pad(packed_codes, ((0, n_pad - n), (0, 0)))
        norms = jnp.pad(norms, (0, n_pad - n))
        factors = jnp.pad(factors, (0, n_pad - n), constant_values=1.0)
    # plane-concat query layout: q_r[0, j*d8 + p] = q[8p + j] (bit j, byte p),
    # flattened on the host so the kernel needs no shape casts
    q_pad = jnp.pad(q_rot.astype(jnp.float32), (0, d8 * 8 - q_rot.shape[0]))
    q_r = q_pad.reshape(d8, 8).T.reshape(1, d8 * 8)
    grid = (n_pad // tile,)
    out = pl.pallas_call(
        functools.partial(_packed_scan_kernel, d=d),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, d8 * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d8), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q_r, packed_codes, norms.reshape(1, -1), factors.reshape(1, -1))
    return out[0, :n]


def _pow2_bucket(n: int, floor: int = 512) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def packed_scan(
    packed_codes, norms, factors, q_rot, *, d: int, pallas: bool | None = None,
    interpret: bool = False,
):
    """Estimated sq-distances for one cluster's packed codes (auto backend).

    Cluster sizes are padded to power-of-2 buckets so repeated searches over
    many differently-sized clusters share compiled kernels instead of
    triggering a fresh XLA/Mosaic compile per shape."""
    from lakesoul_tpu.vector.rabitq import estimate_distances

    n = len(packed_codes)
    if n == 0:
        return jnp.zeros(0, jnp.float32)
    n_pad = _pow2_bucket(n)
    if n_pad != n:
        packed_codes = np.pad(np.asarray(packed_codes), ((0, n_pad - n), (0, 0)))
        norms = np.pad(np.asarray(norms), (0, n_pad - n))
        factors = np.pad(np.asarray(factors), (0, n_pad - n), constant_values=1.0)

    use_pallas = _on_tpu() if pallas is None else pallas
    if use_pallas:
        out = packed_scan_pallas(
            jnp.asarray(packed_codes), jnp.asarray(norms), jnp.asarray(factors),
            jnp.asarray(q_rot), d=d, interpret=interpret,
        )
    else:
        out = estimate_distances(
            jnp.asarray(packed_codes), jnp.asarray(norms), jnp.asarray(factors),
            jnp.asarray(q_rot), d=d,
        )
    # slice on the host: an eager on-device slice would compile per shape
    return np.asarray(out)[:n]


# pad sentinels shared by every padded-candidate path (fused_search host
# wrapper and the device-resident bundle): pad rows must sort last and divide
# safely
PAD_NORM = np.float32(1e9)
PAD_FACTOR = np.float32(1.0)
PAD_RAW = np.float32(1e9)


def _packed_dot_kernel(q_ref, codes_ref, out_ref):
    """bits·Q for one tile (same Mosaic-friendly plane-concat trick as the
    full scan kernel)."""
    packed = codes_ref[:].astype(jnp.int32)
    planes = jnp.concatenate(
        [((packed >> (7 - j)) & 1).astype(jnp.float32) for j in range(8)], axis=1
    )
    bq = jnp.dot(planes, q_ref[:].T, preferred_element_type=jnp.float32)
    out_ref[0, :] = bq[:, 0]


def _packed_dot_batch_kernel(q_ref, codes_ref, out_ref):
    """bits·Q for one tile against MANY queries: the unpacked plane matrix
    only ever exists per (tile, 8·d8) block in VMEM — HBM holds packed codes
    regardless of shard size."""
    packed = codes_ref[:].astype(jnp.int32)
    planes = jnp.concatenate(
        [((packed >> (7 - j)) & 1).astype(jnp.float32) for j in range(8)], axis=1
    )  # [T, 8*d8]
    out_ref[:, :] = jnp.dot(planes, q_ref[:].T, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def packed_dot_batch_pallas(packed_codes, q_rot_batch, *, tile: int = 512,
                            interpret: bool = False):
    """bits·Q over [N, d8] packed codes × [Q, d] queries → [N, Q] f32."""
    n, d8 = packed_codes.shape
    nq = q_rot_batch.shape[0]
    n_pad = ((n + tile - 1) // tile) * tile
    if n_pad != n:
        packed_codes = jnp.pad(packed_codes, ((0, n_pad - n), (0, 0)))
    q_pad = jnp.pad(
        q_rot_batch.astype(jnp.float32), ((0, 0), (0, d8 * 8 - q_rot_batch.shape[1]))
    )
    # per-query plane-concat layout: [Q, 8*d8] with q[:, j*d8 + p] = q[:, 8p+j]
    q_r = q_pad.reshape(nq, d8, 8).transpose(0, 2, 1).reshape(nq, d8 * 8)
    out = pl.pallas_call(
        _packed_dot_batch_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, nq), jnp.float32),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((nq, d8 * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d8), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, nq), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q_r, packed_codes)
    return out[:n]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def packed_dot_pallas(packed_codes, q_rot, *, tile: int = 512,
                      interpret: bool = False):
    """bits·Q over [N, d8] packed codes → [N] f32 (Pallas TPU)."""
    n, d8 = packed_codes.shape
    n_pad = ((n + tile - 1) // tile) * tile
    if n_pad != n:
        packed_codes = jnp.pad(packed_codes, ((0, n_pad - n), (0, 0)))
    q_pad = jnp.pad(q_rot.astype(jnp.float32), (0, d8 * 8 - q_rot.shape[0]))
    q_r = q_pad.reshape(d8, 8).T.reshape(1, d8 * 8)
    out = pl.pallas_call(
        _packed_dot_kernel,
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, d8 * 8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d8), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q_r, packed_codes)
    return out[0, :n]


@jax.jit
def _packed_dot_jnp(packed_codes, q_rot):
    from lakesoul_tpu.vector.rabitq import unpack_bits_jnp

    bits = unpack_bits_jnp(packed_codes, q_rot.shape[0])
    return bits @ q_rot


@functools.partial(jax.jit, static_argnames=("d", "s", "k", "use_pallas", "do_rerank"))
def _fused_search(codes, norms, factors, code_dot_c, csq, csum, q_glob, raw, query,
                  *, d, s, k, use_pallas, do_rerank):
    """One device call per query over the concatenated probe set.

    Estimator in the *global* query frame (rows may come from different
    clusters): with Q = P(query), xc = P(c) - Q per row's cluster,
        dist² ≈ ||r||² + ||xc||² + 2·||r||·<o_bar, xc>/factor
        <o_bar, xc> = (2·(code_dot_c - bits·Q) - csum) / √D
    so the only O(N·D) work is ONE bits·Q MXU scan; csq=||xc||², csum=Σxc
    are per-row scalars precomputed on the host.  Then top-S shortlist →
    on-device gather + exact re-rank → top-k; single [k] readback."""
    bq = (
        packed_dot_pallas(codes, q_glob)
        if use_pallas
        else _packed_dot_jnp(codes, q_glob)
    )
    dot_obar_xc = (2.0 * (code_dot_c - bq) - csum) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    est = norms * norms + csq + 2.0 * norms * dot_obar_xc / factors
    if not do_rerank:
        neg, idx = jax.lax.top_k(-est, k)
        return -neg, idx
    neg_s, idx_s = jax.lax.top_k(-est, s)
    sub = raw[idx_s]  # on-device gather of shortlisted raw vectors
    q = query.astype(jnp.float32)
    exact = jnp.sum(sub * sub, axis=1) - 2.0 * (sub @ q) + jnp.sum(q * q)
    neg, order = jax.lax.top_k(-exact, k)
    return -neg, idx_s[order]


@functools.partial(jax.jit, static_argnames=("d", "s", "k", "use_pallas", "do_rerank"))
def _fused_search_resident(codes, norms, factors, code_dot_c, cluster_id, probe_mask,
                           csq_c, csum_c, q_glob, raw, query,
                           *, d, s, k, use_pallas, do_rerank):
    """Device-resident variant: the WHOLE shard stays in HBM (codes, factors,
    raw, cluster ids); per query only the rotated query and three (nlist,)
    scalar vectors travel.  Non-probed clusters are masked to +inf — on the
    MXU, scanning everything beats re-uploading per-probe concatenations
    (compute is cheaper than transfers)."""
    bq = (
        packed_dot_pallas(codes, q_glob)
        if use_pallas
        else _packed_dot_jnp(codes, q_glob)
    )
    csq = csq_c[cluster_id]
    csum = csum_c[cluster_id]
    dot_obar_xc = (2.0 * (code_dot_c - bq) - csum) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    est = norms * norms + csq + 2.0 * norms * dot_obar_xc / factors
    est = jnp.where(probe_mask[cluster_id], est, jnp.inf)
    if not do_rerank:
        neg, idx = jax.lax.top_k(-est, k)
        return -neg, idx
    neg_s, idx_s = jax.lax.top_k(-est, s)
    sub = raw[idx_s]
    q = query.astype(jnp.float32)
    exact = jnp.sum(sub * sub, axis=1) - 2.0 * (sub @ q) + jnp.sum(q * q)
    exact = jnp.where(jnp.isfinite(-neg_s), exact, jnp.inf)  # masked rows stay out
    neg, order = jax.lax.top_k(-exact, k)
    return -neg, idx_s[order]


def _batched_rerank_topk(est, raw, queries, *, s: int, k: int, do_rerank: bool):
    """Shared tail of the batched resident kernels: [N, Q] estimates →
    (dists [Q, k], indices [Q, k]), with optional on-device exact re-rank."""
    est_t = est.T
    if not do_rerank:
        neg, idx = jax.lax.top_k(-est_t, k)
        return -neg, idx
    neg_s, idx_s = jax.lax.top_k(-est_t, s)
    sub = raw[idx_s]
    q32 = queries.astype(jnp.float32)
    exact = (
        jnp.sum(sub * sub, axis=-1)
        - 2.0 * jnp.einsum("qsd,qd->qs", sub, q32)
        + jnp.sum(q32 * q32, axis=-1)[:, None]
    )
    exact = jnp.where(jnp.isfinite(-neg_s), exact, jnp.inf)
    neg, order = jax.lax.top_k(-exact, k)
    return -neg, jnp.take_along_axis(idx_s, order, axis=1)


@functools.partial(jax.jit, static_argnames=("d", "s", "k", "use_pallas", "do_rerank"))
def _fused_search_resident_batch(codes, norms, factors, code_dot_c, cluster_id,
                                 probe_mask, csq_c, csum_c, q_glob, raw, queries,
                                 *, d, s, k, use_pallas, do_rerank):
    """Batched device-resident search: Q queries amortize one dispatch +
    readback.  On TPU the packed-code Pallas kernel keeps codes packed in HBM
    (plane unpack happens per tile in VMEM); the jnp fallback materializes
    the unpacked bit matrix and is only meant for CPU-sized shards."""
    if use_pallas:
        bq = packed_dot_batch_pallas(codes, q_glob)       # [N, Q]
    else:
        from lakesoul_tpu.vector.rabitq import unpack_bits_jnp

        bits = unpack_bits_jnp(codes, d)                  # [N, d]
        bq = bits @ q_glob.T                              # [N, Q] MXU
    csq = csq_c[cluster_id]                               # [N, Q]
    csum = csum_c[cluster_id]
    dot_obar_xc = (2.0 * (code_dot_c[:, None] - bq) - csum) / jnp.sqrt(
        jnp.asarray(d, jnp.float32)
    )
    est = norms[:, None] ** 2 + csq + 2.0 * norms[:, None] * dot_obar_xc / factors[:, None]
    est = jnp.where(probe_mask[cluster_id], est, jnp.inf)  # [N, Q]
    return _batched_rerank_topk(est, raw, queries, s=s, k=k, do_rerank=do_rerank)


@functools.partial(jax.jit, static_argnames=("s", "k", "do_rerank"))
def _fused_search_ex(codes, scales, norms, factors, code_dot_c, csq, q_glob, raw,
                     query, *, s, k, do_rerank):
    """Fused search over int8 ex-codes (total_bits > 1): one MXU int8 matvec
    u_hat·Q, then the global-frame estimator
        dist² ≈ ||r||² + ||xc||² + 2·||r||·(code_dot_c - u_hat·Q)/factor
    (csum is unnecessary: u_hat is a real-valued vector, not ±1 bits)."""
    g = (codes.astype(jnp.int32) @ q_glob.astype(jnp.float32)) * scales  # [N]
    est = norms * norms + csq + 2.0 * norms * (code_dot_c - g) / factors
    if not do_rerank:
        neg, idx = jax.lax.top_k(-est, k)
        return -neg, idx
    neg_s, idx_s = jax.lax.top_k(-est, s)
    sub = raw[idx_s]
    q = query.astype(jnp.float32)
    exact = jnp.sum(sub * sub, axis=1) - 2.0 * (sub @ q) + jnp.sum(q * q)
    neg, order = jax.lax.top_k(-exact, k)
    return -neg, idx_s[order]


def _pad_tail(a, n_pad: int, const=0):
    """Pad a candidate array's first axis to n_pad with a constant."""
    a = np.asarray(a)
    pad = n_pad - len(a)
    if pad <= 0:
        return a
    width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, width, constant_values=const)


def fused_search_ex(codes, scales, norms, factors, code_dot_c, csq, q_glob, raw,
                    query, *, top_k, shortlist):
    """Host wrapper for the int8 ex-code path (pow2 padding, pad filtering
    mirrors fused_search)."""
    n = len(codes)
    n_pad = _pow2_bucket(n)
    codes = _pad_tail(codes, n_pad)
    scales = _pad_tail(scales, n_pad)
    norms = _pad_tail(norms, n_pad, PAD_NORM)
    factors = _pad_tail(factors, n_pad, PAD_FACTOR)
    code_dot_c = _pad_tail(code_dot_c, n_pad)
    csq = _pad_tail(csq, n_pad)
    if raw is not None:
        raw = _pad_tail(raw, n_pad, PAD_RAW)
    do_rerank = raw is not None
    s = min(shortlist, n_pad)
    k = min(top_k, n_pad)
    dists, idx = _fused_search_ex(
        jnp.asarray(codes),
        jnp.asarray(np.asarray(scales, np.float32)),
        jnp.asarray(np.asarray(norms, np.float32)),
        jnp.asarray(np.asarray(factors, np.float32)),
        jnp.asarray(np.asarray(code_dot_c, np.float32)),
        jnp.asarray(np.asarray(csq, np.float32)),
        jnp.asarray(q_glob, dtype=jnp.float32),
        jnp.asarray(raw) if do_rerank else jnp.zeros((1, 1), jnp.float32),
        jnp.asarray(query, dtype=jnp.float32),
        s=s, k=k, do_rerank=do_rerank,
    )
    return np.asarray(dists), np.asarray(idx)


@functools.partial(jax.jit, static_argnames=("s", "k", "do_rerank"))
def _fused_search_resident_ex_batch(codes, scales, norms, factors, code_dot_c,
                                    cluster_id, probe_mask, csq_c, q_glob, raw,
                                    queries, *, s, k, do_rerank):
    """Device-resident batched search over int8 ex-codes: codes are already
    MXU-native, so u_hat·Q is one (N, d) x (d, Q) int8×f32 matmul — no unpack
    stage at all."""
    g = (codes.astype(jnp.int32) @ q_glob.T.astype(jnp.float32)) * scales[:, None]  # [N, Q]
    csq = csq_c[cluster_id]  # [N, Q]
    est = (
        norms[:, None] ** 2
        + csq
        + 2.0 * norms[:, None] * (code_dot_c[:, None] - g) / factors[:, None]
    )
    est = jnp.where(probe_mask[cluster_id], est, jnp.inf)
    return _batched_rerank_topk(est, raw, queries, s=s, k=k, do_rerank=do_rerank)


def fused_search(codes, norms, factors, code_dot_c, csq, csum, q_glob, raw, query,
                 *, d, top_k, shortlist, pallas: bool | None = None):
    """Host wrapper: pow2-pad candidate arrays, run the fused kernel, return
    (dists, global indices) as numpy — indices >= the true candidate count
    are pad rows the caller must drop."""
    n = len(codes)
    n_pad = _pow2_bucket(n)
    codes = _pad_tail(codes, n_pad)
    # pad rows get a huge norm → huge estimated distance → never selected
    norms = _pad_tail(norms, n_pad, PAD_NORM)
    factors = _pad_tail(factors, n_pad, PAD_FACTOR)
    code_dot_c = _pad_tail(code_dot_c, n_pad)
    csq = _pad_tail(csq, n_pad)
    csum = _pad_tail(csum, n_pad)
    if raw is not None:
        raw = _pad_tail(raw, n_pad, PAD_RAW)
    do_rerank = raw is not None
    s = min(shortlist, n_pad)
    k = min(top_k, n_pad)
    use_pallas = _on_tpu() if pallas is None else pallas
    dists, idx = _fused_search(
        jnp.asarray(codes),
        jnp.asarray(np.asarray(norms, np.float32)),
        jnp.asarray(np.asarray(factors, np.float32)),
        jnp.asarray(np.asarray(code_dot_c, np.float32)),
        jnp.asarray(np.asarray(csq, np.float32)),
        jnp.asarray(np.asarray(csum, np.float32)),
        jnp.asarray(q_glob, dtype=jnp.float32),
        jnp.asarray(raw) if do_rerank else jnp.zeros((1, 1), jnp.float32),
        jnp.asarray(query, dtype=jnp.float32),
        d=d, s=s, k=k, use_pallas=use_pallas, do_rerank=do_rerank,
    )
    return np.asarray(dists), np.asarray(idx)


# --------------------------------------------------------------------------
# brute-force exact scan + top-k
# --------------------------------------------------------------------------


def _bruteforce_kernel(q_ref, x_ref, out_ref):
    x = x_ref[:]  # [T, D]
    q = q_ref[:]  # [1, D]
    dots = jnp.dot(x, q.T, preferred_element_type=jnp.float32)[:, 0]
    x_sq = jnp.sum(x.astype(jnp.float32) * x.astype(jnp.float32), axis=1)
    q_sq = jnp.sum(q * q)
    out_ref[0, :] = x_sq - 2.0 * dots + q_sq


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def bruteforce_distances_pallas(vectors, query, *, tile: int = 512,
                                interpret: bool = False):
    n, d = vectors.shape
    n_pad = ((n + tile - 1) // tile) * tile
    if n_pad != n:
        vectors = jnp.pad(vectors, ((0, n_pad - n), (0, 0)))
    q2 = query.reshape(1, -1).astype(jnp.float32)
    out = pl.pallas_call(
        _bruteforce_kernel,
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q2, vectors)
    return out[0, :n]


@jax.jit
def _bruteforce_jnp(vectors, query):
    v = vectors.astype(jnp.float32)
    q = query.astype(jnp.float32)
    return jnp.sum(v * v, axis=1) - 2.0 * (v @ q) + jnp.sum(q * q)


def bruteforce_topk(vectors, query, k: int, *, pallas: bool | None = None):
    """Exact L2 top-k over [N, D] vectors: returns (dists [k], indices [k]).
    N is padded to a power-of-2 bucket (pad rows at +inf distance) to keep
    the compiled-shape count logarithmic."""
    use_pallas = _on_tpu() if pallas is None else pallas
    n = len(vectors)
    k = min(k, n)
    n_pad = _pow2_bucket(n, floor=max(512, k))
    v = np.asarray(vectors, dtype=np.float32)
    if n_pad != n:
        v = np.pad(v, ((0, n_pad - n), (0, 0)), constant_values=np.float32(1e18))
    v = jnp.asarray(v)
    q = jnp.asarray(query)
    if use_pallas:
        return _topk_pallas(v, q, k=k)
    return _topk_jnp(v, q, k=k)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_pallas(v, q, *, k: int):
    dists = bruteforce_distances_pallas(v, q)
    neg, idx = jax.lax.top_k(-dists, k)
    return -neg, idx


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_jnp(v, q, *, k: int):
    dists = _bruteforce_jnp(v, q)
    neg, idx = jax.lax.top_k(-dists, k)
    return -neg, idx
