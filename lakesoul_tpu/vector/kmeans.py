"""k-means clustering in JAX (Lloyd's iterations as one jitted scan).

Replaces the reference's CPU kmeans (rust/lakesoul-vector/src/rabitq/kmeans.rs)
with an MXU formulation: the assignment step is a single (N, D) x (D, K)
matmul; the update step is a segment-sum via one-hot matmul — both map
straight onto the systolic array.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def _kmeans_jit(data: jax.Array, init_idx: jax.Array, *, k: int, iters: int):
    x = data.astype(jnp.float32)
    n, d = x.shape
    centroids = x[init_idx]  # [K, D]
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # [N, 1]

    def step(carry, _):
        centroids = carry
        c_sq = jnp.sum(centroids * centroids, axis=1)  # [K]
        # ||x - c||^2 = ||x||^2 - 2 x.c + ||c||^2 ; argmin over K
        dots = x @ centroids.T  # [N, K] on the MXU
        assign = jnp.argmin(x_sq - 2.0 * dots + c_sq[None, :], axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32)  # [N, K]
        sums = onehot.T @ x  # [K, D]
        counts = jnp.sum(onehot, axis=0)[:, None]  # [K, 1]
        new_centroids = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), centroids)
        return new_centroids, None

    centroids, _ = jax.lax.scan(step, centroids, None, length=iters)
    c_sq = jnp.sum(centroids * centroids, axis=1)
    assign = jnp.argmin(x_sq - 2.0 * (x @ centroids.T) + c_sq[None, :], axis=1)
    return centroids, assign


def kmeans(data: np.ndarray, k: int, *, iters: int = 10, seed: int = 42):
    """Returns (centroids [K, D] f32, assignments [N] i32)."""
    n = len(data)
    rng = np.random.default_rng(seed)
    k_eff = min(k, n)
    init_idx = jnp.asarray(rng.choice(n, size=k_eff, replace=False))
    if k_eff < k:
        # degenerate tiny input: pad by repeating points
        init_idx = jnp.concatenate([init_idx, init_idx[np.zeros(k - k_eff, dtype=int)]])
    centroids, assign = _kmeans_jit(jnp.asarray(data), init_idx, k=k, iters=iters)
    return np.asarray(centroids), np.asarray(assign).astype(np.int32)
