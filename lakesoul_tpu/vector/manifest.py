"""Versioned vector-index persistence on the object store.

Layout parity with ManifestStore (rust/lakesoul-vector/src/rabitq/
manifest.rs:38): a ``LATEST`` pointer → ``manifests/manifest-<gen>-<ver>.json``
→ ``cluster_<c>[.delta_<i>].seg`` segment files, every blob CRC32-checked.
Segments are npz blobs (codes/norms/factors/ids[/raw]) — host-side IO only,
the chip never touches manifests."""

from __future__ import annotations

import io
import json
import zlib

import numpy as np

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.io.object_store import ensure_dir, filesystem_for
from lakesoul_tpu.runtime import atomicio
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, _Cluster

LATEST = "LATEST"


def _crc_wrap(payload: bytes) -> bytes:
    return zlib.crc32(payload).to_bytes(4, "big") + payload


def _crc_unwrap(blob: bytes, what: str) -> bytes:
    if len(blob) < 4:
        raise VectorIndexError(f"corrupt {what}: too short")
    crc, payload = int.from_bytes(blob[:4], "big"), blob[4:]
    if zlib.crc32(payload) != crc:
        raise VectorIndexError(f"corrupt {what}: CRC mismatch")
    return payload


class ManifestStore:
    def __init__(self, root: str, storage_options: dict | None = None):
        self.root = root.rstrip("/")
        self.storage_options = storage_options or {}
        self.fs, self.root_path = filesystem_for(self.root, self.storage_options, write=True)

    # ------------------------------------------------------------------ write
    def write_index(self, index: IvfRabitqIndex, *, generation: int | None = None,
                    indexed_files: list[str] | None = None) -> int:
        """``indexed_files`` records which table data files this shard covers,
        enabling incremental refresh (only new files are inserted)."""
        ensure_dir(f"{self.root}/manifests", self.storage_options)
        ensure_dir(f"{self.root}/segments", self.storage_options)
        if generation is None:
            generation = self.latest_generation() + 1

        seg_names: dict[str, list[str]] = {"base": [], "delta": []}
        for c, cluster in enumerate(index.clusters):
            name = f"segments/cluster_{c}.gen{generation}.seg"
            self._write_segment(name, cluster)
            seg_names["base"].append(name)
        delta_entries = []
        for c, deltas in enumerate(index.deltas):
            for i, seg in enumerate(deltas):
                name = f"segments/cluster_{c}.gen{generation}.delta_{i}.seg"
                self._write_segment(name, seg)
                delta_entries.append({"cluster": c, "path": name})

        manifest = {
            "generation": generation,
            "config": index.config.encode(),
            "keep_raw": index.keep_raw,
            "num_vectors": index.num_vectors,
            "centroids": index.centroids.tolist() if index.centroids is not None else None,
            "base_segments": seg_names["base"],
            "delta_segments": delta_entries,
            "indexed_files": sorted(indexed_files or []),
        }
        mpath = f"manifests/manifest-{generation}.json"
        self._write_blob(mpath, _crc_wrap(json.dumps(manifest).encode()))
        self._write_blob(LATEST, _crc_wrap(mpath.encode()))
        return generation

    def _write_segment(self, name: str, cluster: _Cluster) -> None:
        buf = io.BytesIO()
        arrays = {
            "codes": cluster.codes,
            "norms": cluster.norms,
            "factors": cluster.factors,
            "ids": cluster.ids,
        }
        if cluster.code_dot_c is not None:
            arrays["code_dot_c"] = cluster.code_dot_c
        if cluster.scales is not None:
            arrays["scales"] = cluster.scales
        if cluster.raw is not None:
            arrays["raw"] = cluster.raw
        np.savez(buf, **arrays)
        self._write_blob(name, _crc_wrap(buf.getvalue()))

    def _write_blob(self, rel: str, data: bytes) -> None:
        # publication through the sanctioned seam: the LATEST pointer is
        # overwritten on every write_index, and a torn in-place overwrite
        # would make the WHOLE store unreadable (CRC error, not old-or-new)
        atomicio.publish_bytes_fs(self.fs, f"{self.root_path}/{rel}", data)

    def _read_blob(self, rel: str) -> bytes:
        with self.fs.open(f"{self.root_path}/{rel}", "rb") as f:
            return f.read()

    # ------------------------------------------------------------------- read
    def latest_generation(self) -> int:
        try:
            mpath = _crc_unwrap(self._read_blob(LATEST), "LATEST").decode()
        except FileNotFoundError:
            return 0
        return int(mpath.rsplit("-", 1)[-1].split(".")[0])

    def exists(self) -> bool:
        return self.fs.exists(f"{self.root_path}/{LATEST}")

    def read_manifest(self) -> dict:
        mpath = _crc_unwrap(self._read_blob(LATEST), "LATEST").decode()
        return json.loads(_crc_unwrap(self._read_blob(mpath), mpath))

    def read_manifest_at(self, generation: int) -> dict:
        """A PINNED generation's manifest, bypassing the LATEST pointer —
        manifests are immutable once written, so a reader holding a
        generation number (the ANN plane's per-shard records) is immune to
        a concurrent rebuild swapping LATEST underneath it."""
        mpath = f"manifests/manifest-{generation}.json"
        return json.loads(_crc_unwrap(self._read_blob(mpath), mpath))

    def read_at(self, generation: int) -> IvfRabitqIndex:
        return self._load(self.read_manifest_at(generation))

    def read_latest(self) -> IvfRabitqIndex:
        return self._load(self.read_manifest())

    def _load(self, manifest: dict) -> IvfRabitqIndex:
        config = VectorIndexConfig.parse(manifest["config"])
        index = IvfRabitqIndex(config)
        index.keep_raw = manifest["keep_raw"]
        index.centroids = (
            np.asarray(manifest["centroids"], dtype=np.float32)
            if manifest["centroids"] is not None
            else None
        )
        index.clusters = [
            self._read_segment(p) for p in manifest["base_segments"]
        ]
        if config.total_bits > 1 and any(
            c.scales is None for c in index.clusters if len(c.ids)
        ):
            # legacy shard: written when total_bits > 1 was accepted but only
            # 1-bit quantization existed (no scales persisted) — treat as 1-bit
            import dataclasses

            index.config = dataclasses.replace(config, total_bits=1)
        index.deltas = [[] for _ in index.clusters]
        for entry in manifest["delta_segments"]:
            index.deltas[entry["cluster"]].append(self._read_segment(entry["path"]))
        return index

    def _read_segment(self, rel: str) -> _Cluster:
        payload = _crc_unwrap(self._read_blob(rel), rel)
        z = np.load(io.BytesIO(payload))
        return _Cluster(
            codes=z["codes"],
            norms=z["norms"],
            factors=z["factors"],
            ids=z["ids"],
            code_dot_c=z["code_dot_c"] if "code_dot_c" in z.files else None,
            raw=z["raw"] if "raw" in z.files else None,
            scales=z["scales"] if "scales" in z.files else None,
        )
