"""Exact brute-force recall oracle, shared by autotune, tests, and benches.

One definition of ground truth for every recall@k claim in the repo: the
``tune_nprobe`` autotuner, the single-shard vs multi-shard parity tests, and
the ``ann_scale`` bench leg all measure against THIS oracle, so a recall
number from any of them means the same thing.  Two shapes:

- :func:`exact_topk` — in-memory corpora: one batched gram matmul for all
  queries (the tune_nprobe formulation, hoisted here).
- :class:`StreamingExactOracle` — corpora too large to hold: consume
  (vectors, ids) chunks and keep a bounded per-query best-k, so exact truth
  over a 10M x 128d stream costs O(Q * k) memory.

Recall semantics match the autotuner's: the denominator is the *achievable*
hit count (truth sets can be smaller than k on tiny or duplicate-id
corpora; a perfect search must be able to reach recall 1.0)."""

from __future__ import annotations

import numpy as np


def subsample_queries(queries: np.ndarray, max_queries: int, seed: int) -> np.ndarray:
    """Seeded query subsample so repeated oracle runs measure the same set."""
    queries = np.asarray(queries, np.float32)
    if len(queries) <= max_queries:
        return queries
    rng = np.random.default_rng(seed)
    return queries[rng.choice(len(queries), max_queries, replace=False)]


def exact_topk(
    base: np.ndarray, base_ids: np.ndarray, queries: np.ndarray, k: int
) -> list[set]:
    """Exact L2 top-k truth sets, one per query.

    ONE batched gram matmul for all queries (not a per-query base pass);
    ``k`` is clamped to the corpus size."""
    base = np.asarray(base, np.float32)
    base_ids = np.asarray(base_ids)
    queries = np.asarray(queries, np.float32)
    d2 = (
        np.sum(queries**2, axis=1, keepdims=True)
        - 2.0 * queries @ base.T
        + np.sum(base**2, axis=1)[None, :]
    )
    k_eff = min(k, d2.shape[1])
    part = np.argpartition(d2, k_eff - 1, axis=1)[:, :k_eff]
    return [set(base_ids[row].tolist()) for row in part]


def recall_at_k(truth: list[set], got_ids) -> float:
    """Achievable-hit recall: |truth ∩ got| summed over queries, divided by
    the total achievable hits (``sum(len(t))``, not ``Q * k``)."""
    hits = sum(
        len(truth[i] & {int(x) for x in got_ids[i]}) for i in range(len(truth))
    )
    return hits / max(1, sum(len(t) for t in truth))


class StreamingExactOracle:
    """Exact top-k over a corpus streamed in chunks (bounded memory).

    Holds per-query running (distances, ids) of size ``k``; each consumed
    chunk costs one [Q, chunk] gram matmul and a k-merge.  ``truth()``
    returns the same ``list[set]`` shape as :func:`exact_topk`."""

    def __init__(self, queries: np.ndarray, k: int):
        self.queries = np.asarray(queries, np.float32)
        self.k = int(k)
        self._q_sq = np.sum(self.queries**2, axis=1, keepdims=True)
        nq = len(self.queries)
        self._best_d = np.full((nq, self.k), np.inf, np.float32)
        self._best_i = np.zeros((nq, self.k), np.uint64)
        self.rows = 0

    def consume(self, vectors: np.ndarray, ids: np.ndarray) -> None:
        vectors = np.asarray(vectors, np.float32)
        ids = np.asarray(ids, np.uint64)
        if not len(ids):
            return
        d2 = (
            self._q_sq
            - 2.0 * self.queries @ vectors.T
            + np.sum(vectors**2, axis=1)[None, :]
        ).astype(np.float32)
        cand_d = np.concatenate([self._best_d, d2], axis=1)
        cand_i = np.concatenate(
            [self._best_i, np.broadcast_to(ids, (len(self.queries), len(ids)))],
            axis=1,
        )
        part = np.argpartition(cand_d, self.k - 1, axis=1)[:, : self.k]
        self._best_d = np.take_along_axis(cand_d, part, axis=1)
        self._best_i = np.take_along_axis(cand_i, part, axis=1)
        self.rows += len(ids)

    def truth(self) -> list[set]:
        k_eff = min(self.k, self.rows)
        out = []
        for qi in range(len(self.queries)):
            order = np.argsort(self._best_d[qi])[:k_eff]
            out.append({int(x) for x in self._best_i[qi][order]})
        return out
