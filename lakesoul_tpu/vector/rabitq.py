"""RaBitQ quantization: rotation + 1-bit sign codes + unbiased distance
estimation factors.

Math (capability parity with rust/lakesoul-vector/src/rabitq/quantizer.rs,
redesigned for TPU layouts — the reference's AVX-512 bit tricks don't
transfer, see SURVEY.md §7):

For a vector v in cluster c:  r = P(v - c)  (P = random rotation)
  norm      = ||r||
  b         = sign(r) ∈ {-1,+1}^D,  stored packed (D/8 uint8, MSB-first)
  o_bar     = b / √D  (the quantized unit vector)
  factor    = <o_bar, r/||r||>  (quantization quality of this vector)

At query time with rotated residual q = P(query - c):
  <r, q> ≈ norm * <o_bar, q> / factor
  ||v - query||² = norm² + ||q||² - 2<r, q>

<o_bar, q> reduces to a ±1 dot, computed on the MXU from unpacked codes:
  b·q = 2·(bits·q) - sum(q)   with bits ∈ {0,1}.

Rotations: "fht" = fast Hadamard transform with random sign flips (FhtKac,
reference rotation.rs) — O(D log D), jittable; "matrix" = dense random
orthonormal matrix (one (D, D) MXU matmul); "identity" for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from lakesoul_tpu.errors import VectorIndexError


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class Rotator:
    """Orthonormal rotation P applied to (possibly zero-padded) vectors.

    Computed in numpy on the host: rotations are O(D log D) per vector and run
    once per build/query, while eager per-shape XLA dispatch would trigger a
    fresh TPU compile for every distinct cluster size — the scans (the actual
    FLOPs) stay on-chip."""

    def __init__(self, dim: int, kind: str = "fht", seed: int = 42, rounds: int = 3):
        self.dim = dim
        self.kind = kind
        self.padded_dim = next_pow2(dim) if kind == "fht" else dim
        rng = np.random.default_rng(seed)
        if kind == "fht":
            # FhtKac: alternating random-sign flips and Hadamard transforms
            self.signs = rng.choice([-1.0, 1.0], size=(rounds, self.padded_dim)).astype(
                np.float32
            )
        elif kind == "matrix":
            a = rng.normal(size=(dim, dim)).astype(np.float32)
            q, _ = np.linalg.qr(a)
            self.matrix = q.astype(np.float32)
        elif kind == "identity":
            pass
        else:
            raise VectorIndexError(f"unknown rotator {kind}")

    def __call__(self, x) -> np.ndarray:
        """x [..., dim] → rotated [..., padded_dim] (numpy)."""
        x = np.asarray(x, dtype=np.float32)
        if self.kind == "identity":
            return x
        if self.kind == "matrix":
            return x @ self.matrix
        pad = self.padded_dim - self.dim
        if pad:
            x = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        scale = np.float32(1.0 / np.sqrt(self.padded_dim))
        for r in range(self.signs.shape[0]):
            x = x * self.signs[r]
            x = _fht(x) * scale
        return x


def _fht(x: np.ndarray) -> np.ndarray:
    """Fast Hadamard transform along the last axis (power-of-two length)."""
    d = x.shape[-1]
    lead = x.shape[:-1]
    h = 1
    x = x.copy()
    while h < d:
        x = x.reshape(lead + (d // (2 * h), 2, h))
        a = x[..., 0, :].copy()
        b = x[..., 1, :].copy()
        x[..., 0, :] = a + b
        x[..., 1, :] = a - b
        x = x.reshape(lead + (d,))
        h *= 2
    return x


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """[N, D] {0,1} → [N, D/8] uint8 (D padded to a byte multiple, MSB-first)."""
    from lakesoul_tpu import native

    if native.available() and bits.ndim == 2 and len(bits):
        return native.pack_bits(bits.astype(np.uint8))
    return np.packbits(bits.astype(np.uint8), axis=-1)


def unpack_bits_jnp(packed: jax.Array, d: int) -> jax.Array:
    """[N, D/8] uint8 → [N, D] {0,1} float32, vectorized shift-and-mask
    (the TPU-native replacement of the AVX-512 unpack, simd.rs:229-290)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)  # MSB-first like np.packbits
    bits = (packed[..., :, None] >> shifts[None, None, :]) & jnp.uint8(1)
    return bits.reshape(packed.shape[0], -1)[:, :d].astype(jnp.float32)


class RabitqQuantizer:
    """Quantize cluster residuals → packed codes + per-vector factors."""

    def __init__(self, dim: int, *, rotator: str = "fht", seed: int = 42):
        self.dim = dim
        self.rotator = Rotator(dim, rotator, seed)
        self.padded_dim = self.rotator.padded_dim

    def quantize(self, vectors: np.ndarray, centroid: np.ndarray):
        """vectors [N, dim], centroid [dim] →
        (codes [N, padded/8] uint8, norms [N] f32, factors [N] f32,
         code_dot_c [N] f32).

        ``code_dot_c`` = bits · P(centroid), precomputed so multi-cluster
        searches can use ONE globally-rotated query:  bits·P(query - c) =
        bits·P(query) - code_dot_c  (rotation is linear)."""
        r = self.rotator(vectors - centroid[None, :])
        norms = np.linalg.norm(r, axis=1)
        safe = np.maximum(norms, 1e-20)
        unit = r / safe[:, None]
        bits = (r > 0).astype(np.uint8)
        o_bar = (bits * 2.0 - 1.0) / np.sqrt(self.padded_dim)
        factors = np.sum(o_bar * unit, axis=1).astype(np.float32)
        # guard: zero/degenerate vectors get factor 1 (estimator returns norm²)
        factors = np.where(np.abs(factors) < 1e-6, 1.0, factors)
        c_rot = self.rotator(centroid.astype(np.float32))
        code_dot_c = (bits.astype(np.float32) @ c_rot).astype(np.float32)
        return pack_bits(bits), norms.astype(np.float32), factors, code_dot_c

    def rotate(self, x: np.ndarray) -> np.ndarray:
        return self.rotator(np.asarray(x, dtype=np.float32))

    def quantize_ex(self, vectors: np.ndarray, centroid: np.ndarray, total_bits: int):
        """Multi-bit quantization (total_bits in [2, 16]) → (codes [N, padded]
        int8|int16, scales [N] f32, norms [N] f32, factors [N] f32,
        code_dot_c [N] f32).

        TPU-native redesign of the reference's 2-16-bit ex-codes
        (quantizer.rs, config.rs:32): instead of tight bit-packing + SIMD
        unpack, codes are symmetric integers in the narrowest MXU-friendly
        lane — int8 through 8 bits, int16 for 9-16 — with a per-vector scale.
        u_hat ≈ scale·codes reconstructs the unit residual; the estimator
        uses factor = <u_hat, u> exactly like the 1-bit path."""
        if not 2 <= total_bits <= 16:
            raise VectorIndexError(
                f"ex-code total_bits must be in [2, 16], got {total_bits}"
            )
        code_dtype = np.int8 if total_bits <= 8 else np.int16
        qmax = float(2 ** (total_bits - 1) - 1)  # symmetric levels, e.g. 127 for 8
        r = self.rotator(vectors - centroid[None, :])
        norms = np.linalg.norm(r, axis=1)
        safe = np.maximum(norms, 1e-20)
        u = r / safe[:, None]
        amax = np.maximum(np.abs(u).max(axis=1), 1e-20)
        codes = np.clip(np.rint(u / amax[:, None] * qmax), -qmax, qmax).astype(code_dtype)
        # effective scale folds qmax: u_hat = codes * scales (kernel-ready)
        scales = (amax / qmax).astype(np.float32)
        u_hat = codes.astype(np.float32) * scales[:, None]
        factors = np.sum(u_hat * u, axis=1).astype(np.float32)
        factors = np.where(np.abs(factors) < 1e-6, 1.0, factors)
        c_rot = self.rotator(centroid.astype(np.float32))
        code_dot_c = (u_hat @ c_rot).astype(np.float32)
        return codes, scales, norms.astype(np.float32), factors, code_dot_c

    def rotate_query(self, query: np.ndarray, centroid: np.ndarray) -> np.ndarray:
        return self.rotator(np.asarray(query - centroid, dtype=np.float32))


@functools.partial(jax.jit, static_argnames=("d",))
def estimate_distances(packed_codes, norms, factors, q_rot, *, d: int):
    """Estimated squared L2 distances of one cluster's codes to the query.

    packed_codes [N, d/8] uint8, norms/factors [N], q_rot [d] (rotated query
    residual).  All compute is one (N, d) x (d,) MXU matvec after on-chip
    unpack."""
    bits = unpack_bits_jnp(packed_codes, d)  # [N, d]
    bq = bits @ q_rot  # MXU
    dot_obar_q = (2.0 * bq - jnp.sum(q_rot)) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    est_rq = norms * dot_obar_q / factors
    q_sq = jnp.sum(q_rot * q_rot)
    return norms * norms + q_sq - 2.0 * est_rq
