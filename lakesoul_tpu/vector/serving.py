"""Micro-batching ANN serving endpoint.

The resident Pallas kernel amortizes its fixed dispatch + link cost over the
query axis (vector/kernels.py scans every packed code once per CALL, not per
query), so the serving-side answer to "requests arrive one at a time" is the
standard accelerator pattern: collect requests for up to ``max_wait_ms`` (or
``max_batch``), run ONE fused batch search, fan results back out.  Throughput
then tracks the batch kernel; per-request latency is bounded by the wait
window plus one device round trip.

The reference serves searches per-call from each engine thread
(lakesoul-vector has no serving layer; vector_index.py:263 re-ranks caller
side) — this endpoint is the TPU-native replacement for that role.

    ep = AnnEndpoint(index, SearchParams(top_k=10), max_wait_ms=2.0)
    ids, dists = ep.search(q)          # blocking, thread-safe
    fut = ep.submit(q); ids, d = fut.result()   # async
    ep.stats()                         # requests / batches / mean batch size
    ep.close()

Overload: the pending queue is bounded (``max_pending``, default
4 × ``max_batch``); beyond it :meth:`submit` raises a typed
:class:`~lakesoul_tpu.errors.OverloadedError` immediately — memory stays
bounded under a client stampede and callers get a retryable signal (the
Flight gateway maps it to UNAVAILABLE).  Per-request latency
(submit → result) lands in the shared obs registry as the
``lakesoul_ann_request_seconds`` histogram next to
``lakesoul_ann_requests_total`` / ``lakesoul_ann_rejected_total``, so
p50/p99 under load are one registry snapshot away.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from lakesoul_tpu.errors import OverloadedError
from lakesoul_tpu.obs import registry
from lakesoul_tpu.vector.index import SearchParams


class AnnEndpoint:
    """Thread-safe micro-batching front end over one ``IvfRabitqIndex``."""

    def __init__(
        self,
        index,
        params: SearchParams | None = None,
        *,
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        max_pending: int | None = None,
        name: str = "default",
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.name = name
        self.index = index
        self.params = params or SearchParams()
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.max_pending = (
            4 * max_batch if max_pending is None else max(1, int(max_pending))
        )
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # (query, extra, future, submit time): ``extra`` carries per-request
        # parameters subclasses thread through to their batch execution (the
        # sharded endpoint's per-query nprobe); the base endpoint passes None
        self._pending: list[tuple[np.ndarray, object, Future, float]] = []
        self._closed = False
        self._n_requests = 0
        self._n_rejected = 0
        self._n_batches = 0
        self._n_batched_requests = 0
        reg = registry()
        self._c_requests = reg.counter("lakesoul_ann_requests_total")
        self._c_rejected = reg.counter("lakesoul_ann_rejected_total")
        # latency carries an endpoint= label so stats() quantiles stay
        # per-endpoint: several endpoints in one process (serving + overload
        # hammer + shard sweeps in the bench) must not contaminate each
        # other's p50/p99 through the name-keyed registry
        self._h_latency = reg.histogram(
            "lakesoul_ann_request_seconds", endpoint=name
        )
        self._g_pending = reg.gauge("lakesoul_ann_pending")
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ API
    def submit(self, query: np.ndarray) -> Future:
        """Enqueue one query; the Future resolves to (ids, dists).  Raises
        :class:`OverloadedError` when the bounded pending queue is full."""
        return self._submit(query, None)

    def _submit(self, query: np.ndarray, extra) -> Future:
        q = np.asarray(query, dtype=np.float32)
        if q.ndim != 1:
            raise ValueError("submit() takes a single [d] query")
        dim = getattr(getattr(self.index, "config", None), "dim", None)
        if dim is not None and len(q) != dim:
            # reject here: a wrong-width query inside a batch would otherwise
            # fail np.stack and take the whole batch down with it
            raise ValueError(f"query has dim {len(q)}, index expects {dim}")
        fut: Future = Future()
        with self._wake:
            if self._closed:
                raise RuntimeError("endpoint is closed")
            if len(self._pending) >= self.max_pending:
                self._n_rejected += 1
                self._c_rejected.inc()
                raise OverloadedError(
                    f"ann endpoint overloaded ({len(self._pending)} queued,"
                    f" bound {self.max_pending}); retry later"
                )
            self._pending.append((q, extra, fut, time.monotonic()))
            self._n_requests += 1
            self._c_requests.inc()
            self._g_pending.inc()
            self._wake.notify()
        return fut

    def search(self, query: np.ndarray, timeout: float | None = None):
        """Blocking single-query search through the batching window."""
        return self.submit(query).result(timeout)

    def stats(self) -> dict:
        # latency quantiles come straight from the registry histogram
        # (Histogram.quantile), so callers stop digging through snapshot
        # buckets; the histogram takes its own lock, so read it outside ours
        p50 = self._h_latency.quantile(0.5)
        p99 = self._h_latency.quantile(0.99)
        with self._lock:
            return {
                "requests": self._n_requests,
                "rejected": self._n_rejected,
                "pending": len(self._pending),
                "max_pending": self.max_pending,
                "batches": self._n_batches,
                "mean_batch": (
                    self._n_batched_requests / self._n_batches if self._n_batches else 0.0
                ),
                "latency_p50": p50,
                "latency_p99": p99,
            }

    def close(self) -> None:
        """Drain pending requests, then stop the worker."""
        with self._wake:
            self._closed = True
            self._wake.notify()
        self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------------- worker
    def _execute(self, queries: list[np.ndarray], extras: list):
        """Run ONE fused batch; returns (ids_list, dists_list) aligned with
        the inputs.  Subclasses override to route the batch elsewhere (the
        sharded endpoint fuses ``extras`` — per-query nprobe — into one
        ragged multi-shard dispatch)."""
        return self.index.batch_search(np.stack(queries), self.params)

    def _take_batch(self) -> list[tuple[np.ndarray, object, Future, float]]:
        """Block until work exists, then hold the window open for stragglers
        up to max_wait_s (or until max_batch queue up)."""
        with self._wake:
            while not self._pending and not self._closed:
                self._wake.wait()
            if not self._pending:
                return []  # closed and drained
            deadline = time.monotonic() + self.max_wait_s
            while len(self._pending) < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._wake.wait(remaining)
            batch = self._pending[: self.max_batch]
            del self._pending[: self.max_batch]
            self._g_pending.dec(len(batch))
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            # everything below is fenced: the worker must survive ANY per-
            # batch failure (a dead worker would hang every future request)
            try:
                ids, dists = self._execute(
                    [q for q, _, _, _ in batch], [e for _, e, _, _ in batch]
                )
            except Exception as e:  # fan the failure out to every waiter
                for _, _, fut, _ in batch:
                    try:
                        fut.set_exception(e)
                    except Exception:  # cancelled/raced: nobody is waiting
                        pass
                continue
            with self._lock:
                self._n_batches += 1
                self._n_batched_requests += len(batch)
            done = time.monotonic()
            for i, (_, _, fut, submitted) in enumerate(batch):
                self._h_latency.observe(done - submitted)
                try:
                    fut.set_result((ids[i], dists[i]))
                except Exception:  # cancelled between check and set: ignore
                    pass
