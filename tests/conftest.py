"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding logic is validated on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
reference fakes "multi-node" with many clients on one PG instance
(SURVEY.md §4 takeaway).  Must run before jax initializes its backends.
"""

import os

# force CPU even when the session env points JAX at a real TPU: the axon boot
# hook (sitecustomize) sets jax.config jax_platforms="axon,cpu", which beats
# the env var — override the config itself before any backend initializes
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


@pytest.fixture()
def tmp_warehouse(tmp_path):
    """A throwaway warehouse dir + metadata db for catalog tests."""
    wh = tmp_path / "warehouse"
    wh.mkdir()
    return wh


# --------------------------------------------------------------- lockcheck
# LAKESOUL_LOCKCHECK=1 arms lakelint's runtime lock-order/race detector
# (lakesoul_tpu/analysis/lockgraph.py) for the modules whose race classes
# have bitten before: the runtime pool/pipelines (nested-pool deadlock) and
# the metadata store (shared :memory: sqlite cursor race).  Any lock-order
# cycle or lock-held-across-pool.submit recorded during such a test fails
# it at teardown.

_LOCKCHECK_MODULES = ("test_runtime", "test_metadata")

# -------------------------------------------------------------- tracecheck
# LAKESOUL_TRACECHECK=1 arms lakelint's runtime retrace detector
# (lakesoul_tpu/analysis/tracecheck.py) for the suites that drive jit entry
# points hard: the ANN kernels (test_vector), the sharded model steps
# (test_models_parallel), and the loader path (test_catalog).  A function
# that accumulates more distinct abstract signatures than its budget during
# one test — each one a fresh XLA compilation — fails that test at
# teardown with the triggering shapes/dtypes.

_TRACECHECK_MODULES = ("test_vector", "test_models_parallel", "test_catalog")

# --------------------------------------------------------------- racecheck
# LAKESOUL_RACECHECK=1 arms lakelint's runtime race detector
# (lakesoul_tpu/analysis/racecheck.py) for the suites that drive the
# concurrent hot classes hard: the pipeline/pool machinery (test_runtime),
# the admission/breaker/ANN serving surfaces (test_resilience), and the
# lease heartbeat (test_topology).  Eraser lockset tracking on instrumented
# class fields: a field written by two threads with no common lock — or a
# collate-ring slot reused while a borrowed view is live — fails the test
# at teardown with both access stacks.

_RACECHECK_MODULES = ("test_runtime", "test_resilience", "test_topology")


@pytest.fixture(autouse=True)
def _racecheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _RACECHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import racecheck

    if not racecheck.env_requested() or racecheck.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    racecheck.reset()
    racecheck.enable()
    try:
        yield
    finally:
        violations = racecheck.violations()
        racecheck.disable()
        racecheck.reset()
    assert not violations, "racecheck violations:\n" + "\n\n".join(
        v.render() for v in violations
    )


@pytest.fixture(autouse=True)
def _tracecheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _TRACECHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import tracecheck

    if not tracecheck.env_requested() or tracecheck.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    tracecheck.reset()
    tracecheck.enable()
    try:
        yield
    finally:
        violations = tracecheck.violations()
        tracecheck.disable()
        tracecheck.reset()
    assert not violations, "tracecheck violations:\n" + "\n\n".join(
        v.render() for v in violations
    )


@pytest.fixture(autouse=True)
def _lockcheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _LOCKCHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import lockgraph

    if not lockgraph.env_requested() or lockgraph.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    lockgraph.reset()
    lockgraph.enable()
    try:
        yield
    finally:
        violations = lockgraph.violations()
        lockgraph.disable()
        lockgraph.reset()
    assert not violations, "lockgraph violations:\n" + "\n\n".join(
        v.render() for v in violations
    )


# ----------------------------------------------------------------- fscheck
# LAKESOUL_FSCHECK=1 arms lakelint's crash-prefix replay detector
# (lakesoul_tpu/analysis/fscheck.py) for the suites that publish
# cross-process artifacts: the spool/session protocol (test_scanplane),
# the spill rung + fleet docs (test_fleet), and the lease/topology docs
# (test_topology).  Every traced publication is replayed at teardown — the
# filesystem state after a crash at EVERY op prefix is materialized in a
# scratch dir and the real readers must see old-complete or new-complete,
# never torn; any violation fails the test with both stacks.

_FSCHECK_MODULES = ("test_scanplane", "test_fleet", "test_topology")


@pytest.fixture(autouse=True)
def _fscheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _FSCHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import fscheck

    if not fscheck.env_requested() or fscheck.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    fscheck.reset()
    fscheck.enable()
    try:
        yield
    finally:
        try:
            fscheck.replay()
        finally:
            violations = fscheck.violations()
            fscheck.disable()
            fscheck.reset()
    assert not violations, "fscheck violations:\n" + "\n\n".join(
        v.render() for v in violations
    )


# ---------------------------------------------------------------- txncheck
# LAKESOUL_TXNCHECK=1 arms lakelint's transaction-interleaving replayer
# (lakesoul_tpu/analysis/txncheck.py) for the suites that drive the
# metadata store's concurrent protocols.  Every committed transaction's
# statement trace is recorded at the store seam; teardown replays the
# history under READ COMMITTED interleavings and fails the test on any
# lost-update window or fencing-token regression, with both transactions'
# statement stacks.

_TXNCHECK_MODULES = ("test_metadata", "test_lease", "test_topology")


@pytest.fixture(autouse=True)
def _txncheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _TXNCHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import txncheck

    if not txncheck.env_requested() or txncheck.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    txncheck.reset()
    txncheck.enable()
    try:
        yield
    finally:
        try:
            txncheck.replay()
        finally:
            violations = txncheck.violations()
            txncheck.disable()
            txncheck.reset()
    assert not violations, "txncheck violations:\n" + "\n\n".join(
        v.render() for v in violations
    )


# --------------------------------------------------------------- leakcheck
# LAKESOUL_LEAKCHECK=1 arms lakelint's resource-leak detector
# (lakesoul_tpu/analysis/leakcheck.py) for the suites that open, serve,
# spawn, and spool the hardest: the pipeline/pool machinery
# (test_runtime), the spool/session protocol (test_scanplane), the worker
# autoscaler (test_fleet), the serving surfaces (test_resilience), and
# the follower plane (test_freshness).  Each test runs inside a resource
# scope — /proc/self/fd, live threads, tracked children, and tracked
# scratch artifacts are snapshotted before and diffed after; any thread,
# child, tmpfs fd, or staged tmp that outlives the test fails it at
# teardown with its creation stack.

_LEAKCHECK_MODULES = (
    "test_runtime",
    "test_scanplane",
    "test_fleet",
    "test_resilience",
    "test_freshness",
)


@pytest.fixture(autouse=True)
def _leakcheck(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "") or ""
    if name.rpartition(".")[2] not in _LEAKCHECK_MODULES:
        yield
        return
    from lakesoul_tpu.analysis import leakcheck

    if not leakcheck.env_requested() or leakcheck.enabled():
        # not armed, or something else already manages the detector
        yield
        return
    leakcheck.reset()
    leakcheck.enable()
    try:
        with leakcheck.scope(request.node.nodeid):
            yield
    finally:
        violations = leakcheck.violations()
        leakcheck.disable()
        leakcheck.reset()
    assert not violations, "leakcheck violations:\n" + "\n\n".join(
        v.render() for v in violations
    )
