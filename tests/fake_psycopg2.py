"""Wire-faithful psycopg2 stand-in for PostgresMetadataStore tests.

The TPU image has no psycopg2 and no PostgreSQL server, but the store's
concurrency claims rest on PG realities the sqlite shim test couldn't catch
(VERDICT r1 weak #5).  This module reproduces the psycopg2 behaviors the
store depends on, backed by a file sqlite database per DSN so SEPARATE
connections really do contend through the storage engine:

- ``format`` paramstyle (``%s`` placeholders), translated per statement
- ``connection.autocommit`` switching: True → every statement commits
  immediately; False → statements join one transaction until commit()
- ``with conn:`` commits/rolls back the TRANSACTION but does NOT close the
  connection (psycopg2's documented — and surprising — semantics)
- psycopg2's exception hierarchy: ``Error ← DatabaseError ←
  IntegrityError / OperationalError``; integrity violations raise THIS
  module's IntegrityError class, not sqlite's
- cursors with execute/fetchone/fetchall/rowcount/close
"""

from __future__ import annotations


import re
import sqlite3
import tempfile
import threading

_FOR_UPDATE_RE = re.compile(r"\s+FOR\s+UPDATE\b", re.IGNORECASE)


class Error(Exception):
    pass


class DatabaseError(Error):
    pass


class IntegrityError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class InterfaceError(Error):
    pass


_DSN_DBS: dict[str, str] = {}
_DSN_LOCK = threading.Lock()


def _db_path_for(dsn: str) -> str:
    with _DSN_LOCK:
        path = _DSN_DBS.get(dsn)
        if path is None:
            path = tempfile.mktemp(prefix="fakepg_", suffix=".db")
            _DSN_DBS[dsn] = path
        return path


def reset(dsn: str | None = None) -> None:
    """Drop the backing database(s) — a fresh 'server' per test."""
    import os

    with _DSN_LOCK:
        keys = [dsn] if dsn is not None else list(_DSN_DBS)
        for k in keys:
            path = _DSN_DBS.pop(k, None)
            if path:
                for suffix in ("", "-wal", "-shm"):
                    try:
                        os.remove(path + suffix)
                    except OSError:
                        pass


class Cursor:
    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._cur = conn._sqlite.cursor()

    def execute(self, sql: str, params=None):
        if self._conn.closed:
            raise InterfaceError("connection already closed")
        sql_q = sql.replace("%s", "?")
        # PG row locks have no sqlite spelling — BEGIN IMMEDIATE already
        # serializes writers in the backing database, so dropping the
        # clause preserves the store's locking semantics here
        sql_q = _FOR_UPDATE_RE.sub("", sql_q)
        try:
            self._conn._begin_if_needed(sql_q)
            self._cur.execute(sql_q, tuple(params or ()))
            if self._conn.autocommit and self._conn._sqlite.in_transaction:
                self._conn._sqlite.commit()
        except sqlite3.IntegrityError as e:
            raise IntegrityError(str(e)) from e
        except sqlite3.OperationalError as e:
            raise OperationalError(str(e)) from e
        except sqlite3.Error as e:
            raise DatabaseError(str(e)) from e
        return self

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    def __iter__(self):
        return iter(self._cur)

    @property
    def rowcount(self):
        return self._cur.rowcount

    def close(self):
        self._cur.close()


class Connection:
    def __init__(self, dsn: str):
        self._sqlite = sqlite3.connect(
            _db_path_for(dsn), timeout=10.0, isolation_level=None
        )
        self._sqlite.execute("PRAGMA journal_mode=WAL")
        self._sqlite.execute("PRAGMA busy_timeout=10000")
        # PG always has the byte-order "C" collation; the store's desc range
        # predicates name it explicitly (COLLATE "C") to defeat linguistic
        # collations, so the fake must know it too
        self._sqlite.create_collation(
            "C", lambda a, b: -1 if a < b else (0 if a == b else 1)
        )
        self.autocommit = False
        self.closed = 0

    # one explicit transaction model: sqlite in isolation_level=None does
    # nothing implicitly, so transaction boundaries are exactly ours
    def _begin_if_needed(self, sql: str) -> None:
        head = sql.lstrip()[:6].upper()
        if head in ("BEGIN ", "BEGIN", "COMMIT", "ROLLBA"):
            return
        if not self.autocommit and not self._sqlite.in_transaction:
            self._sqlite.execute("BEGIN IMMEDIATE")

    def cursor(self) -> Cursor:
        if self.closed:
            raise InterfaceError("connection already closed")
        return Cursor(self)

    def commit(self):
        if self._sqlite.in_transaction:
            self._sqlite.commit()

    def rollback(self):
        if self._sqlite.in_transaction:
            self._sqlite.rollback()

    # psycopg2 semantics: `with conn:` manages the transaction, NOT the
    # connection lifetime
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.commit()
        else:
            self.rollback()
        return False

    def close(self):
        if not self.closed:
            self._sqlite.close()
            self.closed = 1


def connect(dsn: str, **kwargs) -> Connection:
    return Connection(dsn)
