"""Test fixtures: reference tables, seeded lint/lock bugs (lint/, lockbugs)."""
