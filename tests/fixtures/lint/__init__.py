"""Seeded-bug fixtures for lakelint (tests/test_analysis.py).

Each ``bad_*.py`` module deliberately violates exactly the invariants one
lint rule guards; the engine must flag every seeded line.  ``ok_clean.py``
exercises the allowed variants of the same patterns and must stay clean.
These modules are parsed by the analyzer, never imported.
"""
