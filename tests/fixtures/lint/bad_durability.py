"""Seeded durability violations: hand-rolled publications bypassing the
runtime/atomicio seam (torn-publish), renames of never-fsynced bytes
(unfsynced-rename), and barriers written before their data
(barrier-order) — plus the legal shapes (atomicio-routed publication,
read-mode opens, fsynced flows with the barrier last) that must stay
silent."""

import json
import os

from lakesoul_tpu.runtime import atomicio

LATEST = "LATEST"


def publish_in_place(path, doc):
    # in-place overwrite: a crashed (or concurrent) reader sees a torn doc
    with open(path, "w") as f:  # SEED: torn-publish
        f.write(json.dumps(doc))


def publish_hand_rolled(path, doc):
    # hand-rolled tmp→fsync→rename: correct ordering, wrong seam — only
    # atomicio may hold the raw ops (fsync keeps unfsynced-rename silent)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:  # SEED: torn-publish
        f.write(json.dumps(doc))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish_without_fsync(path, doc):
    # rename of bytes the flow never fsynced: a host crash can land the
    # final name on an empty inode
    tmp = path + ".tmp"
    with open(tmp, "w") as f:  # SEED: torn-publish
        f.write(json.dumps(doc))
    os.replace(tmp, path)  # SEED: unfsynced-rename


def _stage_doc(tmp, doc):
    # the producing half of a publication split across functions — the
    # write itself is a bare publication-path open
    with open(tmp, "w") as f:  # SEED: torn-publish
        f.write(json.dumps(doc))


def publish_via_helper(path, doc):
    # interprocedural: the caller renames what its callee wrote (and never
    # fsynced) — both rules follow the 1-hop flow
    tmp = path + ".tmp"
    _stage_doc(tmp, doc)
    os.replace(tmp, path)  # SEED: torn-publish SEED: unfsynced-rename


def publish_crc_first(fs, seg_path, payload, crc_doc):
    # the CRC sidecar is the barrier: writing it before the segment means
    # a crash leaves a barrier naming bytes that never landed
    crc_path = seg_path + ".crc"
    atomicio.publish_bytes_fs(fs, crc_path, crc_doc)  # SEED: barrier-order
    atomicio.publish_bytes_fs(fs, seg_path, payload)


def swing_pointer_before_record(store, rel, record):
    # LATEST must name an already-durable manifest, not a future one
    store._write_blob(LATEST, rel.encode())  # SEED: barrier-order
    store._write_blob(rel, record)


def publish_sanctioned(path, doc):
    # allowed: the sanctioned seam owns the raw ops
    atomicio.publish_atomic(path, json.dumps(doc))


def publish_data_then_barrier(fs, seg_path, payload, crc_doc):
    # allowed: data first, barrier last — exactly the spill-rung ordering
    crc_path = seg_path + ".crc"
    atomicio.publish_bytes_fs(fs, seg_path, payload)
    atomicio.publish_bytes_fs(fs, crc_path, crc_doc)


def read_back(path):
    # allowed: read-mode opens are not publications
    with open(path) as f:
        return json.loads(f.read())


def move_untouched(src, dst):
    # allowed: a pure move of bytes this flow never wrote (sweeper shape)
    os.replace(src, dst)
