"""Seeded hardcoded-endpoint violations: literal network addresses."""

import os


def connect():
    gateway = "grpc://10.0.0.5:8815"  # SEED: hardcoded-endpoint (literal IP endpoint)
    metrics = "localhost:9090"  # SEED: hardcoded-endpoint (bare localhost:port)
    dashboard = "http://localhost/status"  # SEED: hardcoded-endpoint (loopback URI, no port)
    broker = "broker.prod.internal:5432"  # SEED: hardcoded-endpoint (dotted hostname:port)
    # allowed spellings: ephemeral binds, config resolution, plain labels
    bind = "grpc://127.0.0.1:0"  # allowed (port 0 = bind-me-anywhere)
    configured = os.environ.get("LAKESOUL_SCANPLANE_SPOOL", "localhost:9090")  # allowed (env default IS config)
    label = "attempt:3"  # allowed (word:digits label, not an address)
    return gateway, metrics, dashboard, broker, bind, configured, label
