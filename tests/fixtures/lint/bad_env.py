"""Seeded undocumented-env violation: a knob no README row documents."""

import os

SECRET_KNOB = os.environ.get("LAKESOUL_UNDOCUMENTED_KNOB", "0")  # SEED: undocumented-env
DOCUMENTED = os.environ.get("LAKESOUL_FIXTURE_DOCUMENTED", "")  # allowed: in fixture README
