"""Seeded hot-path-materialize violations: the intermediate-table
materializations PR 8 deleted from the scan/loader hot path — per-window
concat_tables, per-column combine_chunks, to_pandas — plus the legal
shapes (zero-copy slices, a pragma'd bounded copy) that must stay silent."""

import pyarrow as pa


def rebatch_by_concat(pending, n):
    big = pa.concat_tables(pending)  # SEED: hot-path-materialize
    return big.slice(0, n)


def collate_by_combine(table):
    out = {}
    for name in table.column_names:
        out[name] = table.column(name).combine_chunks()  # SEED: hot-path-materialize
    return out


def collate_via_pandas(table):
    return table.to_pandas()  # SEED: hot-path-materialize


def bare_import_style(concat_tables, pending):
    # an un-qualified call is the same materialization
    return concat_tables(pending)  # SEED: hot-path-materialize


def zero_copy_window_is_fine(batches, start, length):
    # allowed: Table.from_batches over zero-copy slices — no buffer copies
    return pa.Table.from_batches([b.slice(start, length) for b in batches])


def justified_remainder_copy(buffer, cut):
    # allowed: pragma'd bounded copy (unpins decoded parents)
    return buffer.slice(cut).combine_chunks()  # lakelint: ignore[hot-path-materialize] bounded remainder copy unpins parents
