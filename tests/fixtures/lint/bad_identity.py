"""Seeded fleet-identity-label violations: hand-rolled identity strings."""

from lakesoul_tpu.obs import registry, stage_merge
from lakesoul_tpu.obs.fleet import identity_labels, process_identity


def record(n):
    registry().gauge("lakesoul_widget_up", role="scanworker").set(n)  # SEED: fleet-identity-label (literal role)
    registry().counter("lakesoul_widget_jobs_total", service_id=f"w-{n}").inc()  # SEED: fleet-identity-label (f-string service_id)
    stage_merge("decode", 0.5, 2, worker="worker-7")  # SEED: fleet-identity-label (literal worker)
    # sanctioned spellings: values traced to the ONE registered identity
    ident = process_identity(role="scanworker")
    registry().gauge("lakesoul_widget_up", **identity_labels()).set(n)  # allowed
    registry().counter(
        "lakesoul_widget_jobs_total", service_id=ident.service_id
    ).inc()  # allowed
    stage_merge("decode", 0.5, 2, worker=ident.service_id)  # allowed
    registry().gauge("lakesoul_widget_depth", stage="fill").set(n)  # allowed (not identity)
