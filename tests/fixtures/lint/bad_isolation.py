"""Seeded isolation-portability violations: blind coordination-table
writes (cas-guard), store reads flowing into dependent blind writes
(read-modify-write, including a flow split across a helper), writes
outside any transaction context plus seam reach-arounds (txn-boundary),
and sqlite-only SQL headed for the backend seam (sqlite-ism) — plus the
legal shapes (full CAS with rowcount consumed, transaction()-wrapped
writes, conn-routed helpers, the sqlite backend class speaking sqlite)
that must stay silent."""


# ------------------------------------------------------------- cas-guard


def blind_lease_touch(conn):
    # re-checks only the primary key: a racing takeover's commit between
    # read and write is silently overwritten
    with conn:
        conn.execute("UPDATE lease SET expires_at_ms=5 WHERE lease_key='k'")  # SEED: cas-guard


def unchecked_lease_cas(conn):
    # the CAS predicate is right but nobody reads .rowcount — losing the
    # race is indistinguishable from winning it
    with conn:
        conn.execute(  # SEED: cas-guard
            "UPDATE lease SET holder_id='' WHERE lease_key='k' "
            "AND holder_id='h' AND fencing_token=3"
        )


def drop_lease_row(conn):
    # lease rows are tombstoned, never deleted: deleting restarts fencing
    # tokens and re-arms a zombie ex-holder's stale token
    with conn:
        conn.execute("DELETE FROM lease WHERE lease_key='k'")  # SEED: cas-guard


def clobber_partition_versions(conn):
    # missing the version column: the write spans the whole version chain
    with conn:
        conn.execute(  # SEED: cas-guard
            "UPDATE partition_info SET expression='merge' "
            "WHERE table_id='t' AND partition_desc='d'"
        )


def cas_with_rowcount(conn):
    # allowed: full CAS predicate and the result is consumed
    with conn:
        cur = conn.execute(
            "UPDATE lease SET holder_id='', expires_at_ms=0 "
            "WHERE lease_key='k' AND holder_id='h' AND fencing_token=3"
        )
        return cur.rowcount > 0


# ----------------------------------------------------- read-modify-write


def rmw_direct(store):
    # classic lost update: read, then write the derived value blind
    current = store.get_global_config("flags")
    store.set_global_config("flags", current)  # SEED: read-modify-write


def _publish(store, key, value):
    # the writing half of a flow split across functions
    store.set_global_config(key, value)  # SEED: read-modify-write


def rmw_via_helper(store, key):
    # interprocedural: the helper writes what this function read
    current = store.get_global_config(key)
    _publish(store, key, current)


def rmw_sanctioned(store):
    # allowed: read and write inside one transaction — the seam (plus a
    # ROW_LOCK read) makes the pair unsplittable
    with store.transaction() as conn:
        current = store.get_global_config("flags")
        store.set_global_config("flags", current)


# ---------------------------------------------------------- txn-boundary


def autocommit_writes(conn):
    # each statement commits alone: the pair's invariant straddles a
    # commit point under READ COMMITTED
    conn.execute("UPDATE global_config SET value='v' WHERE key='k'")  # SEED: txn-boundary
    conn.execute("INSERT INTO global_config (key, value) VALUES ('a', 'b')")  # SEED: txn-boundary


def reach_around_seam(store):
    # transaction internals on a store receiver outside meta/store.py —
    # subclass overrides and txncheck instrumentation no longer apply
    with store._txn() as conn:  # SEED: txn-boundary
        store._exec(conn, "SELECT value FROM global_config WHERE key='k'")  # SEED: txn-boundary


def steal_raw_connection(store):
    return store._conn()  # SEED: txn-boundary


def sanctioned_txn_write(store):
    # allowed: the named seam owns the transaction
    with store.transaction() as conn:
        conn.execute("UPDATE global_config SET value='v2' WHERE key='k'")
        conn.execute("INSERT INTO global_config (key, value) VALUES ('c', 'd')")


class StoreShim:
    def _exec(self, conn, sql, params=()):
        raise NotImplementedError

    def _apply(self, conn, value):
        # allowed: a helper writing on the transaction's conn it received
        self._exec(conn, "UPDATE global_config SET value='x' WHERE key='q'")


# ------------------------------------------------------------ sqlite-ism


def sqlite_only_sql(conn, key):
    with conn:
        conn.execute(  # SEED: sqlite-ism
            "INSERT OR REPLACE INTO global_config (key, value) "
            "VALUES ('k', 'v')"
        )
        conn.execute("SELECT datetime('now')")  # SEED: sqlite-ism
        conn.execute("SELECT rowid FROM global_config")  # SEED: sqlite-ism
        conn.execute("PRAGMA synchronous=OFF")  # SEED: sqlite-ism
        conn.execute(  # SEED: sqlite-ism
            "CREATE TABLE audit (id INTEGER PRIMARY KEY AUTOINCREMENT)"
        )
        conn.execute(  # SEED: sqlite-ism
            "INSERT OR IGNORE INTO global_config (key, value) "
            "VALUES ('k', 'v')"
        )
        conn.execute("SELECT value FROM global_config WHERE key=?", (key,))  # SEED: sqlite-ism


class SqliteBackendShim:
    # allowed: the sqlite backend class speaks sqlite by definition
    def tune(self, conn):
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("SELECT rowid FROM global_config")
