"""Seeded resource-boundedness & lifecycle violations: unbounded queue and
deque constructions on the data path (unbounded-queue), a self-container
growing inside a background service loop with no eviction anywhere in the
class (unbounded-growth), started threads nothing can join or stop
(thread-lifecycle), spawned children that never reach wait/poll/kill
(child-reap), and tmpfs/tempdir scratch with no prune seam (shm-debris) —
plus the legal shapes (bounded queues, evicting services, joined and
stop-event-wired threads, reaped registries, atexit-pruned scratch) that
must stay silent."""

import os
import subprocess
import tempfile
import threading
from collections import deque
from queue import Queue, SimpleQueue


# ---------------------------------------------------------- unbounded-queue


def build_buffers():
    inbox = Queue()  # SEED: unbounded-queue
    backlog = deque()  # SEED: unbounded-queue
    chute = SimpleQueue()  # SEED: unbounded-queue
    return inbox, backlog, chute


def build_bounded_buffers(depth):
    # allowed: every buffer carries a structural capacity
    inbox = Queue(maxsize=16)
    ring = deque(maxlen=128)
    window = deque((), depth)
    sized = Queue(depth)
    return inbox, ring, window, sized


# --------------------------------------------------------- unbounded-growth


class LeakyCollector:
    """Background loop appends forever; nothing in the class evicts."""

    def __init__(self):
        self._events = []
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            self._events.append(self._stop.wait(0.01))  # SEED: unbounded-growth


class DrainingCollector:
    """Same loop shape, but drain() evicts — allowed."""

    def __init__(self):
        self._events = []
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._pump, daemon=True)
        self._worker.start()

    def stop(self):
        self._stop.set()

    def drain(self):
        out = list(self._events)
        self._events.clear()
        return out

    def _pump(self):
        while not self._stop.is_set():
            self._events.append(self._stop.wait(0.01))


class RingCollector:
    """Growth into a bounded deque — the bound IS the eviction; allowed."""

    def __init__(self):
        self._ring = deque(maxlen=64)
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._tick, daemon=True)
        self._worker.start()

    def stop(self):
        self._stop.set()

    def _tick(self):
        while not self._stop.is_set():
            self._ring.append(self._stop.wait(0.01))


# --------------------------------------------------------- thread-lifecycle


def fire_and_forget(work):
    threading.Thread(target=work, daemon=True).start()  # SEED: thread-lifecycle


def escaped_handle(work):
    pump = threading.Thread(target=work)  # SEED: thread-lifecycle
    pump.start()
    return pump


class UnjoinedPump:
    """Handle kept on self but no join and no stop-event wiring.  (The
    attr name must differ from JoinedPump's — join detection is
    deliberately name-based across the module.)"""

    def __init__(self):
        self._pump_t = None

    def start(self, work):
        self._pump_t = threading.Thread(target=work)  # SEED: thread-lifecycle
        self._pump_t.start()


class JoinedPump:
    """Allowed: close() joins the handle."""

    def __init__(self):
        self._t = None

    def start(self, work):
        self._t = threading.Thread(target=work)
        self._t.start()

    def close(self):
        if self._t is not None:
            self._t.join(timeout=2.0)


def joined_locally(work):
    # allowed: the creating function joins its own handle
    runner = threading.Thread(target=work)
    runner.start()
    runner.join()


# --------------------------------------------------------------- child-reap


def orphan_spawn(argv):
    subprocess.Popen(argv)  # SEED: child-reap


class NeverReaped:
    """Registry that no method ever waits, polls, or kills."""

    def __init__(self):
        self._procs = []

    def spawn(self, argv):
        p = subprocess.Popen(argv)  # SEED: child-reap
        self._procs.append(p)
        return p.pid


class ZombieRetirer:
    """Terminates the popped child but never collects its exit status."""

    def __init__(self):
        self._kids = []

    def retire(self):
        if not self._kids:
            return None
        victim = self._kids.pop()
        victim.terminate()  # SEED: child-reap
        return victim.pid


class ReapedSpawner:
    """Allowed: reap() polls the registry, stop_all() waits with a kill
    fallback, and retire() waits the child it terminated."""

    def __init__(self):
        self._children = []

    def spawn(self, argv):
        child = subprocess.Popen(argv)
        self._children.append(child)
        return child.pid

    def retire(self):
        if not self._children:
            return None
        child = self._children.pop()
        child.terminate()
        child.wait(5.0)
        return child.pid

    def reap(self):
        gone = [c for c in self._children if c.poll() is not None]
        self._children = [c for c in self._children if c.poll() is None]
        return [c.pid for c in gone]

    def stop_all(self):
        for c in self._children:
            c.terminate()
        for c in self._children:
            try:
                c.wait(5.0)
            except Exception:
                c.kill()
        self._children = []


# --------------------------------------------------------------- shm-debris


def bare_scratch():
    return tempfile.mkdtemp(prefix="fixture-")  # SEED: shm-debris


def bare_shm_dir(name):
    os.makedirs("/dev/shm/" + name, exist_ok=True)  # SEED: shm-debris
    return "/dev/shm/" + name


def pruned_scratch():
    # allowed: the creating function registers the prune seam
    import atexit
    import shutil

    d = tempfile.mkdtemp(prefix="fixture-")
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return d


class OwnedScratch:
    """Allowed: the owning class's close() prunes what open() created."""

    def __init__(self):
        self._dir = None

    def open(self):
        self._dir = tempfile.mkdtemp(prefix="fixture-")
        return self._dir

    def close(self):
        import shutil

        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None
