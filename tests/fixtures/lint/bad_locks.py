"""Seeded lock-held-call violations: the nested-pool deadlock shape."""

import threading
import time


class Staging:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._pool = pool
        self._futures = []

    def schedule(self, fn):
        with self._lock:
            fut = self._pool.submit(fn)  # SEED: lock-held-call (submit)
            self._futures.append(fut)
            return fut.result()  # SEED: lock-held-call (result)

    def drain(self):
        with self._lock:
            time.sleep(0.1)  # SEED: lock-held-call (sleep)
            data = open("/tmp/state.json").read()  # SEED: lock-held-call (open)
        return data

    def reap(self, worker_thread):
        with self._lock:
            worker_thread.join()  # SEED: lock-held-call (thread join)

    def closure_is_fine(self):
        with self._lock:
            # nested function bodies run LATER, outside the critical
            # section — must not be flagged
            def later():
                return self._pool.submit(len)

            self._futures.append(later)

    def string_and_path_joins_are_fine(self, parts, sep, base, name):
        import os

        with self._lock:
            key = sep.join(parts)  # allowed: positional-arg join = assembly
            path = os.path.join(base, name)  # allowed: path assembly
        return key, path
