"""Seeded unstoppable-loop violations: while-True poll loops that sleep
blind in a service layer — only a process kill can end them — plus the
legal shapes (event-riding waits, while-not-stop conditions, stop checks
in the body, attempt budgets that raise, data-drain loops) that must stay
silent.  The test instantiates the rule with this file in scope (the
default scope is streaming//compaction//scanplane//freshness/)."""

import time


def poll_forever(store):
    while True:  # SEED: unstoppable-loop
        store.get_candidates()
        time.sleep(1.0)


def poll_with_bare_sleep(q):
    while 1:  # SEED: unstoppable-loop
        item = q.get_nowait()
        if item is None:
            sleep(0.1)  # noqa: F821 — the bare-name import shape counts too
        else:
            item.run()


def stoppable_wait(stop, store):
    # allowed: the idle wait rides the stop event — one-tick shutdown
    while True:
        store.get_candidates()
        if stop.wait(1.0):
            return


def stoppable_condition(stop, store):
    # allowed: not a while-True loop at all
    while not stop.is_set():
        store.get_candidates()
        time.sleep(1.0)


def stop_checked_in_body(stop_event, store):
    # allowed: an if-test naming the stop event consults it every tick
    while True:
        if stop_event.is_set():
            return
        store.get_candidates()
        time.sleep(1.0)


def attempt_budget(fetch, max_attempts):
    # allowed: raises on exhaustion — ends under persistent failure
    attempts = 0
    while True:
        try:
            return fetch()
        except ConnectionError:
            attempts += 1
            if attempts >= max_attempts:
                raise
            time.sleep(0.05)


def drain_cursor(cur):
    # allowed: no sleep — a data-drain loop that terminates with its input
    while True:
        rows = cur.fetchmany(1024)
        if not rows:
            break
        yield rows
