"""Seeded metric-name violations: naming scheme + kind clashes."""

from lakesoul_tpu.obs import registry


def record(n):
    registry().counter("BadCamelName").inc(n)  # SEED: metric-name (scheme)
    registry().counter("lakesoul_widget_count").inc(n)  # SEED: metric-name (_total)
    registry().histogram("lakesoul_widget_latency").observe(n)  # SEED: metric-name (_seconds)
    registry().counter("lakesoul_clash_total").inc(n)  # SEED: metric-name (kind clash)
    registry().gauge("lakesoul_clash_total").set(n)
    registry().counter("lakesoul_widget_rows_total").inc(n)  # allowed
    registry().histogram("lakesoul_widget_decode_seconds").observe(n)  # allowed
    registry().gauge("lakesoul_widget_depth").set(n)  # allowed
