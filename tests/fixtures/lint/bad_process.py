"""Seeded raw-process violations: the ad-hoc process/socket shapes the
scan-plane topology layer (scanplane/, runtime/, the sanctioned serving
entries) exists to replace — unsupervised subprocess children, a
multiprocessing pool outside the runtime, and a raw HTTP serving socket
with no admission control or RBAC."""

import multiprocessing  # SEED: raw-process (multiprocessing import)
import subprocess
from subprocess import Popen  # imported name tracked, flagged at the call


def spawn_unsupervised_child(cmd):
    return subprocess.Popen(cmd)  # SEED: raw-process (subprocess.Popen)


def shell_out(cmd):
    return subprocess.run(cmd, capture_output=True)  # SEED: raw-process (subprocess.run)


def from_imported_popen(cmd):
    return Popen(cmd)  # SEED: raw-process (from-imported Popen)


def handrolled_pool(n, fn, items):
    with multiprocessing.Pool(n) as pool:  # SEED: raw-process (multiprocessing.Pool)
        return pool.map(fn, items)


def fork_by_hand():
    import os

    pid = os.fork()  # SEED: raw-process (os.fork)
    return pid


def raw_http_server(handler):
    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)  # SEED: raw-process (raw socket server)
    return srv


def raw_socket_listener():
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # SEED: raw-process (raw socket server)
    s.bind(("127.0.0.1", 0))
    s.listen(16)
    return s


def client_socket_is_fine(host):
    # connect-and-talk sockets never listen: not a serving surface
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.connect((host, 443))
    return s


def allowed_with_pragma(cmd):
    # a justified one-shot invocation stays legal when it names why
    return subprocess.run(cmd)  # lakelint: ignore[raw-process] fixture: demonstrates the pragma escape hatch


def not_a_process(items):
    # plain calls that merely LOOK process-shaped stay legal
    run = items.run if hasattr(items, "run") else None
    return run
