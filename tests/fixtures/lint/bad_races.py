"""Seeded shared-state races: fields touched from two thread roots with no
common lock — the Eraser lockset class — plus the legal shapes (one lock
everywhere, condition-aliased locks, single-root writers) that must stay
silent."""

import threading


class Telemetry:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.synced = 0
        self.pending = []

    def worker_loop(self):
        self.count += 1  # SEED: shared-state-race
        self.pending.append(1)  # SEED: shared-state-race
        with self._lock:
            self.synced += 1

    def reset(self):
        self.count = 0
        if len(self.pending) > 10:  # SEED: racy-check-then-act
            self.pending.clear()

    def bump_synced(self):
        with self._lock:
            self.synced += 1

    def drain_locked(self):
        with self._lock:
            if len(self.pending) > 10:  # locked: check-then-act is atomic
                self.pending.clear()

    def spill(self, path):
        if len(self.pending) > 100:  # SEED: racy-check-then-act
            with open(path, "w") as f:  # a non-lock `with` shields nothing
                f.write("spill")
                self.pending.clear()

    def start(self):
        threading.Thread(target=self.worker_loop).start()


class ConditionAliased:
    """``Condition(self._mu)`` wraps the SAME lock: ``with self._cv`` and
    ``with self._mu`` must intersect to a non-empty lockset."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self.depth = 0

    def producer_loop(self):
        with self._cv:
            self.depth += 1
            self._cv.notify()

    def take(self):
        with self._mu:
            self.depth -= 1

    def start(self):
        threading.Thread(target=self.producer_loop).start()


class MainOnly:
    """Unlocked writes from two *main-root* methods: one thread of control,
    no race, no finding."""

    def __init__(self):
        self.cursor = 0

    def advance(self):
        self.cursor += 1

    def rewind(self):
        self.cursor = 0
