"""Seeded replay-host-roundtrip violations: host materializations of
device-resident replay data — ``np.asarray`` readbacks, ``.tolist()``,
``.to_pandas()`` — plus the legal shapes (device-side accounting and
permutation, a pragma'd verification readback) that must stay silent."""

import numpy as np


def replay_via_host(batches):
    out = []
    for rows, batch in batches:
        host = np.asarray(batch["x"])  # SEED: replay-host-roundtrip
        out.append((rows, host))
    return out


def log_first_rows(batch):
    return batch["x"].tolist()  # SEED: replay-host-roundtrip


def inspect_as_frame(table):
    return table.to_pandas()  # SEED: replay-host-roundtrip


def bare_import_style(asarray, batch):
    # an un-qualified call is the same round trip
    return asarray(batch["x"])  # SEED: replay-host-roundtrip


def account_on_device(batch):
    # allowed: residency accounting reads metadata, not bytes
    return sum(leaf.nbytes for leaf in batch.values())


def permute_on_device(batch, key, jax):
    # allowed: the permutation is drawn and applied by the backend
    idx = jax.random.permutation(key, batch["x"].shape[0])
    return {k: v[idx] for k, v in batch.items()}


def verification_readback(got, want):
    # allowed: pragma'd readback naming its purpose
    return np.asarray(got) == want  # lakelint: ignore[replay-host-roundtrip] verification readback against the host twin
