"""Seeded unclosed-reader violations: every leak tier the rule knows."""

import pyarrow as pa


def chained_use_and_drop(path):
    return pa.ipc.open_file(path).schema  # SEED: unclosed-reader (chained)


def assigned_never_closed(path):
    mm = pa.memory_map(path, "r")  # SEED: unclosed-reader (no close)
    return mm.size()


class Holder:
    """Stores a mapping on self but can never release it."""

    def __init__(self, path):
        self._mm = pa.memory_map(path, "r")  # SEED: unclosed-reader (no close method)


def with_block_is_fine(path):
    with pa.ipc.open_stream(path) as rd:  # allowed: context-managed
        return rd.read_all()


def closed_is_fine(path):
    mm = pa.memory_map(path, "r")  # allowed: closed below
    try:
        return mm.read_buffer(mm.size())
    finally:
        mm.close()


class ClosableHolder:
    """Stores a mapping on self AND owns its lifetime."""

    def __init__(self, path):
        self._mm = pa.memory_map(path, "r")  # allowed: close() below

    def close(self):
        self._mm.close()
