"""Seeded ad-hoc-retry violations: the hand-rolled retry dialects the
resilience layer (runtime/resilience.py) replaced — a for-range loop that
swallows the error to go around again, and sleep-based backoff inside it."""

import time


def commit_with_handrolled_retry(do_commit):
    last = None
    for attempt in range(5):  # SEED: ad-hoc-retry (retry loop)
        try:
            return do_commit()
        except OSError as e:
            last = e
            time.sleep(0.01 * (attempt + 1))  # SEED: ad-hoc-retry (sleep backoff)
    raise last


def fixed_attempts_swallowing(compact):
    for _ in range(3):  # SEED: ad-hoc-retry (retry loop)
        try:
            compact()
            return True
        except ValueError:
            pass
    return False


def reraising_handler_is_fine(fetch):
    for _ in range(2):  # allowed: the handler always re-raises
        try:
            return fetch()
        except ValueError:
            raise


def reraising_handler_with_sleep_is_fine(probe):
    # a bounded poll: the handler re-raises, so the loop never retries an
    # error — the sleep is a poll cadence, not hand-rolled backoff
    for _ in range(50):  # allowed: no exception swallowing
        try:
            if probe():
                return True
        except ValueError:
            raise
        time.sleep(0.01)  # allowed: not inside a retry loop
    return False


def while_poll_is_fine(ready):
    # condition polls are not retry loops (no exception swallowing)
    while not ready():
        time.sleep(0.01)
    return True


def plain_range_loop_is_fine(items):
    total = 0
    for i in range(len(items)):  # allowed: no try/except at all
        total += items[i]
    return total
