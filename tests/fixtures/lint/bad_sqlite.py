"""Seeded sqlite-scope violations: bypassing the serialized meta store."""

import sqlite3  # SEED: sqlite-scope (import)


def count_rows(db_path):
    conn = sqlite3.connect(db_path)  # SEED: sqlite-scope (connect)
    cur = conn.cursor()  # SEED: sqlite-scope (cursor)
    return cur.execute("SELECT COUNT(*) FROM t").fetchone()[0]
