"""Seeded stage-nondeterminism violations (scoped in via the rule's
``scope`` parameter — stands in for an ordered pipeline-stage module)."""

import random
import time


def decode_batch(batch):
    started = time.time()  # SEED: stage-nondeterminism (wall clock)
    if random.random() < 0.5:  # SEED: stage-nondeterminism (global rng)
        batch = list(reversed(batch))
    return batch, time.time() - started  # SEED: stage-nondeterminism


def seeded_jitter_is_fine(seed):
    rng = random.Random(seed)  # allowed: seeded instance
    return rng.random()


def monotonic_is_fine():
    return time.monotonic(), time.perf_counter()  # allowed
