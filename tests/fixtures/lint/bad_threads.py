"""Seeded raw-thread violations: both spellings of both primitives."""

import threading
from concurrent.futures import ThreadPoolExecutor


def spawn_worker(fn):
    t = threading.Thread(target=fn, daemon=True)  # SEED: raw-thread
    t.start()
    return t


def fan_out(fns):
    ex = ThreadPoolExecutor(max_workers=4)  # SEED: raw-thread
    return [ex.submit(f) for f in fns]
