"""Seeded buffer-lifetime bugs: zero-copy views and reuse-ring slots that
escape their release point, and a ``_BufferRing`` built without the
``cache='device'`` exclusion — plus the sanctioned shapes (argument
hand-off, view-travels-with-its-batch, guarded ring) that must stay
silent."""


def _np_column_views(batch):
    return {"c": batch}


class _BufferRing:
    def __init__(self, size):
        self._slots = [{} for _ in range(size)]
        self._next = 0

    def next_slot(self):
        slot = self._slots[self._next]
        self._next = (self._next + 1) % len(self._slots)
        return slot


class BadRebatcher:
    def __init__(self):
        self._ring = _BufferRing(4)  # SEED: ring-aliasing
        self._pending = []
        self._stash = None

    def push(self, batch):
        views = _np_column_views(batch)
        self._stash = views  # SEED: view-escapes-release
        self._pending.append(views)  # SEED: view-escapes-release
        return views  # SEED: view-escapes-release

    def push_ok(self, batch):
        views = _np_column_views(batch)
        self._pending.append((batch, views))  # ok: travels with its batch

    def collate_bad(self, window):
        slot = self._ring.next_slot()
        self._pending.append(slot)  # SEED: view-escapes-release

        def deliver_later():  # SEED: view-escapes-release
            return dict(slot)

        return deliver_later

    def collate_ok(self, window):
        slot = self._ring.next_slot()
        return window.collate(slot)  # ok: argument hand-off, not an escape


def make_guarded_ring(cache):
    if cache != "device":
        return _BufferRing(4)  # ok: the device-cache exclusion guards it
    return None


def delivery_copies(dtypes):
    return bool(dtypes)


def make_probe_guarded_ring(dtypes):
    if delivery_copies(dtypes):
        return _BufferRing(4)  # ok: the measured aliasing probe guards it
    return None


def make_inverted_probe_ring(dtypes):
    # the inverted-guard bug: arms the ring precisely when puts ALIAS
    if not delivery_copies(dtypes):
        return _BufferRing(4)  # SEED: ring-aliasing
    return None


def make_else_branch_probe_ring(dtypes):
    if delivery_copies(dtypes):
        ring = None
    else:
        ring = _BufferRing(4)  # SEED: ring-aliasing
    return ring
