"""Seeded wall-clock-lease violations: time.time() arithmetic deciding
TTL/deadline/lease questions — the clock bugs the lease table makes
load-bearing — plus the legal shapes (monotonic math, plain epoch
timestamping) that must stay silent."""

import time

LEASE_TTL_S = 30.0


def hold_lease_with_wall_clock(store, key, holder):
    deadline = time.time() + LEASE_TTL_S  # SEED: wall-clock-lease
    while time.time() < deadline:  # SEED: wall-clock-lease
        store.renew(key, holder)


def lease_expired(lease):
    return lease.expires_at < time.time()  # SEED: wall-clock-lease


def sweep_with_timeout(jobs, timeout):
    sweep_deadline = time.time() + timeout  # SEED: wall-clock-lease
    for job in jobs:
        if time.time() >= sweep_deadline:  # SEED: wall-clock-lease
            break
        job.run()


def stamp_event(event):
    # allowed: a plain epoch timestamp (no duration/TTL math in the
    # statement) — the now_millis()-style stamping the store relies on
    event.timestamp_ms = int(time.time() * 1000)
    return event


def monotonic_deadline_is_fine(ttl_s):
    # allowed: local windows on the monotonic clock are exactly the fix
    deadline = time.monotonic() + ttl_s
    while time.monotonic() < deadline:
        pass


def keyword_in_body_not_test(flag):
    # allowed: the while's CONTROLLING expression has no ttl-ish name;
    # the lease work in the body is separate statements with no wall clock
    while flag.is_set():
        renew_lease = True
        del renew_lease
