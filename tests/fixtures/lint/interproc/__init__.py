# seeded-bug fixture package for the interprocedural lakelint rules — each
# module carries exactly the cross-function bug shape its rule exists for,
# marked with "SEED: <rule-id>" on the line the rule must report
