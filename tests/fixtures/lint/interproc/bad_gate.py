"""Seeded bug: a Flight-style handler that mutates the catalog through a
helper that skips ``_check`` — invisible to any per-function rule, exactly
what ``rbac-gate-reachability`` exists for.  The guarded branch and the
gate-carrying helper must stay clean."""


class BadServer:
    def _check(self, context, namespace, table):
        raise PermissionError("denied")

    def _ensure_access(self, context, table):
        # gate-carrying helper: establishes the check for its caller
        self._check(context, "default", table)

    def _mutate_helper(self, body):
        # no check anywhere on this path — the handler below is to blame
        self.catalog.drop_table(body["table"])  # SEED: rbac-gate-reachability

    def do_action(self, context, action):
        body = {"table": "t"}
        if action == "drop":
            self._mutate_helper(body)
        if action == "guarded_drop":
            self._check(context, "default", body["table"])
            self.catalog.drop_table(body["table"])  # guarded: NOT a finding
        if action == "helper_guarded_drop":
            self._ensure_access(context, body["table"])
            self.catalog.drop_table(body["table"])  # guarded: NOT a finding
        return []

    def do_get(self, context, ticket):
        # read-only handler: no mutation, no finding
        return self.catalog.table(ticket["table"])
