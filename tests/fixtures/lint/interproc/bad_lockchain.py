"""Seeded bug: lock → helper → helper → sleep, the refactor shape the
lexical ``lock-held-call`` rule cannot see (the blocking call is two
call-graph hops away from the ``with _lock:`` body)."""

import threading
import time

_lock = threading.Lock()


def _inner():
    time.sleep(0.1)  # blocking, two hops from the lock


def _helper():
    _inner()


def do_work():
    with _lock:
        _helper()  # SEED: transitive-lock-held-call


def do_safe():
    with _lock:
        x = 1 + 1
    _helper()  # outside the critical section: NOT a finding
    return x
