"""Seeded bug: reader ownership dropped across a call boundary.  The
lexical rule treats "passed to a call" and "returned" as transfers; the
interprocedural rule follows the transfer and must flag (only) the chains
where nobody ever owns the fd."""

import pyarrow as pa


def _use_and_drop(reader):
    # neither closes, stores, returns, nor forwards the reader
    return reader.schema


def _closes(reader):
    reader.close()


def leak_across_call(path):
    f = pa.ipc.open_file(path)  # SEED: interprocedural-unclosed-reader
    return _use_and_drop(f)


def open_reader(path):
    # ownership transferred to the caller — clean by itself
    return pa.ipc.open_file(path)


def drop_factory_result(path):
    open_reader(path)  # SEED: interprocedural-unclosed-reader


def good_factory_use(path):
    with open_reader(path) as f:
        return f.schema


def good_handoff(path):
    f = pa.ipc.open_file(path)
    _closes(f)  # the helper closes it: NOT a finding
