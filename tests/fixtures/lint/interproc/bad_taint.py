"""Seeded bug: a request-derived filename laundered through a helper into
a filesystem call — the sanitizer is skipped on one path and honored on
the other, so ``taint-path-segments`` must flag exactly one flow."""


def sanitize_path_segments(parts):
    for p in parts:
        if p in ("", ".", ".."):
            return None
    return parts


class BadHandler:
    def _authorize(self):
        import urllib.parse

        url = urllib.parse.urlsplit(self.path)
        self._query = {
            k: (v[0] if v else "")
            for k, v in urllib.parse.parse_qs(url.query).items()
        }
        return True

    def _write_to(self, path, data):
        fs, p = filesystem_for(path, {})  # SEED: taint-path-segments
        with fs.open(p, "wb") as f:
            f.write(data)

    def do_PUT(self):
        # laundered: the query value rides through the helper unsanitized
        name = self._query.get("file", "")
        self._write_to(name, b"data")

    def do_safe_PUT(self):
        name = self._query.get("file", "")
        parts = sanitize_path_segments([name])
        if parts is None:
            return
        self._write_to(parts[0], b"data")  # sanitized: NOT a finding
