"""Seeded-bug fixtures for the JAX/TPU device rule pack
(lakesoul_tpu/analysis/rules/jaxtpu.py) — one known-bad module per rule,
each with ``SEED: <rule-id>`` on the exact line the rule must report plus
clean twins that must stay silent.  Parsed by the analyzer, never
imported."""
