"""Seeded pallas-blockspec violations: spec/grid/kernel mismatches that
fail only at Mosaic-compile time on real TPUs (never on the CPU fallback
CI runs) — or worse, quietly read the wrong tile."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _forgets_output(x_ref, o_ref):  # SEED: pallas-blockspec (output never written)
    tmp = x_ref[...] * 2.0
    del tmp


def index_map_arity_mismatch(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        grid=(2, 2),
        in_specs=[pl.BlockSpec((64, 64), lambda i: (i, 0))],  # SEED: pallas-blockspec (index_map arity)
        out_specs=pl.BlockSpec((64, 64), lambda i, j: (i, j)),
    )(x)


def index_map_rank_mismatch(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        grid=(2,),
        in_specs=[pl.BlockSpec((64, 64), lambda i: (i,))],  # SEED: pallas-blockspec (coordinate rank)
        out_specs=pl.BlockSpec((64, 64), lambda i: (i, 0)),
    )(x)


def kernel_arity_mismatch(a, b):
    return pl.pallas_call(  # SEED: pallas-blockspec (kernel arity)
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        grid=(2,),
        in_specs=[
            pl.BlockSpec((64, 128), lambda i: (i, 0)),
            pl.BlockSpec((64, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
    )(a, b)


def unwritten_output(x):
    return pl.pallas_call(
        _forgets_output,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        grid=(2,),
        in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
    )(x)


def vmem_blowout(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((8192, 8192), jnp.float32),
        grid=(2,),
        in_specs=[pl.BlockSpec((4096, 8192), lambda i: (i, 0))],  # SEED: pallas-blockspec (VMEM budget)
        out_specs=pl.BlockSpec((1, 8192), lambda i: (i, 0)),
    )(x)


def dropped_tail(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((1000, 128), jnp.float32),
        grid=(1000 // 512,),  # SEED: pallas-blockspec (grid drops rows)
        in_specs=[pl.BlockSpec((512, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((512, 128), lambda i: (i, 0)),
    )(x)


def clean_call(x):
    # the packed_scan shape: everything lines up
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct((1024, 128), jnp.float32),
        grid=(8,),
        in_specs=[
            pl.BlockSpec((128, 128), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(x)
