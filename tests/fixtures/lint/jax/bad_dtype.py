"""Seeded tpu-dtype-width violations: 64-bit values reaching a device
boundary, where TPU silently demotes to 32 bits."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def accumulate(x):
    acc = jnp.zeros(4, jnp.float64)  # SEED: tpu-dtype-width (traced f64)
    idx = x.astype(jnp.int64)  # SEED: tpu-dtype-width (traced i64)
    return acc + idx.sum()


@jax.jit
def searcher(codes, q):
    return jnp.dot(codes, q)


def stage_rows(rows):
    wide = np.asarray(rows, np.int64)
    on_device = jax.device_put(wide)  # SEED: tpu-dtype-width (device_put)
    return on_device


def stage_scores(scores, q):
    promoted = scores.astype("float64")
    dists = searcher(promoted, q)  # SEED: tpu-dtype-width (jit boundary)
    big = jnp.asarray(4000000000)  # SEED: tpu-dtype-width (int32 overflow)
    return dists, big


def clean_stage(rows, q):
    # explicit 32-bit conversions on the host: the blessed pattern
    narrow = np.asarray(rows, np.float32)
    ids = np.asarray(rows, dtype=np.int32)
    on_device = jax.device_put(narrow)
    return searcher(on_device, q), ids
