"""Seeded trace-host-sync violations: concretizing a traced value forces a
device→host sync (or a ConcretizationTypeError) inside jitted code."""

import jax
import jax.numpy as jnp
import numpy as np


def _norm_host(v):
    # helper reached with a traced argument: the sync hides one call deep
    arr = np.asarray(v)  # SEED: trace-host-sync (np.asarray via helper)
    return arr / np.linalg.norm(arr)


@jax.jit
def leaky_distance(q, x):
    scale = float(q)  # SEED: trace-host-sync (float() on traced value)
    host = x.item()  # SEED: trace-host-sync (.item())
    listed = x.tolist()  # SEED: trace-host-sync (.tolist())
    x.block_until_ready()  # SEED: trace-host-sync (.block_until_ready())
    normed = _norm_host(x)
    del host, listed, normed
    return jnp.sum(x * scale)


@jax.jit
def clean_distance(q, x):
    # static metadata reads and device-side ops never sync
    d = float(x.shape[0])
    n = int(x.ndim)
    y = jnp.asarray(x, jnp.float32)  # jnp stays on device
    return jnp.sum(y) / d + n


def host_collate(rows):
    # NOT traced: host-side numpy conversion is the loader's job
    return np.asarray(rows, dtype=np.float32)


def hot_stage_sync(batch):
    """Stands in for a loader pipeline stage (scoped in via the rule's
    ``hot_path`` parameter in the test)."""
    out = jax.device_put(batch)
    out["x"].block_until_ready()  # SEED: trace-host-sync (loader hot path)
    return out
