"""Seeded trace-impure-call violations: host side effects inside traced
code run once at trace time and silently never again."""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np

_SEEN = []
_CACHE = {}


@jax.jit
def stamped_step(x):
    started = time.time()  # SEED: trace-impure-call (wall clock)
    noise = random.random()  # SEED: trace-impure-call (global rng)
    jitter = np.random.normal()  # SEED: trace-impure-call (numpy rng)
    print("step", started)  # SEED: trace-impure-call (trace-time print)
    _SEEN.append(noise)  # SEED: trace-impure-call (captured list)
    _CACHE.update(last=jitter)  # SEED: trace-impure-call (captured dict)
    return x * noise + jitter


def scan_body(carry, x):
    with open("/tmp/trace.log", "a") as f:  # SEED: trace-impure-call (host io)
        f.write(str(x))  # noqa — inside the with, runs at trace time
    return carry + x, x


def run_scan(xs):
    # scan callbacks are traced even without an enclosing jit
    return jax.lax.scan(scan_body, jnp.float32(0.0), xs)


@jax.jit
def clean_step(key, x):
    # jax.random with an explicit key is the traced-code RNG; local
    # containers are trace-local and legal
    parts = []
    parts.append(jax.random.normal(key, x.shape))
    rng = random.Random(0)  # seeded instance construction: allowed
    del rng
    return x + parts[0]


def host_wrapper(x):
    # NOT traced: host timing around the device call is fine
    started = time.time()
    out = clean_step(jax.random.key(0), jnp.asarray(x))
    return out, time.time() - started
