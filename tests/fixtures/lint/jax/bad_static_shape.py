"""Seeded jit-static-arg-shape violations: data-dependent shapes retrace
per batch; static_argnames typos silently trace the arg dynamic."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("tile",))
def bucketed(x, tile_size):  # SEED: jit-static-arg-shape (static name typo)
    return x.reshape(-1, tile_size)


@jax.jit
def filter_positive(x):
    hits = x[x > 0]  # SEED: jit-static-arg-shape (boolean mask)
    where_idx = jnp.where(x > 0)  # SEED: jit-static-arg-shape (1-arg where)
    nz = jnp.nonzero(x)  # SEED: jit-static-arg-shape (nonzero, no size=)
    uniq = jnp.unique(x)  # SEED: jit-static-arg-shape (unique, no size=)
    return hits, where_idx, nz, uniq


@jax.jit
def masked_fixed(x):
    # fixed-shape alternatives: always legal under jit
    kept = jnp.where(x > 0, x, 0.0)
    nz = jnp.nonzero(x, size=4)
    return kept, nz


def host_search(x, n):
    tail = filter_positive(x[:n])  # SEED: jit-static-arg-shape (dynamic slice)
    head = filter_positive(x[:128])  # constant slice: one compile, fine
    return tail, head
