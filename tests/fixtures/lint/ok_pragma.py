"""Inline-pragma fixture: the violation is real but explicitly justified."""

import threading


def watchdog(fn):
    t = threading.Thread(target=fn, daemon=True)  # lakelint: ignore[raw-thread] fixture: justified watchdog
    t.start()
    return t
