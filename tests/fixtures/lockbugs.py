"""Deliberate runtime concurrency bugs for the lockgraph detector
(tests/test_analysis.py).  Each function reproduces one race class with the
threads SEQUENCED so the bug is observable without the test ever actually
deadlocking:

- :func:`lock_order_inversion` — thread 1 takes A→B, thread 2 takes B→A.
  Run back-to-back (never concurrently) it cannot deadlock, but the
  acquisition graph records A→B then sees B→A close the cycle — exactly
  the evidence a production deadlock leaves AFTER the fact, available here
  BEFORE it.
- :func:`submit_while_locked` — pool work submitted while a lock is held:
  the nested-pool deadlock shape (a worker needing that lock + a full pool
  = wedge).
- :func:`well_ordered` — the same primitives used correctly; must stay
  violation-free (false-positive guard).
"""

from __future__ import annotations

import threading


def lock_order_inversion() -> None:
    a = threading.Lock()
    b = threading.Lock()

    def first():
        with a:
            with b:
                pass

    def second():
        with b:
            with a:  # inversion: the graph already holds a -> b
                pass

    for fn in (first, second):
        t = threading.Thread(target=fn)
        t.start()
        t.join()


def submit_while_locked() -> None:
    from lakesoul_tpu.runtime.pool import get_pool

    guard = threading.Lock()
    with guard:
        fut = get_pool().submit(lambda: 1)
    assert fut.result() == 1


def well_ordered(rounds: int = 3) -> None:
    a = threading.Lock()
    b = threading.Lock()
    r = threading.RLock()

    def use():
        for _ in range(rounds):
            with a:
                with b:
                    pass
            with r:
                with r:  # re-entrancy is not an inversion
                    pass

    threads = [threading.Thread(target=use) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
