"""Generate the committed reference-layout interop fixture.

Builds a table EXACTLY the way the reference writer lays one out on disk
(VERDICT r3 missing #5 / next-round item 5) — using an INDEPENDENT
implementation of every convention, so the committed files cross-check this
repo's reader/hash/naming code rather than round-tripping it:

- file naming ``part-<alnum16>_<bucket:04d>.parquet``
  (reference: rust/lakesoul-io/src/writer/mod.rs:120, utils/mod.rs:31)
- partition sub-paths ``k=v/`` and desc strings ``k=v,k=v`` / ``-5``
  (helpers/mod.rs:453-489)
- rows bucketed by Spark-variant Murmur3 (x86_32, seed 42, byte-wise tail,
  sign-extended small ints) mod hash_bucket_num, implemented here from the
  published Spark algorithm in plain Python ints — ZERO imports from
  lakesoul_tpu (utils/hash/spark_murmur3.rs, repartition/mod.rs:259)
- parquet written zstd level 1, dictionary OFF, rows PK-sorted within each
  file (writer/mod.rs:215-240 parquet_options, SortAsyncWriter)

Run from the repo root:  python tests/fixtures/make_reference_fixture.py
Outputs into tests/fixtures/reference_table/ (committed).
"""

import json
import pathlib
import random

import pyarrow as pa
import pyarrow.parquet as pq

OUT = pathlib.Path(__file__).parent / "reference_table"
SEED = 20260729
HASH_SEED = 42

_MASK = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def _mix_k(k: int) -> int:
    k = (k * 0xCC9E2D51) & _MASK
    k = _rotl(k, 15)
    return (k * 0x1B873593) & _MASK


def _mix_h(h: int, k: int) -> int:
    h ^= _mix_k(k)
    h = _rotl(h, 13)
    return (h * 5 + 0xE6546B64) & _MASK


def _fmix(h: int, length: int) -> int:
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    return h ^ (h >> 16)


def murmur3_long(v: int, seed: int = HASH_SEED) -> int:
    """Spark hashLong: low word then high word, finalized with length 8."""
    v &= 0xFFFFFFFFFFFFFFFF
    h = seed & _MASK
    h = _mix_h(h, v & _MASK)
    h = _mix_h(h, (v >> 32) & _MASK)
    return _fmix(h, 8)


def murmur3_bytes(data: bytes, seed: int = HASH_SEED) -> int:
    """Spark hashUnsafeBytes: 4-byte LE words, then each remaining byte
    processed as its own SIGN-EXTENDED block; total length finalizes."""
    h = seed & _MASK
    n = len(data)
    for i in range(0, n - n % 4, 4):
        h = _mix_h(h, int.from_bytes(data[i : i + 4], "little"))
    for b in data[n - n % 4 :]:
        signed = b - 256 if b >= 128 else b
        h = _mix_h(h, signed & _MASK)
    return _fmix(h, n)


def bucket_of_long(v: int, num: int) -> int:
    return murmur3_long(v) % num


def bucket_of_str(s: str, num: int) -> int:
    return murmur3_bytes(s.encode()) % num


ALNUM = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"


def random_str(rng: random.Random, n: int = 16) -> str:
    return "".join(rng.choice(ALNUM) for _ in range(n))


def write_parquet(path: pathlib.Path, table: pa.Table) -> int:
    path.parent.mkdir(parents=True, exist_ok=True)
    pq.write_table(
        table,
        path,
        compression="zstd",
        compression_level=1,
        use_dictionary=False,
    )
    return path.stat().st_size


def main() -> None:
    rng = random.Random(SEED)
    manifest = {"tables": []}

    # ---- table 1: int64 PK, range-partitioned on date, 4 buckets ---------
    n_buckets = 4
    schema = pa.schema(
        [("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())]
    )
    commits = []
    dates = ["2024-01-01", "2024-01-02"]

    def emit(ids, vs, date, op):
        files = []
        by_bucket: dict[int, list[int]] = {}
        for i, row_id in enumerate(ids):
            by_bucket.setdefault(bucket_of_long(row_id, n_buckets), []).append(i)
        for bucket, rows in sorted(by_bucket.items()):
            rows_sorted = sorted(rows, key=lambda i: ids[i])  # PK-sorted file
            t = pa.table(
                {
                    "id": pa.array([ids[i] for i in rows_sorted], pa.int64()),
                    "v": pa.array([vs[i] for i in rows_sorted], pa.float64()),
                    "date": pa.array([date] * len(rows_sorted), pa.string()),
                }
            )
            rel = f"interop/date={date}/part-{random_str(rng)}_{bucket:04d}.parquet"
            size = write_parquet(OUT / rel, t)
            files.append({"path": rel, "size": size, "rows": len(rows_sorted)})
        commits.append({"desc": f"date={date}", "op": op, "files": files})

    for d_i, date in enumerate(dates):
        ids = list(range(d_i * 100, d_i * 100 + 100))
        vs = [float(i) for i in ids]
        emit(ids, vs, date, "AppendCommit")
    # second, overlapping append into the first partition (MOR upsert)
    emit(list(range(0, 50)), [1000.0 + i for i in range(50)], dates[0], "MergeCommit")

    manifest["tables"].append(
        {
            "name": "interop",
            "data_dir": "interop",
            "schema_ipc_hex": schema.serialize().to_pybytes().hex(),
            "primary_keys": ["id"],
            "range_partitions": ["date"],
            "hash_bucket_num": n_buckets,
            "commits": commits,
        }
    )

    # ---- table 2: string PK, unpartitioned ("-5" desc), 2 buckets --------
    n_buckets2 = 2
    schema2 = pa.schema([("name", pa.string()), ("score", pa.int64())])
    names = [f"user-{i:03d}" for i in range(40)] + ["émile", "data🏔peak", ""]
    commits2 = []
    by_bucket: dict[int, list[str]] = {}
    for nm in names:
        by_bucket.setdefault(bucket_of_str(nm, n_buckets2), []).append(nm)
    files2 = []
    for bucket, nms in sorted(by_bucket.items()):
        nms = sorted(nms)
        t = pa.table(
            {
                "name": pa.array(nms, pa.string()),
                "score": pa.array([len(n) for n in nms], pa.int64()),
            }
        )
        rel = f"interop_str/part-{random_str(rng)}_{bucket:04d}.parquet"
        size = write_parquet(OUT / rel, t)
        files2.append({"path": rel, "size": size, "rows": len(nms)})
    commits2.append({"desc": "-5", "op": "AppendCommit", "files": files2})
    manifest["tables"].append(
        {
            "name": "interop_str",
            "data_dir": "interop_str",
            "schema_ipc_hex": schema2.serialize().to_pybytes().hex(),
            "primary_keys": ["name"],
            "range_partitions": [],
            "hash_bucket_num": n_buckets2,
            "commits": commits2,
        }
    )

    (OUT / "manifest.json").write_text(json.dumps(manifest, indent=1))
    n_files = sum(
        len(c["files"]) for tb in manifest["tables"] for c in tb["commits"]
    )
    print(f"wrote {n_files} data files under {OUT}")


if __name__ == "__main__":
    main()
