"""Deliberate runtime shared-state races for the racecheck detector
(tests/test_racecheck.py).  Each function reproduces one race class with
the threads SEQUENCED so the bug is observable without the test ever
depending on a lucky interleaving — exactly the lockbugs.py discipline:

- :class:`UnsyncCounter` / :func:`unsynchronized_writes` — two threads
  ``+=`` the same field with no lock.  Even when the threads happen to run
  back-to-back, Eraser's lockset goes empty on the second thread's first
  write and the violation records both access stacks — the evidence a
  production torn update leaves AFTER corrupting a run, available BEFORE.
- :class:`SyncCounter` / :func:`synchronized_writes` — the same shape with
  every write under one lock; must stay violation-free.
- :class:`HandoffFlag` / :func:`locked_publish_after_init` — the
  init-phase pattern the detector must NOT flag: the constructing thread
  writes unlocked (construction happens-before publication), the second
  thread publishes under a lock.
"""

from __future__ import annotations

import threading


class UnsyncCounter:
    def __init__(self):
        self.value = 0

    def bump(self, n: int) -> None:
        for _ in range(n):
            self.value += 1


class SyncCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self, n: int) -> None:
        for _ in range(n):
            with self._lock:
                self.value += 1


class HandoffFlag:
    def __init__(self):
        self._guard = threading.Lock()
        self.fenced = False  # init-phase write: unlocked on purpose

    def fence(self) -> None:
        with self._guard:
            self.fenced = True


def _run_sequenced(fn, rounds: int = 2) -> None:
    """Run ``fn`` on ``rounds`` threads back-to-back (never concurrently):
    the detector keys on lockset evidence, not on timing."""
    for _ in range(rounds):
        t = threading.Thread(target=fn)
        t.start()
        t.join()


def unsynchronized_writes() -> UnsyncCounter:
    c = UnsyncCounter()
    _run_sequenced(lambda: c.bump(50))
    return c


def synchronized_writes() -> SyncCounter:
    c = SyncCounter()
    _run_sequenced(lambda: c.bump(50))
    return c


def locked_publish_after_init() -> HandoffFlag:
    f = HandoffFlag()
    _run_sequenced(f.fence, rounds=1)
    return f
