"""Ray/Daft adapter round-trips against wire-faithful stubs (VERDICT r1 #6).

Ray is not in the TPU image, so the stub reproduces the exact public-API
behavior the adapter depends on (documented in data/ray_adapter.py):
``from_items`` wraps each item in an ``{"item": ...}`` row, ``map_batches``
slices rows into ``batch_size`` pandas DataFrames and accepts pyarrow/pandas
returns, ``take_all`` yields dict rows."""

import sys
import types

import pandas as pd
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


class _StubDataset:
    def __init__(self, rows):
        self.rows = rows  # list[dict]

    def map_batches(self, fn, *, batch_size=None, batch_format="pandas"):
        if batch_format != "pandas":
            raise NotImplementedError("stub supports pandas batches only")
        size = batch_size or max(1, len(self.rows))
        out_rows = []
        for start in range(0, len(self.rows), size):
            chunk = self.rows[start : start + size]
            df = pd.DataFrame(chunk)
            result = fn(df)
            if isinstance(result, pa.Table):
                out_rows.extend(result.to_pylist())
            elif isinstance(result, pd.DataFrame):
                out_rows.extend(result.to_dict("records"))
            else:
                raise NotImplementedError(type(result))
        return _StubDataset(out_rows)

    def take_all(self):
        return list(self.rows)

    def to_arrow(self):
        return pa.Table.from_pylist(self.rows)


def _install_ray_stub(monkeypatch):
    from collections.abc import Mapping

    ray = types.ModuleType("ray")
    ray_data = types.ModuleType("ray.data")
    # faithful from_items: a Mapping item IS a row (keys become columns);
    # anything else wraps as {"item": obj} — ray.data's documented behavior
    ray_data.from_items = lambda items: _StubDataset(
        [dict(it) if isinstance(it, Mapping) else {"item": it} for it in items]
    )
    ray.data = ray_data
    monkeypatch.setitem(sys.modules, "ray", ray)
    monkeypatch.setitem(sys.modules, "ray.data", ray_data)


def _install_daft_stub(monkeypatch):
    """Wire-faithful daft surface used by the adapter: ``from_arrow``
    accepts a Table or an ITERABLE of tables (the reference passes a
    generator, daft/__init__.py:34) and materializes lazily;
    ``to_arrow_iter`` yields the underlying tables."""
    daft = types.ModuleType("daft")

    class _DF:
        def __init__(self, obj):
            self._obj = obj  # table or lazy iterable — consumed on demand

        def _tables(self):
            if isinstance(self._obj, pa.Table):
                self._obj = [self._obj]
            elif not isinstance(self._obj, list):
                self._obj = list(self._obj)
            return self._obj

        def to_arrow(self):
            return pa.concat_tables(self._tables())

        def to_arrow_iter(self):
            yield from self._tables()

    daft.from_arrow = lambda obj: _DF(obj)
    monkeypatch.setitem(sys.modules, "daft", daft)


@pytest.fixture()
def table(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("adp", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    t.write_arrow(pa.table({"id": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]}))
    t.upsert(pa.table({"id": [2], "v": [20.0]}))
    return t


class TestRayAdapter:
    def test_read_round_trip(self, table, monkeypatch):
        _install_ray_stub(monkeypatch)
        from lakesoul_tpu.data.ray_adapter import read_lakesoul

        ds = read_lakesoul(table.scan())
        got = ds.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3, 4]
        assert got.column("v").to_pylist() == [1.0, 20.0, 3.0, 4.0]  # MOR applied

    def test_read_respects_filter_and_projection(self, table, monkeypatch):
        _install_ray_stub(monkeypatch)
        from lakesoul_tpu.data.ray_adapter import read_lakesoul
        from lakesoul_tpu.io.filters import col

        ds = read_lakesoul(table.scan().filter(col("v") > 2.5).select(["id"]))
        got = ds.to_arrow().sort_by("id")
        assert got.column_names == ["id"]
        assert got.column("id").to_pylist() == [2, 3, 4]

    def test_write_stages_then_single_commit(self, tmp_warehouse, monkeypatch):
        _install_ray_stub(monkeypatch)
        import ray

        from lakesoul_tpu.data.ray_adapter import write_lakesoul

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("rw", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        ds = _StubDataset(
            pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]}).to_pylist()
        )
        write_lakesoul(ds, t)
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3]
        # one commit → version 0 heads only
        heads = catalog.client.store.get_all_latest_partition_info(t.info.table_id)
        assert all(h.version == 0 for h in heads)
        assert ray is sys.modules["ray"]  # the stub was what the adapter used

    def test_read_and_write_compose(self, table, tmp_warehouse, monkeypatch):
        _install_ray_stub(monkeypatch)
        from lakesoul_tpu.data.ray_adapter import read_lakesoul, write_lakesoul

        dst = table.catalog.create_table(
            "adp_copy", SCHEMA, primary_keys=["id"], hash_bucket_num=1
        )
        write_lakesoul(read_lakesoul(table.scan()), dst)
        assert dst.to_arrow().sort_by("id").equals(table.to_arrow().sort_by("id"))


class TestDaftAdapter:
    def test_round_trip(self, table, monkeypatch):
        _install_daft_stub(monkeypatch)
        from lakesoul_tpu.data.daft_adapter import read_lakesoul, write_lakesoul

        df = read_lakesoul(table.scan())
        dst = table.catalog.create_table(
            "adp_daft", SCHEMA, primary_keys=["id"], hash_bucket_num=1
        )
        write_lakesoul(df, dst)
        assert dst.to_arrow().sort_by("id").equals(table.to_arrow().sort_by("id"))

    def test_read_is_lazy_and_per_unit(self, table, monkeypatch):
        """read_lakesoul must hand daft a LAZY per-scan-unit iterator — no
        decode until daft consumes, one table per (partition, bucket)."""
        _install_daft_stub(monkeypatch)
        import lakesoul_tpu.io.reader as reader_mod
        from lakesoul_tpu.data.daft_adapter import read_lakesoul

        calls = []
        real = reader_mod.read_scan_unit
        monkeypatch.setattr(
            reader_mod, "read_scan_unit",
            lambda *a, **k: (calls.append(1) or real(*a, **k)),
        )
        df = read_lakesoul(table.scan())
        assert calls == [], "read_lakesoul decoded eagerly"
        n_units = len(table.scan().scan_plan())
        assert n_units >= 2  # 2 hash buckets
        tables = list(df.to_arrow_iter())
        assert len(calls) == n_units and len(tables) == n_units
        got = pa.concat_tables(tables).sort_by("id")
        assert got.column("v").to_pylist() == [1.0, 20.0, 3.0, 4.0]

    def test_write_streams_iter_single_commit(self, tmp_warehouse, monkeypatch):
        """write_lakesoul streams to_arrow_iter() partitions through one
        writer and commits once (version-0 heads)."""
        _install_daft_stub(monkeypatch)
        import daft

        from lakesoul_tpu.data.daft_adapter import write_lakesoul

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("dw", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        parts = [
            pa.table({"id": [1, 2], "v": [1.0, 2.0]}),
            pa.table({"id": [3], "v": [3.0]}),
            pa.table({"id": [4, 5], "v": [4.0, 5.0]}),
        ]
        df = daft.from_arrow(iter(parts))
        ops = write_lakesoul(df, t)
        assert ops  # committed file ops returned
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3, 4, 5]
        heads = catalog.client.store.get_all_latest_partition_info(t.info.table_id)
        assert all(h.version == 0 for h in heads)  # exactly one commit


class TestRealEngines:
    """ARMED real-engine runs (VERDICT r4 item 9): these execute the same
    adapter round-trips against the REAL libraries and are auto-skipped
    while ray/daft are absent from the image (pip is off).  The moment
    either install lands, the suite verifies the adapter against the real
    scheduler/serialization path with zero code changes.  Until then the
    stub tests above are the verified surface — PARITY.md states exactly
    that, per adapter."""

    def test_ray_real_round_trip(self, table):
        pytest.importorskip("ray")
        from lakesoul_tpu.data.ray_adapter import read_lakesoul

        ds = read_lakesoul(table.scan())
        rows = sorted(r["id"] for r in ds.take_all())
        assert rows == sorted(table.to_arrow().column("id").to_pylist())

    def test_daft_real_round_trip(self, table):
        pytest.importorskip("daft")
        from lakesoul_tpu.data.daft_adapter import read_lakesoul

        df = read_lakesoul(table.scan())
        got = df.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == sorted(
            table.to_arrow().column("id").to_pylist()
        )
