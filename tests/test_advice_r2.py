"""Regression tests pinning every ADVICE round-2 finding.

Each test exercises the exact failure scenario the advisor described, so the
fixes in meta/client.py (desc-prefix fallback), meta/store.py (prefix upper
bound), sql/parser.py (AS OF timezone), parallel/moe.py (int token ranks),
and catalog.py (prune accounting) stay fixed.
"""

import datetime
import os
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.meta import (
    CommitOp,
    DataCommitInfo,
    DataFileOp,
    MetaDataClient,
    PartitionInfo,
)
from lakesoul_tpu.meta.store import desc_prefix_upper_bound


SCHEMA = pa.schema([("id", pa.int64()), ("a", pa.string()), ("b", pa.string())])


def _hand_commit(client, info, desc, path):
    """Insert a partition version + data commit DIRECTLY into the store,
    bypassing the client's desc canonicalization — simulating a legacy or
    external writer (the advisor's 'b=2,a=1' scenario)."""
    cid = DataCommitInfo.new_commit_id()
    ts = int(time.time() * 1000)
    client.store.insert_data_commit_info(
        [
            DataCommitInfo(
                table_id=info.table_id,
                partition_desc=desc,
                commit_id=cid,
                file_ops=[DataFileOp(path=path, size=10)],
                commit_op=CommitOp.APPEND,
                committed=True,
                timestamp=ts,
            )
        ]
    )
    client.store.transaction_insert_partition_info(
        [
            PartitionInfo(
                table_id=info.table_id,
                partition_desc=desc,
                version=0,
                commit_op=CommitOp.APPEND,
                timestamp=ts,
                snapshot=[cid],
            )
        ]
    )


class TestDescPrefixFallback:
    """medium: the desc-prefix range fast path silently dropped legacy
    non-canonical descs from scans filtered on a leading range column."""

    def _table(self, tmp_path, ranges=("a", "b")):
        client = MetaDataClient(db_path=str(tmp_path / "meta.db"))
        info = client.create_table(
            "t", "/tmp/wh/t", SCHEMA, range_partitions=list(ranges)
        )
        return client, info

    def test_legacy_desc_seen_by_leading_range_filter(self, tmp_path):
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        # legacy writer committed the same logical partition keys reversed
        _hand_commit(client, info, "b=2,a=1", "/d/legacy_0000.parquet")
        plan = client.get_scan_plan_partitions("t", {"a": "1"})
        descs = {u.partition_desc for u in plan}
        assert "b=2,a=1" in descs, "legacy non-canonical desc vanished from scan"
        assert "a=1,b=1" in descs

    def test_fast_path_restored_after_migration(self, tmp_path):
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        _hand_commit(client, info, "b=2,a=1", "/d/legacy_0000.parquet")
        assert not client._descs_all_canonical(info)
        n = client.canonicalize_partition_descs("t")
        assert n == 1
        # store now holds only canonical descs, the flag is durable, and the
        # migrated partition still matches (as its canonical spelling)
        assert client._descs_all_canonical(info)
        fresh = MetaDataClient(store=client.store)
        assert fresh._descs_all_canonical(info)
        plan = client.get_scan_plan_partitions("t", {"a": "1"})
        assert {u.partition_desc for u in plan} == {"a=1,b=1", "a=1,b=2"}
        # data files survive the rename
        files = [f for u in plan for f in u.data_files]
        assert "/d/legacy_0000.parquet" in files

    def test_canonical_only_store_keeps_fast_path(self, tmp_path):
        """With only client-written descs the verification flips the
        global_config flag once; later commits don't re-trigger the scan."""
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        assert client._descs_all_canonical(info)
        flag = client.store.get_global_config(
            client._CANONICAL_FLAG + info.table_id
        )
        assert flag == client.store.get_desc_epoch(info.table_id)

    def test_point_lookup_sees_colliding_legacy_chain(self, tmp_path):
        """A fully-specified partition filter must also union a legacy
        spelling of the SAME logical partition — the point-lookup hit is
        only trusted on a verified-canonical store."""
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        _hand_commit(client, info, "b=1,a=1", "/d/legacy_0000.parquet")
        plan = client.get_scan_plan_partitions("t", {"a": "1", "b": "1"})
        files = {f for u in plan for f in u.data_files}
        assert files == {"/d/p1_0000.parquet", "/d/legacy_0000.parquet"}

    def test_drop_table_clears_bookkeeping_keys(self, tmp_path):
        from lakesoul_tpu.meta.store import DESC_EPOCH_KEY, DESCS_VERIFIED_KEY

        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        assert client._descs_all_canonical(info)
        assert client.store.get_global_config(DESC_EPOCH_KEY + info.table_id)
        client.drop_table("t")
        assert client.store.get_global_config(DESC_EPOCH_KEY + info.table_id) is None
        assert client.store.get_global_config(DESCS_VERIFIED_KEY + info.table_id) is None

    def test_hand_commit_after_verification_still_seen(self, tmp_path):
        """The verified-canonical flag must not outlive the partition set it
        verified: an external writer adding a non-canonical desc AFTER the
        flag was set (count changes) forces re-verification."""
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        assert client._descs_all_canonical(info)  # sets the durable flag
        _hand_commit(client, info, "b=2,a=1", "/d/legacy_0000.parquet")
        plan = client.get_scan_plan_partitions("t", {"a": "1"})
        assert {u.partition_desc for u in plan} == {"a=1,b=1", "b=2,a=1"}
        # a fresh client sharing the store must not trust the stale flag
        fresh = MetaDataClient(store=client.store)
        assert not fresh._descs_all_canonical(info)

    def test_subset_key_desc_forces_fallback(self, tmp_path):
        """A desc holding only a PREFIX of the range columns ('a=1' on an
        (a, b) table) sorts below the 'a=1,' prefix bound; it must count as
        non-canonical so the full-scan fallback picks it up."""
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        _hand_commit(client, info, "a=1", "/d/partial_0000.parquet")
        assert not client._descs_all_canonical(info)
        plan = client.get_scan_plan_partitions("t", {"a": "1"})
        assert {u.partition_desc for u in plan} == {"a=1,b=1", "a=1"}

    def test_migration_skips_colliding_chain(self, tmp_path):
        """Canonicalizing 'b=1,a=1' when 'a=1,b=1' already exists would merge
        two version chains; the migration must skip it (logged), finish, and
        leave the fallback active."""
        client, info = self._table(tmp_path)
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        _hand_commit(client, info, "b=1,a=1", "/d/legacy_0000.parquet")
        _hand_commit(client, info, "b=9,a=9", "/d/l9_0000.parquet")
        n = client.canonicalize_partition_descs("t")
        assert n == 1  # b=9,a=9 rewritten; the colliding chain skipped
        descs = set(client.store.get_partition_descs(info.table_id))
        assert descs == {"a=1,b=1", "b=1,a=1", "a=9,b=9"}
        assert not client._descs_all_canonical(info)  # fallback stays on
        plan = client.get_scan_plan_partitions("t", {"a": "1"})
        assert {u.partition_desc for u in plan} == {"a=1,b=1", "b=1,a=1"}

    def test_new_legacy_desc_invalidates_negative_cache(self, tmp_path):
        client, info = self._table(tmp_path)
        _hand_commit(client, info, "b=1,a=1", "/d/l1_0000.parquet")
        assert not client._descs_all_canonical(info)
        # count changed → recheck runs; still non-canonical
        _hand_commit(client, info, "b=2,a=2", "/d/l2_0000.parquet")
        assert not client._descs_all_canonical(info)
        plan = client.get_scan_plan_partitions("t", {"a": "2"})
        assert {u.partition_desc for u in plan} == {"b=2,a=2"}


class TestEpochRestamp:
    """Client commits of new canonical descs must NOT degrade planning to a
    full desc re-scan: the store CASes the verified flag forward with the
    epoch bump in the same transaction."""

    def test_canonical_commit_keeps_plan_o1(self, tmp_path):
        client = MetaDataClient(db_path=str(tmp_path / "meta.db"))
        info = client.create_table(
            "t", "/tmp/wh/t", SCHEMA, range_partitions=["a", "b"]
        )
        client.commit_data_files(
            info, {"a=1,b=1": [DataFileOp(path="/d/p1_0000.parquet")]}, CommitOp.APPEND
        )
        assert client._descs_all_canonical(info)  # one verification scan
        calls = []
        orig = client.store.get_partition_descs
        client.store.get_partition_descs = lambda tid: (calls.append(tid) or orig(tid))
        try:
            for i in range(2, 5):
                client.commit_data_files(
                    info,
                    {f"a={i},b={i}": [DataFileOp(path=f"/d/p{i}_0000.parquet")]},
                    CommitOp.APPEND,
                )
                plan = client.get_scan_plan_partitions("t", {"a": str(i)})
                assert {u.partition_desc for u in plan} == {f"a={i},b={i}"}
            assert calls == [], "canonical commits must not force desc re-scans"
        finally:
            client.store.get_partition_descs = orig
        # and a fresh client trusts the restamped flag without scanning
        fresh = MetaDataClient(store=client.store)
        fresh.store.get_partition_descs = lambda tid: (calls.append(tid) or orig(tid))
        try:
            assert fresh._descs_all_canonical(info)
            assert calls == []
        finally:
            fresh.store.get_partition_descs = orig


class TestPgCollation:
    """The desc-prefix range must name the byte collation on PG: linguistic
    cluster collations treat ',' as primary-ignorable, breaking the bound
    math.  Runs against the wire-faithful psycopg2 fake (which registers the
    'C' collation like PG always has)."""

    def test_prefix_range_on_pg_store(self, tmp_path, monkeypatch):
        import sys

        import fake_psycopg2

        monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        dsn = f"postgresql://fake/{tmp_path.name}-collate"
        store = PostgresMetadataStore(dsn)
        try:
            assert 'COLLATE "C"' in store.DESC_RANGE_COLLATION
            client = MetaDataClient(store=store)
            info = client.create_table(
                "t", "/tmp/wh/t", SCHEMA, range_partitions=["a", "b"]
            )
            client.commit_data_files(
                info,
                {"a=1,b=1": [DataFileOp(path="/d/p_0000.parquet")]},
                CommitOp.APPEND,
            )
            got = store.get_all_latest_partition_info(info.table_id, desc_prefix="a=1,")
            assert [p.partition_desc for p in got] == ["a=1,b=1"]
            plan = client.get_scan_plan_partitions("t", {"a": "1"})
            assert len(plan) == 1
        finally:
            fake_psycopg2.reset(dsn)


class TestPrefixUpperBound:
    """low: prefix + '\\uffff' upper bound dropped descs whose next char is a
    supplementary-plane codepoint (sorts above U+FFFF)."""

    def test_upper_bound_helper(self):
        assert desc_prefix_upper_bound("a=1,") == "a=1" + chr(ord(",") + 1)
        # carry over max codepoints
        m = chr(0x10FFFF)
        assert desc_prefix_upper_bound("a" + m) == "b"
        assert desc_prefix_upper_bound(m * 3) is None
        # surrogate block is skipped, not produced
        assert desc_prefix_upper_bound(chr(0xD7FF)) == chr(0xE000)

    def test_supplementary_plane_desc_survives_prefix_range(self, tmp_path):
        client = MetaDataClient(db_path=str(tmp_path / "meta.db"))
        # a range column whose NAME starts beyond the BMP: the desc char
        # right after the 'a=1,' prefix is U+1F600
        emoji_col = "\U0001F600col"
        schema = pa.schema([("id", pa.int64()), ("a", pa.string()), (emoji_col, pa.string())])
        info = client.create_table(
            "emoji", "/tmp/wh/emoji", schema, range_partitions=["a", emoji_col]
        )
        client.commit_data_files(
            info,
            {f"a=1,{emoji_col}=x": [DataFileOp(path="/d/e_0000.parquet")]},
            CommitOp.APPEND,
        )
        got = client.store.get_all_latest_partition_info(
            info.table_id, desc_prefix="a=1,"
        )
        assert [p.partition_desc for p in got] == [f"a=1,{emoji_col}=x"]
        plan = client.get_scan_plan_partitions("emoji", {"a": "1"})
        assert len(plan) == 1


class TestAsOfTimezone:
    """low: naive AS OF literals were interpreted in the host's local zone."""

    @pytest.fixture()
    def nyc_tz(self):
        old = os.environ.get("TZ")
        os.environ["TZ"] = "America/New_York"
        time.tzset()
        yield
        if old is None:
            os.environ.pop("TZ", None)
        else:
            os.environ["TZ"] = old
        time.tzset()

    def _as_of_ms(self, sql):
        from lakesoul_tpu.sql.parser import parse

        return parse(sql).as_of_ms

    def test_naive_literal_is_utc(self, nyc_tz):
        want = datetime.datetime(
            2026, 1, 2, 3, 4, 5, tzinfo=datetime.timezone.utc
        ).timestamp() * 1000
        got = self._as_of_ms(
            "SELECT * FROM t TIMESTAMP AS OF '2026-01-02T03:04:05'"
        )
        assert got == int(want), "naive AS OF literal drifted with host TZ"

    def test_explicit_offset_wins(self, nyc_tz):
        got = self._as_of_ms(
            "SELECT * FROM t FOR SYSTEM_TIME AS OF '2026-01-02T03:04:05+02:00'"
        )
        want = datetime.datetime.fromisoformat(
            "2026-01-02T03:04:05+02:00"
        ).timestamp() * 1000
        assert got == int(want)

    def test_epoch_ms_unaffected(self, nyc_tz):
        assert self._as_of_ms("SELECT * FROM t FOR SYSTEM_TIME AS OF 1700000000000") \
            == 1700000000000


class TestMoeIntRanks:
    """low: token ranks within an expert were float32-cumsum'd; exactness is
    now int32.  Pin exact capacity keep/drop at the boundary."""

    def test_capacity_boundary_exact(self):
        import jax.numpy as jnp

        from lakesoul_tpu.parallel.moe import moe_capacity, moe_ffn

        N, h, E = 64, 8, 2
        rng = np.random.default_rng(0)
        # positive activations so every row-sum is positive → the +100 gate
        # column routes EVERY token to expert 0
        x = jnp.asarray(np.abs(rng.normal(size=(N, h))) + 0.1, dtype=jnp.float32)
        gate_w = jnp.concatenate(
            [jnp.ones((h, 1)) * 100.0, jnp.zeros((h, E - 1))], axis=1
        ).astype(jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(E, h, 4)), dtype=jnp.float32)
        b1 = jnp.zeros((E, 4), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(E, 4, h)), dtype=jnp.float32)
        b2 = jnp.zeros((E, h), jnp.float32)
        out, _ = moe_ffn(x, gate_w, w1, b1, w2, b2, capacity_factor=0.25)
        C = moe_capacity(N, E, 0.25)
        nz = np.abs(np.asarray(out)).sum(axis=1) > 0
        # exactly the first C tokens (token-order rank) pass; the rest drop
        assert nz[:C].all()
        assert not nz[C:].any()


class TestExplainPruneAccounting:
    """low: buckets_pruned counted scan units; now units_pruned counts units
    and buckets_pruned counts distinct bucket ids gone entirely."""

    def test_multi_partition_counts(self, tmp_warehouse):
        from lakesoul_tpu.catalog import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table(
            "acct",
            pa.schema([("id", pa.int64()), ("p", pa.string()), ("v", pa.int64())]),
            primary_keys=["id"],
            range_partitions=["p"],
            hash_bucket_num=4,
        )
        n = 64
        ids = np.arange(n)
        for part in ("x", "y"):
            t.write_arrow(
                pa.table(
                    {"id": ids, "p": np.repeat(part, n), "v": np.ones(n, np.int64)}
                )
            )
        d = t.scan().filter("id = 3").explain()
        assert d["units_before_bucket_prune"] == 8  # 2 partitions × 4 buckets
        assert d["units"] == 2  # the one matching bucket per partition
        assert d["units_pruned"] == 6
        # 3 whole buckets vanished across BOTH partitions — not 6
        assert d["buckets_pruned"] == 3
