"""Regression pins for the round-4 advisor findings (ADVICE.md r4).

1. high — gateway RBAC must cover EVERY table a statement references
   (joins, derived tables, EXISTS/IN/scalar subqueries), not just the
   primary FROM table.
2. medium — CommandStatementIngest REPLACE must be atomic: a failed stream
   leaves the old data intact, the table_id never changes, and replaying a
   transaction id after success is a no-op.
3. medium — correlated (and uncorrelated) NOT IN follows SQL three-valued
   logic: NULL probes and NULL-bearing subquery results yield UNKNOWN
   (row filtered), not TRUE.
4. low — prepared-statement parameters: floats render as plain decimals the
   tokenizer can parse, bytes are rejected, arity mismatches fail at bind.
5. low — CommandGetSqlInfo id 8 (FLIGHT_SQL_SERVER_TRANSACTION) rides the
   bigint branch of the union as the int SqlSupportedTransaction enum.
"""

import types
import uuid

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service import _flight_sql_pb2 as pb
from lakesoul_tpu.service.flight_sql import (
    FlightSqlClient,
    LakeSoulFlightSqlServer,
    bind_parameters,
)
from lakesoul_tpu.service.jwt import Claims
from lakesoul_tpu.sql import SqlSession
from lakesoul_tpu.sql.parser import parse, referenced_tables

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


# --------------------------------------------------------------------- 1
class TestReferencedTables:
    def test_join_and_subqueries_collected(self):
        stmt = parse(
            "SELECT a.id FROM a JOIN b ON a.id = b.id WHERE EXISTS"
            " (SELECT * FROM c WHERE c.id = a.id)"
            " AND a.id IN (SELECT id FROM d)"
        )
        assert referenced_tables(stmt) == {"a", "b", "c", "d"}

    def test_derived_table(self):
        stmt = parse("SELECT * FROM (SELECT id FROM secret) x")
        assert referenced_tables(stmt) == {"secret"}

    def test_insert_select_and_setop(self):
        stmt = parse("INSERT INTO t SELECT id FROM u")
        assert referenced_tables(stmt) == {"t", "u"}
        stmt = parse("SELECT id FROM a UNION SELECT id FROM b")
        assert referenced_tables(stmt) == {"a", "b"}

    def test_create_table_target_excluded(self):
        stmt = parse("CREATE TABLE fresh (id bigint PRIMARY KEY)")
        assert referenced_tables(stmt) == set()

    def test_call_addresses_table(self):
        stmt = parse("CALL compact('t1')")
        assert referenced_tables(stmt) == {"t1"}

    def test_explain_recurses(self):
        stmt = parse("EXPLAIN SELECT a.id FROM a JOIN b ON a.id = b.id")
        assert referenced_tables(stmt) == {"a", "b"}


@pytest.fixture()
def rbac_server(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("pub", SCHEMA, primary_keys=["id"])
    t.write_arrow(pa.table({"id": np.arange(5), "v": np.zeros(5)}))
    info = catalog.client.create_table(
        "secret", f"{tmp_warehouse}/secret", SCHEMA, domain="team1"
    )
    del info
    srv = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0", jwt_secret="k")
    token = srv.jwt_server.create_token(Claims(sub="eve", group="public"))
    client = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}", token=token)
    yield srv, client
    client.close()
    srv.shutdown()


class TestRbacCoversAllTables:
    def test_primary_from_still_checked(self, rbac_server):
        _, client = rbac_server
        with pytest.raises(flight.FlightError, match="no access"):
            client.execute("SELECT * FROM secret")

    def test_join_checked(self, rbac_server):
        _, client = rbac_server
        with pytest.raises(flight.FlightError, match="no access"):
            client.execute(
                "SELECT pub.id FROM pub JOIN secret ON pub.id = secret.id"
            )

    def test_derived_table_checked(self, rbac_server):
        _, client = rbac_server
        with pytest.raises(flight.FlightError, match="no access"):
            client.execute("SELECT * FROM (SELECT id FROM secret) x")

    def test_subquery_checked(self, rbac_server):
        _, client = rbac_server
        with pytest.raises(flight.FlightError, match="no access"):
            client.execute(
                "SELECT id FROM pub WHERE id IN (SELECT id FROM secret)"
            )
        with pytest.raises(flight.FlightError, match="no access"):
            client.execute(
                "SELECT id FROM pub p WHERE EXISTS"
                " (SELECT * FROM secret WHERE secret.id = p.id)"
            )

    def test_allowed_tables_still_work(self, rbac_server):
        _, client = rbac_server
        out = client.execute(
            "SELECT count(*) AS c FROM pub WHERE id IN (SELECT id FROM pub)"
        )
        assert out.column("c").to_pylist() == [5]

    def test_json_sql_action_checked(self, rbac_server):
        srv, _ = rbac_server
        import json

        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        token = srv.jwt_server.create_token(Claims(sub="eve", group="public"))
        opts = flight.FlightCallOptions(
            headers=[(b"authorization", f"Bearer {token}".encode())]
        )
        body = json.dumps({
            "statement": "SELECT pub.id FROM pub JOIN secret ON pub.id = secret.id"
        }).encode()
        with pytest.raises(flight.FlightError, match="no access"):
            list(raw.do_action(flight.Action("sql", body), options=opts))
        raw.close()


# --------------------------------------------------------------------- 2
@pytest.fixture()
def server(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("orders", SCHEMA, primary_keys=["id"])
    t.write_arrow(pa.table({"id": np.arange(10), "v": np.arange(10) * 1.0}))
    srv = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0")
    yield srv, catalog
    srv.shutdown()


@pytest.fixture()
def client(server):
    srv, _ = server
    c = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}")
    yield c
    c.close()


class _BoomReader:
    """Flight reader stub whose stream dies mid-way (client disconnect)."""

    def __init__(self, schema: pa.Schema, batches: list[pa.RecordBatch]):
        self.schema = schema
        self._batches = batches

    def __iter__(self):
        for b in self._batches:
            yield types.SimpleNamespace(data=b)
        raise flight.FlightError("stream interrupted")


class _AnonContext:
    @staticmethod
    def get_middleware(name):
        return None


def _replace_msg(table: str) -> pb.CommandStatementIngest:
    tdo = pb.CommandStatementIngest.TableDefinitionOptions(
        if_not_exist=pb.CommandStatementIngest.TableDefinitionOptions.TABLE_NOT_EXIST_OPTION_CREATE,
        if_exists=pb.CommandStatementIngest.TableDefinitionOptions.TABLE_EXISTS_OPTION_REPLACE,
    )
    return pb.CommandStatementIngest(
        table_definition_options=tdo, table=table, schema="default"
    )


class TestReplaceAtomicity:
    def test_failed_stream_leaves_old_data(self, server):
        srv, catalog = server
        batch = pa.record_batch({"id": np.arange(3), "v": np.zeros(3)})
        reader = _BoomReader(SCHEMA, [batch])
        with pytest.raises(flight.FlightError, match="interrupted"):
            srv._ingest(_AnonContext(), _replace_msg("orders"), reader)
        out = catalog.table("orders").scan().to_arrow()
        assert out.num_rows == 10  # the pre-replace content, fully intact
        assert sorted(out.column("id").to_pylist()) == list(range(10))

    def test_replace_keeps_table_id(self, server, client):
        _, catalog = server
        before = catalog.table("orders").info.table_id
        client.ingest(
            "orders", pa.table({"id": np.arange(3), "v": np.ones(3)}),
            mode="replace",
        )
        after = catalog.table("orders").info.table_id
        assert before == after
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [3]

    def test_replace_replay_is_noop(self, server, client):
        _, catalog = server
        data = pa.table({"id": np.arange(4), "v": np.full(4, 7.0)})
        txn = b"replace-job:epoch-1"
        assert client.ingest("orders", data, mode="replace",
                             transaction_id=txn) == 4
        # replay after success: must neither destroy nor duplicate
        client.ingest("orders", data, mode="replace", transaction_id=txn)
        out = client.execute("SELECT count(*) AS c, sum(v) AS s FROM orders")
        assert out.column("c").to_pylist() == [4]
        assert out.column("s").to_pylist() == [28.0]

    def test_replace_empties_untouched_partitions(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("p", pa.utf8()), ("id", pa.int64())])
        t = catalog.create_table("parts", schema, range_partitions=["p"])
        t.write_arrow(pa.table({"p": ["a", "a", "b"], "id": [1, 2, 3]}))
        from lakesoul_tpu.streaming import CheckpointedWriter

        w = CheckpointedWriter(t)
        w.write(pa.table({"p": ["a"], "id": [9]}))
        w.checkpoint_replace("epoch-1")
        out = catalog.table("parts").scan().to_arrow()
        assert out.column("p").to_pylist() == ["a"]
        assert out.column("id").to_pylist() == [9]  # b was emptied, a swapped


# --------------------------------------------------------------------- 3
@pytest.fixture()
def null_session(tmp_warehouse):
    cat = LakeSoulCatalog(str(tmp_warehouse))
    s = SqlSession(cat)
    s.execute("CREATE TABLE o (k bigint, x bigint)")
    s.execute("CREATE TABLE t (k bigint, c bigint)")
    s.execute("INSERT INTO o VALUES (1, 10), (1, NULL), (2, 20), (3, 30)")
    # group k=1 contains a NULL; k=2 matches 20; k=3 has no group rows
    s.execute("INSERT INTO t VALUES (1, 11), (1, NULL), (2, 20), (2, 21)")
    return s


class TestNotInThreeValuedLogic:
    def test_uncorrelated_not_in_with_null_in_set(self, null_session):
        # set contains NULL → every non-matching row is UNKNOWN → filtered;
        # matching rows are FALSE → filtered.  Result: no rows.
        out = null_session.execute(
            "SELECT x FROM o WHERE x NOT IN (SELECT c FROM t)"
        )
        assert out.num_rows == 0

    def test_uncorrelated_not_in_null_probe(self, null_session):
        # NULL probe vs a non-empty NULL-free set → UNKNOWN → filtered
        out = null_session.execute(
            "SELECT x FROM o WHERE x NOT IN (SELECT c FROM t WHERE c IS NOT NULL)"
        )
        assert sorted(out.column("x").to_pylist()) == [10, 30]

    def test_uncorrelated_in_unaffected(self, null_session):
        out = null_session.execute(
            "SELECT x FROM o WHERE x IN (SELECT c FROM t)"
        )
        assert out.column("x").to_pylist() == [20]

    def test_correlated_not_in_group_with_null(self, null_session):
        # k=1 rows: group {11, NULL} → both o-rows UNKNOWN (10 unmatched vs
        # NULL-bearing group; NULL probe) → filtered.
        # k=2 row: x=20 matches → FALSE → filtered.
        # k=3 row: empty group → TRUE → kept.
        out = null_session.execute(
            "SELECT x FROM o WHERE x NOT IN (SELECT c FROM t WHERE t.k = o.k)"
        )
        assert out.column("x").to_pylist() == [30]

    def test_correlated_not_in_null_probe_empty_group_kept(self, null_session):
        # NULL probe with an EMPTY group is still TRUE (NOT IN over the
        # empty set), so only group-bearing NULL probes are filtered:
        # (1,10) vs {11} → TRUE; (1,NULL) vs {11} → UNKNOWN; (2,20) vs
        # {20,21} → FALSE; (3,30) and (9,NULL) have empty groups → TRUE
        null_session.execute("INSERT INTO o VALUES (9, NULL)")
        out = null_session.execute(
            "SELECT k FROM o WHERE x NOT IN"
            " (SELECT c FROM t WHERE t.k = o.k AND c IS NOT NULL)"
        )
        assert sorted(out.column("k").to_pylist()) == [1, 3, 9]

    def test_correlated_not_in_without_nulls_unchanged(self, null_session):
        null_session.execute("DELETE FROM t WHERE c IS NULL")
        null_session.execute("DELETE FROM o WHERE x IS NULL")
        out = null_session.execute(
            "SELECT x FROM o WHERE x NOT IN (SELECT c FROM t WHERE t.k = o.k)"
        )
        assert sorted(out.column("x").to_pylist()) == [10, 30]


# --------------------------------------------------------------------- 4
class TestParameterRendering:
    def test_float_exponent_renders_decimal(self, client):
        client.execute_update("INSERT INTO orders VALUES (100, 0.0000001)")
        handle = client.prepare("SELECT id FROM orders WHERE v = ?")
        out = client.execute_prepared(handle, params=[1e-07])
        assert out.column("id").to_pylist() == [100]
        client.close_prepared(handle)

    def test_float_round_trip_exact(self):
        lit = bind_parameters("SELECT ?", None, [1e-07]).split()[-1]
        assert "e" not in lit.lower()
        assert float(lit) == 1e-07

    def test_bytes_rejected(self):
        with pytest.raises(flight.FlightError, match="binary parameters"):
            bind_parameters("SELECT * FROM t WHERE b = ?", None, [b"ab"])

    def test_nonfinite_float_rejected(self):
        with pytest.raises(flight.FlightError, match="non-finite"):
            bind_parameters("SELECT ?", None, [float("inf")])

    def test_arity_mismatch_rejected(self):
        with pytest.raises(flight.FlightError, match="2 parameter"):
            bind_parameters("SELECT * FROM t WHERE a = ? AND b = ?", None, [1])
        with pytest.raises(flight.FlightError, match="1 parameter"):
            bind_parameters("SELECT * FROM t WHERE a = ?", None, [1, 2])

    def test_bind_time_arity_error(self, client):
        handle = client.prepare("SELECT v FROM orders WHERE id = ?")
        with pytest.raises(flight.FlightError, match="1 parameter"):
            client.execute_prepared(handle, params=[1, 2])
        client.close_prepared(handle)


# --------------------------------------------------------------------- 5
class TestSqlInfoTransactionEnum:
    def test_id8_is_bigint_enum(self, client):
        info = client.get_sql_info(ids=[8])
        assert info.column("info_name").to_pylist() == [8]
        value = info.column("value")[0]
        assert value.as_py() == 1  # SQL_SUPPORTED_TRANSACTION_TRANSACTION
        # strict drivers read the union child by declared type: must be the
        # bigint branch, not bool
        chunk = info.column("value").chunk(0)
        assert chunk.type_codes.to_pylist() == [2]
