"""lakelint: every rule must catch its seeded fixture bug, suppression must
work both ways (pragma + baseline), and the lockgraph detector must catch
the seeded lock-order inversion and lock-held-across-submit — and stay
silent on correct code, including the real runtime/meta paths."""

from __future__ import annotations

import json
import pathlib
import threading

import pytest

from lakesoul_tpu.analysis import Baseline, run
from lakesoul_tpu.analysis import lockgraph
from lakesoul_tpu.analysis.engine import Module
from lakesoul_tpu.analysis.rules.determinism import StageNondeterminismRule

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"
LINT = FIXTURES / "lint"


def lint_fixture(name: str, rules=None):
    findings, _ = run([LINT / name], root=LINT, rules=rules)
    return findings


# --------------------------------------------------------------- lint rules


def test_raw_thread_rule_catches_both_primitives():
    found = lint_fixture("bad_threads.py")
    rules = [f.rule for f in found]
    assert rules.count("raw-thread") == 2
    lines = {f.line for f in found if f.rule == "raw-thread"}
    src = (LINT / "bad_threads.py").read_text().splitlines()
    for line in lines:
        assert "SEED: raw-thread" in src[line - 1]


def test_lock_held_call_rule_catches_each_blocking_call():
    found = [f for f in lint_fixture("bad_locks.py") if f.rule == "lock-held-call"]
    called = sorted(f.message.split("(", 1)[0] for f in found)
    assert len(found) == 5, found
    assert any("submit" in c for c in called)
    assert any("result" in c for c in called)
    assert any("sleep" in c for c in called)
    assert any("worker_thread.join" in c for c in called)
    assert any(c.strip() == "open" for c in called)
    # the closure body must NOT be flagged (runs outside the lock)
    src = (LINT / "bad_locks.py").read_text().splitlines()
    for f in found:
        assert "SEED: lock-held-call" in src[f.line - 1]


def test_stage_nondeterminism_rule():
    rules = [StageNondeterminismRule(scope=("bad_stage.py",))]
    found = [
        f for f in lint_fixture("bad_stage.py", rules=rules)
        if f.rule == "stage-nondeterminism"
    ]
    assert len(found) == 3, found
    src = (LINT / "bad_stage.py").read_text().splitlines()
    for f in found:
        assert "SEED: stage-nondeterminism" in src[f.line - 1]
    # out-of-scope module: silent even with violations present
    assert lint_fixture("bad_stage.py") == []


def test_unclosed_reader_rule_flags_each_leak_tier_only():
    found = [f for f in lint_fixture("bad_resources.py") if f.rule == "unclosed-reader"]
    src = (LINT / "bad_resources.py").read_text().splitlines()
    assert len(found) == 3, found
    for f in found:
        assert "SEED: unclosed-reader" in src[f.line - 1]


def test_undocumented_env_rule_reads_readme_table():
    found = [f for f in lint_fixture("bad_env.py") if f.rule == "undocumented-env"]
    assert len(found) == 1
    assert "LAKESOUL_UNDOCUMENTED_KNOB" in found[0].message


def test_undocumented_env_wildcard_direction(tmp_path):
    """A wildcard README row covers vars UNDER the prefix and explicit
    dynamic-prefix constants (ending in "_"), but a var that merely happens
    to be a prefix of the row must NOT pass."""
    (tmp_path / "README.md").write_text(
        "| `LAKESOUL_PROXY_S3_*` | unset | proxy config |\n"
    )
    (tmp_path / "mod.py").write_text(
        'import os\n'
        'a = os.environ.get("LAKESOUL_PROXY_S3_ENDPOINT")  # covered\n'
        'b = "LAKESOUL_PROXY_S3_"  # dynamic prefix: covered\n'
        'c = os.environ.get("LAKESOUL_PROXY")  # NOT documented\n'
    )
    found, _ = run([tmp_path / "mod.py"], root=tmp_path)
    env = [f for f in found if f.rule == "undocumented-env"]
    assert len(env) == 1, env
    assert env[0].message.startswith("LAKESOUL_PROXY ")


def test_metric_name_rule_scheme_suffixes_and_kind_clash():
    found = [f for f in lint_fixture("bad_metrics.py") if f.rule == "metric-name"]
    msgs = "\n".join(f.message for f in found)
    assert "'BadCamelName'" in msgs
    assert "'lakesoul_widget_count'" in msgs and "_total" in msgs
    assert "'lakesoul_widget_latency'" in msgs and "_seconds" in msgs
    assert "multiple kinds" in msgs and "'lakesoul_clash_total'" in msgs
    assert len(found) == 4, found


def test_sqlite_scope_rule():
    found = [f for f in lint_fixture("bad_sqlite.py") if f.rule == "sqlite-scope"]
    assert len(found) >= 2  # import + connect (cursor heuristic is a bonus)
    msgs = "\n".join(f.message for f in found)
    assert "import sqlite3" in msgs
    assert "sqlite3.connect" in msgs


# ------------------------------------------------------------- suppression


def test_inline_pragma_suppresses_finding():
    assert lint_fixture("ok_pragma.py") == []
    # the same code without the pragma IS a finding
    mod = Module.load(LINT / "ok_pragma.py", LINT)
    assert mod.pragma_rules(7) == {"raw-thread"}


def test_baseline_suppresses_and_reports_stale(tmp_path):
    findings, _ = run([LINT / "bad_threads.py"], root=LINT)
    assert findings
    entries = [
        {"rule": f.rule, "path": f.path, "message": f.message, "reason": "test"}
        for f in findings
    ]
    stale = {
        "rule": "raw-thread",
        "path": "gone.py",
        "message": "was fixed long ago",
        "reason": "test",
    }
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(
        json.dumps({"version": 1, "suppressions": entries + [stale]})
    )
    baseline = Baseline.load(bl_path)
    left, baseline = run([LINT / "bad_threads.py"], root=LINT, baseline=baseline)
    assert left == []
    stales = baseline.stale_entries()
    assert len(stales) == 1 and stales[0]["path"] == "gone.py"


def test_baseline_requires_reasons(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({
        "version": 1,
        "suppressions": [{"rule": "x", "path": "y", "message": "z"}],
    }))
    with pytest.raises(ValueError, match="justified"):
        Baseline.load(p)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    from lakesoul_tpu.analysis.__main__ import main

    rc = main([str(LINT / "bad_threads.py"), "--no-baseline", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    assert {f["rule"] for f in payload} == {"raw-thread"}

    rc = main([str(LINT / "ok_pragma.py"), "--no-baseline"])
    assert rc == 0


# ---------------------------------------------------------------- lockgraph


@pytest.fixture()
def clean_lockgraph():
    lockgraph.reset()
    yield
    lockgraph.disable()
    lockgraph.reset()


def test_lockgraph_catches_seeded_inversion(clean_lockgraph):
    from fixtures import lockbugs

    with lockgraph.watch() as w:
        lockbugs.lock_order_inversion()
    kinds = [v.kind for v in w.violations]
    assert kinds == ["lock-cycle"]
    v = w.violations[0]
    assert "inverts an existing lock order" in v.message
    assert v.stacks  # the acquiring stacks ship with the report


def test_lockgraph_catches_submit_while_locked(clean_lockgraph):
    from fixtures import lockbugs
    from lakesoul_tpu.runtime.pool import shutdown_pool

    try:
        with lockgraph.watch() as w:
            lockbugs.submit_while_locked()
    finally:
        shutdown_pool()
    kinds = [v.kind for v in w.violations]
    assert kinds == ["submit-while-locked"]
    assert "pool.submit while holding" in w.violations[0].message


def test_lockgraph_silent_on_correct_code(clean_lockgraph):
    from fixtures import lockbugs

    with lockgraph.watch() as w:
        lockbugs.well_ordered()
    assert w.violations == []


def test_lockgraph_handles_condition_and_queue(clean_lockgraph):
    """Checked locks must stay duck-compatible with Condition/Queue — the
    places a wrapper with missing protocol methods corrupts bookkeeping."""
    import queue

    with lockgraph.watch() as w:
        q: queue.Queue = queue.Queue(maxsize=2)

        def produce():
            for i in range(10):
                q.put(i)

        t = threading.Thread(target=produce)
        t.start()
        got = [q.get() for _ in range(10)]
        t.join()
        assert got == list(range(10))

        cond = threading.Condition()
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            hits.append(1)
            cond.notify_all()
        t.join()
    assert w.violations == []


def test_lockgraph_no_false_cycle_from_address_reuse(clean_lockgraph):
    """Edges are keyed by per-wrapper serials: GC'd locks whose id() gets
    reused must not poison the graph with stale edges (regression: 200
    fresh a->b pairs used to yield dozens of false cycles)."""
    with lockgraph.watch() as w:
        for _ in range(200):
            a, b = threading.Lock(), threading.Lock()
            with a:
                with b:
                    pass
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_lockgraph_cross_thread_release_clears_hold(clean_lockgraph):
    """A plain Lock released by another thread (handoff/gate pattern) must
    clear the acquiring thread's hold — no phantom submit-while-locked."""
    from lakesoul_tpu.runtime.pool import get_pool, shutdown_pool

    try:
        with lockgraph.watch() as w:
            gate = threading.Lock()
            gate.acquire()

            def release_from_other_thread():
                gate.release()

            t = threading.Thread(target=release_from_other_thread)
            t.start()
            t.join()
            assert lockgraph.current_held() == []
            assert get_pool().submit(lambda: 1).result() == 1
    finally:
        shutdown_pool()
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_lockgraph_disable_restores_primitives(clean_lockgraph):
    real_lock, real_rlock = threading.Lock, threading.RLock
    with lockgraph.watch():
        assert threading.Lock is not real_lock
        assert threading.RLock is not real_rlock
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock


def test_lockgraph_clean_on_real_data_path(clean_lockgraph, tmp_path):
    """Integration guard: the runtime pipeline + meta store under
    instrumentation — the two subsystems whose race classes this PR exists
    to keep dead — must produce zero violations."""
    import pyarrow as pa

    from lakesoul_tpu.runtime.pipeline import pipeline
    from lakesoul_tpu.runtime.pool import shutdown_pool

    try:
        with lockgraph.watch() as w:
            it = (
                pipeline("lockcheck")
                .source(range(64))
                .map_parallel(lambda x: x * 2, workers=4, name="double")
                .prefetch(2)
                .run()
            )
            assert list(it) == [x * 2 for x in range(64)]
            it.close()

            from lakesoul_tpu import LakeSoulCatalog

            catalog = LakeSoulCatalog(
                str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
            )
            t = catalog.create_table(
                "lockcheck_t", pa.schema([("id", pa.int64())])
            )
            t.write_arrow(pa.table({"id": list(range(100))}))
            assert t.to_arrow().num_rows == 100
    finally:
        shutdown_pool()
    assert w.violations == [], "\n".join(v.render() for v in w.violations)
