"""lakelint: every rule must catch its seeded fixture bug, suppression must
work both ways (pragma + baseline), the call-graph builder must resolve
what it claims to resolve (and record what it cannot as unknown edges),
the interprocedural rules must catch their seeded cross-function bugs, the
SARIF/diff output contracts must hold, and the lockgraph detector must
catch the seeded lock-order inversion and lock-held-across-submit — and
stay silent on correct code, including the real runtime/meta paths."""

from __future__ import annotations

import json
import pathlib
import subprocess
import threading

import pytest

from lakesoul_tpu.analysis import Baseline, run
from lakesoul_tpu.analysis import lockgraph
from lakesoul_tpu.analysis.engine import Module, Project
from lakesoul_tpu.analysis.rules.determinism import StageNondeterminismRule
from lakesoul_tpu.analysis.rules.security import (
    RbacGateReachabilityRule,
    TaintPathSegmentsRule,
)

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"
LINT = FIXTURES / "lint"
INTERPROC = LINT / "interproc"


def lint_fixture(name: str, rules=None):
    findings, _ = run([LINT / name], root=LINT, rules=rules)
    return findings


def assert_seed_lines(findings, fixture_rel: str, rule: str):
    """Every finding for ``rule`` sits on a line carrying its SEED marker,
    and every SEED marker in the fixture is found — no misses, no drift."""
    src = (LINT / fixture_rel).read_text().splitlines()
    seeded = {
        i + 1 for i, line in enumerate(src) if f"SEED: {rule}" in line
    }
    got = {f.line for f in findings if f.rule == rule}
    assert got == seeded, (rule, sorted(got), sorted(seeded))


# --------------------------------------------------------------- lint rules


def test_raw_thread_rule_catches_both_primitives():
    found = lint_fixture("bad_threads.py")
    rules = [f.rule for f in found]
    assert rules.count("raw-thread") == 2
    lines = {f.line for f in found if f.rule == "raw-thread"}
    src = (LINT / "bad_threads.py").read_text().splitlines()
    for line in lines:
        assert "SEED: raw-thread" in src[line - 1]


def test_lock_held_call_rule_catches_each_blocking_call():
    found = [f for f in lint_fixture("bad_locks.py") if f.rule == "lock-held-call"]
    called = sorted(f.message.split("(", 1)[0] for f in found)
    assert len(found) == 5, found
    assert any("submit" in c for c in called)
    assert any("result" in c for c in called)
    assert any("sleep" in c for c in called)
    assert any("worker_thread.join" in c for c in called)
    assert any(c.strip() == "open" for c in called)
    # the closure body must NOT be flagged (runs outside the lock)
    src = (LINT / "bad_locks.py").read_text().splitlines()
    for f in found:
        assert "SEED: lock-held-call" in src[f.line - 1]


def test_stage_nondeterminism_rule():
    rules = [StageNondeterminismRule(scope=("bad_stage.py",))]
    found = [
        f for f in lint_fixture("bad_stage.py", rules=rules)
        if f.rule == "stage-nondeterminism"
    ]
    assert len(found) == 3, found
    src = (LINT / "bad_stage.py").read_text().splitlines()
    for f in found:
        assert "SEED: stage-nondeterminism" in src[f.line - 1]
    # out-of-scope module: silent even with violations present
    assert lint_fixture("bad_stage.py") == []


def test_ad_hoc_retry_rule_line_exact():
    """The 17th rule: for-range retry loops (swallowed exceptions) and
    sleep-based backoff are flagged line-exactly; re-raising handlers,
    while-polls, and plain range loops stay silent."""
    found = [f for f in lint_fixture("bad_retry.py") if f.rule == "ad-hoc-retry"]
    assert len(found) == 3, found
    assert_seed_lines(found, "bad_retry.py", "ad-hoc-retry")
    messages = sorted(f.message for f in found)
    assert sum(m.startswith("for-range loop") for m in messages) == 2
    assert sum(m.startswith("sleep-based backoff") for m in messages) == 1


def test_wall_clock_lease_rule_line_exact():
    """The 18th rule: time.time() arithmetic in TTL/deadline/lease math is
    flagged line-exactly; plain epoch stamping, monotonic math, and
    keyword-free control expressions stay silent."""
    from lakesoul_tpu.analysis.rules.wallclock import WallClockLeaseRule

    rules = [WallClockLeaseRule(scope=("bad_wallclock.py",))]
    found = [
        f for f in lint_fixture("bad_wallclock.py", rules=rules)
        if f.rule == "wall-clock-lease"
    ]
    assert len(found) == 5, found
    assert_seed_lines(found, "bad_wallclock.py", "wall-clock-lease")
    # out-of-scope path (fixture root isn't service/compaction/meta): the
    # default-scoped catalog stays silent even with violations present
    assert lint_fixture("bad_wallclock.py") == []


def test_durability_rules_line_exact():
    """The durability pack: bare write-mode opens on publication paths
    (torn-publish, including the interprocedural rename-of-callee-written
    flow), renames whose flow never fsyncs (unfsynced-rename), and
    barriers — CRC sidecars, LATEST pointers — published before their
    data (barrier-order) are flagged line-exactly; the atomicio-routed,
    fsynced, data-then-barrier shapes stay silent."""
    from lakesoul_tpu.analysis.rules.durability import (
        BarrierOrderRule,
        TornPublishRule,
        UnfsyncedRenameRule,
    )

    scope = ("bad_durability.py",)
    rules = [
        TornPublishRule(scope=scope),
        UnfsyncedRenameRule(scope=scope),
        BarrierOrderRule(scope=scope),
    ]
    found = lint_fixture("bad_durability.py", rules=rules)
    assert len(found) == 9, found
    assert_seed_lines(found, "bad_durability.py", "torn-publish")
    assert_seed_lines(found, "bad_durability.py", "unfsynced-rename")
    assert_seed_lines(found, "bad_durability.py", "barrier-order")
    messages = " ".join(f.message for f in found)
    assert "runtime/atomicio" in messages
    assert "empty inode" in messages
    assert "barrier" in messages
    # the fixture is outside the default publication-module scope: the
    # full default catalog stays silent on it
    assert lint_fixture("bad_durability.py") == []


def test_isolation_cas_guard_line_exact():
    """Blind coordination-table writes: PK-only lease updates, CAS whose
    rowcount is never read, DELETE FROM lease (tombstone invariant), and
    partition writes missing the version column are flagged line-exactly;
    the full-CAS-with-rowcount shape stays silent."""
    from lakesoul_tpu.analysis.rules.isolation import CasGuardRule

    found = lint_fixture(
        "bad_isolation.py", rules=[CasGuardRule(scope=("bad_isolation.py",))]
    )
    assert len(found) == 4, found
    assert_seed_lines(found, "bad_isolation.py", "cas-guard")
    messages = " ".join(f.message for f in found)
    assert "tombstoned" in messages
    assert ".rowcount" in messages
    assert "READ COMMITTED" in messages


def test_isolation_read_modify_write_line_exact():
    """Store reads flowing into dependent blind writes — direct and split
    across a helper — are flagged at the sink; the same pair inside a
    ``with store.transaction()`` block is sanctioned."""
    from lakesoul_tpu.analysis.rules.isolation import ReadModifyWriteRule

    found = lint_fixture(
        "bad_isolation.py",
        rules=[ReadModifyWriteRule(scope=("bad_isolation.py",))],
    )
    assert len(found) == 2, found
    assert_seed_lines(found, "bad_isolation.py", "read-modify-write")
    # the interprocedural flow names both hops
    chains = " ".join(f.message for f in found)
    assert "rmw_via_helper" in chains and "_publish" in chains


def test_isolation_txn_boundary_line_exact():
    """Autocommit write statements and seam reach-arounds
    (store._exec/_txn/_conn outside meta/store.py) are flagged
    line-exactly; transaction()-wrapped writes and conn-routed helpers
    stay silent."""
    from lakesoul_tpu.analysis.rules.isolation import TxnBoundaryRule

    found = lint_fixture(
        "bad_isolation.py",
        rules=[TxnBoundaryRule(scope=("bad_isolation.py",))],
    )
    assert len(found) == 5, found
    assert_seed_lines(found, "bad_isolation.py", "txn-boundary")


def test_isolation_sqlite_ism_line_exact():
    """sqlite-only SQL outside the sqlite backend class — OR REPLACE,
    datetime('now'), rowid, AUTOINCREMENT, PRAGMA, and qmark/OR-IGNORE
    bound past translate_sql via a raw execute — is flagged line-exactly;
    the Sqlite* class speaks sqlite freely."""
    from lakesoul_tpu.analysis.rules.isolation import SqliteIsmRule

    found = lint_fixture(
        "bad_isolation.py", rules=[SqliteIsmRule(scope=("bad_isolation.py",))]
    )
    assert len(found) == 7, found
    assert_seed_lines(found, "bad_isolation.py", "sqlite-ism")


def test_isolation_default_scope_is_the_metadata_path():
    """The per-module isolation rules default to meta/ (and txn-boundary
    to the package): the fixture sits outside all of them, so the
    default-scoped instances stay silent even with violations present.
    (read-modify-write is repo-wide by design — flows START anywhere.)"""
    from lakesoul_tpu.analysis.rules.isolation import (
        CasGuardRule,
        SqliteIsmRule,
        TxnBoundaryRule,
    )

    rules = [CasGuardRule(), TxnBoundaryRule(), SqliteIsmRule()]
    assert lint_fixture("bad_isolation.py", rules=rules) == []


def test_durability_sanctioned_seam_exempt_from_torn_publish():
    """runtime/atomicio.py is the ONE module allowed to hold raw
    write-mode opens — torn-publish skips it while unfsynced-rename and
    barrier-order still apply (the seam itself fsyncs before renaming)."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules.durability import (
        BarrierOrderRule,
        TornPublishRule,
        UnfsyncedRenameRule,
    )

    rules = [TornPublishRule(), UnfsyncedRenameRule(), BarrierOrderRule()]
    findings, _ = run(rules=rules, baseline=Baseline([]))
    atomicio = [f for f in findings if "atomicio" in f.path]
    assert atomicio == [], "\n".join(f.render() for f in atomicio)


def test_raw_process_rule_line_exact():
    """The 24th rule: ad-hoc subprocess spawning (dotted and from-imported),
    multiprocessing (import and calls), os.fork, and raw socket-server
    construction are flagged line-exactly; the pragma escape hatch and
    merely process-shaped attribute names stay silent."""
    found = [f for f in lint_fixture("bad_process.py") if f.rule == "raw-process"]
    assert len(found) == 8, found
    assert_seed_lines(found, "bad_process.py", "raw-process")
    messages = " ".join(f.message for f in found)
    assert "unsupervised child process" in messages
    assert "multiprocessing" in messages
    assert "raw serving socket" in messages


def test_raw_process_allows_topology_layers(tmp_path):
    """The same shapes inside scanplane//runtime/ (and the sanctioned
    serving entries) are the POINT of those layers — the rule keys on the
    module path, so the real package lints clean (test_analysis_clean)
    while the fixture catches every seeded site."""
    from lakesoul_tpu.analysis.rules.process import RawProcessRule

    rule = RawProcessRule()
    src = (LINT / "bad_process.py").read_text()
    for rel in (
        "lakesoul_tpu/scanplane/service.py",
        "lakesoul_tpu/runtime/pool.py",
        "lakesoul_tpu/obs/exporter.py",
        "lakesoul_tpu/service/storage_proxy.py",
    ):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        mod = Module.load(p, tmp_path)
        assert list(rule.check(mod)) == [], rel


def test_unstoppable_loop_rule_line_exact():
    """The 25th rule: while-True poll loops that sleep blind in the
    service layers are flagged line-exactly; event-riding waits,
    while-not-stop conditions, in-body stop checks, attempt budgets that
    raise, and sleepless data-drain loops stay silent."""
    from lakesoul_tpu.analysis.rules.loops import UnstoppableLoopRule

    rules = [UnstoppableLoopRule(scope=("bad_loop.py",))]
    found = [
        f for f in lint_fixture("bad_loop.py", rules=rules)
        if f.rule == "unstoppable-loop"
    ]
    assert len(found) == 2, found
    assert_seed_lines(found, "bad_loop.py", "unstoppable-loop")
    assert "stop" in found[0].message
    # out-of-scope path (fixture root isn't streaming//compaction//
    # scanplane//freshness/): the default-scoped catalog stays silent
    assert lint_fixture("bad_loop.py") == []


def test_unstoppable_loop_allows_real_service_loops(tmp_path):
    """The settled real-code idioms — compaction's run_forever
    (stop.wait-paced), the scan-plane client's attempt-budget reconnect
    loop — stay silent under the default scope."""
    import pathlib

    from lakesoul_tpu.analysis.rules.loops import UnstoppableLoopRule

    rule = UnstoppableLoopRule()
    repo = pathlib.Path(__file__).resolve().parents[1]
    for rel in (
        "lakesoul_tpu/compaction/service.py",
        "lakesoul_tpu/scanplane/client.py",
        "lakesoul_tpu/scanplane/worker.py",
        "lakesoul_tpu/streaming/db_sync.py",
        "lakesoul_tpu/freshness/follower.py",
    ):
        mod = Module.load(repo / rel, repo)
        assert mod is not None, rel
        assert list(rule.check(mod)) == [], rel


def test_hot_path_materialize_rule_line_exact():
    """The 19th rule: concat_tables / .combine_chunks() / .to_pandas() in
    the scan/loader hot-path modules are flagged line-exactly; zero-copy
    window assembly and pragma'd bounded copies stay silent."""
    from lakesoul_tpu.analysis.rules.perf import HotPathMaterializeRule

    rules = [HotPathMaterializeRule(scope=("bad_hotpath.py",))]
    found = [
        f for f in lint_fixture("bad_hotpath.py", rules=rules)
        if f.rule == "hot-path-materialize"
    ]
    assert len(found) == 4, found
    assert_seed_lines(found, "bad_hotpath.py", "hot-path-materialize")
    # out-of-scope path (fixture root isn't the scan/loader modules): the
    # default-scoped catalog stays silent even with violations present
    assert lint_fixture("bad_hotpath.py") == []


def test_shared_state_race_rule_line_exact():
    """The lockset rule: fields written from ≥2 thread roots with no common
    lock are flagged line-exactly; one-lock-everywhere fields,
    condition-aliased locks, and single-root writers stay silent."""
    from lakesoul_tpu.analysis.rules.races import SharedStateRaceRule

    rules = [SharedStateRaceRule(scope=("bad_races.py",))]
    found = [
        f for f in lint_fixture("bad_races.py", rules=rules)
        if f.rule == "shared-state-race"
    ]
    assert len(found) == 2, found
    assert_seed_lines(found, "bad_races.py", "shared-state-race")
    msgs = "\n".join(f.message for f in found)
    assert "self.count" in msgs and "self.pending" in msgs
    assert "thread:Telemetry.worker_loop" in msgs and "main" in msgs
    assert "self.synced" not in msgs  # locked twin
    assert "self.depth" not in msgs  # condition-aliased lock agrees
    assert "self.cursor" not in msgs  # single-root writer
    # out-of-scope (the default scope is the package): the catalog's only
    # finding on this fixture is the raw Thread the race needs to exist
    assert {f.rule for f in lint_fixture("bad_races.py")} == {"raw-thread"}


def test_racy_check_then_act_rule_line_exact():
    from lakesoul_tpu.analysis.rules.races import RacyCheckThenActRule

    rules = [RacyCheckThenActRule(scope=("bad_races.py",))]
    found = [
        f for f in lint_fixture("bad_races.py", rules=rules)
        if f.rule == "racy-check-then-act"
    ]
    assert len(found) == 2, found
    assert_seed_lines(found, "bad_races.py", "racy-check-then-act")
    msgs = "\n".join(f.message for f in found)
    assert "self.pending" in msgs and "TOCTOU" in msgs
    # the locked twin (drain_locked) must stay silent — the check and the
    # act are atomic under the class lock; a non-lock `with` (spill's
    # open()) shields nothing


def test_view_escapes_release_rule_line_exact():
    from lakesoul_tpu.analysis.rules.lifetime import ViewEscapesReleaseRule

    rules = [ViewEscapesReleaseRule(scope=("bad_viewescape.py",))]
    found = [
        f for f in lint_fixture("bad_viewescape.py", rules=rules)
        if f.rule == "view-escapes-release"
    ]
    assert len(found) == 5, found
    assert_seed_lines(found, "bad_viewescape.py", "view-escapes-release")
    msgs = "\n".join(f.message for f in found)
    assert "is stored" in msgs and "is returned" in msgs
    assert "is closed over" in msgs
    # the sanctioned shapes stay silent: argument hand-off (collate_ok) and
    # the view-travels-with-its-batch tuple (push_ok)


def test_ring_aliasing_rule_line_exact():
    from lakesoul_tpu.analysis.rules.lifetime import RingAliasingRule

    rules = [RingAliasingRule(scope=("bad_viewescape.py",))]
    found = [
        f for f in lint_fixture("bad_viewescape.py", rules=rules)
        if f.rule == "ring-aliasing"
    ]
    assert len(found) == 3, found
    assert_seed_lines(found, "bad_viewescape.py", "ring-aliasing")
    assert "cache='device'" in found[0].message
    assert "delivery_copies" in found[0].message
    # the probe-guarded ring (make_probe_guarded_ring) is SANCTIONED — the
    # measured-aliasing hand-off the tensor plane introduced — while the
    # INVERTED guard (`if not delivery_copies(...)`) and the else-branch
    # ring are flagged: a probe only guards when its truth selects the
    # ring (assert_seed_lines pinned all three findings line-exactly)
    # out-of-scope default: both lifetime rules default to data/jax_iter.py
    assert lint_fixture("bad_viewescape.py") == []


def test_replay_host_roundtrip_rule_line_exact():
    """The 26th rule: np.asarray / .tolist() / .to_pandas() host
    materializations inside the tensor plane are flagged line-exactly;
    device-side accounting/permutation and the pragma'd verification
    readback stay silent."""
    from lakesoul_tpu.analysis.rules.replay import ReplayHostRoundtripRule

    rules = [ReplayHostRoundtripRule(scope=("bad_replay.py",))]
    found = [
        f for f in lint_fixture("bad_replay.py", rules=rules)
        if f.rule == "replay-host-roundtrip"
    ]
    assert len(found) == 4, found
    assert_seed_lines(found, "bad_replay.py", "replay-host-roundtrip")
    msgs = "\n".join(f.message for f in found)
    assert "asarray" in msgs and ".tolist()" in msgs and ".to_pandas()" in msgs
    # out-of-scope default: the rule scopes to lakesoul_tpu/tensorplane/
    assert lint_fixture("bad_replay.py") == []


def test_thread_root_inference_on_fixture():
    """The root index must see the Thread(target=) entry, keep the worker
    off the main root, and leave uncalled public methods main-rooted."""
    from lakesoul_tpu.analysis.threadroots import thread_roots

    project = Project(root=LINT)
    project.modules.append(Module.load(LINT / "bad_races.py", LINT))
    idx = thread_roots(project)
    assert ("thread", "bad_races.py::Telemetry.worker_loop") in idx.entries
    worker = idx.roots_of("bad_races.py::Telemetry.worker_loop")
    assert worker == {"thread:bad_races.py::Telemetry.worker_loop"}
    assert idx.roots_of("bad_races.py::Telemetry.reset") == {"main"}


def test_thread_root_inference_on_real_loader():
    """Real-repo shapes: the pipeline source generator carries the pipeline
    root, the lease heartbeat its thread root, the Flight verbs handler
    roots — and the per-request HTTP handler collapses to ONE root."""
    from lakesoul_tpu.analysis.engine import package_root
    from lakesoul_tpu.analysis.threadroots import thread_roots

    project = Project(root=package_root().parent)
    for rel in (
        "data/jax_iter.py", "compaction/service.py", "service/flight.py",
        "service/storage_proxy.py",
    ):
        mod = Module.load(package_root() / rel, package_root().parent)
        assert mod is not None
        project.modules.append(mod)
    idx = thread_roots(project)
    kinds = {k for k, _ in idx.entries}
    assert {"thread", "pipeline", "handler"} <= kinds
    hb = idx.roots_of(
        "lakesoul_tpu/compaction/service.py::_LeaseHeartbeat._run"
    )
    assert any(r.startswith("thread:") for r in hb)
    src = idx.roots_of(
        "lakesoul_tpu/data/jax_iter.py::JaxBatchIterator._epoch_windows"
    )
    assert any(r.startswith("pipeline:") for r in src)
    # every do_* verb of the per-request proxy handler shares one root
    proxy_roots = {
        r
        for q, roots in idx.roots.items()
        if "storage_proxy.py::StorageProxy.__init__.Handler.do_" in q
        for r in roots
        if r.startswith("handler:")
    }
    assert len(proxy_roots) == 1, proxy_roots


def test_concurrency_rules_silent_on_real_hot_modules():
    """The fixed runtime/pipeline, page cache, loader, serving and
    heartbeat modules hold under the whole concurrency pack with NO
    baseline: the PR-8/PR-6 machinery is lockset-clean."""
    from lakesoul_tpu.analysis.rules.lifetime import (
        RingAliasingRule,
        ViewEscapesReleaseRule,
    )
    from lakesoul_tpu.analysis.rules.races import (
        RacyCheckThenActRule,
        SharedStateRaceRule,
    )

    findings, _ = run(rules=[
        SharedStateRaceRule(), RacyCheckThenActRule(),
        ViewEscapesReleaseRule(), RingAliasingRule(),
    ], baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_hot_path_modules_clean_without_baseline():
    """The three hot-path modules hold under the rule with NO baseline at
    all: every surviving materialization carries an inline pragma whose
    reason names why the copy is legal (zero-copy chunk-list ops, bounded
    remainder copies)."""
    from lakesoul_tpu.analysis.rules.perf import HotPathMaterializeRule

    found, _ = run(rules=[HotPathMaterializeRule()], baseline=Baseline([]))
    assert [f for f in found if f.rule == "hot-path-materialize"] == [], found


def test_ad_hoc_retry_rule_exempts_resilience_module(tmp_path):
    """The one legal retry loop lives in runtime/resilience.py — the same
    shape there must not be flagged."""
    mod = tmp_path / "runtime"
    mod.mkdir()
    target = mod / "resilience.py"
    target.write_text(
        "import time\n"
        "def run(fn):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return fn()\n"
        "        except OSError:\n"
        "            time.sleep(0.01)\n"
    )
    found, _ = run([target], root=tmp_path)
    assert [f for f in found if f.rule == "ad-hoc-retry"] == []


def test_unclosed_reader_rule_flags_each_leak_tier_only():
    found = [f for f in lint_fixture("bad_resources.py") if f.rule == "unclosed-reader"]
    src = (LINT / "bad_resources.py").read_text().splitlines()
    assert len(found) == 3, found
    for f in found:
        assert "SEED: unclosed-reader" in src[f.line - 1]


def test_undocumented_env_rule_reads_readme_table():
    found = [f for f in lint_fixture("bad_env.py") if f.rule == "undocumented-env"]
    assert len(found) == 1
    assert "LAKESOUL_UNDOCUMENTED_KNOB" in found[0].message


def test_undocumented_env_wildcard_direction(tmp_path):
    """A wildcard README row covers vars UNDER the prefix and explicit
    dynamic-prefix constants (ending in "_"), but a var that merely happens
    to be a prefix of the row must NOT pass."""
    (tmp_path / "README.md").write_text(
        "| `LAKESOUL_PROXY_S3_*` | unset | proxy config |\n"
    )
    (tmp_path / "mod.py").write_text(
        'import os\n'
        'a = os.environ.get("LAKESOUL_PROXY_S3_ENDPOINT")  # covered\n'
        'b = "LAKESOUL_PROXY_S3_"  # dynamic prefix: covered\n'
        'c = os.environ.get("LAKESOUL_PROXY")  # NOT documented\n'
    )
    found, _ = run([tmp_path / "mod.py"], root=tmp_path)
    env = [f for f in found if f.rule == "undocumented-env"]
    assert len(env) == 1, env
    assert env[0].message.startswith("LAKESOUL_PROXY ")


def test_metric_name_rule_scheme_suffixes_and_kind_clash():
    found = [f for f in lint_fixture("bad_metrics.py") if f.rule == "metric-name"]
    msgs = "\n".join(f.message for f in found)
    assert "'BadCamelName'" in msgs
    assert "'lakesoul_widget_count'" in msgs and "_total" in msgs
    assert "'lakesoul_widget_latency'" in msgs and "_seconds" in msgs
    assert "multiple kinds" in msgs and "'lakesoul_clash_total'" in msgs
    assert len(found) == 4, found


def test_fleet_identity_label_rule_seed_exact():
    """Literal and f-string identity labels (role=/service_id=/worker=) at
    metric/stage call sites are flagged line-exactly; values routed through
    the obs.fleet identity helpers (or any variable/attribute) pass."""
    findings = [
        f for f in lint_fixture("bad_identity.py")
        if f.rule == "fleet-identity-label"
    ]
    assert_seed_lines(findings, "bad_identity.py", "fleet-identity-label")
    msgs = "\n".join(f.message for f in findings)
    assert "role=" in msgs and "service_id=" in msgs and "worker=" in msgs
    assert all("identity_labels()" in f.message for f in findings)


def test_hardcoded_endpoint_rule_seed_exact():
    """Literal endpoints (URI with nonzero port, bare host:port with a
    real host, loopback URIs) are flagged line-exactly; port-0 ephemeral
    binds, env-lookup defaults, and word:digits labels pass."""
    findings = [
        f for f in lint_fixture("bad_endpoint.py")
        if f.rule == "hardcoded-endpoint"
    ]
    assert_seed_lines(findings, "bad_endpoint.py", "hardcoded-endpoint")
    msgs = "\n".join(f.message for f in findings)
    assert "grpc://10.0.0.5:8815" in msgs
    assert all("configuration" in f.message for f in findings)


def test_sqlite_scope_rule():
    found = [f for f in lint_fixture("bad_sqlite.py") if f.rule == "sqlite-scope"]
    assert len(found) >= 2  # import + connect (cursor heuristic is a bonus)
    msgs = "\n".join(f.message for f in found)
    assert "import sqlite3" in msgs
    assert "sqlite3.connect" in msgs


# ---------------------------------------------------------------- callgraph


def _interproc_project() -> Project:
    project = Project(root=LINT)
    for p in sorted(INTERPROC.glob("*.py")):
        mod = Module.load(p, LINT)
        if mod is not None:
            project.modules.append(mod)
    return project


def test_callgraph_builds_nodes_and_resolves_edges():
    graph = _interproc_project().callgraph()
    # module functions, class methods and the module pseudo-node all exist
    assert "interproc/bad_lockchain.py::_helper" in graph.functions
    assert "interproc/bad_gate.py::BadServer.do_action" in graph.functions
    fn = graph.functions["interproc/bad_gate.py::BadServer.do_action"]
    assert fn.is_method and fn.class_qname == "interproc/bad_gate.py::BadServer"
    # plain-name resolution: do_work → _helper → _inner
    edges = graph.callees("interproc/bad_lockchain.py::do_work")
    assert any(e.callee == "interproc/bad_lockchain.py::_helper" for e in edges)
    edges = graph.callees("interproc/bad_lockchain.py::_helper")
    assert any(e.callee == "interproc/bad_lockchain.py::_inner" for e in edges)
    # self.<method> resolution through the enclosing class
    edges = graph.callees("interproc/bad_gate.py::BadServer.do_action")
    assert any(
        e.callee == "interproc/bad_gate.py::BadServer._mutate_helper"
        for e in edges
    )


def test_callgraph_records_unknown_edges_conservatively():
    graph = _interproc_project().callgraph()
    # self.catalog.drop_table: dynamic receiver → unknown edge with the
    # receiver/attr text preserved for rules to pattern-match
    edges = graph.callees("interproc/bad_gate.py::BadServer._mutate_helper")
    dyn = [e for e in edges if e.attr == "drop_table"]
    assert len(dyn) == 1 and not dyn[0].resolved
    assert dyn[0].receiver == "self.catalog"
    assert dyn[0].raw == "self.catalog.drop_table"
    stats = graph.stats()
    assert stats["unknown_edges"] >= 1 and stats["resolved_edges"] >= 4


def test_callgraph_resolves_base_class_methods():
    """``self._check`` on the Flight SQL server resolves into the base
    gateway class — the real cross-module shape the RBAC rule leans on."""
    from lakesoul_tpu.analysis.engine import package_root

    project = Project(root=package_root().parent)
    for rel in ("service/flight.py", "service/flight_sql.py"):
        mod = Module.load(package_root() / rel, package_root().parent)
        assert mod is not None
        project.modules.append(mod)
    graph = project.callgraph()
    q = graph.resolve_method(
        "lakesoul_tpu/service/flight_sql.py::LakeSoulFlightSqlServer", "_check"
    )
    assert q == "lakesoul_tpu/service/flight.py::LakeSoulFlightServer._check"


# ------------------------------------------------------ interprocedural rules


def test_rbac_gate_reachability_catches_gate_skipping_helper():
    rules = [RbacGateReachabilityRule(scope=("interproc/bad_gate.py",))]
    found = lint_fixture("interproc/bad_gate.py", rules=rules)
    assert_seed_lines(found, "interproc/bad_gate.py", "rbac-gate-reachability")
    assert len(found) == 1
    msg = found[0].message
    assert "do_action" in msg and "_mutate_helper" in msg


def test_taint_path_segments_catches_laundered_segment():
    rules = [TaintPathSegmentsRule(scope=("interproc/bad_taint.py",))]
    found = lint_fixture("interproc/bad_taint.py", rules=rules)
    assert_seed_lines(found, "interproc/bad_taint.py", "taint-path-segments")
    assert len(found) == 1
    assert "do_PUT" in found[0].message and "_write_to" in found[0].message


def test_transitive_lock_held_call_catches_chain():
    found = [
        f for f in lint_fixture("interproc/bad_lockchain.py")
        if f.rule == "transitive-lock-held-call"
    ]
    assert_seed_lines(
        found, "interproc/bad_lockchain.py", "transitive-lock-held-call"
    )
    assert len(found) == 1
    assert "time.sleep" in found[0].message and "_inner" in found[0].message
    # the lexical rule must NOT double-report the chain
    assert not [
        f for f in lint_fixture("interproc/bad_lockchain.py")
        if f.rule == "lock-held-call"
    ]


def test_interprocedural_unclosed_reader_catches_drops():
    found = [
        f for f in lint_fixture("interproc/bad_reader_drop.py")
        if f.rule == "interprocedural-unclosed-reader"
    ]
    assert_seed_lines(
        found, "interproc/bad_reader_drop.py", "interprocedural-unclosed-reader"
    )
    assert len(found) == 2  # handed-to-dropping-helper + factory result dropped
    msgs = "\n".join(f.message for f in found)
    assert "drops it" in msgs and "returns an open reader" in msgs


def test_interproc_rules_silent_on_real_gateways():
    """The real service/ modules (post-fix) must be clean under the
    interprocedural rules without any baseline — pragmas only."""
    from lakesoul_tpu.analysis.engine import package_root
    from lakesoul_tpu.analysis.rules.concurrency import TransitiveLockHeldCallRule

    findings, _ = run(
        [package_root() / "service"],
        rules=[
            RbacGateReachabilityRule(),
            TaintPathSegmentsRule(),
            TransitiveLockHeldCallRule(),
        ],
    )
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------- boundedness pack (rules 36-40)


def _boundedness_rules():
    from lakesoul_tpu.analysis.rules.boundedness import (
        ChildReapRule,
        ShmDebrisRule,
        ThreadLifecycleRule,
        UnboundedGrowthRule,
        UnboundedQueueRule,
    )

    scope = ("bad_leaks.py",)
    return {
        "unbounded-queue": UnboundedQueueRule(scope=scope),
        "unbounded-growth": UnboundedGrowthRule(scope=scope),
        "thread-lifecycle": ThreadLifecycleRule(scope=scope),
        "child-reap": ChildReapRule(scope=scope),
        "shm-debris": ShmDebrisRule(scope=scope),
    }


def test_unbounded_queue_line_exact():
    """Queue()/deque()/SimpleQueue() without a bound are flagged
    line-exactly; every capacity-carrying construction stays silent."""
    found = lint_fixture(
        "bad_leaks.py", rules=[_boundedness_rules()["unbounded-queue"]]
    )
    assert len(found) == 3, found
    assert_seed_lines(found, "bad_leaks.py", "unbounded-queue")
    messages = " ".join(f.message for f in found)
    assert "SimpleQueue" in messages and "maxlen" in messages


def test_unbounded_growth_line_exact():
    """The background service loop appending to an unevicted self-list is
    flagged; the draining and ring-bounded variants stay silent."""
    found = lint_fixture(
        "bad_leaks.py", rules=[_boundedness_rules()["unbounded-growth"]]
    )
    assert len(found) == 1, found
    assert_seed_lines(found, "bad_leaks.py", "unbounded-growth")
    (f,) = found
    assert "_events" in f.message and "LeakyCollector" in f.message
    # the report names the background root that reaches the loop
    assert "thread:" in f.message


def test_thread_lifecycle_line_exact():
    """Anonymous, escaped-local, and unjoined-attr thread starts are each
    flagged; joined handles and stop-event-wired publishers stay silent."""
    found = lint_fixture(
        "bad_leaks.py", rules=[_boundedness_rules()["thread-lifecycle"]]
    )
    assert len(found) == 3, found
    assert_seed_lines(found, "bad_leaks.py", "thread-lifecycle")
    messages = " ".join(f.message for f in found)
    assert "without keeping the handle" in messages
    assert "_pump_t" in messages


def test_child_reap_line_exact():
    """The bare spawn, the never-reaped registry, and the
    terminate-without-wait zombie are flagged; the reaped spawner with
    poll()-based reap and wait-with-kill-fallback stays silent."""
    found = lint_fixture(
        "bad_leaks.py", rules=[_boundedness_rules()["child-reap"]]
    )
    assert len(found) == 3, found
    assert_seed_lines(found, "bad_leaks.py", "child-reap")
    messages = " ".join(f.message for f in found)
    assert "zombie" in messages and "_procs" in messages


def test_shm_debris_line_exact():
    """mkdtemp and /dev/shm makedirs with no prune seam are flagged; the
    atexit-registered and class-owned cleanup shapes stay silent."""
    found = lint_fixture(
        "bad_leaks.py", rules=[_boundedness_rules()["shm-debris"]]
    )
    assert len(found) == 2, found
    assert_seed_lines(found, "bad_leaks.py", "shm-debris")


def test_boundedness_pack_all_rules_together():
    """One run with all five rules reproduces exactly the union of the
    fixture's SEED lines — the shared per-class index serves every rule."""
    found = lint_fixture("bad_leaks.py", rules=list(_boundedness_rules().values()))
    src = (LINT / "bad_leaks.py").read_text().splitlines()
    seeded = {
        (line.split("SEED: ")[1].strip(), i + 1)
        for i, line in enumerate(src)
        if "SEED: " in line
    }
    got = {(f.rule, f.line) for f in found}
    assert got == seeded, (sorted(got - seeded), sorted(seeded - got))


# ------------------------------------------------------------------- sarif


def test_sarif_output_shape():
    from lakesoul_tpu.analysis.rules import all_rules
    from lakesoul_tpu.analysis.sarif import to_sarif

    findings = lint_fixture("bad_threads.py")
    assert findings
    log = to_sarif(findings, all_rules())
    # the SARIF 2.1.0 shape code-scanning consumers read
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-2.1.0.json")
    (run_,) = log["runs"]
    driver = run_["tool"]["driver"]
    assert driver["name"] == "lakesoul-lint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert len(rule_ids) == 40 and "rbac-gate-reachability" in rule_ids
    assert "unbounded-queue" in rule_ids and "unbounded-growth" in rule_ids
    assert "thread-lifecycle" in rule_ids and "child-reap" in rule_ids
    assert "shm-debris" in rule_ids
    assert "cas-guard" in rule_ids and "read-modify-write" in rule_ids
    assert "txn-boundary" in rule_ids and "sqlite-ism" in rule_ids
    assert "torn-publish" in rule_ids and "unfsynced-rename" in rule_ids
    assert "barrier-order" in rule_ids
    assert "raw-process" in rule_ids
    assert "unstoppable-loop" in rule_ids
    assert "replay-host-roundtrip" in rule_ids
    assert "fleet-identity-label" in rule_ids
    assert "hardcoded-endpoint" in rule_ids
    assert "pallas-blockspec" in rule_ids
    assert "shared-state-race" in rule_ids and "view-escapes-release" in rule_ids
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    assert len(run_["results"]) == len(findings)
    for res, f in zip(run_["results"], findings):
        assert res["ruleId"] == f.rule
        assert res["message"]["text"] == f.message
        (loc,) = res["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"] == f.path
        assert phys["region"]["startLine"] == f.line


def test_cli_sarif_flag(capsys):
    from lakesoul_tpu.analysis.__main__ import main

    rc = main([str(LINT / "bad_threads.py"), "--no-baseline", "--sarif"])
    assert rc == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    assert {r["ruleId"] for r in log["runs"][0]["results"]} == {"raw-thread"}


# ----------------------------------------------------------------- diff mode


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=str(cwd), check=True, capture_output=True,
    )


def test_diff_mode_reports_only_changed_lines(tmp_path):
    """Two-commit synthetic repo: the legacy violation predates BASE, the
    new one lands in the diff — only the new one may fail the gate."""
    from lakesoul_tpu.analysis.gitdiff import changed_lines, filter_to_diff

    _git(tmp_path, "init", "-q")
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import threading\n"
        "\n"
        "def legacy():\n"
        "    return threading.Thread(target=print)\n"
    )
    _git(tmp_path, "add", "mod.py")
    _git(tmp_path, "commit", "-qm", "base")
    mod.write_text(
        "import threading\n"
        "\n"
        "def legacy():\n"
        "    return threading.Thread(target=print)\n"
        "\n"
        "def fresh():\n"
        "    return threading.Thread(target=print)\n"
    )
    _git(tmp_path, "add", "mod.py")
    _git(tmp_path, "commit", "-qm", "new code")

    findings, _ = run([mod], root=tmp_path)
    raw = [f for f in findings if f.rule == "raw-thread"]
    assert {f.line for f in raw} == {4, 7}  # both, pre-filter

    changed = changed_lines("HEAD~1", tmp_path)
    assert changed == {"mod.py": {5, 6, 7}}

    kept = filter_to_diff(raw, "HEAD~1", tmp_path)
    assert [f.line for f in kept] == [7]
    # a base equal to HEAD: nothing changed, nothing reported
    assert filter_to_diff(raw, "HEAD", tmp_path) == []

    # user git config must not change the '+++' prefix out from under the
    # parser (a 'w/' prefix would silently empty the map → vacuous gate)
    _git(tmp_path, "config", "diff.mnemonicprefix", "true")
    assert changed_lines("HEAD~1", tmp_path) == {"mod.py": {5, 6, 7}}


def test_diff_mode_engine_error_is_exit_2(capsys):
    from lakesoul_tpu.analysis.__main__ import main

    rc = main([str(LINT / "bad_threads.py"), "--no-baseline",
               "--diff", "no-such-ref-xyzzy"])
    assert rc == 2
    assert "engine error" in capsys.readouterr().err


# ------------------------------------------------------------- CLI filters


def test_cli_rule_filter_and_formats(capsys):
    from lakesoul_tpu.analysis.__main__ import main

    # --rule filters to one id; --format json parses
    rc = main([str(LINT / "bad_locks.py"), "--no-baseline",
               "--rule", "lock-held-call", "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 1
    assert {f["rule"] for f in json.loads(out)} == {"lock-held-call"}
    # filtering to a rule with no findings in the file exits clean
    rc = main([str(LINT / "bad_locks.py"), "--no-baseline",
               "--rule", "sqlite-scope"])
    capsys.readouterr()
    assert rc == 0
    # unknown rule id is an engine error, not findings
    rc = main(["--rule", "not-a-rule"])
    assert rc == 2
    assert "unknown rule id" in capsys.readouterr().err
    # --write-baseline under a rule filter would destroy the other rules'
    # suppressions: refused as an engine error before touching the file
    rc = main(["--rule", "raw-thread", "--write-baseline"])
    assert rc == 2
    assert "--write-baseline with --rule" in capsys.readouterr().err


def test_console_lint_mirrors_cli_filters(tmp_warehouse):
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.service.console import Console

    c = Console(LakeSoulCatalog(str(tmp_warehouse)))
    out = c.execute("lint --rule raw-thread --format json")
    assert json.loads(out) == []  # repo is clean under the filter
    sarif = json.loads(c.execute("lint --format sarif"))
    assert sarif["version"] == "2.1.0"
    assert c.execute("lint --rule nope").startswith("lint: engine error")


# ------------------------------------------------------------- suppression


def test_inline_pragma_suppresses_finding():
    assert lint_fixture("ok_pragma.py") == []
    # the same code without the pragma IS a finding
    mod = Module.load(LINT / "ok_pragma.py", LINT)
    assert mod.pragma_rules(7) == {"raw-thread"}


def test_baseline_suppresses_and_reports_stale(tmp_path):
    findings, _ = run([LINT / "bad_threads.py"], root=LINT)
    assert findings
    entries = [
        {"rule": f.rule, "path": f.path, "message": f.message, "reason": "test"}
        for f in findings
    ]
    stale = {
        "rule": "raw-thread",
        "path": "gone.py",
        "message": "was fixed long ago",
        "reason": "test",
    }
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(
        json.dumps({"version": 1, "suppressions": entries + [stale]})
    )
    baseline = Baseline.load(bl_path)
    left, baseline = run([LINT / "bad_threads.py"], root=LINT, baseline=baseline)
    assert left == []
    stales = baseline.stale_entries()
    assert len(stales) == 1 and stales[0]["path"] == "gone.py"


def test_baseline_requires_reasons(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({
        "version": 1,
        "suppressions": [{"rule": "x", "path": "y", "message": "z"}],
    }))
    with pytest.raises(ValueError, match="justified"):
        Baseline.load(p)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    from lakesoul_tpu.analysis.__main__ import main

    rc = main([str(LINT / "bad_threads.py"), "--no-baseline", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    assert {f["rule"] for f in payload} == {"raw-thread"}

    rc = main([str(LINT / "ok_pragma.py"), "--no-baseline"])
    assert rc == 0


# ---------------------------------------------------------------- lockgraph


@pytest.fixture()
def clean_lockgraph():
    lockgraph.reset()
    yield
    lockgraph.disable()
    lockgraph.reset()


def test_lockgraph_catches_seeded_inversion(clean_lockgraph):
    from fixtures import lockbugs

    with lockgraph.watch() as w:
        lockbugs.lock_order_inversion()
    kinds = [v.kind for v in w.violations]
    assert kinds == ["lock-cycle"]
    v = w.violations[0]
    assert "inverts an existing lock order" in v.message
    assert v.stacks  # the acquiring stacks ship with the report


def test_lockgraph_catches_submit_while_locked(clean_lockgraph):
    from fixtures import lockbugs
    from lakesoul_tpu.runtime.pool import shutdown_pool

    try:
        with lockgraph.watch() as w:
            lockbugs.submit_while_locked()
    finally:
        shutdown_pool()
    kinds = [v.kind for v in w.violations]
    assert kinds == ["submit-while-locked"]
    assert "pool.submit while holding" in w.violations[0].message


def test_lockgraph_silent_on_correct_code(clean_lockgraph):
    from fixtures import lockbugs

    with lockgraph.watch() as w:
        lockbugs.well_ordered()
    assert w.violations == []


def test_lockgraph_handles_condition_and_queue(clean_lockgraph):
    """Checked locks must stay duck-compatible with Condition/Queue — the
    places a wrapper with missing protocol methods corrupts bookkeeping."""
    import queue

    with lockgraph.watch() as w:
        q: queue.Queue = queue.Queue(maxsize=2)

        def produce():
            for i in range(10):
                q.put(i)

        t = threading.Thread(target=produce)
        t.start()
        got = [q.get() for _ in range(10)]
        t.join()
        assert got == list(range(10))

        cond = threading.Condition()
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            hits.append(1)
            cond.notify_all()
        t.join()
    assert w.violations == []


def test_lockgraph_no_false_cycle_from_address_reuse(clean_lockgraph):
    """Edges are keyed by per-wrapper serials: GC'd locks whose id() gets
    reused must not poison the graph with stale edges (regression: 200
    fresh a->b pairs used to yield dozens of false cycles)."""
    with lockgraph.watch() as w:
        for _ in range(200):
            a, b = threading.Lock(), threading.Lock()
            with a:
                with b:
                    pass
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_lockgraph_cross_thread_release_clears_hold(clean_lockgraph):
    """A plain Lock released by another thread (handoff/gate pattern) must
    clear the acquiring thread's hold — no phantom submit-while-locked."""
    from lakesoul_tpu.runtime.pool import get_pool, shutdown_pool

    try:
        with lockgraph.watch() as w:
            gate = threading.Lock()
            gate.acquire()

            def release_from_other_thread():
                gate.release()

            t = threading.Thread(target=release_from_other_thread)
            t.start()
            t.join()
            assert lockgraph.current_held() == []
            assert get_pool().submit(lambda: 1).result() == 1
    finally:
        shutdown_pool()
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_lockgraph_disable_restores_primitives(clean_lockgraph):
    real_lock, real_rlock = threading.Lock, threading.RLock
    with lockgraph.watch():
        assert threading.Lock is not real_lock
        assert threading.RLock is not real_rlock
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock


def test_lockgraph_clean_on_real_data_path(clean_lockgraph, tmp_path):
    """Integration guard: the runtime pipeline + meta store under
    instrumentation — the two subsystems whose race classes this PR exists
    to keep dead — must produce zero violations."""
    import pyarrow as pa

    from lakesoul_tpu.runtime.pipeline import pipeline
    from lakesoul_tpu.runtime.pool import shutdown_pool

    try:
        with lockgraph.watch() as w:
            it = (
                pipeline("lockcheck")
                .source(range(64))
                .map_parallel(lambda x: x * 2, workers=4, name="double")
                .prefetch(2)
                .run()
            )
            assert list(it) == [x * 2 for x in range(64)]
            it.close()

            from lakesoul_tpu import LakeSoulCatalog

            catalog = LakeSoulCatalog(
                str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
            )
            t = catalog.create_table(
                "lockcheck_t", pa.schema([("id", pa.int64())])
            )
            t.write_arrow(pa.table({"id": list(range(100))}))
            assert t.to_arrow().num_rows == 100
    finally:
        shutdown_pool()
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


# ------------------------------------------------------- device rule pack


JAXF = LINT / "jax"


def jax_fixture(name: str, rules=None):
    findings, _ = run([JAXF / name], root=LINT, rules=rules)
    return findings


def test_trace_impure_call_catches_each_side_effect():
    found = [
        f for f in jax_fixture("bad_impure.py")
        if f.rule == "trace-impure-call"
    ]
    assert_seed_lines(found, "jax/bad_impure.py", "trace-impure-call")
    msgs = "\n".join(f.message for f in found)
    # the scan callback is traced without any enclosing jit
    assert "scan_body" in msgs
    assert "captured container" in msgs
    assert "jax.debug.print" in msgs


def test_trace_host_sync_catches_syncs_and_loader_stage():
    from lakesoul_tpu.analysis.rules.jaxtpu import TraceHostSyncRule

    found = [
        f for f in jax_fixture(
            "bad_host_sync.py",
            rules=[TraceHostSyncRule(hot_path=("bad_host_sync.py",))],
        )
        if f.rule == "trace-host-sync"
    ]
    assert_seed_lines(found, "jax/bad_host_sync.py", "trace-host-sync")
    # the helper's sink is found interprocedurally (tainted arg one call deep)
    assert any("np.asarray(v)" in f.message for f in found)


def test_trace_host_sync_clean_half_without_hot_path_scope():
    """With the default (real) hot-path scope the fixture's traced-code
    seeds still fire; only the stand-in loader stage needs the scope."""
    found = [
        f for f in jax_fixture("bad_host_sync.py")
        if f.rule == "trace-host-sync"
    ]
    assert {f.line for f in found} == {11, 17, 18, 19, 20}


def test_tpu_dtype_width_catches_traced_and_host_flows():
    from lakesoul_tpu.analysis.rules.jaxtpu import TpuDtypeWidthRule

    found = [
        f for f in jax_fixture(
            "bad_dtype.py", rules=[TpuDtypeWidthRule(scope=("bad_dtype.py",))]
        )
        if f.rule == "tpu-dtype-width"
    ]
    assert_seed_lines(found, "jax/bad_dtype.py", "tpu-dtype-width")
    msgs = "\n".join(f.message for f in found)
    assert "device_put" in msgs  # host value crossing the boundary
    assert "searcher" in msgs  # jit entry as the boundary
    assert "4000000000" in msgs  # promoting literal


def test_jit_static_arg_shape_catches_each_shape_hazard():
    found = [
        f for f in jax_fixture("bad_static_shape.py")
        if f.rule == "jit-static-arg-shape"
    ]
    assert_seed_lines(found, "jax/bad_static_shape.py", "jit-static-arg-shape")
    msgs = "\n".join(f.message for f in found)
    assert "static_argnames" in msgs
    assert "boolean-mask" in msgs
    assert "pad to a bucketed size" in msgs


def test_pallas_blockspec_catches_each_mismatch():
    found = [
        f for f in jax_fixture("bad_blockspec.py")
        if f.rule == "pallas-blockspec"
    ]
    assert_seed_lines(found, "jax/bad_blockspec.py", "pallas-blockspec")
    msgs = "\n".join(f.message for f in found)
    assert "grid has rank" in msgs
    assert "VMEM" in msgs
    assert "never writes output ref" in msgs
    assert "drops" in msgs


def test_device_pack_fixture_files_trip_only_their_own_rule():
    """Cross-contamination guard: each device fixture seeds exactly one
    rule (the clean twins in every file stay silent under the whole
    catalog, minus the scope-parameterized halves tested above)."""
    for name, rule in [
        ("bad_impure.py", "trace-impure-call"),
        ("bad_static_shape.py", "jit-static-arg-shape"),
        ("bad_blockspec.py", "pallas-blockspec"),
    ]:
        others = [
            f for f in jax_fixture(name)
            if f.rule != rule and f.rule != "undocumented-env"
        ]
        assert others == [], (name, others)


def test_device_index_shapes():
    """The shared device index must classify the fixture correctly:
    decorated entries, transform callbacks, pallas kernels."""
    from lakesoul_tpu.analysis.engine import Module, Project
    from lakesoul_tpu.analysis.rules.jaxtpu import device_index

    project = Project(root=LINT)
    for name in ("bad_impure.py", "bad_blockspec.py"):
        project.modules.append(Module.load(JAXF / name, LINT))
    idx = device_index(project)
    entries = {q.rsplit("::", 1)[-1] for q in idx.jit_entries}
    assert {"stamped_step", "clean_step"} <= entries
    traced = {q.rsplit("::", 1)[-1] for q in idx.traced}
    assert "scan_body" in traced  # lax.scan callback
    assert "host_wrapper" not in traced  # host code stays host
    kernels = {q.rsplit("::", 1)[-1] for q in idx.pallas_kernels}
    assert {"_scale_kernel", "_forgets_output"} <= kernels


def test_device_rules_in_sarif_and_diff(tmp_path):
    """The new rules ride the same output contracts: SARIF carries their
    ids, and --diff BASE keeps only findings on changed lines."""
    from lakesoul_tpu.analysis.gitdiff import filter_to_diff
    from lakesoul_tpu.analysis.rules import all_rules
    from lakesoul_tpu.analysis.sarif import to_sarif

    findings = [
        f for f in jax_fixture("bad_static_shape.py")
        if f.rule == "jit-static-arg-shape"
    ]
    log = to_sarif(findings, all_rules())
    ids = {r["id"] for r in log["runs"][0]["tool"]["driver"]["rules"]}
    assert {
        "trace-impure-call", "trace-host-sync", "tpu-dtype-width",
        "jit-static-arg-shape", "pallas-blockspec",
    } <= ids
    assert all(
        r["ruleId"] == "jit-static-arg-shape" for r in log["runs"][0]["results"]
    )

    _git(tmp_path, "init", "-q")
    mod = tmp_path / "mod.py"
    mod.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "@jax.jit\n"
        "def legacy(x):\n"
        "    return x[x > 0]\n"
    )
    _git(tmp_path, "add", "mod.py")
    _git(tmp_path, "commit", "-qm", "base")
    mod.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "@jax.jit\n"
        "def legacy(x):\n"
        "    return x[x > 0]\n"
        "\n"
        "@jax.jit\n"
        "def fresh(x):\n"
        "    return jnp.unique(x)\n"
    )
    _git(tmp_path, "add", "mod.py")
    _git(tmp_path, "commit", "-qm", "new code")
    findings, _ = run([mod], root=tmp_path)
    shape = [f for f in findings if f.rule == "jit-static-arg-shape"]
    assert {f.line for f in shape} == {6, 10}
    kept = filter_to_diff(shape, "HEAD~1", tmp_path)
    assert [f.line for f in kept] == [10]


def test_pallas_blockspec_scratch_and_positional_out_shape(tmp_path):
    """Pallas ref order is (in, out, scratch): the output-write check must
    target the middle params, and a positional multi-output out_shape must
    count toward the kernel arity."""
    from lakesoul_tpu.analysis.rules.jaxtpu import PallasBlockSpecRule

    (tmp_path / "m.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "\n"
        "def good(x_ref, o_ref, acc_ref):\n"
        "    o_ref[...] = x_ref[...] + acc_ref[...]\n"
        "\n"
        "def bad(x_ref, o_ref, acc_ref):\n"
        "    acc_ref[...] = x_ref[...]\n"
        "\n"
        "def two_out(x_ref, a_ref, b_ref):\n"
        "    a_ref[...] = x_ref[...]\n"
        "    b_ref[...] = x_ref[...]\n"
        "\n"
        "def calls(x):\n"
        "    a = pl.pallas_call(good,\n"
        "        out_shape=jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "        grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((32, 64), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((32, 64), lambda i: (i, 0)),\n"
        "        scratch_shapes=(1,))(x)\n"
        "    b = pl.pallas_call(bad,\n"
        "        out_shape=jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "        grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((32, 64), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((32, 64), lambda i: (i, 0)),\n"
        "        scratch_shapes=(1,))(x)\n"
        "    c = pl.pallas_call(two_out,\n"
        "        (jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "         jax.ShapeDtypeStruct((64, 64), jnp.float32)),\n"
        "        grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((32, 64), lambda i: (i, 0))],\n"
        "        out_specs=(pl.BlockSpec((32, 64), lambda i: (i, 0)),\n"
        "                   pl.BlockSpec((32, 64), lambda i: (i, 0))))(x)\n"
        "    return a, b, c\n"
    )
    findings, _ = run(
        [tmp_path / "m.py"], root=tmp_path, rules=[PallasBlockSpecRule()]
    )
    assert len(findings) == 1, [f.render() for f in findings]
    assert "bad" in findings[0].message and "'o_ref'" in findings[0].message


def test_trace_impure_skips_bare_name_callback_targets(tmp_path):
    """`from jax import pure_callback` + a bare-name call must still exclude
    the callback target from the traced closure (host I/O there is the
    sanctioned pattern)."""
    from lakesoul_tpu.analysis.rules.jaxtpu import TraceImpureCallRule

    (tmp_path / "m.py").write_text(
        "import jax\n"
        "from jax import pure_callback\n"
        "\n"
        "def log_row(x):\n"
        "    print('row', x)\n"
        "    return x\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return pure_callback(log_row, x, x)\n"
    )
    findings, _ = run(
        [tmp_path / "m.py"], root=tmp_path, rules=[TraceImpureCallRule()]
    )
    assert findings == [], [f.render() for f in findings]


def test_device_rules_allow_store_staticnum_and_const_slices(tmp_path):
    """False-positive guards: pl.store counts as an output write,
    static_argnums params are static (host math on them is legal), and
    constant-expression slice bounds are not data-dependent."""
    from lakesoul_tpu.analysis.rules.jaxtpu import (
        JitStaticArgShapeRule,
        PallasBlockSpecRule,
        TraceHostSyncRule,
    )

    (tmp_path / "m.py").write_text(
        "import functools\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "\n"
        "def store_kernel(x_ref, o_ref):\n"
        "    pl.store(o_ref, (pl.dslice(0, 32),), x_ref[...])\n"
        "\n"
        "def call(x):\n"
        "    return pl.pallas_call(store_kernel,\n"
        "        out_shape=jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "        grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((32, 64), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((32, 64), lambda i: (i, 0)))(x)\n"
        "\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def topk(x, k):\n"
        "    width = int(k)\n"
        "    return jnp.sort(x)[:width]\n"
        "\n"
        "def host(codes, n):\n"
        "    a = topk(codes[:-1], 4)\n"
        "    b = topk(codes[:2 * 8], 4)\n"
        "    c = topk(codes[:n], 4)  # the only dynamic slice\n"
        "    return a, b, c\n"
    )
    rules = [PallasBlockSpecRule(), TraceHostSyncRule(), JitStaticArgShapeRule()]
    findings, _ = run([tmp_path / "m.py"], root=tmp_path, rules=rules)
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].rule == "jit-static-arg-shape"
    assert "codes[:n]" in (tmp_path / "m.py").read_text().splitlines()[
        findings[0].line - 1
    ]


def test_pallas_blockspec_skips_non_literal_grid_and_out_shape(tmp_path):
    """Literal-first, never guessed: a name holding the grid tuple or the
    out_shape must skip the rank/arity checks rather than assume rank 1 /
    one output."""
    from lakesoul_tpu.analysis.rules.jaxtpu import PallasBlockSpecRule

    (tmp_path / "m.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "\n"
        "GRID = (2, 2)\n"
        "OUT = (jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "       jax.ShapeDtypeStruct((64, 64), jnp.float32))\n"
        "\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "\n"
        "def k2(x_ref, a_ref, b_ref):\n"
        "    a_ref[...] = x_ref[...]\n"
        "    b_ref[...] = x_ref[...]\n"
        "\n"
        "def call_var_grid(x):\n"
        "    return pl.pallas_call(k,\n"
        "        out_shape=jax.ShapeDtypeStruct((64, 64), jnp.float32),\n"
        "        grid=GRID,\n"
        "        in_specs=[pl.BlockSpec((32, 32), lambda i, j: (i, j))],\n"
        "        out_specs=pl.BlockSpec((32, 32), lambda i, j: (i, j)))(x)\n"
        "\n"
        "def call_var_out(x):\n"
        "    return pl.pallas_call(k2, OUT, grid=(2,),\n"
        "        in_specs=[pl.BlockSpec((32, 64), lambda i: (i, 0))],\n"
        "        out_specs=(pl.BlockSpec((32, 64), lambda i: (i, 0)),\n"
        "                   pl.BlockSpec((32, 64), lambda i: (i, 0))))(x)\n"
    )
    findings, _ = run(
        [tmp_path / "m.py"], root=tmp_path, rules=[PallasBlockSpecRule()]
    )
    assert findings == [], [f.render() for f in findings]
