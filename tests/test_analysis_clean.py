"""CI gate: the repo must lint clean.

``python -m lakesoul_tpu.analysis`` must exit 0 — zero unsuppressed
findings over the whole package — and the checked-in baseline must stay
honest: every suppression justified, none stale.  A new finding here means
either fix the code or add a *justified* baseline entry in the same PR."""

from __future__ import annotations

from lakesoul_tpu.analysis import run_repo
from lakesoul_tpu.analysis.engine import Baseline, default_baseline_path


def test_package_lints_clean():
    findings, _ = run_repo()
    assert findings == [], "unsuppressed lint findings:\n" + "\n".join(
        f.render() for f in findings
    )


def test_baseline_entries_all_used_and_justified():
    baseline = Baseline.load(default_baseline_path())
    for e in baseline.entries:
        reason = e.get("reason", "")
        assert reason and "TODO" not in reason, (
            f"baseline entry for {e['path']} lacks a real justification"
        )
    _, baseline = run_repo()
    stale = baseline.stale_entries()
    assert stale == [], "stale baseline entries (delete them):\n" + "\n".join(
        f"[{e['rule']}] {e['path']}: {e['message']}" for e in stale
    )


def test_cli_gate_exit_zero(capsys):
    from lakesoul_tpu.analysis.__main__ import main

    assert main([]) == 0
    assert "clean" in capsys.readouterr().out


def test_console_lint_command(tmp_warehouse):
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.service.console import Console

    c = Console(LakeSoulCatalog(str(tmp_warehouse)))
    out = c.execute("lint")
    assert "lint clean" in out
    assert "lint" in c.execute("help")
