"""CI gate: the repo must lint clean — under ALL 40 rules: the 15
per-function ones (incl. ad-hoc-retry, wall-clock-lease,
hot-path-materialize, raw-process, unstoppable-loop,
replay-host-roundtrip, fleet-identity-label and hardcoded-endpoint), the
4 interprocedural ones (call graph + dataflow), the 5 device-pack ones
(jit/pallas trace safety), the 4 concurrency-pack ones (thread-root
locksets + buffer lifetimes), the 3 durability-pack ones (atomic
publication discipline over the runtime/atomicio seam), the 4
isolation-pack ones (READ COMMITTED portability of the metadata path),
and the 5 boundedness-pack ones (resource budgets + lifecycles — what a
soak run dies of).

``python -m lakesoul_tpu.analysis`` must exit 0 — zero unsuppressed
findings over the whole package — and the checked-in baseline must stay
honest: every suppression justified, none stale.  A new finding here means
either fix the code or add a *justified* baseline entry in the same PR."""

from __future__ import annotations

from lakesoul_tpu.analysis import run_repo
from lakesoul_tpu.analysis.engine import Baseline, default_baseline_path

EXPECTED_RULES = {
    # per-function (PR 3; ad-hoc-retry joined with the resilience layer,
    # wall-clock-lease with the lease table, hot-path-materialize with the
    # zero-copy scan path, raw-process with the scan-plane topology,
    # unstoppable-loop with the freshness follower, replay-host-roundtrip
    # with the tensor plane, fleet-identity-label with the fleet obs
    # plane, hardcoded-endpoint with the fleet transport plane)
    "raw-thread", "lock-held-call", "stage-nondeterminism",
    "unclosed-reader", "undocumented-env", "metric-name", "sqlite-scope",
    "ad-hoc-retry", "wall-clock-lease", "hot-path-materialize",
    "raw-process", "unstoppable-loop", "replay-host-roundtrip",
    "fleet-identity-label", "hardcoded-endpoint",
    # interprocedural
    "rbac-gate-reachability", "taint-path-segments",
    "transitive-lock-held-call", "interprocedural-unclosed-reader",
    # device pack (jit/pallas trace safety)
    "trace-impure-call", "trace-host-sync", "tpu-dtype-width",
    "jit-static-arg-shape", "pallas-blockspec",
    # concurrency pack (thread-root locksets + buffer lifetimes)
    "shared-state-race", "racy-check-then-act",
    "view-escapes-release", "ring-aliasing",
    # durability pack (every publication rides runtime/atomicio; barriers
    # land after the data they cover)
    "torn-publish", "unfsynced-rename", "barrier-order",
    # isolation pack (the metadata path must survive PG at READ COMMITTED)
    "cas-guard", "read-modify-write", "txn-boundary", "sqlite-ism",
    # boundedness pack (bounded memory + clean resource lifecycles)
    "unbounded-queue", "unbounded-growth", "thread-lifecycle",
    "child-reap", "shm-debris",
}

DEVICE_RULES = {
    "trace-impure-call", "trace-host-sync", "tpu-dtype-width",
    "jit-static-arg-shape", "pallas-blockspec",
}

CONCURRENCY_RULES = {
    "shared-state-race", "racy-check-then-act",
    "view-escapes-release", "ring-aliasing",
}

DURABILITY_RULES = {"torn-publish", "unfsynced-rename", "barrier-order"}

ISOLATION_RULES = {"cas-guard", "read-modify-write", "txn-boundary", "sqlite-ism"}

BOUNDEDNESS_RULES = {
    "unbounded-queue", "unbounded-growth", "thread-lifecycle",
    "child-reap", "shm-debris",
}


def test_all_forty_rules_registered():
    """run_repo runs the full catalog — a rule silently dropped from the
    registry would turn this gate into a no-op for its invariant."""
    from lakesoul_tpu.analysis.rules import rule_ids

    ids = rule_ids()
    assert len(ids) == len(set(ids)) == 40
    assert set(ids) == EXPECTED_RULES


def test_package_lints_clean():
    findings, _ = run_repo()
    assert findings == [], "unsuppressed lint findings:\n" + "\n".join(
        f.render() for f in findings
    )


def test_interprocedural_rules_clean_repo_wide_without_baseline():
    """The four interprocedural rules hold with NO baseline entries at all:
    every intentionally-unguarded site carries an inline pragma whose
    reason names the invariant (the baseline is reserved for the
    pre-existing per-function suppressions)."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    interproc = [r for r in all_rules() if r.id in {
        "rbac-gate-reachability", "taint-path-segments",
        "transitive-lock-held-call", "interprocedural-unclosed-reader",
    }]
    assert len(interproc) == 4
    findings, _ = run(rules=interproc, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_baseline_entries_all_used_and_justified():
    baseline = Baseline.load(default_baseline_path())
    for e in baseline.entries:
        reason = e.get("reason", "")
        assert reason and "TODO" not in reason, (
            f"baseline entry for {e['path']} lacks a real justification"
        )
    _, baseline = run_repo()
    stale = baseline.stale_entries()
    assert stale == [], "stale baseline entries (delete them):\n" + "\n".join(
        f"[{e['rule']}] {e['path']}: {e['message']}" for e in stale
    )


def test_cli_gate_exit_zero(capsys):
    from lakesoul_tpu.analysis.__main__ import main

    assert main([]) == 0
    assert "clean" in capsys.readouterr().out


def test_console_lint_command(tmp_warehouse):
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.service.console import Console

    c = Console(LakeSoulCatalog(str(tmp_warehouse)))
    out = c.execute("lint")
    assert "lint clean" in out
    assert "lint" in c.execute("help")


def test_device_pack_clean_repo_wide_without_baseline():
    """The five device rules hold with NO baseline entries at all: every
    intentionally-unguarded site carries an inline pragma whose reason
    names the invariant (same contract as the interprocedural rules)."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    device = [r for r in all_rules() if r.id in DEVICE_RULES]
    assert len(device) == 5
    findings, _ = run(rules=device, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_concurrency_pack_clean_repo_wide_without_baseline():
    """The four concurrency rules hold with NO baseline entries at all —
    the real shared-state findings this PR surfaced were FIXED (page-cache
    index under its lock, pipeline thread/queue registries under _lock,
    heartbeat publishes under a guard), not suppressed."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    conc = [r for r in all_rules() if r.id in CONCURRENCY_RULES]
    assert len(conc) == 4
    findings, _ = run(rules=conc, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_durability_pack_clean_repo_wide_without_baseline():
    """The three durability rules hold with NO baseline entries at all —
    the real findings this PR surfaced were FIXED by consolidating every
    publication (obs fleet docs, spool segments + session manifests, the
    spill rung, LATEST/PLANE store pointers, the freshness oracle doc)
    onto the runtime/atomicio seam, not suppressed."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    dur = [r for r in all_rules() if r.id in DURABILITY_RULES]
    assert len(dur) == 3
    findings, _ = run(rules=dur, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_isolation_pack_clean_repo_wide_without_baseline():
    """The four isolation rules hold with NO baseline entries at all — the
    real findings this PR surfaced were FIXED (client-side lease CAS,
    merge helpers made transactional, update_global_config's read locked,
    the :memory: cursor growing .rowcount), the four store call sites
    whose CAS shape the parser cannot see carry inline pragmas naming the
    predicate, and everything else holds by construction."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    iso = [r for r in all_rules() if r.id in ISOLATION_RULES]
    assert len(iso) == 4
    findings, _ = run(rules=iso, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_boundedness_pack_clean_repo_wide_without_baseline():
    """The five boundedness rules hold with NO baseline entries at all —
    the real findings this PR surfaced were FIXED (the exporter's serve
    thread joined on the shutdown path, the autoscaler's retire() handing
    terminated children to a reaped retiring list, default spool dirs
    pid-stamped + atexit-swept + prune_stale_spools for SIGKILLed owners),
    and the two window-bounded pipeline deques carry inline pragmas naming
    their structural bound."""
    from lakesoul_tpu.analysis import Baseline, run
    from lakesoul_tpu.analysis.rules import all_rules

    bound = [r for r in all_rules() if r.id in BOUNDEDNESS_RULES]
    assert len(bound) == 5
    findings, _ = run(rules=bound, baseline=Baseline([]))
    assert findings == [], "\n".join(f.render() for f in findings)
