"""Sharded ANN plane: memory-bounded multi-shard build + shard-exact resume,
plane-manifest atomicity, ragged scoring (Pallas-interpret vs jnp item
kernel vs host grouped GEMMs), multi-shard vs single-shard parity against
the shared exact oracle, per-query nprobe fusion, fleet serving with typed
overload sheds at 64 concurrent clients, the Flight ``ann_search`` action
(JWT auth, per-table RBAC, UNAVAILABLE on shed), and the cross-chip top-k
merge dryrun on the virtual 8-device mesh."""

import threading

import numpy as np
import pytest

from lakesoul_tpu.annplane import (
    AnnPlane,
    AnnPlaneBinding,
    AnnPlaneConfig,
    PlaneManifestStore,
    ShardedAnnBuilder,
    ShardedAnnEndpoint,
    build_table_ann_plane,
    cross_chip_topk,
    dryrun_multichip,
)
from lakesoul_tpu.annplane import ragged
from lakesoul_tpu.errors import OverloadedError, VectorIndexError
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams
from lakesoul_tpu.vector.oracle import exact_topk, recall_at_k


def make_corpus(n=24_000, d=32, modes=64, seed=0, spread=3.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(modes, d)).astype(np.float32) * spread
    vecs = (
        centers[rng.integers(0, modes, n)]
        + rng.normal(size=(n, d)).astype(np.float32)
    )
    queries = (
        centers[rng.integers(0, modes, 48)]
        + rng.normal(size=(48, d)).astype(np.float32)
    )
    return vecs, np.arange(n, dtype=np.uint64), queries


def plane_config(d=32, *, rows_per_shard=8_000, nlist=16, total_bits=4,
                 keep_raw=True, **kw):
    index = VectorIndexConfig(column="e", dim=d, nlist=nlist,
                              total_bits=total_bits, **kw)
    probe = AnnPlaneConfig(index=index, shard_budget_bytes=1 << 30,
                           keep_raw=keep_raw)
    return AnnPlaneConfig(
        index=index,
        shard_budget_bytes=rows_per_shard * probe.bytes_per_vector(),
        keep_raw=keep_raw,
    )


def stream(vecs, ids, batch=6_000):
    for lo in range(0, len(ids), batch):
        yield vecs[lo : lo + batch], ids[lo : lo + batch]


@pytest.fixture(scope="module")
def built_plane(tmp_path_factory):
    """One 3-shard plane shared by the search/serving tests (module-scoped:
    the build is the expensive part)."""
    vecs, ids, queries = make_corpus()
    cfg = plane_config()
    root = str(tmp_path_factory.mktemp("plane") / "p")
    manifest = ShardedAnnBuilder(root, cfg).build(stream(vecs, ids))
    plane = AnnPlane.open(root, use_pallas=False)
    return root, cfg, plane, manifest, vecs, ids, queries


class TestConfig:
    def test_rows_per_shard_from_budget(self):
        cfg = plane_config(rows_per_shard=5_000)
        assert cfg.rows_per_shard() == 5_000

    def test_digest_covers_layout(self):
        a = plane_config(rows_per_shard=5_000)
        b = plane_config(rows_per_shard=6_000)
        c = plane_config(rows_per_shard=5_000, nlist=32)
        assert a.digest() != b.digest()
        assert a.digest() != c.digest()
        assert a.digest() == plane_config(rows_per_shard=5_000).digest()

    def test_env_budget(self, monkeypatch):
        monkeypatch.setenv("LAKESOUL_ANN_SHARD_BUDGET_BYTES", "12345678")
        cfg = AnnPlaneConfig(index=VectorIndexConfig(column="e", dim=16))
        assert cfg.budget_bytes == 12345678
        monkeypatch.setenv("LAKESOUL_ANN_SHARD_BUDGET_BYTES", "bogus")
        with pytest.raises(VectorIndexError, match="BUDGET"):
            AnnPlaneConfig(index=VectorIndexConfig(column="e", dim=16))

    def test_budget_too_small_raises(self):
        with pytest.raises(VectorIndexError, match="cannot hold"):
            AnnPlaneConfig(
                index=VectorIndexConfig(column="e", dim=128),
                shard_budget_bytes=64,
            )


class TestBuilderAndResume:
    def test_multi_shard_build_rows_exact(self, tmp_path):
        vecs, ids, _ = make_corpus(n=20_000)
        cfg = plane_config()
        m = ShardedAnnBuilder(str(tmp_path / "p"), cfg).build(stream(vecs, ids))
        assert m["complete"] and m["total_rows"] == 20_000
        assert [s["row_start"] for s in m["shards"]] == [0, 8_000, 16_000]
        assert [s["row_end"] for s in m["shards"]] == [8_000, 16_000, 20_000]
        assert sum(s["num_vectors"] for s in m["shards"]) == 20_000

    def test_interrupted_build_resumes_shard_exact(self, tmp_path):
        vecs, ids, _ = make_corpus(n=20_000)
        cfg = plane_config()
        root = str(tmp_path / "p")
        builder = ShardedAnnBuilder(root, cfg)

        class Boom(Exception):
            pass

        def broken():
            yield vecs[:8_000], ids[:8_000]
            yield vecs[8_000:12_000], ids[8_000:12_000]
            raise Boom()

        with pytest.raises(Boom):
            builder.build(broken())
        partial = PlaneManifestStore(root).read()
        # only COMPLETE shards are durable; the half-buffered second shard
        # never became visible
        assert not partial["complete"]
        assert len(partial["shards"]) == 1
        assert partial["shards"][0]["row_end"] == 8_000

        m = builder.build(stream(vecs, ids))
        assert m["complete"] and len(m["shards"]) == 3
        # shard 0 was NOT rebuilt: same per-shard manifest generation
        assert m["shards"][0]["generation"] == partial["shards"][0]["generation"]

        fresh_root = str(tmp_path / "fresh")
        fresh = ShardedAnnBuilder(fresh_root, cfg).build(stream(vecs, ids))
        assert [
            (s["row_start"], s["row_end"], s["num_vectors"]) for s in m["shards"]
        ] == [
            (s["row_start"], s["row_end"], s["num_vectors"])
            for s in fresh["shards"]
        ]
        # and the resumed plane answers exactly like the from-scratch one
        a = AnnPlane.open(root, use_pallas=False)
        b = AnnPlane.open(fresh_root, use_pallas=False)
        params = SearchParams(top_k=10, nprobe=8)
        ia, da = a.search(vecs[123], params)
        ib, db = b.search(vecs[123], params)
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_allclose(da, db, rtol=1e-5, atol=1e-5)

    def test_config_change_forces_fresh_generation(self, tmp_path):
        vecs, ids, _ = make_corpus(n=12_000)
        root = str(tmp_path / "p")
        m1 = ShardedAnnBuilder(root, plane_config()).build(stream(vecs, ids))
        cfg2 = plane_config(rows_per_shard=5_000)
        m2 = ShardedAnnBuilder(root, cfg2).build(stream(vecs, ids))
        assert m2["generation"] == m1["generation"] + 1
        assert len(m2["shards"]) == 3  # 5k + 5k + 2k under the new layout
        plane = AnnPlane.open(root, use_pallas=False)
        assert plane.num_vectors == 12_000

    def test_completed_build_is_idempotent(self, tmp_path):
        vecs, ids, _ = make_corpus(n=9_000)
        cfg = plane_config()
        builder = ShardedAnnBuilder(str(tmp_path / "p"), cfg)
        m1 = builder.build(stream(vecs, ids))
        m2 = builder.build(stream(vecs, ids))
        assert m2 == m1  # durable plane: second build is a no-op read

    def test_empty_stream_raises(self, tmp_path):
        with pytest.raises(VectorIndexError, match="no vectors"):
            ShardedAnnBuilder(str(tmp_path / "p"), plane_config()).build(iter(()))

    def test_dim_mismatch_raises(self, tmp_path):
        vecs = np.zeros((10, 8), np.float32)
        with pytest.raises(VectorIndexError, match="expected"):
            ShardedAnnBuilder(str(tmp_path / "p"), plane_config(d=16)).build(
                [(vecs, np.arange(10, dtype=np.uint64))]
            )

    def test_build_from_table_via_bounded_scan(self, tmp_warehouse):
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        d, n = 16, 6_000
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(n, d)).astype(np.float32)
        schema = pa.schema(
            [("id", pa.int64()), ("emb", pa.list_(pa.float32(), d))]
        )
        t = catalog.create_table(
            "corpus", schema, properties={"lakesoul.file_format": "lsf"}
        )
        arr = pa.FixedSizeListArray.from_arrays(pa.array(vals.reshape(-1)), d)
        t.write_arrow(pa.table({"id": np.arange(n), "emb": arr}, schema=schema))
        manifest = build_table_ann_plane(
            t, "emb", id_column="id", nlist=8, total_bits=4,
            shard_budget_bytes=plane_config(d=d, rows_per_shard=2_500)
            .budget_bytes,
        )
        assert manifest["complete"] and manifest["total_rows"] == n
        assert len(manifest["shards"]) >= 2
        plane = AnnPlane.open(
            f"{t.info.table_path}/_ann_plane/emb", use_pallas=False
        )
        ids, _ = plane.search(vals[42], SearchParams(top_k=1, nprobe=8))
        assert int(ids[0]) == 42


class TestManifestAtomicity:
    def test_missing_reads_none(self, tmp_path):
        assert PlaneManifestStore(str(tmp_path / "nope")).read() is None

    def test_corrupt_record_raises_not_restarts(self, tmp_path):
        vecs, ids, _ = make_corpus(n=9_000)
        root = str(tmp_path / "p")
        ShardedAnnBuilder(root, plane_config()).build(stream(vecs, ids))
        store = PlaneManifestStore(root)
        # flip one byte of the pointed record
        from lakesoul_tpu.vector.manifest import _crc_unwrap

        with store.fs.open(f"{store.root_path}/PLANE", "rb") as f:
            rel = _crc_unwrap(f.read(), "PLANE").decode()
        path = f"{store.root_path}/{rel}"
        with store.fs.open(path, "rb") as f:
            blob = bytearray(f.read())
        blob[10] ^= 0xFF
        with store.fs.open(path, "wb") as f:
            f.write(bytes(blob))
        with pytest.raises(VectorIndexError, match="CRC"):
            store.read()

    def test_open_pins_shard_generations(self, tmp_path):
        """A concurrent rebuild swaps per-shard LATEST pointers one by one;
        a reader must load the generations its plane record PINNED, never a
        mixed plane."""
        vecs, ids, _ = make_corpus(n=9_000)
        root = str(tmp_path / "p")
        cfg = plane_config()
        ShardedAnnBuilder(root, cfg).build(stream(vecs, ids))
        from lakesoul_tpu.annplane.build import shard_root
        from lakesoul_tpu.vector.manifest import ManifestStore

        # simulate the racing rebuild: shard 0's LATEST now names a tiny
        # replacement index (generation bumped), plane record unchanged
        other = IvfRabitqIndex.train(vecs[:100], ids[:100], cfg.index)
        ManifestStore(shard_root(root, 0)).write_index(other)
        plane = AnnPlane.open(root, use_pallas=False)
        assert plane.num_vectors == 9_000  # NOT 100 + shard-1 rows

    def test_open_refuses_mid_build_plane(self, tmp_path):
        vecs, ids, _ = make_corpus(n=20_000)
        root = str(tmp_path / "p")

        class Boom(Exception):
            pass

        def broken():
            yield vecs[:9_000], ids[:9_000]
            raise Boom()

        with pytest.raises(Boom):
            ShardedAnnBuilder(root, plane_config()).build(broken())
        with pytest.raises(VectorIndexError, match="mid-build"):
            AnnPlane.open(root)


class TestRaggedKernels:
    def test_ragged_arange(self):
        out = ragged.ragged_arange(np.array([5, 0, 9]), np.array([3, 0, 2]))
        np.testing.assert_array_equal(out, [5, 6, 7, 9, 10])

    def _plan(self, seed=0, n_rows=4_096, d=64, nlist=12, nq=6, tile=128):
        rng = np.random.default_rng(seed)
        counts = rng.multinomial(n_rows, np.ones(nlist) / nlist)
        padded = (counts + tile - 1) // tile * tile
        n_pad = int(padded.sum())
        tile_start = np.concatenate([[0], np.cumsum(padded[:-1] // tile)]).astype(
            np.int32
        )
        tile_count = (padded // tile).astype(np.int32)
        row_start = tile_start.astype(np.int64) * tile
        codes = np.zeros((n_pad, d), np.float32)
        a = np.zeros(n_pad, np.float32)
        b = np.full(n_pad, ragged.PAD_B, np.float32)
        h = np.zeros(n_pad, np.float32)
        for c in range(nlist):
            rs, n_c = int(row_start[c]), int(counts[c])
            codes[rs : rs + n_c] = rng.normal(size=(n_c, d)).astype(np.float32)
            a[rs : rs + n_c] = rng.random(n_c).astype(np.float32) + 0.5
            b[rs : rs + n_c] = rng.random(n_c).astype(np.float32) * 10
            h[rs : rs + n_c] = rng.random(n_c).astype(np.float32)
        # ragged probe sets: query q probes a random subset of clusters
        pairs_q, pairs_c = [], []
        for q in range(nq):
            probed = rng.choice(nlist, rng.integers(1, nlist), replace=False)
            pairs_q.extend([q] * len(probed))
            pairs_c.extend(sorted(probed))
        pairs_q = np.asarray(pairs_q, np.int64)
        pairs_c = np.asarray(pairs_c, np.int64)
        csq = rng.random(len(pairs_q)).astype(np.float32) * 5
        csum = rng.random(len(pairs_q)).astype(np.float32)
        q_glob = rng.normal(size=(nq, d)).astype(np.float32)
        return dict(
            codes=codes, a=a, b=b, h=h, row_start=row_start,
            row_count=counts.astype(np.int64), tile_start=tile_start,
            tile_count=tile_count, pairs_q=pairs_q, pairs_c=pairs_c,
            csq=csq, csum=csum, q_glob=q_glob, nq=nq, tile=tile,
        )

    def test_host_vs_jnp_item_kernel(self):
        p = self._plan()
        rows_h, est_h = ragged.ragged_topk_host(
            p["codes"], p["a"], p["b"], p["h"], p["row_start"], p["row_count"],
            p["pairs_q"], p["pairs_c"], p["csq"], p["csum"], p["q_glob"],
            p["nq"], 16,
        )
        item_q, item_tile, icsq, icsum = ragged.plan_items(
            p["pairs_q"], p["pairs_c"], p["csq"], p["csum"],
            p["tile_start"], p["tile_count"],
        )
        est = ragged.ragged_score_jnp(
            item_q, item_tile, icsq, icsum, p["q_glob"],
            p["codes"], p["a"], p["b"], p["h"], tile=p["tile"],
        )
        rows_j, est_j = ragged.items_topk(
            est, item_q, item_tile, p["nq"], 16, tile=p["tile"]
        )
        for q in range(p["nq"]):
            # same candidate SET and same distances (order can differ on ties)
            np.testing.assert_allclose(
                np.sort(est_h[q]), np.sort(est_j[q]), rtol=1e-5, atol=1e-4
            )
            assert set(rows_h[q][rows_h[q] >= 0]) == set(rows_j[q][rows_j[q] >= 0])

    def test_numpy_fallback_matches_native(self, monkeypatch):
        """ragged_topk_host has two executors — the C kernel and the numpy
        grouped-GEMM fallback (searchsorted row recovery); both must return
        the same candidate sets and distances."""
        from lakesoul_tpu import native

        if not native.available():
            pytest.skip("native library unavailable — nothing to compare")
        p = self._plan(seed=11)
        args = (
            p["codes"], p["a"], p["b"], p["h"], p["row_start"], p["row_count"],
            p["pairs_q"], p["pairs_c"], p["csq"], p["csum"], p["q_glob"],
            p["nq"], 16,
        )
        rows_n, est_n = ragged.ragged_topk_host(*args)
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        rows_f, est_f = ragged.ragged_topk_host(*args)
        for q in range(p["nq"]):
            np.testing.assert_allclose(
                np.sort(est_f[q]), np.sort(est_n[q]), rtol=1e-4, atol=1e-3
            )
            assert set(rows_f[q][rows_f[q] >= 0]) == set(rows_n[q][rows_n[q] >= 0])

    def test_pallas_interpret_vs_jnp(self):
        p = self._plan(seed=7, n_rows=1_024, nlist=6, nq=4)
        item_q, item_tile, icsq, icsum = ragged.plan_items(
            p["pairs_q"], p["pairs_c"], p["csq"], p["csum"],
            p["tile_start"], p["tile_count"],
        )
        ref = ragged.ragged_score_jnp(
            item_q, item_tile, icsq, icsum, p["q_glob"],
            p["codes"], p["a"], p["b"], p["h"], tile=p["tile"],
        )
        got = ragged.ragged_score_pallas(
            item_q, item_tile, icsq, icsum, p["q_glob"],
            p["codes"], p["a"], p["b"], p["h"], tile=p["tile"], interpret=True,
        )
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-3)

    def test_fold_cluster_matches_reference_estimator(self):
        """The folded (a, b, h) form reproduces the kernels' estimator: an
        est-only plane search equals IvfRabitqIndex.search(rerank=False)."""
        rng = np.random.default_rng(5)
        n, d = 4_000, 32
        vecs = rng.normal(size=(n, d)).astype(np.float32)
        ids = np.arange(n, dtype=np.uint64)
        for bits in (1, 4):
            cfg = plane_config(
                rows_per_shard=n + 1, nlist=8, total_bits=bits, keep_raw=False
            )
            index = IvfRabitqIndex.train(
                vecs, ids, cfg.index, keep_raw=False
            )
            from lakesoul_tpu.annplane.search import _ShardResident

            plane = AnnPlane(cfg, [_ShardResident(index)], use_pallas=False)
            params = SearchParams(top_k=10, nprobe=8, rerank_depth=10)
            q = vecs[17]
            p_ids, p_d = plane.search(q, params)
            r_ids, r_d = index.search(q, params, rerank=False)
            np.testing.assert_allclose(
                np.sort(p_d), np.sort(r_d), rtol=1e-3, atol=1e-2
            )


class TestMultiShardSearch:
    def test_recall_against_shared_oracle(self, built_plane):
        _, _, plane, _, vecs, ids, queries = built_plane
        params = SearchParams(top_k=10, nprobe=12, rerank_depth=80)
        got, _ = plane.batch_search(queries, params)
        truth = exact_topk(vecs, ids, queries, 10)
        assert recall_at_k(truth, got) >= 0.95

    def test_single_vs_multi_shard_parity(self, built_plane, tmp_path):
        """Same corpus, one shard vs three: full-probe searches return the
        same top-k distances (ids equal up to exact ties)."""
        _, cfg, plane, _, vecs, ids, queries = built_plane
        cfg1 = AnnPlaneConfig(
            index=cfg.index,
            shard_budget_bytes=cfg.bytes_per_vector() * (len(ids) + 1),
        )
        root1 = str(tmp_path / "one")
        ShardedAnnBuilder(root1, cfg1).build(stream(vecs, ids))
        single = AnnPlane.open(root1, use_pallas=False)
        assert len(single.shards) == 1 and len(plane.shards) == 3
        params = SearchParams(top_k=10, nprobe=10**6, rerank_depth=200)
        s_ids, s_d = single.batch_search(queries, params)
        m_ids, m_d = plane.batch_search(queries, params)
        for i in range(len(queries)):
            np.testing.assert_allclose(s_d[i], m_d[i], rtol=1e-4, atol=1e-4)
            tie_free = np.diff(s_d[i]) > 1e-5
            keep = np.concatenate([[True], tie_free]) & np.concatenate(
                [tie_free, [True]]
            )
            np.testing.assert_array_equal(s_ids[i][keep], m_ids[i][keep])

    def test_per_query_nprobe_fuses_exactly(self, built_plane):
        """A mixed-nprobe ragged batch returns exactly what per-query calls
        with the same nprobe return — raggedness changes cost, not answers."""
        _, _, plane, _, _, _, queries = built_plane
        params = SearchParams(top_k=5, nprobe=8)
        nprobes = np.array([1, 4, 16, 2, 8, 32, 3, 48], np.int64)
        sub = queries[: len(nprobes)]
        m_ids, m_d = plane.batch_search(sub, params, nprobes=nprobes)
        for i, npb in enumerate(nprobes):
            one_ids, one_d = plane.batch_search(
                sub[i : i + 1], SearchParams(top_k=5, nprobe=int(npb))
            )
            np.testing.assert_array_equal(m_ids[i], one_ids[0])
            np.testing.assert_allclose(m_d[i], one_d[0], rtol=1e-5, atol=1e-5)

    def test_one_bit_plane(self, tmp_path):
        vecs, ids, queries = make_corpus(n=10_000)
        cfg = plane_config(rows_per_shard=4_000, total_bits=1)
        root = str(tmp_path / "p1")
        ShardedAnnBuilder(root, cfg).build(stream(vecs, ids))
        plane = AnnPlane.open(root, use_pallas=False)
        got, _ = plane.batch_search(
            queries, SearchParams(top_k=10, nprobe=12, rerank_depth=80)
        )
        truth = exact_topk(vecs, ids, queries, 10)
        assert recall_at_k(truth, got) >= 0.9

    def test_keep_raw_false_serves_estimates(self, tmp_path):
        vecs, ids, queries = make_corpus(n=8_000)
        cfg = plane_config(rows_per_shard=3_000, keep_raw=False)
        root = str(tmp_path / "p")
        ShardedAnnBuilder(root, cfg).build(stream(vecs, ids))
        plane = AnnPlane.open(root, use_pallas=False)
        got, dists = plane.batch_search(queries, SearchParams(top_k=10, nprobe=16))
        assert all(len(g) == 10 for g in got)
        truth = exact_topk(vecs, ids, queries, 10)
        assert recall_at_k(truth, got) >= 0.6  # estimator-only floor

    def test_num_vectors_and_manifest(self, built_plane):
        _, _, plane, manifest, vecs, _, _ = built_plane
        assert plane.num_vectors == len(vecs)
        assert plane.manifest["complete"]


class TestServing:
    def test_endpoint_matches_direct(self, built_plane):
        _, _, plane, _, vecs, _, queries = built_plane
        params = SearchParams(top_k=5, nprobe=8)
        with ShardedAnnEndpoint(plane, params, max_wait_ms=1.0) as ep:
            futs = [ep.submit(q) for q in queries[:16]]
            direct_ids, direct_d = plane.batch_search(queries[:16], params)
            for i, f in enumerate(futs):
                ids, dists = f.result(timeout=30)
                np.testing.assert_array_equal(ids, direct_ids[i])
                np.testing.assert_allclose(dists, direct_d[i], rtol=1e-4, atol=1e-4)
            st = ep.stats()
        assert st["requests"] == 16
        assert "latency_p50" in st and "latency_p99" in st
        assert st["latency_p99"] >= st["latency_p50"] >= 0.0

    def test_mixed_nprobe_requests_share_one_batch(self, built_plane):
        _, _, plane, _, _, _, queries = built_plane
        params = SearchParams(top_k=5, nprobe=8)
        with ShardedAnnEndpoint(plane, params, max_wait_ms=20.0) as ep:
            futs = [
                ep.submit(queries[i], nprobe=[1, 8, 32, None][i % 4])
                for i in range(16)
            ]
            outs = [f.result(timeout=30) for f in futs]
            st = ep.stats()
        assert st["mean_batch"] > 1.0  # the window actually fused them
        for i, (ids, _) in enumerate(outs):
            want, _ = plane.batch_search(
                queries[i : i + 1],
                SearchParams(top_k=5, nprobe=[1, 8, 32, 8][i % 4]),
            )
            np.testing.assert_array_equal(ids, want[0])

    def test_overload_64_clients_typed_sheds(self, built_plane):
        """The PR-6 overload contract re-proven at the plane scale: 64
        concurrent clients against a tiny pending bound — every request
        either completes correctly or sheds TYPED; the endpoint survives."""
        _, _, plane, _, _, _, queries = built_plane
        params = SearchParams(top_k=1, nprobe=4)
        ep = ShardedAnnEndpoint(
            plane, params, max_batch=8, max_wait_ms=5.0, max_pending=16
        )
        sheds = [0] * 64
        errors = []

        def client(ci):
            for j in range(8):
                try:
                    ep.search(queries[(ci + j) % len(queries)], timeout=60)
                except OverloadedError:
                    sheds[ci] += 1
                except Exception as e:  # pragma: no cover — surfaced below
                    errors.append(e)

        threads = [
            threading.Thread(target=client, args=(ci,)) for ci in range(64)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st = ep.stats()
        ep.close()
        assert not errors
        assert sum(sheds) > 0  # the bound actually bit
        assert st["rejected"] == sum(sheds)  # every shed was the typed kind
        assert st["requests"] == 64 * 8 - sum(sheds)

    def test_env_max_pending(self, built_plane, monkeypatch):
        _, _, plane, _, _, _, _ = built_plane
        monkeypatch.setenv("LAKESOUL_ANN_MAX_PENDING", "7")
        ep = ShardedAnnEndpoint(plane, SearchParams(top_k=1))
        try:
            assert ep.max_pending == 7
        finally:
            ep.close()


class TestFlightAnnSearch:
    @pytest.fixture()
    def gateway(self, tmp_warehouse, built_plane):
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.flight import (
            LakeSoulFlightClient,
            LakeSoulFlightServer,
        )
        from lakesoul_tpu.service.jwt import Claims

        _, _, plane, _, _, _, _ = built_plane
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        catalog.create_table("corpus", schema)
        catalog.client.create_table(
            "secret", f"{tmp_warehouse}/secret", schema, domain="team1"
        )
        ep = ShardedAnnEndpoint(
            plane, SearchParams(top_k=5, nprobe=8), max_wait_ms=1.0
        )
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", jwt_secret="s3cr3t",
            ann_planes={
                "emb": AnnPlaneBinding(ep, "default", "corpus"),
                "locked": AnnPlaneBinding(ep, "default", "secret"),
            },
        )
        token = server.jwt_server.create_token(Claims(sub="alice", group="public"))
        yield server, f"grpc://127.0.0.1:{server.port}", token
        ep.close()
        server.shutdown()

    def test_search_and_rbac(self, gateway, built_plane):
        import json

        import pyarrow.flight as flight

        from lakesoul_tpu.service.flight import LakeSoulFlightClient

        _, _, plane, _, _, _, queries = built_plane
        server, location, token = gateway
        client = LakeSoulFlightClient(location, token=token)
        out = json.loads(
            client.action(
                "ann_search", {"plane": "emb", "query": queries[0].tolist()}
            )[0]
        )
        want, _ = plane.batch_search(
            queries[:1], SearchParams(top_k=5, nprobe=8)
        )
        assert out["ids"] == [int(i) for i in want[0]]
        # batch form + per-request nprobe + top_k trim
        outs = json.loads(
            client.action(
                "ann_search",
                {
                    "plane": "emb",
                    "queries": [q.tolist() for q in queries[:3]],
                    "nprobe": 16,
                    "top_k": 2,
                },
            )[0]
        )
        assert len(outs) == 3 and all(len(o["ids"]) == 2 for o in outs)
        # unknown plane is a server error, not a crash
        with pytest.raises(flight.FlightServerError, match="unknown ann plane"):
            client.action("ann_search", {"plane": "nope", "query": [0.0]})
        # RBAC: the plane inherits its table's domain
        with pytest.raises(flight.FlightError):
            client.action(
                "ann_search", {"plane": "locked", "query": queries[0].tolist()}
            )

    def test_unauthenticated_rejected(self, gateway):
        import pyarrow.flight as flight

        _server, location, _token = gateway
        raw = flight.FlightClient(location)
        with pytest.raises(flight.FlightError):
            list(raw.do_action(flight.Action("ann_search", b"{}")))

    def test_overload_maps_to_unavailable(self, tmp_warehouse, built_plane):
        import pyarrow as pa
        import pyarrow.flight as flight

        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.flight import LakeSoulFlightServer

        _, _, plane, _, _, _, queries = built_plane
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        catalog.create_table(
            "corpus", pa.schema([("id", pa.int64())])
        )
        # a pending bound of 1 with a slow window: the second concurrent
        # submit sheds, and the gateway maps it to UNAVAILABLE
        ep = ShardedAnnEndpoint(
            plane, SearchParams(top_k=1, nprobe=4),
            max_batch=1, max_wait_ms=200.0, max_pending=1,
        )
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0",
            ann_planes={"emb": AnnPlaneBinding(ep, "default", "corpus")},
        )
        try:
            client = flight.FlightClient(f"grpc://127.0.0.1:{server.port}")
            body = {"plane": "emb", "query": queries[0].tolist()}
            import json

            sheds = [0]

            def call():
                try:
                    list(
                        client.do_action(
                            flight.Action("ann_search", json.dumps(body).encode())
                        )
                    )
                except flight.FlightUnavailableError:
                    sheds[0] += 1

            threads = [threading.Thread(target=call) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sheds[0] > 0
        finally:
            ep.close()
            server.shutdown()


class TestCrossChipMerge:
    def test_dryrun_multichip_8(self):
        out = dryrun_multichip(8)
        assert out["devices"] == 8 and len(out["dists"]) == 10

    def test_merge_matches_host(self):
        rng = np.random.default_rng(3)
        dists = rng.random((4, 6)).astype(np.float32)
        rows = rng.integers(0, 1000, (4, 6)).astype(np.int32)
        d, r, src = cross_chip_topk(dists, rows, k=8)
        order = np.argsort(dists.reshape(-1), kind="stable")[:8]
        np.testing.assert_allclose(d, dists.reshape(-1)[order], rtol=1e-6)
        np.testing.assert_array_equal(r, rows.reshape(-1)[order])
        np.testing.assert_array_equal(src, order // 6)

    def test_shape_mismatch_raises(self):
        with pytest.raises(VectorIndexError, match="mismatch"):
            cross_chip_topk(np.zeros((2, 3)), np.zeros((2, 4), np.int32))
