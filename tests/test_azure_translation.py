"""S3-dialect → Azure translation (the azure.rs role, tentpole PR 7).

The acceptance contract: the Azure upstream serves ListObjectsV2-dialect
listing and a ≥3-part multipart upload through the UNCHANGED S3-dialect
client contract — the same ``ProxyStorageClient`` calls that work against
the S3 upstream and the direct proxy work against Azure, replacing the
old ``query:`` 501 path.

The fake Blob endpoint verifies every request's Shared-Key signature
(including canonicalized query parameters, which the old fake never saw)
and implements the Blob-service subset the translation targets: List
Blobs with prefix/marker/maxresults paging, Put Block, Put Block List.
Its ``maxresults`` default is capped low so the continuation-marker ↔
continuation-token mapping is exercised by every listing, not just
1000+-key ones."""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape as xml_escape

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service.azure import (
    API_VERSION,
    AzureUpstream,
    AzureUpstreamConfig,
    string_to_sign,
)

ACCOUNT = "transacct"
KEY = base64.b64encode(b"translation-test-key-32-bytes!!!").decode()
CONTAINER = "lake"
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


class FakeAzureBlob:
    """Blob-service fake: signature-verified (path AND query canonicalized),
    whole blobs + Put Block/Put Block List + List Blobs with paging."""

    def __init__(self, *, max_results_cap: int = 2):
        store: dict[str, bytes] = {}           # blob path → bytes
        uncommitted: dict[tuple[str, str], bytes] = {}  # (path, blockid) → bytes
        block_puts: list[tuple[str, str]] = []
        fake = self
        fake.max_results_cap = max_results_cap

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _split(self):
                url = urllib.parse.urlsplit(self.path)
                q = {
                    k: (v[0] if v else "")
                    for k, v in urllib.parse.parse_qs(
                        url.query, keep_blank_values=True
                    ).items()
                }
                return urllib.parse.unquote(url.path), q

            def _check(self, path: str, q: dict) -> bool:
                if self.headers.get("x-ms-version") != API_VERSION:
                    self.send_error(400, "missing x-ms-version")
                    return False
                auth = self.headers.get("Authorization", "")
                if not auth.startswith(f"SharedKey {ACCOUNT}:"):
                    self.send_error(403, "no shared key")
                    return False
                headers = {k: v for k, v in self.headers.items()}
                # independent re-derivation, query included — a client that
                # signed the query wrong (or not at all) dies here
                sts = string_to_sign(self.command, ACCOUNT, path, q, headers)
                want = base64.b64encode(
                    hmac.new(
                        base64.b64decode(KEY), sts.encode(), hashlib.sha256
                    ).digest()
                ).decode()
                if not hmac.compare_digest(auth.split(":", 1)[1], want):
                    self.send_error(403, "signature mismatch")
                    return False
                return True

            def _xml(self, body: str, status: int = 200):
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_PUT(self):
                path, q = self._split()
                if not self._check(path, q):
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if q.get("comp") == "block":
                    uncommitted[(path, q.get("blockid", ""))] = body
                    block_puts.append((path, q.get("blockid", "")))
                elif q.get("comp") == "blocklist":
                    manifest = ET.fromstring(body)
                    pieces = []
                    for el in manifest.iter():
                        if el.tag == "Latest":
                            blk = uncommitted.get((path, el.text or ""))
                            if blk is None:
                                self.send_error(400, "unknown block id")
                                return
                            pieces.append(blk)
                    store[path] = b"".join(pieces)
                else:
                    if self.headers.get("x-ms-blob-type") != "BlockBlob":
                        self.send_error(400, "missing x-ms-blob-type")
                        return
                    store[path] = body
                self.send_response(201)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _do_list(self, q: dict):
                prefix = q.get("prefix", "")
                marker = q.get("marker", "")
                cap = min(
                    int(q.get("maxresults", fake.max_results_cap)),
                    fake.max_results_cap,
                )
                root = f"/{CONTAINER}/"
                names = sorted(
                    p[len(root):] for p in store if p.startswith(root)
                )
                names = [n for n in names if n.startswith(prefix)]
                if marker:
                    names = [n for n in names if n >= marker]
                delim = q.get("delimiter", "")
                # (sort key, xml entry) — with a delimiter, names sharing the
                # segment up to+including it collapse into one BlobPrefix,
                # exactly the Blob-service grouping the translation parses
                entries: list[tuple[str, str]] = []
                seen_groups: set[str] = set()
                for n in names:
                    cut = n[len(prefix):].find(delim) if delim else -1
                    if delim and cut >= 0:
                        group = n[: len(prefix) + cut + len(delim)]
                        if group in seen_groups:
                            continue
                        seen_groups.add(group)
                        entries.append((group,
                            f"<BlobPrefix><Name>{xml_escape(group)}</Name>"
                            "</BlobPrefix>"))
                    else:
                        entries.append((n,
                            f"<Blob><Name>{xml_escape(n)}</Name><Properties>"
                            f"<Content-Length>{len(store[root + n])}"
                            "</Content-Length></Properties></Blob>"))
                page, rest = entries[:cap], entries[cap:]
                blobs = "".join(x for _, x in page)
                nxt = (
                    f"<NextMarker>{xml_escape(rest[0][0])}</NextMarker>"
                    if rest else "<NextMarker/>"
                )
                self._xml(
                    '<?xml version="1.0" encoding="utf-8"?>'
                    f'<EnumerationResults ContainerName="{CONTAINER}">'
                    f"<Prefix>{xml_escape(prefix)}</Prefix>"
                    f"<Blobs>{blobs}</Blobs>{nxt}</EnumerationResults>"
                )

            def do_GET(self):
                path, q = self._split()
                if not self._check(path, q):
                    return
                if q.get("comp") == "list":
                    self._do_list(q)
                    return
                blob = store.get(path)
                if blob is None:
                    self.send_error(404)
                    return
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    a, _, b = rng[6:].partition("-")
                    start = int(a)
                    end = int(b) + 1 if b else len(blob)
                    piece = blob[start:end]
                    self.send_response(206)
                    self.send_header(
                        "Content-Range", f"bytes {start}-{end - 1}/{len(blob)}"
                    )
                else:
                    piece = blob
                    self.send_response(200)
                self.send_header("Content-Length", str(len(piece)))
                self.end_headers()
                self.wfile.write(piece)

            def do_HEAD(self):
                path, q = self._split()
                if not self._check(path, q):
                    return
                blob = store.get(path)
                if blob is None:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()

            def do_DELETE(self):
                path, q = self._split()
                if not self._check(path, q):
                    return
                if store.pop(path, None) is None:
                    # Azure Delete Blob: absent blob is 404 BlobNotFound
                    self.send_error(404)
                    return
                self.send_response(202)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.store = store
        self.uncommitted = uncommitted
        self.block_puts = block_puts
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def port(self):
        return self.server.server_address[1]

    def stop(self):
        self.server.shutdown()


@pytest.fixture()
def blob():
    s = FakeAzureBlob()
    yield s
    s.stop()


def _upstream(port) -> AzureUpstream:
    cfg = AzureUpstreamConfig(
        account=ACCOUNT, key_b64=KEY, container=CONTAINER,
        endpoint=f"http://127.0.0.1:{port}",
    )
    return AzureUpstream(
        cfg,
        resolver=lambda host, p: ["127.0.0.1"],
        health_check=lambda ip, p: True,
    )


def _read(resp) -> bytes:
    try:
        return resp.read()
    finally:
        resp.close()


class TestPlainVerbDialect:
    def test_delete_is_idempotent_like_s3(self, blob):
        # S3 DeleteObject answers 204 whether or not the key exists; the
        # direct proxy maps FileNotFoundError the same way, so a retried
        # cleanup sweep must not fail only on the Azure backend
        up = _upstream(blob.port)
        _, _, resp = up.request("PUT", "wh/t/gone.bin", body=b"x")
        _read(resp)
        status, _, resp = up.request("DELETE", "wh/t/gone.bin")
        _read(resp)
        assert status == 204
        status, headers, resp = up.request("DELETE", "wh/t/gone.bin")
        data = _read(resp)
        assert status == 204
        assert data == b"" and headers.get("Content-Length") == "0"


class TestListTranslation:
    def test_list_pages_through_continuation_markers(self, blob):
        up = _upstream(blob.port)
        for name, size in (("wh/t/a.parquet", 3), ("wh/t/b.parquet", 5),
                           ("wh/t/sub/c.parquet", 7), ("other/x", 1)):
            status, _, resp = up.request("PUT", name, body=b"z" * size)
            _read(resp)
            assert status == 201
        keys, token, pages = [], None, 0
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote("wh/t/", safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token, safe="")
            status, headers, resp = up.request("GET", "", query=q)
            data = _read(resp)
            assert status == 200
            pages += 1
            root = ET.fromstring(data)
            ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
            for c in root.findall("s3:Contents", ns):
                keys.append((c.findtext("s3:Key", "", ns),
                             int(c.findtext("s3:Size", "0", ns))))
            truncated = root.findtext("s3:IsTruncated", "false", ns)
            token = root.findtext("s3:NextContinuationToken", None, ns)
            if truncated != "true":
                break
        # fake caps pages at 2 keys → the 3-key listing NEEDS the marker hop
        assert pages >= 2
        assert keys == [("wh/t/a.parquet", 3), ("wh/t/b.parquet", 5),
                        ("wh/t/sub/c.parquet", 7)]

    def test_keycount_includes_common_prefixes(self, blob):
        # S3's KeyCount spans Contents AND CommonPrefixes — a delimiter
        # listing over directory-only prefixes must not read as empty
        up = _upstream(blob.port)
        for name in ("wh/t/sub/c.parquet", "wh/t/sub2/d.parquet"):
            _, _, resp = up.request("PUT", name, body=b"z")
            _read(resp)
        q = ("list-type=2&prefix=" + urllib.parse.quote("wh/t/", safe="")
             + "&delimiter=" + urllib.parse.quote("/", safe=""))
        status, _, resp = up.request("GET", "", query=q)
        data = _read(resp)
        assert status == 200
        root = ET.fromstring(data)
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        prefixes = [p.findtext("s3:Prefix", "", ns)
                    for p in root.findall("s3:CommonPrefixes", ns)]
        contents = root.findall("s3:Contents", ns)
        assert prefixes == ["wh/t/sub/", "wh/t/sub2/"]
        assert int(root.findtext("s3:KeyCount", "-1", ns)) == (
            len(contents) + len(prefixes)
        )

    def test_unsupported_query_still_explicit_501_shape(self, blob):
        up = _upstream(blob.port)
        with pytest.raises(NotImplementedError):
            up.request("POST", "", query="delete")
        with pytest.raises(NotImplementedError):
            up.request("GET", "", query="list-type=2&start-after=x")


class TestMultipartTranslation:
    def _initiate(self, up, key) -> str:
        status, _, resp = up.request("POST", key, query="uploads", body=b"")
        data = _read(resp)
        assert status == 200
        upload_id = ET.fromstring(data).findtext("UploadId")
        assert upload_id
        return upload_id

    def test_three_part_upload_assembles_via_block_list(self, blob):
        up = _upstream(blob.port)
        key = "wh/t/big.parquet"
        upload_id = self._initiate(up, key)
        parts = [b"a" * 100, b"b" * 50, b"c" * 7]
        for i, p in enumerate(parts, start=1):
            status, headers, resp = up.request(
                "PUT", key, body=p,
                query=f"partNumber={i}&uploadId={upload_id}",
            )
            _read(resp)
            assert status == 200 and "ETag" in headers
        status, _, resp = up.request("POST", key, query=f"uploadId={upload_id}")
        data = _read(resp)
        assert status == 200 and b"CompleteMultipartUploadResult" in data
        # the object went down as ≥3 Put Blocks + one Put Block List
        assert len(blob.block_puts) == 3
        assert blob.store[f"/{CONTAINER}/{key}"] == b"".join(parts)
        status, _, resp = up.request("GET", key)
        assert status == 200 and _read(resp) == b"".join(parts)

    def test_manifest_selects_parts(self, blob):
        up = _upstream(blob.port)
        key = "wh/t/sel.bin"
        upload_id = self._initiate(up, key)
        for i in range(1, 5):
            _, _, resp = up.request(
                "PUT", key, body=bytes([i]) * 4,
                query=f"partNumber={i}&uploadId={upload_id}",
            )
            _read(resp)
        manifest = (
            "<CompleteMultipartUpload>"
            "<Part><PartNumber>2</PartNumber></Part>"
            "<Part><PartNumber>4</PartNumber></Part>"
            "</CompleteMultipartUpload>"
        ).encode()
        status, _, resp = up.request(
            "POST", key, query=f"uploadId={upload_id}", body=manifest
        )
        _read(resp)
        assert status == 200
        assert blob.store[f"/{CONTAINER}/{key}"] == bytes([2]) * 4 + bytes([4]) * 4

    def test_out_of_order_or_duplicate_manifest_rejected(self, blob):
        # S3 answers InvalidPartOrder; assembling in manifest order would
        # commit scrambled / duplicated bytes instead
        up = _upstream(blob.port)
        key = "wh/t/ord.bin"
        upload_id = self._initiate(up, key)
        for i in (1, 2):
            _, _, resp = up.request(
                "PUT", key, body=bytes([i]) * 4,
                query=f"partNumber={i}&uploadId={upload_id}",
            )
            _read(resp)
        for bad in ("<Part><PartNumber>2</PartNumber></Part>"
                    "<Part><PartNumber>1</PartNumber></Part>",
                    "<Part><PartNumber>1</PartNumber></Part>"
                    "<Part><PartNumber>1</PartNumber></Part>"):
            manifest = (
                f"<CompleteMultipartUpload>{bad}</CompleteMultipartUpload>"
            ).encode()
            status, _, resp = up.request(
                "POST", key, query=f"uploadId={upload_id}", body=manifest
            )
            data = _read(resp)
            assert status == 400 and b"InvalidPartOrder" in data
        assert f"/{CONTAINER}/{key}" not in blob.store

    def test_get_uploads_does_not_mint_an_upload(self, blob):
        # GET ?uploads is ListMultipartUploads — a read must not initiate
        up = _upstream(blob.port)
        with pytest.raises(NotImplementedError):
            up.request("GET", "", query="uploads")

    def test_part_read_does_not_clobber_upload_state(self, blob):
        # GET/HEAD ?partNumber&uploadId is S3's part READ — translating it
        # to Put Block would overwrite the in-flight part with zero bytes
        up = _upstream(blob.port)
        key = "wh/t/pr.bin"
        upload_id = self._initiate(up, key)
        _, _, resp = up.request(
            "PUT", key, body=b"p" * 8,
            query=f"partNumber=2&uploadId={upload_id}",
        )
        _read(resp)
        with pytest.raises(NotImplementedError):
            up.request("GET", key, query=f"partNumber=2&uploadId={upload_id}")
        manifest = (
            "<CompleteMultipartUpload><Part><PartNumber>2</PartNumber></Part>"
            "</CompleteMultipartUpload>"
        ).encode()
        status, _, resp = up.request(
            "POST", key, query=f"uploadId={upload_id}", body=manifest
        )
        _read(resp)
        assert status == 200
        assert blob.store[f"/{CONTAINER}/{key}"] == b"p" * 8

    def test_unknown_upload_and_missing_part_rejected(self, blob):
        up = _upstream(blob.port)
        status, _, resp = up.request(
            "PUT", "wh/t/x", body=b"z",
            query="partNumber=1&uploadId=" + "f" * 32,
        )
        _read(resp)
        assert status == 404
        key = "wh/t/y"
        upload_id = self._initiate(up, key)
        manifest = (
            "<CompleteMultipartUpload><Part><PartNumber>9</PartNumber></Part>"
            "</CompleteMultipartUpload>"
        ).encode()
        status, _, resp = up.request(
            "POST", key, query=f"uploadId={upload_id}", body=manifest
        )
        _read(resp)
        assert status == 400

    def test_abort_tombstones_the_upload(self, blob):
        up = _upstream(blob.port)
        key = "wh/t/ab.bin"
        upload_id = self._initiate(up, key)
        _, _, resp = up.request(
            "PUT", key, body=b"q" * 8,
            query=f"partNumber=1&uploadId={upload_id}",
        )
        _read(resp)
        status, _, resp = up.request(
            "DELETE", key, query=f"uploadId={upload_id}"
        )
        _read(resp)
        assert status == 204
        status, _, resp = up.request("POST", key, query=f"uploadId={upload_id}")
        _read(resp)
        assert status == 404
        assert f"/{CONTAINER}/{key}" not in blob.store
        # re-abort of the tombstoned id is NoSuchUpload, like S3
        status, _, resp = up.request("DELETE", key, query=f"uploadId={upload_id}")
        _read(resp)
        assert status == 404

    def test_abort_unknown_upload_rejected(self, blob):
        up = _upstream(blob.port)
        status, _, resp = up.request(
            "DELETE", "wh/t/none.bin", query="uploadId=deadbeef"
        )
        data = _read(resp)
        assert status == 404
        assert b"NoSuchUpload" in data


class TestUnchangedClientContractRoundTrip:
    """THE acceptance check: ProxyStorageClient — the S3-dialect client used
    against the direct proxy and the S3 upstream, byte-for-byte unchanged —
    drives listing and a 3-part multipart upload against the Azure cloud."""

    @pytest.fixture()
    def env(self, tmp_path, blob):
        from lakesoul_tpu.service.storage_proxy import (
            ProxyStorageClient,
            StorageProxy,
        )

        cat = LakeSoulCatalog(str(tmp_path / "wh"), db_path=str(tmp_path / "m.db"))
        cat.create_table("az", SCHEMA)
        proxy = StorageProxy(cat, upstream=_upstream(blob.port))
        proxy.start()
        client = ProxyStorageClient(f"http://127.0.0.1:{proxy.port}")
        yield client
        proxy.stop()

    def test_multipart_and_list_through_proxy(self, env, blob):
        parts = [b"p1" * 64, b"p2" * 32, b"p3" * 16]
        upload_id = env.initiate_multipart("default/az/data.bin")
        for i, p in enumerate(parts, start=1):
            env.upload_part("default/az/data.bin", upload_id, i, p)
        env.complete_multipart("default/az/data.bin", upload_id)
        assert env.get("default/az/data.bin") == b"".join(parts)
        # plain puts beside it, then a paged ListObjectsV2 sees everything
        env.put("default/az/extra1.bin", b"x" * 9)
        env.put("default/az/extra2.bin", b"y" * 11)
        listing = env.list_objects("default/az")
        assert listing == [
            ("default/az/data.bin", len(b"".join(parts))),
            ("default/az/extra1.bin", 9),
            ("default/az/extra2.bin", 11),
        ]
        # the 3-key listing crossed the fake's 2-key page cap, so the
        # continuation-token → marker mapping really ran
        env.delete("default/az/extra2.bin")
        assert [k for k, _ in env.list_objects("default/az")] == [
            "default/az/data.bin", "default/az/extra1.bin",
        ]

    def test_abort_via_client(self, env, blob):
        upload_id = env.initiate_multipart("default/az/gone.bin")
        env.upload_part("default/az/gone.bin", upload_id, 1, b"zz")
        env.abort_multipart("default/az/gone.bin", upload_id)
        with pytest.raises(OSError):
            env.complete_multipart("default/az/gone.bin", upload_id)
