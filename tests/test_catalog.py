"""End-to-end catalog tests: table lifecycle, upsert + merge-on-read,
compaction, CDC, sharding, time travel, JAX delivery."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.io.filters import col
from lakesoul_tpu.meta.entity import CommitOp


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("name", pa.string())])


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


def seed_pk_table(catalog, name="t", buckets=2):
    t = catalog.create_table(name, SCHEMA, primary_keys=["id"], hash_bucket_num=buckets)
    t.write_arrow(
        pa.table({"id": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0], "name": ["a", "b", "c", "d"]})
    )
    return t


class TestEndToEnd:
    def test_write_read_round_trip(self, catalog):
        t = catalog.create_table("plain", SCHEMA)
        t.write_arrow(pa.table({"id": [1, 2], "v": [0.5, 1.5], "name": ["x", "y"]}))
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2]
        assert got.column("v").to_pylist() == [0.5, 1.5]

    def test_upsert_merge_on_read(self, catalog):
        t = seed_pk_table(catalog)
        t.upsert(pa.table({"id": [2, 5], "v": [20.0, 5.0], "name": ["B", "e"]}))
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3, 4, 5]
        assert got.column("v").to_pylist() == [1.0, 20.0, 3.0, 4.0, 5.0]
        assert got.column("name").to_pylist()[1] == "B"

    def test_filter_and_projection(self, catalog):
        t = seed_pk_table(catalog)
        got = t.scan().filter(col("v") >= 3.0).select(["id", "v"]).to_arrow().sort_by("id")
        assert got.column_names == ["id", "v"]
        assert got.column("id").to_pylist() == [3, 4]

    def test_bucket_pruning_reads_fewer_units(self, catalog):
        t = seed_pk_table(catalog, buckets=4)
        scan_all = t.scan()
        scan_pruned = t.scan().filter(col("id") == 2)
        assert len(scan_pruned.scan_plan()) < len(scan_all.scan_plan())
        got = scan_pruned.to_arrow()
        assert got.column("id").to_pylist() == [2]

    def test_range_partitions_and_partition_filter(self, catalog):
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())])
        t = catalog.create_table(
            "events", schema, primary_keys=["id"], range_partitions=["date"], hash_bucket_num=2
        )
        t.write_arrow(
            pa.table(
                {"id": [1, 2, 3], "v": [1.0, 2.0, 3.0], "date": ["d1", "d1", "d2"]}
            )
        )
        got = t.scan().partitions({"date": "d1"}).to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2]
        assert got.column("date").to_pylist() == ["d1", "d1"]  # filled back in
        # filter on partition column works too
        got2 = t.scan().filter(col("date") == "d2").to_arrow()
        assert got2.column("id").to_pylist() == [3]

    def test_compaction_preserves_data_and_drops_merge(self, catalog):
        t = seed_pk_table(catalog)
        t.upsert(pa.table({"id": [1], "v": [100.0], "name": ["A"]}))
        before = t.to_arrow().sort_by("id")
        n_compacted = t.compact()
        assert n_compacted == 1
        plan = t.scan().scan_plan()
        assert all(u.primary_keys == [] for u in plan)  # merge skipped now
        assert all(len(u.data_files) == 1 for u in plan)
        after = t.to_arrow().sort_by("id")
        assert after.equals(before)
        # discard list captured replaced files for the cleaner
        assert len(catalog.client.store.list_discard_files()) > 0

    def test_cdc_table_delete_row(self, catalog):
        t = catalog.create_table("cdc_t", SCHEMA, primary_keys=["id"], cdc=True)
        rk = t.info.cdc_column
        t.write_arrow(
            pa.table({"id": [1, 2], "v": [1.0, 2.0], "name": ["a", "b"], rk: ["insert", "insert"]})
        )
        t.write_arrow(pa.table({"id": [1], "v": [0.0], "name": ["a"], rk: ["delete"]}))
        got = t.to_arrow()
        assert got.column("id").to_pylist() == [2]
        # CDC consumers can keep the delete rows
        raw = t.scan().with_cdc_deletes().to_arrow().sort_by("id")
        assert raw.column("id").to_pylist() == [1, 2]

    def test_delete_partitions(self, catalog):
        t = seed_pk_table(catalog)
        t.delete_partitions()
        assert t.to_arrow().num_rows == 0


class TestSharding:
    def test_shard_partitions_scan_units(self, catalog):
        t = seed_pk_table(catalog, buckets=4)
        all_units = t.scan().scan_plan()
        u0 = t.scan().shard(0, 2).scan_plan()
        u1 = t.scan().shard(1, 2).scan_plan()
        assert len(u0) + len(u1) == len(all_units)
        rows0 = t.scan().shard(0, 2).to_arrow().num_rows
        rows1 = t.scan().shard(1, 2).to_arrow().num_rows
        assert rows0 + rows1 == 4

    def test_auto_shard_single_process_noop(self, catalog):
        t = seed_pk_table(catalog)
        assert len(t.scan().auto_shard().scan_plan()) == len(t.scan().scan_plan())


class TestTimeTravelScan:
    def test_snapshot_and_incremental_scan(self, catalog):
        import time

        t = seed_pk_table(catalog)
        ts0 = catalog.client.store.get_latest_partition_info(t.info.table_id, "-5").timestamp
        time.sleep(0.002)
        t.upsert(pa.table({"id": [9], "v": [9.0], "name": ["z"]}))
        snap = t.scan().snapshot_at(ts0).to_arrow()
        assert snap.num_rows == 4
        inc = t.scan().incremental(ts0).to_arrow()
        assert inc.column("id").to_pylist() == [9]


class TestJaxDelivery:
    def test_host_iter_fixed_batches(self, catalog):
        t = catalog.create_table("big", SCHEMA)
        n = 1000
        t.write_arrow(
            pa.table(
                {"id": np.arange(n), "v": np.arange(n, dtype=np.float64), "name": ["x"] * n}
            )
        )
        it = t.scan().batch_size(128).to_jax_iter(device_put=False)
        batches = list(it)
        assert all(len(b["id"]) == 128 for b in batches)
        assert len(batches) == n // 128  # drop_remainder default
        total = np.concatenate([b["id"] for b in batches])
        assert len(np.unique(total)) == len(total)

    def test_device_put_and_transform(self, catalog):
        import jax

        t = catalog.create_table("feat", SCHEMA)
        t.write_arrow(
            pa.table({"id": np.arange(64), "v": np.ones(64), "name": ["x"] * 64})
        )

        def transform(b):
            return {"x": np.stack([b["id"].astype(np.float32), b["v"].astype(np.float32)], 1)}

        it = t.scan().batch_size(32).to_jax_iter(transform=transform)
        batches = list(it)
        assert len(batches) == 2
        assert isinstance(batches[0]["x"], jax.Array)
        assert batches[0]["x"].shape == (32, 2)

    def test_sharded_device_put(self, catalog):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        assert len(jax.devices()) == 8  # conftest forces 8 CPU devices
        t = catalog.create_table("shardme", SCHEMA)
        t.write_arrow(
            pa.table({"id": np.arange(128), "v": np.ones(128), "name": ["x"] * 128})
        )
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))

        def transform(b):
            return b["v"].astype(np.float32)

        it = t.scan().batch_size(64).to_jax_iter(transform=transform, sharding=sharding)
        batches = list(it)
        assert len(batches) == 2
        assert batches[0].sharding == sharding
        assert batches[0].shape == (64,)

    def test_filter_accepts_predicate_strings(self, catalog):
        from lakesoul_tpu.errors import ConfigError

        t = catalog.create_table("strf", SCHEMA)
        t.write_arrow(
            pa.table({"id": np.arange(100), "v": np.arange(100, dtype=np.float64), "name": ["x"] * 100})
        )
        assert len(t.scan().filter("v >= 90 AND id < 95").to_arrow()) == 5
        assert len(t.scan().filter("id IN (3, 7) OR v > 98.5").to_arrow()) == 3
        with pytest.raises(Exception):
            t.scan().filter("v LIKE 'a%'")  # non-pushable → clear parse error
        with pytest.raises(ConfigError):
            t.scan().filter(123)

    def test_device_cache_replays_epoch(self, catalog):
        import jax

        t = catalog.create_table("hbm", SCHEMA)
        n = 512
        t.write_arrow(
            pa.table({"id": np.arange(n), "v": np.arange(n, dtype=np.float64), "name": ["x"] * n})
        )

        def transform(b):
            return {"x": b["v"].astype(np.float32)}

        it = t.scan().batch_size(128).to_jax_iter(transform=transform, cache="device")
        first = list(it)
        assert len(first) == 4 and isinstance(first[0]["x"], jax.Array)
        # steady state: replay serves THE SAME device arrays — no new
        # transfers, byte-identical epochs
        second = list(it)
        assert [b["x"] is a["x"] for a, b in zip(first, second)] == [True] * 4
        # consumers mutating a yielded dict in place must not poison the
        # cache: every epoch hands out fresh containers over shared leaves
        for b in it:
            b["x"] = None
        assert all(b["x"] is not None for b in it)

    def test_device_cache_ignores_abandoned_epoch(self, catalog):
        t = catalog.create_table("hbm2", SCHEMA)
        t.write_arrow(
            pa.table({"id": np.arange(256), "v": np.ones(256), "name": ["x"] * 256})
        )
        it = t.scan().batch_size(64).to_jax_iter(
            cache="device", transform=lambda b: {"v": b["v"].astype(np.float32)}
        )
        for b in it:
            break  # abandon mid-epoch: the partial pass must NOT become the cache
        assert it._device_cached is None
        assert len(list(it)) == 4  # next pass streams (and completes) normally

    def test_device_cache_rejects_checkpoint(self, catalog):
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint
        from lakesoul_tpu.errors import ConfigError

        t = seed_pk_table(catalog)
        with pytest.raises(ConfigError):
            t.scan().to_jax_iter(cache="device", checkpoint=LoaderCheckpoint())

    def test_producer_error_propagates(self, catalog):
        t = seed_pk_table(catalog)

        def bad_transform(b):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(t.scan().batch_size(2).to_jax_iter(device_put=False, transform=bad_transform))

    def test_collate_keeps_stringlike_columns_including_dictionary(self):
        """Strings keep the documented stay-as-object contract — including
        dictionary-encoded ones (Parquet readers commonly produce them) —
        while fixed_size_list tensors collate to real 2-D arrays."""
        from lakesoul_tpu.data.jax_iter import _default_collate

        out = _default_collate(
            pa.table(
                {
                    "label": pa.array(["a", "b"]).dictionary_encode(),
                    "name": pa.array(["x", "y"]),
                    "tokens": pa.FixedSizeListArray.from_arrays(
                        np.arange(8, dtype=np.int32), 4
                    ),
                }
            )
        )
        assert out["label"].dtype == object
        assert out["name"].dtype == object
        assert out["tokens"].dtype == np.int32
        assert out["tokens"].shape == (2, 4)

    def test_collate_rejects_object_dtype_columns_by_name(self, catalog):
        """A column that only collates to dtype=object (nested list) must
        fail with a ConfigError naming the column and its Arrow type — not
        surface later as an opaque device_put failure."""
        from lakesoul_tpu.errors import ConfigError

        schema = pa.schema(
            [("id", pa.int64()), ("emb", pa.list_(pa.float32()))]
        )
        t = catalog.create_table("nested", schema)
        t.write_arrow(
            pa.table(
                {"id": [1, 2], "emb": [[1.0, 2.0], [3.0]]}, schema=schema
            )
        )
        with pytest.raises(ConfigError, match="'emb'.*list"):
            list(t.scan().batch_size(2).to_jax_iter(device_put=False))


class TestAdapters:
    def test_torch_adapter(self, catalog):
        t = seed_pk_table(catalog)
        ds = t.scan().to_torch()
        rows = sum(len(b) for b in ds)
        assert rows == 4

    def test_hf_adapter(self, catalog):
        pytest.importorskip("datasets")
        t = seed_pk_table(catalog)
        ds = t.scan().to_huggingface()
        assert len(list(ds)) == 4


class TestReviewRegressions:
    def test_filter_only_column_with_projection(self, catalog):
        # filter references a non-PK, non-selected column: must still work
        t = seed_pk_table(catalog, name="fp")
        got = t.scan().select(["id"]).filter(col("v") >= 3.0).to_arrow().sort_by("id")
        assert got.column_names == ["id"]
        assert got.column("id").to_pylist() == [3, 4]

    def test_partition_filter_with_projection_dropping_partition_col(self, catalog):
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())])
        t = catalog.create_table("pp", schema, primary_keys=["id"], range_partitions=["date"])
        t.write_arrow(pa.table({"id": [1, 2], "v": [1.0, 2.0], "date": ["d1", "d2"]}))
        got = t.scan().select(["id"]).filter(col("date") == "d2").to_arrow()
        assert got.column_names == ["id"]
        assert got.column("id").to_pylist() == [2]

    def test_hf_dataset_two_epochs(self, catalog):
        pytest.importorskip("datasets")
        t = seed_pk_table(catalog, name="hf2")
        ds = t.scan().to_huggingface()
        assert len(list(ds)) == 4
        assert len(list(ds)) == 4  # second epoch must not fail

    def test_incremental_respects_partition_filter(self, catalog):
        import time

        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())])
        t = catalog.create_table("incp", schema, primary_keys=["id"], range_partitions=["date"])
        t.write_arrow(pa.table({"id": [1], "v": [1.0], "date": ["d1"]}))
        ts0 = max(
            p.timestamp
            for p in catalog.client.store.get_all_latest_partition_info(t.info.table_id)
        )
        time.sleep(0.002)
        t.write_arrow(pa.table({"id": [2, 3], "v": [2.0, 3.0], "date": ["d1", "d2"]}))
        inc = t.scan().incremental(ts0).partitions({"date": "d2"}).to_arrow()
        assert inc.column("id").to_pylist() == [3]

    def test_abandoned_iterator_does_not_leak_producer(self, catalog):
        import threading
        import time

        t = catalog.create_table("leak", SCHEMA)
        n = 4096
        t.write_arrow(
            pa.table({"id": np.arange(n), "v": np.ones(n), "name": ["x"] * n})
        )
        before = threading.active_count()
        it = iter(t.scan().batch_size(64).to_jax_iter(device_put=False, prefetch=1))
        next(it)
        del it  # abandon mid-stream with a full queue
        deadline = time.time() + 5
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before

    def test_second_compact_is_noop(self, catalog):
        t = seed_pk_table(catalog, name="c2")
        t.upsert(pa.table({"id": [1], "v": [10.0], "name": ["A"]}))
        assert t.compact() == 1
        assert t.compact() == 0


class TestScanCache:
    def test_cached_epochs_skip_decode(self, catalog, monkeypatch):
        t = seed_pk_table(catalog, name="cch")
        calls = {"n": 0}
        import lakesoul_tpu.catalog as cat_mod

        orig = cat_mod.read_scan_unit

        def counting(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        monkeypatch.setattr(cat_mod, "read_scan_unit", counting)
        scan = t.scan().cache()
        first = scan.to_arrow()
        after_first = calls["n"]
        assert after_first > 0
        second = scan.to_arrow()
        assert calls["n"] == after_first  # cache hit: no re-decode
        assert first.equals(second)
        # batches + jax iter also served from cache
        rows = sum(len(b) for b in t.scan().cache().batch_size(2).to_batches())
        assert rows == 4
        assert calls["n"] == after_first

    def test_commit_invalidates_cache(self, catalog):
        t = seed_pk_table(catalog, name="cch2")
        scan = t.scan().cache()
        assert scan.to_arrow().num_rows == 4
        t.upsert(pa.table({"id": [9], "v": [9.0], "name": ["z"]}))
        assert t.scan().cache().to_arrow().num_rows == 5  # new version, new key

    def test_cache_byte_bounded(self, catalog):
        # eviction is by BYTES (VERDICT r1 weak #9): shrink the budget to the
        # size of ~2 cached results and verify LRU eviction keeps the bound
        t = seed_pk_table(catalog, name="cch3")
        one = t.scan().cache().select(["id"]).filter(col("id") >= 0).to_arrow()
        catalog._scan_cache.clear()
        catalog._scan_cache_bytes = 0
        catalog._scan_cache_max_bytes = max(1, one.nbytes * 2)
        # 4 equally-sized results (distinct keys): only ~2 can stay resident
        for i in range(-4, 0):
            t.scan().cache().select(["id"]).filter(col("id") >= i).to_arrow()
        assert catalog._scan_cache_bytes <= catalog._scan_cache_max_bytes
        assert 1 <= len(catalog._scan_cache) <= 2
        assert sum(v.nbytes for v in catalog._scan_cache.values()) == catalog._scan_cache_bytes

    def test_oversized_result_not_cached(self, catalog):
        t = seed_pk_table(catalog, name="cch5")
        catalog._scan_cache_max_bytes = 1  # everything is oversized
        t.scan().cache().to_arrow()
        assert catalog._scan_cache == {} and catalog._scan_cache_bytes == 0

    def test_schema_evolution_invalidates_cache(self, catalog):
        t = seed_pk_table(catalog, name="cch4")
        assert "extra" not in t.scan().cache().to_arrow().column_names
        t.add_columns(pa.field("extra", pa.string()))
        got = t.scan().cache().to_arrow()
        assert "extra" in got.column_names  # schema digest changed the key

    def test_cache_miss_through_threaded_batches(self, catalog):
        t = seed_pk_table(catalog, name="cch5", buckets=4)
        rows = sum(len(b) for b in t.scan().cache().batch_size(2).to_batches(num_threads=3))
        assert rows == 4
        rows2 = sum(len(b) for b in t.scan().cache().batch_size(2).to_batches())
        assert rows2 == 4  # second epoch from cache


class TestCountShortcut:
    def test_metadata_only_count_after_compaction(self, catalog, monkeypatch):
        t = seed_pk_table(catalog, name="cnt1")
        assert t.scan().count_rows() == 4  # PK units → slow path (correct)
        t.compact()
        # post-compaction: PKs dropped → footer-only count; prove no decode
        import lakesoul_tpu.io.formats as fmts

        called = {"n": 0}
        orig = fmts.ParquetFormat.read_table

        def counting(self, *a, **k):
            called["n"] += 1
            return orig(self, *a, **k)

        monkeypatch.setattr(fmts.ParquetFormat, "read_table", counting)
        monkeypatch.setattr(
            fmts.ParquetFormat, "iter_batches", lambda *a, **k: (_ for _ in ()).throw(AssertionError("decoded!"))
        )
        assert t.scan().count_rows() == 4
        assert called["n"] == 0

    def test_merge_units_count_correctly(self, catalog):
        # duplicate PKs inside one file: metadata count would be wrong, the
        # slow path must be taken
        t = catalog.create_table(
            "cnt2",
            pa.schema([("id", pa.int64()), ("v", pa.float64())]),
            primary_keys=["id"], hash_bucket_num=1,
        )
        t.write_arrow(pa.table({"id": [1, 1, 2], "v": [1.0, 2.0, 3.0]}))
        assert t.scan().count_rows() == 2  # dup id=1 merges

    def test_sql_count_star_uses_shortcut(self, catalog):
        from lakesoul_tpu.sql import SqlSession

        t = seed_pk_table(catalog, name="cnt3")
        t.compact()
        out = SqlSession(catalog).execute("SELECT count(*) AS n FROM cnt3")
        assert out.column("n").to_pylist() == [4]
        # filtered counts still go the exact way
        out2 = SqlSession(catalog).execute("SELECT count(*) AS n FROM cnt3 WHERE id > 1")
        assert out2.column("n").to_pylist() == [3]


class TestScanLimit:
    def test_limit_truncates_and_stops_early(self, catalog, monkeypatch):
        schema = pa.schema(
            [("id", pa.int64()), ("v", pa.float64()), ("part", pa.string())]
        )
        t = catalog.create_table("lim", schema, range_partitions=["part"])
        for wave in range(4):
            t.write_arrow(pa.table({
                "id": np.arange(wave * 100, (wave + 1) * 100),
                "v": np.zeros(100), "part": [f"p{wave}"] * 100,
            }))
        got = t.scan().limit(150).to_arrow()
        assert got.num_rows == 150
        assert t.scan().limit(0).to_arrow().num_rows == 0
        assert t.scan().limit(10**9).to_arrow().num_rows == 400

        # early stop is UNIT-granular: limit 50 decodes one partition's unit,
        # the other three partitions' files are never read
        import lakesoul_tpu.io.formats as fmts

        calls = {"n": 0}
        orig = fmts.ParquetFormat.read_table

        def counting(self, *a, **k):
            calls["n"] += 1
            return orig(self, *a, **k)

        monkeypatch.setattr(fmts.ParquetFormat, "read_table", counting)
        assert t.scan().limit(50).to_arrow().num_rows == 50
        assert calls["n"] <= 2  # not all 4 units

    def test_count_rows_respects_limit(self, catalog):
        t = catalog.create_table("lim2", SCHEMA, hash_bucket_num=1)
        t.write_arrow(pa.table({"id": np.arange(100), "v": np.zeros(100), "name": ["x"] * 100}))
        assert t.scan().limit(7).count_rows() == 7
        assert t.scan().count_rows() == 100

    def test_sql_limit_pushes_into_scan(self, catalog):
        from lakesoul_tpu.sql import SqlSession

        t = catalog.create_table("lim3", SCHEMA, hash_bucket_num=1)
        t.write_arrow(pa.table({"id": np.arange(50), "v": np.zeros(50), "name": ["x"] * 50}))
        out = SqlSession(catalog).execute("SELECT id FROM lim3 LIMIT 5")
        assert out.num_rows == 5
        # ordered LIMIT still exact: full sort then slice
        out2 = SqlSession(catalog).execute("SELECT id FROM lim3 ORDER BY id DESC LIMIT 3")
        assert out2.column("id").to_pylist() == [49, 48, 47]


class TestLoaderCheckpoint:
    """Mid-epoch input-stream resume (tf.data-checkpoint role): a trainer
    restarting from (model, LoaderCheckpoint) continues exactly after the
    last delivered batch."""

    def _table(self, catalog, n=1000):
        t = catalog.create_table("lck", SCHEMA, hash_bucket_num=1)
        t.write_arrow(pa.table({
            "id": np.arange(n), "v": np.arange(n, dtype=np.float64), "name": ["x"] * n,
        }))
        return t

    def test_resume_mid_epoch_no_replay_no_loss(self, catalog):
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint

        t = self._table(catalog)
        ckpt = LoaderCheckpoint()
        seen = []
        it = iter(t.scan().batch_size(128).to_jax_iter(
            device_put=False, checkpoint=ckpt,
        ))
        for _ in range(3):  # consume 3 batches, then "crash"
            seen.extend(next(it)["id"].tolist())
        state = ckpt.to_json()

        restored = LoaderCheckpoint.from_json(state)
        assert restored.rows_delivered == 3 * 128
        for b in t.scan().batch_size(128).to_jax_iter(
            device_put=False, checkpoint=restored,
        ):
            seen.extend(b["id"].tolist())
        # drop_remainder drops the final 1000-896=104-row tail; everything
        # delivered exactly once
        assert len(seen) == len(set(seen)) == (1000 // 128) * 128

    def test_checkpoint_counts_before_yield(self, catalog):
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint

        t = self._table(catalog, n=512)
        ckpt = LoaderCheckpoint()
        it = iter(t.scan().batch_size(128).to_jax_iter(device_put=False, checkpoint=ckpt))
        next(it)
        # after receiving batch 0 (a trainer would now step + save), the
        # position already includes it
        assert ckpt.rows_delivered == 128

    def test_close_quiesces_producer_thread(self, catalog):
        """Closing a loader iterator JOINS the pipeline's prefetch pump
        instead of merely signalling it: an abandoned producer that keeps
        decoding in the background races whatever runs next (a resumed
        iterator, a monkeypatch, interpreter shutdown) — the root cause of
        a flaky full-suite failure where a stale phase-1 producer polluted
        phase 2's decode spy under CPU contention.  The pump is the
        runtime pipeline's ``loader-prefetch`` thread now."""
        import threading

        t = self._table(catalog, n=2000)
        it = iter(t.scan().batch_size(100).to_jax_iter(device_put=False))
        next(it)
        it.close()
        assert not any(
            th.name == "loader-prefetch" and th.is_alive()
            for th in threading.enumerate()
        )

    def test_resume_fast_skips_whole_units_without_decode(self, catalog, monkeypatch):
        """Resume drops whole pre-position units via metadata row counts —
        they must never be decoded (footer-count fast path)."""
        import lakesoul_tpu.catalog as cat_mod
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint

        t = catalog.create_table("lck_fast", SCHEMA, primary_keys=["id"], hash_bucket_num=4)
        n = 2000
        t.write_arrow(pa.table({
            "id": np.arange(n), "v": np.arange(n, dtype=np.float64), "name": ["x"] * n,
        }))
        t.compact()  # steady state: 4 single-file units, merge-skip (no PKs)
        units = t.scan().scan_plan()
        assert len(units) == 4 and all(not u.primary_keys for u in units)

        ckpt = LoaderCheckpoint()
        seen = []
        it = iter(t.scan().batch_size(100).to_jax_iter(device_put=False, checkpoint=ckpt))
        # consume past at least one whole unit (largest unit < 700 rows here)
        while ckpt.rows_delivered < 700:
            seen.extend(next(it)["id"].tolist())
        state = ckpt.to_json()
        it.close()  # the "crash": stop the abandoned producer thread

        decoded = []
        real = cat_mod.iter_scan_unit_batches

        def spy(files, pks, **kw):
            decoded.append(list(files))
            return real(files, pks, **kw)

        monkeypatch.setattr(cat_mod, "iter_scan_unit_batches", spy)
        for b in t.scan().batch_size(100).to_jax_iter(
            device_put=False, drop_remainder=False,
            checkpoint=LoaderCheckpoint.from_json(state),
        ):
            seen.extend(b["id"].tolist())
        assert sorted(seen) == list(range(n))  # exactly-once across the resume
        assert len(decoded) < len(units)  # at least one unit skipped undecoded

    def test_cdc_table_skips_footer_fast_paths(self, catalog):
        """Compacted CDC files retain delete rows the decode drops, so the
        footer-count shortcuts (count_rows AND checkpoint fast-skip) must not
        trust them: counts would misalign the resume position."""
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint

        t = catalog.create_table("lck_cdc", SCHEMA, primary_keys=["id"], cdc=True)
        rk = t.info.cdc_column
        n = 600
        t.write_arrow(pa.table({
            "id": np.arange(n), "v": np.zeros(n), "name": ["x"] * n,
            rk: ["insert"] * n,
        }))
        t.write_arrow(pa.table({
            "id": np.arange(0, 100), "v": np.zeros(100), "name": ["x"] * 100,
            rk: ["delete"] * 100,
        }))
        t.compact()
        units = t.scan().scan_plan()
        assert all(not u.primary_keys for u in units)  # compacted heads
        live = n - 100
        assert t.scan().count_rows() == live  # shortcut must not overcount
        ckpt = LoaderCheckpoint()
        seen = []
        it = iter(t.scan().batch_size(64).to_jax_iter(device_put=False, checkpoint=ckpt))
        for _ in range(3):
            seen.extend(next(it)["id"].tolist())
        state = ckpt.to_json()
        it.close()
        for b in t.scan().batch_size(64).to_jax_iter(
            device_put=False, drop_remainder=False,
            checkpoint=LoaderCheckpoint.from_json(state),
        ):
            seen.extend(b["id"].tolist())
        assert sorted(seen) == list(range(100, n))  # exactly-once, no replay

    def test_table_version_change_rejected(self, catalog):
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint
        from lakesoul_tpu.errors import ConfigError

        t = self._table(catalog, n=256)
        ckpt = LoaderCheckpoint()
        it = iter(t.scan().batch_size(64).to_jax_iter(device_put=False, checkpoint=ckpt))
        next(it)
        state = ckpt.to_json()
        t.write_arrow(pa.table({"id": [9999], "v": [0.0], "name": ["y"]}))  # new commit
        with pytest.raises(ConfigError, match="different table"):
            t.scan().batch_size(64).to_jax_iter(
                device_put=False, checkpoint=LoaderCheckpoint.from_json(state)
            )
