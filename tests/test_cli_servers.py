"""The installable server binaries (pyproject [project.scripts]): the
reference ships flight_sql_server and the s3-proxy as deployables
(bin/flight_sql_server.rs:22); these drive the equivalent CLI mains as real
subprocesses — gateway with Prometheus /metrics, storage proxy."""

import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_for(proc, port, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(proc.stdout.read()[-2000:])
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=0.5)
            s.close()
            return
        except OSError:
            time.sleep(0.3)
    raise AssertionError(f"server never listened on {port}")


@pytest.fixture()
def env():
    # strip ambient LAKESOUL_* config: a host with LAKESOUL_JWT_SECRET or
    # LAKESOUL_PROXY_S3_* exported must not reconfigure the servers under test
    clean = {k: v for k, v in os.environ.items() if not k.startswith("LAKESOUL_")}
    clean["JAX_PLATFORMS"] = "cpu"
    return clean


def test_flight_sql_server_cli(tmp_path, env):
    port, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "lakesoul_tpu.service.flight_sql",
         "--warehouse", str(tmp_path / "wh"), "--host", "127.0.0.1",
         "--port", str(port), "--metrics-port", str(mport)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        _wait_for(proc, port)
        from lakesoul_tpu.service.flight_sql import FlightSqlClient

        c = FlightSqlClient(f"grpc://127.0.0.1:{port}")
        assert c.ingest("t", pa.table({"a": np.arange(5)})) == 5
        assert c.execute("SELECT sum(a) AS s FROM t").column("s").to_pylist() == [10]
        c.close()
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics"
        ).read().decode()
        assert "lakesoul_flight_rows_in 5" in metrics
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_storage_proxy_cli(tmp_path, env):
    # pre-create a table + object through the library, then fetch via proxy
    from lakesoul_tpu import LakeSoulCatalog

    wh = tmp_path / "wh"
    cat = LakeSoulCatalog(str(wh))
    t = cat.create_table("t", pa.schema([("a", pa.int64())]))
    t.write_arrow(pa.table({"a": [1, 2, 3]}))
    data_file = next(
        f for f in os.listdir(wh / "default" / "t") if not f.startswith(".")
    )
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "lakesoul_tpu.service.storage_proxy",
         "--warehouse", str(wh), "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        _wait_for(proc, port)
        url = f"http://127.0.0.1:{port}/default/t/{data_file}"
        assert urllib.request.urlopen(url).status == 200
        req = urllib.request.Request(url, headers={"Range": "bytes=0-3"})
        assert len(urllib.request.urlopen(req).read()) == 4
    finally:
        proc.terminate()
        proc.wait(timeout=10)
