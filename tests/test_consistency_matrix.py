"""Cross-surface consistency matrix.

The reference's answer to "same semantics everywhere" is a matrix harness
running every case through every writer×reader engine pair and diffing
normalized tables (python/tests/compat/run_matrix.py).  Here the "engines"
are this framework's write and read surfaces — each pair must produce the
identical logical table."""

import json

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql import SqlSession


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("name", pa.string())])

ROWS = [
    {"id": 1, "v": 1.5, "name": "a"},
    {"id": 2, "v": 2.5, "name": "b"},
    {"id": 3, "v": None, "name": None},
]
UPSERT_ROWS = [{"id": 2, "v": 99.0, "name": "B"}]
EXPECTED = [
    {"id": 1, "v": 1.5, "name": "a"},
    {"id": 2, "v": 99.0, "name": "B"},
    {"id": 3, "v": None, "name": None},
]


def to_table(rows):
    return pa.table(
        {
            "id": pa.array([r["id"] for r in rows], type=pa.int64()),
            "v": pa.array([r["v"] for r in rows], type=pa.float64()),
            "name": pa.array([r["name"] for r in rows], type=pa.string()),
        }
    )


# ----------------------------------------------------------------- writers
def write_catalog(catalog, name):
    t = catalog.create_table(name, SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    t.write_arrow(to_table(ROWS))
    t.upsert(to_table(UPSERT_ROWS))


def write_sql(catalog, name):
    sql = SqlSession(catalog)
    sql.execute(
        f"CREATE TABLE {name} (id bigint PRIMARY KEY, v double, name string)"
        " WITH (hashBucketNum = '2')"
    )
    sql.execute(
        f"INSERT INTO {name} VALUES (1, 1.5, 'a'), (2, 2.5, 'b'), (3, NULL, NULL)"
    )
    sql.execute(f"INSERT INTO {name} VALUES (2, 99.0, 'B')")


def write_checkpointed(catalog, name):
    from lakesoul_tpu.streaming import CheckpointedWriter

    t = catalog.create_table(name, SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    w = CheckpointedWriter(t)
    w.write(to_table(ROWS))
    w.checkpoint(1)
    w.write(to_table(UPSERT_ROWS))
    w.checkpoint(2)


def write_arrow_ipc_format(catalog, name):
    """Same logical writes through the second physical format: ipc files in
    the first commit, parquet in the upsert → a MIXED-format partition."""
    t = catalog.create_table(name, SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    orig = t.io_config

    def ipc_cfg(**overrides):
        cfg = orig(**overrides)
        cfg.file_format = "arrow"
        return cfg

    t.io_config = ipc_cfg
    t.write_arrow(to_table(ROWS))
    t.io_config = orig
    t.upsert(to_table(UPSERT_ROWS))


def write_lsf_format(catalog, name):
    """Same logical writes through the native LSF columnar format, with the
    upsert in parquet → a mixed lsf+parquet partition read transparently."""
    t = catalog.create_table(
        name, SCHEMA, primary_keys=["id"], hash_bucket_num=2,
        properties={"lakesoul.file_format": "lsf"},
    )
    t.write_arrow(to_table(ROWS))
    t.set_properties({"lakesoul.file_format": "parquet"})
    catalog.table(name).upsert(to_table(UPSERT_ROWS))


def write_debezium(catalog, name):
    from lakesoul_tpu.streaming import DebeziumJsonConsumer

    c = DebeziumJsonConsumer(catalog, primary_keys={name: ["id"]})
    for r in ROWS:
        c.consume({"op": "c", "after": r, "source": {"table": name}})
    for r in UPSERT_ROWS:
        c.consume({"op": "u", "after": r, "source": {"table": name}})
    c.checkpoint(1)


def write_flight(catalog, name, server_port, token):
    from lakesoul_tpu.service.flight import LakeSoulFlightClient

    client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server_port}", token=token)
    schema_hex = SCHEMA.serialize().to_pybytes().hex()
    client.action(
        "create_table",
        {"table": name, "schema_ipc_hex": schema_hex, "primary_keys": ["id"],
         "hash_bucket_num": 2},
    )
    client.write(name, to_table(ROWS))
    client.write(name, to_table(UPSERT_ROWS))


# ----------------------------------------------------------------- readers
def read_scan(catalog, name, **_):
    return catalog.table(name).to_arrow()


def read_sql(catalog, name, **_):
    return SqlSession(catalog).execute(f"SELECT * FROM {name}")


def read_batches(catalog, name, **_):
    batches = list(catalog.table(name).scan().batch_size(2).to_batches())
    return pa.Table.from_batches(batches, schema=batches[0].schema)


def read_flight(catalog, name, server_port=None, token=None):
    from lakesoul_tpu.service.flight import LakeSoulFlightClient

    client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server_port}", token=token)
    return client.scan(name)


def read_torch(catalog, name, **_):
    ds = catalog.table(name).scan().to_torch()
    batches = list(ds)
    return pa.Table.from_batches(batches, schema=batches[0].schema)


def normalize(table: pa.Table):
    """Sort by PK and convert to plain python for diffing (compat/normalize.py
    role)."""
    table = table.select(["id", "v", "name"]).sort_by("id")
    return table.to_pylist()


def read_substrait_scan(catalog, name, **_):
    """Scan with a substrait-serialized always-true predicate: exercises the
    external-engine filter wire without changing the result set."""
    import pyarrow.dataset as pads

    from lakesoul_tpu.io.filters import Filter

    t = catalog.table(name)
    import pyarrow.substrait as ps

    expr = pads.field("id") >= -(10**9)
    data = bytes(ps.serialize_expressions([expr], ["f"], t.schema))
    return t.scan().filter(Filter.from_substrait(data)).to_arrow()


WRITERS = {
    "catalog": write_catalog,
    "sql": write_sql,
    "checkpointed": write_checkpointed,
    "flight": write_flight,
    "ipc_format": write_arrow_ipc_format,
    "lsf_format": write_lsf_format,
    "debezium": write_debezium,
}
READERS = {
    "scan": read_scan,
    "sql": read_sql,
    "batches": read_batches,
    "flight": read_flight,
    "torch": read_torch,
    "substrait": read_substrait_scan,
}


@pytest.fixture(scope="module")
def matrix_env(tmp_path_factory):
    wh = tmp_path_factory.mktemp("matrix_wh")
    catalog = LakeSoulCatalog(str(wh))
    from lakesoul_tpu.service.flight import LakeSoulFlightServer

    server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
    yield catalog, server.port, None
    server.shutdown()


@pytest.mark.parametrize("writer", sorted(WRITERS))
@pytest.mark.parametrize("reader", sorted(READERS))
def test_matrix(matrix_env, writer, reader):
    catalog, port, token = matrix_env
    name = f"m_{writer}"
    if not catalog.table_exists(name):
        if writer == "flight":
            WRITERS[writer](catalog, name, port, token)
        else:
            WRITERS[writer](catalog, name)
    got = READERS[reader](catalog, name, server_port=port, token=token)
    assert normalize(got) == EXPECTED, f"writer={writer} reader={reader}"
