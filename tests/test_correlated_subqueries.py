"""Native correlated subqueries (VERDICT r3 item 9).

The executor decorrelates EXISTS / IN / scalar-aggregate subqueries
mechanically — hash semi-joins on equality correlation keys, grouped left
joins for scalar aggregates — with scope resolution by qualifier first and
bare-name membership second (innermost wins).  TPC-H Q2/Q4/Q17/Q20/Q22 run
in their real correlated shapes (tests/test_tpch.py verifies them against
pandas); these tests pin the machinery itself.
"""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql import SqlSession
from lakesoul_tpu.sql.parser import SqlError


@pytest.fixture()
def s(tmp_warehouse):
    cat = LakeSoulCatalog(str(tmp_warehouse))
    s = SqlSession(cat)
    s.execute(
        "CREATE TABLE orders (okey bigint PRIMARY KEY, cust string, total double)"
    )
    s.execute(
        "CREATE TABLE items (ikey bigint PRIMARY KEY, okey bigint, qty double,"
        " price double)"
    )
    s.execute(
        "INSERT INTO orders VALUES (1,'a',10.0),(2,'b',20.0),(3,'c',30.0),(4,'d',40.0)"
    )
    s.execute(
        "INSERT INTO items VALUES (10,1,5.0,1.0),(11,1,7.0,2.0),(12,3,2.0,3.0),"
        "(13,4,9.0,4.0)"
    )
    return s


def _custs(out):
    return sorted(out.column("cust").to_pylist())


class TestCorrelatedExists:
    def test_exists_equality(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey AND qty > 4)"
        )
        assert _custs(out) == ["a", "d"]

    def test_not_exists(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE NOT EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey)"
        )
        assert _custs(out) == ["b"]

    def test_same_name_correlation_via_qualifiers(self, s):
        # okey exists in BOTH scopes: the qualifier decides
        out = s.execute(
            "SELECT cust FROM orders WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = orders.okey AND price >= 3)"
        )
        assert _custs(out) == ["c", "d"]

    def test_mixed_nonequality_conjunct(self, s):
        # qty > total/4 references both scopes and is not an equality —
        # evaluated on the joined pairs
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey AND qty > o.total / 4.0)"
        )
        # a: total 10, qtys 5,7 > 2.5 ✓; c: 2 > 7.5 ✗; d: 9 > 10 ✗
        assert _custs(out) == ["a"]

    def test_outer_only_conjunct_inside_exists(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey AND o.total < 35)"
        )
        assert _custs(out) == ["a", "c"]


class TestCorrelatedIn:
    def test_in_with_correlated_predicate(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE okey IN"
            " (SELECT items.okey FROM items WHERE qty < o.total / 4.0)"
        )
        # a: qty<2.5 → none of (5,7) for okey 1 ✗; c: 2 < 7.5 ✓; d: 9 < 10 ✓
        assert _custs(out) == ["c", "d"]

    def test_not_in_correlated(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE okey NOT IN"
            " (SELECT items.okey FROM items WHERE qty < o.total / 4.0)"
        )
        assert _custs(out) == ["a", "b"]


class TestCorrelatedScalar:
    def test_sum(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE total >"
            " (SELECT sum(qty) FROM items WHERE items.okey = o.okey)"
        )
        # a: 10 > 12 ✗; b: NULL ✗; c: 30 > 2 ✓; d: 40 > 9 ✓
        assert _custs(out) == ["c", "d"]

    def test_count_star_fills_zero(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE"
            " (SELECT count(*) FROM items WHERE items.okey = o.okey) = 0"
        )
        assert _custs(out) == ["b"]

    def test_scalar_with_arith_over_agg(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE total <"
            " (SELECT 2.0 * sum(qty) FROM items WHERE items.okey = o.okey)"
        )
        # a: 10 < 24 ✓; b NULL ✗; c: 30 < 4 ✗; d: 40 < 18 ✗
        assert _custs(out) == ["a"]

    def test_correlation_through_join_key_rename(self, s):
        """Q17 shape: the correlation column is a join key the outer join
        coalesced away; the rename must reach inside the subquery."""
        out = s.execute(
            "SELECT cust, qty FROM items"
            " JOIN orders ON items.okey = orders.okey"
            " WHERE qty > (SELECT 0.5 * sum(i2.qty) FROM items i2"
            "              WHERE i2.okey = orders.okey)"
        )
        # group sums: okey1=12, okey3=2, okey4=9 → keep qty>6: a/7, c/2>1 ✓, d/9>4.5 ✓
        assert sorted(zip(out.column("cust").to_pylist(),
                          out.column("qty").to_pylist())) == [
            ("a", 7.0), ("c", 2.0), ("d", 9.0),
        ]


class TestReviewRegressions:
    def test_mixed_conjunct_reusing_join_key(self, s):
        """A non-equality correlated predicate that references the equality
        key column itself — the join coalesces the inner key away, so the
        ref must read the surviving outer-side key."""
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey"
            "  AND items.okey > o.total / 11.0)"
        )
        # a: okey 1 > 0.909 ✓; c: 3 > 2.72 ✓; d: 4 > 3.63 ✓; b no rows
        assert _custs(out) == ["a", "c", "d"]

    def test_correlated_scalar_in_select_list(self, s):
        out = s.execute(
            "SELECT cust, (SELECT sum(qty) FROM items WHERE items.okey = o.okey)"
            " AS total_qty FROM orders o ORDER BY cust"
        )
        assert out.column("cust").to_pylist() == ["a", "b", "c", "d"]
        assert out.column("total_qty").to_pylist() == [12.0, None, 2.0, 9.0]


class TestQualifiedSimplePredicates:
    @pytest.fixture()
    def s2(self, tmp_warehouse):
        """Schema where BOTH tables have a 'total' column — qualifiers must
        decide the scope of simple predicates too."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE orders (okey bigint PRIMARY KEY, cust string, total double)")
        s.execute("CREATE TABLE items (ikey bigint PRIMARY KEY, okey bigint, total double)")
        s.execute("INSERT INTO orders VALUES (1,'a',10.0),(2,'b',20.0)")
        s.execute("INSERT INTO items VALUES (10,1,999.0),(11,2,999.0)")
        return s

    def test_qualified_outer_col_vs_literal(self, s2):
        out = s2.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey AND o.total < 15)"
        )
        assert _custs(out) == ["a"]

    def test_qualified_outer_between_and_in_list(self, s2):
        out = s2.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey"
            "  AND o.total BETWEEN 15 AND 25)"
        )
        assert _custs(out) == ["b"]
        out = s2.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey AND o.total IN (10.0))"
        )
        assert _custs(out) == ["a"]


class TestReviewRegressions2:
    def test_outer_ref_inside_func_in_mixed_conjunct(self, s):
        # the outer ref is buried inside a Func call (substring): the
        # semi-join rewrite must descend into Func/Case, not just Arith
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey"
            "  AND substring(o.cust, 1, 1) = 'a')"
        )
        assert _custs(out) == ["a"]
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey"
            "  AND qty + 0 > o.total / 4.0 + 0)"
        )
        assert _custs(out) == ["a"]
        # inner ref + outer ref inside CASE: a genuinely mixed conjunct
        # whose outer ref sits under a non-Arith expression node
        out = s.execute(
            "SELECT cust FROM orders o WHERE EXISTS"
            " (SELECT * FROM items WHERE items.okey = o.okey"
            "  AND qty > CASE WHEN o.total > 15 THEN 8 ELSE 4 END)"
        )
        # a (total 10): qtys 5,7 > 4 ✓; c (30): 2 > 8 ✗; d (40): 9 > 8 ✓
        assert _custs(out) == ["a", "d"]

    def test_count_inside_arith_fills_zero(self, s):
        out = s.execute(
            "SELECT cust FROM orders o WHERE"
            " (SELECT count(*) + 0 FROM items WHERE items.okey = o.okey) = 0"
        )
        assert _custs(out) == ["b"]

    def test_agg_expr_referencing_group_key_keeps_null(self, s):
        """The empty-set fill probe must not crash when the aggregate
        expression also references a column (no constant empty value
        exists); missing groups stay NULL."""
        out = s.execute(
            "SELECT cust FROM orders o WHERE"
            " (SELECT okey + count(*) FROM items WHERE items.okey = o.okey) > 0"
        )
        assert _custs(out) == ["a", "c", "d"]  # b has no group → NULL → false


class TestErrors:
    def test_unknown_column_raises(self, s):
        with pytest.raises(SqlError, match="unknown column"):
            s.execute(
                "SELECT cust FROM orders o WHERE EXISTS"
                " (SELECT * FROM items WHERE items.okey = o.nope)"
            )

    def test_correlated_in_requires_plain_column(self, s):
        with pytest.raises(SqlError, match="single plain column"):
            s.execute(
                "SELECT cust FROM orders o WHERE okey IN"
                " (SELECT items.okey + 1 FROM items WHERE qty < o.total)"
            )

    def test_correlated_scalar_requires_aggregate(self, s):
        with pytest.raises(SqlError, match="single aggregate"):
            s.execute(
                "SELECT cust FROM orders o WHERE total >"
                " (SELECT qty FROM items WHERE items.okey = o.okey)"
            )
