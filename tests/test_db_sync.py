"""Whole-DB ingest entry (VERDICT r1 missing #7): snapshot sync from a
DB-API source and Debezium-format CDC consumption with auto DDL."""

import sqlite3

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError
from lakesoul_tpu.streaming.db_sync import DatabaseSyncer, DebeziumJsonConsumer


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


@pytest.fixture()
def source():
    conn = sqlite3.connect(":memory:")
    conn.executescript(
        """
        CREATE TABLE users (uid INTEGER PRIMARY KEY, name TEXT, score REAL);
        CREATE TABLE events (ts BIGINT, kind TEXT);
        INSERT INTO users VALUES (1, 'a', 0.5), (2, 'b', 1.5), (3, 'c', 2.5);
        INSERT INTO events VALUES (100, 'x'), (200, 'y');
        """
    )
    return conn


class TestDatabaseSyncer:
    def test_whole_db_snapshot(self, catalog, source):
        out = DatabaseSyncer(catalog).sync(source)
        assert out == {"users": 3, "events": 2}
        users = catalog.table("users")
        assert users.primary_keys == ["uid"]
        got = users.to_arrow().sort_by("uid")
        assert got.column("name").to_pylist() == ["a", "b", "c"]
        assert got.schema.field("score").type == pa.float64()
        assert catalog.table("events").to_arrow().num_rows == 2

    def test_resync_converges_on_pk_tables(self, catalog, source):
        s = DatabaseSyncer(catalog)
        s.sync(source, tables=["users"])
        source.execute("UPDATE users SET score = 9.9 WHERE uid = 2")
        source.execute("INSERT INTO users VALUES (4, 'd', 4.0)")
        s.sync(source, tables=["users"])
        got = catalog.table("users").to_arrow().sort_by("uid")
        assert got.num_rows == 4  # upsert, not duplication
        assert got.column("score").to_pylist()[1] == 9.9


def _ev(table, op, row, before=None):
    return {
        "payload": {
            "op": op,
            "after": row if op != "d" else None,
            "before": before if before is not None else (row if op == "d" else None),
            "source": {"table": table},
        }
    }


class TestDebeziumConsumer:
    def test_multi_table_stream_with_auto_create(self, catalog):
        c = DebeziumJsonConsumer(
            catalog, primary_keys={"users": ["uid"], "orders": ["oid"]}
        )
        c.consume_many(
            [
                _ev("users", "c", {"uid": 1, "name": "a"}),
                _ev("orders", "c", {"oid": 10, "total": 5.0}),
                _ev("users", "u", {"uid": 1, "name": "A"}),
                _ev("users", "c", {"uid": 2, "name": "b"}),
                _ev("orders", "d", {"oid": 10, "total": 5.0}),
            ]
        )
        assert c.checkpoint(1) >= 2
        users = catalog.table("users").to_arrow().sort_by("uid")
        assert users.column("name").to_pylist() == ["A", "b"]
        assert catalog.table("orders").to_arrow().num_rows == 0  # deleted

    def test_checkpoint_replay_is_noop(self, catalog):
        c = DebeziumJsonConsumer(catalog, primary_keys={"t": ["id"]})
        c.consume(_ev("t", "c", {"id": 1, "v": 1.0}))
        assert c.checkpoint(7) == 1
        c.consume(_ev("t", "c", {"id": 1, "v": 1.0}))
        assert c.checkpoint(7) == 0  # same epoch replays idempotently
        assert catalog.table("t").to_arrow().num_rows == 1

    def test_auto_schema_evolution(self, catalog):
        c = DebeziumJsonConsumer(catalog, primary_keys={"t": ["id"]})
        c.consume(_ev("t", "c", {"id": 1, "v": 1.0}))
        # mid-stream DDL on the source: a new column appears
        c.consume(_ev("t", "c", {"id": 2, "v": 2.0, "extra": "new"}))
        c.checkpoint(1)
        got = catalog.table("t").to_arrow().sort_by("id")
        assert got.column("extra").to_pylist() == [None, "new"]

    def test_unknown_table_without_pks_rejected(self, catalog):
        c = DebeziumJsonConsumer(catalog)
        with pytest.raises(ConfigError, match="primary"):
            c.consume(_ev("mystery", "c", {"id": 1}))

    def test_flattened_event_form(self, catalog):
        c = DebeziumJsonConsumer(catalog, primary_keys={"t": ["id"]})
        c.consume({"op": "c", "after": {"id": 1, "v": 2.0}, "source": {"table": "t"}})
        c.checkpoint(1)
        assert catalog.table("t").to_arrow().column("v").to_pylist() == [2.0]
