"""Randomized DML correctness fuzz.

The reference validates correctness with randomized DDL/DML sequences
diffed against MySQL (script/benchmark/*, SURVEY §4 'benchmarks as tests').
Same idea here: drive a PK table through random upsert / update / delete /
compact sequences and diff every step against an exact in-memory model —
plus time-travel checks against remembered model snapshots."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.io.filters import col

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("tag", pa.string())])
KEYSPACE = 60


class Model:
    """Exact reference state: dict pk → row."""

    def __init__(self):
        self.rows: dict[int, dict] = {}

    def upsert(self, batch: list[dict]):
        for r in batch:
            self.rows[r["id"]] = dict(r)

    def update_where_v_gt(self, threshold: float, assignments: dict):
        for r in self.rows.values():
            if r["v"] is not None and r["v"] > threshold:
                r.update(assignments)

    def delete_where_v_gt(self, threshold: float) -> int:
        doomed = [k for k, r in self.rows.items() if r["v"] is not None and r["v"] > threshold]
        for k in doomed:
            del self.rows[k]
        return len(doomed)

    def snapshot(self):
        return sorted((dict(r) for r in self.rows.values()), key=lambda r: r["id"])


def table_state(t):
    got = t.to_arrow().sort_by("id")
    return got.to_pylist()


def random_batch(rng, n):
    return [
        {
            "id": int(k),
            "v": None if rng.random() < 0.05 else round(float(rng.normal()), 3),
            "tag": None if rng.random() < 0.05 else f"t{int(rng.integers(0, 9))}",
        }
        for k in rng.choice(KEYSPACE, size=n, replace=False)
    ]


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_random_dml_sequences_match_model(tmp_warehouse, seed):
    import time

    rng = np.random.default_rng(seed)
    catalog = LakeSoulCatalog(str(tmp_warehouse / f"fuzz{seed}"))
    t = catalog.create_table(
        f"fz{seed}", SCHEMA, primary_keys=["id"],
        hash_bucket_num=int(rng.integers(1, 4)),
    )
    model = Model()
    time_points = []  # (timestamp_ms, model snapshot)

    ops = 0
    for step in range(40):
        roll = rng.random()
        if roll < 0.5:
            batch = random_batch(rng, int(rng.integers(1, 12)))
            t.upsert(pa.table(
                {
                    "id": pa.array([r["id"] for r in batch], type=pa.int64()),
                    "v": pa.array([r["v"] for r in batch], type=pa.float64()),
                    "tag": pa.array([r["tag"] for r in batch], type=pa.string()),
                }
            ))
            model.upsert(batch)
        elif roll < 0.65 and model.rows:
            thr = round(float(rng.normal()), 3)
            tag = f"u{step}"
            expected_n = sum(
                1 for r in model.rows.values() if r["v"] is not None and r["v"] > thr
            )
            n = t.update_where(col("v") > thr, {"tag": tag})
            model.update_where_v_gt(thr, {"tag": tag})
            assert n == expected_n, f"step {step}: updated {n} != model {expected_n}"
        elif roll < 0.8 and model.rows:
            thr = round(float(rng.normal(1.0)), 3)
            n = t.delete_where(col("v") > thr)
            expected_n = model.delete_where_v_gt(thr)
            assert n == expected_n, f"step {step}: deleted {n} != model {expected_n}"
        elif roll < 0.87:
            t.compact()
        elif roll < 0.93 and time_points and rng.random() < 0.5:
            # rollback to a remembered instant: table AND model rewind
            ts, past = time_points[int(rng.integers(0, len(time_points)))]
            t.rollback(to_timestamp_ms=ts)
            model.rows = {r["id"]: dict(r) for r in past}
            # older remembered instants stay valid; drop the later ones
            # (their history is now shadowed by the rollback commit)
            time_points = [(p_ts, p) for p_ts, p in time_points if p_ts <= ts]
            time.sleep(0.002)
        else:
            # remember a consistent point for time travel
            heads = catalog.client.store.get_all_latest_partition_info(t.info.table_id)
            if heads:
                ts = max(h.timestamp for h in heads)
                time_points.append((ts, model.snapshot()))
                time.sleep(0.002)  # ensure later commits get later stamps
        ops += 1
        if step % 5 == 0 or step == 39:
            assert table_state(t) == model.snapshot(), f"divergence at step {step}"

    assert table_state(t) == model.snapshot()

    # time travel: every remembered instant reproduces the model's past
    for ts, past in time_points:
        got = t.scan().snapshot_at(ts).to_arrow().sort_by("id").to_pylist()
        assert got == past, f"time travel to {ts} diverged"
