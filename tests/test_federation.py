"""FROM-less SELECT and federated external tables (SURVEY §2.5's ADBC
federation role: lakesoul-datafusion queries a mysql catalog from the same
SQL session; here any Arrow table / data file / fetch-callable registers as
a read-only external table that joins and subqueries against lakehouse
tables)."""

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql import SqlSession
from lakesoul_tpu.sql.parser import SqlError


@pytest.fixture()
def s(tmp_warehouse):
    cat = LakeSoulCatalog(str(tmp_warehouse))
    s = SqlSession(cat)
    s.execute("CREATE TABLE fact (id bigint PRIMARY KEY, dim_id bigint, v double)")
    s.execute("INSERT INTO fact VALUES (1,10,1.5),(2,20,2.5),(3,10,3.5)")
    return s


class TestFromlessSelect:
    def test_literals(self, s):
        assert s.execute("SELECT 1").to_pydict() == {"1": [1]}
        out = s.execute("SELECT 1 + 1 AS two, 'hi' AS msg")
        assert out.to_pydict() == {"two": [2], "msg": ["hi"]}

    def test_star_requires_from(self, s):
        with pytest.raises(SqlError, match="FROM"):
            s.execute("SELECT *")

    def test_trailing_clauses(self, s):
        # connection pools probe with `SELECT 1 LIMIT 1`; WHERE gates the row
        assert s.execute("SELECT 1 LIMIT 1").to_pydict() == {"1": [1]}
        assert s.execute("SELECT 1 WHERE 1 = 2").num_rows == 0
        assert s.execute("SELECT 1 AS x ORDER BY x").to_pydict() == {"x": [1]}

    def test_over_flight_sql(self, tmp_warehouse):
        """The ADBC connection-probe statement works over the protocol."""
        from lakesoul_tpu.service.flight_sql import (
            FlightSqlClient,
            LakeSoulFlightSqlServer,
        )

        srv = LakeSoulFlightSqlServer(
            LakeSoulCatalog(str(tmp_warehouse)), "grpc://127.0.0.1:0"
        )
        try:
            c = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}")
            assert c.execute("SELECT 1").to_pydict() == {"1": [1]}
            c.close()
        finally:
            srv.shutdown()


class TestExternalTables:
    def test_arrow_table_join(self, s):
        s.register_external(
            "dims", pa.table({"dim_id": [10, 20], "name": ["a", "b"]})
        )
        out = s.execute(
            "SELECT name, sum(v) AS sv FROM fact JOIN dims ON"
            " fact.dim_id = dims.dim_id GROUP BY name ORDER BY name"
        )
        assert out.column("name").to_pylist() == ["a", "b"]
        assert out.column("sv").to_pylist() == [5.0, 2.5]

    def test_file_source(self, s, tmp_path):
        path = tmp_path / "ext.parquet"
        pq.write_table(pa.table({"id": [1, 2], "tag": ["x", "y"]}), path)
        s.register_external("tags", str(path))
        out = s.execute("SELECT tag FROM tags ORDER BY tag")
        assert out.column("tag").to_pylist() == ["x", "y"]

    def test_callable_fetched_once_per_statement(self, s):
        calls = []

        def fetch():
            calls.append(1)
            return pa.table({"dim_id": [10], "w": [2.0]})

        s.register_external("live", fetch)
        out = s.execute(
            "SELECT sum(v * w) AS x FROM fact JOIN live ON"
            " fact.dim_id = live.dim_id"
            " WHERE dim_id IN (SELECT dim_id FROM live)"
        )
        assert out.column("x").to_pylist() == [10.0]
        assert len(calls) == 1  # one consistent snapshot per statement
        s.execute("SELECT count(*) AS c FROM live")
        assert len(calls) == 2  # next statement re-fetches

    def test_external_in_correlated_subquery(self, s):
        s.register_external(
            "quota", pa.table({"dim_id": [10, 20], "cap": [4.0, 1.0]})
        )
        out = s.execute(
            "SELECT id FROM fact f WHERE v < "
            "(SELECT max(cap) FROM quota WHERE quota.dim_id = f.dim_id)"
            " ORDER BY id"
        )
        assert out.column("id").to_pylist() == [1, 3]

    def test_external_shadows_and_is_read_only(self, s):
        s.register_external("fact2", pa.table({"id": [99]}))
        with pytest.raises(SqlError, match="read-only"):
            s.execute("INSERT INTO fact2 VALUES (1)")
        with pytest.raises(SqlError, match="read-only"):
            s.execute("DROP TABLE fact2")
        # lakehouse DML still works
        s.execute("DELETE FROM fact WHERE id = 3")
        out = s.execute("SELECT count(*) AS c FROM fact")
        assert out.column("c").to_pylist() == [2]

    def test_explain_shows_external_scan(self, s):
        s.register_external("dims", pa.table({"dim_id": [10], "name": ["a"]}))
        plan = "\n".join(
            s.execute("EXPLAIN SELECT name FROM dims WHERE dim_id = 10")
            .column("plan").to_pylist()
        )
        assert "ExternalScan: dims" in plan
