"""Fleet plane: transport negotiation, worker autoscaling, multi-host axis.

Three surfaces, one contract — the fleet gets bigger without the data
changing:

- **transport matrix**: the same scan forced through each rung (shm /
  spill / stream) delivers byte-identical batches, and each rung meters
  its own bytes/ranges into the obs registry;
- **autoscaler**: the leased controller is a deterministic machine under
  an injected clock — scale-up tracks backlog, scale-down waits out idle
  polls, a lapsed lease is taken over with a BUMPED fencing token and the
  zombie demotes itself (retiring its own children);
- **multihost**: ``to_jax_iter(multihost=True)`` ranks are disjoint,
  their union is the whole table, and each rank's stream matches a plain
  ``scan.shard(rank, world)``.
"""

from __future__ import annotations

import math
import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError, ScanPlaneWaitTimeout
from lakesoul_tpu.fleet import multihost, transport
from lakesoul_tpu.fleet.autoscale import (
    AutoscalePolicy,
    AutoscaleSignals,
    WorkerAutoscaler,
    WorkerSpawner,
    collect_signals,
    lease_key,
    spool_backlog,
)
from lakesoul_tpu.obs import fleet as obs_fleet
from lakesoul_tpu.obs import registry
from lakesoul_tpu.scanplane.client import ScanPlaneClient
from lakesoul_tpu.scanplane.delivery import ScanPlaneDelivery
from lakesoul_tpu.scanplane.session import ScanSession
from lakesoul_tpu.scanplane.worker import ScanPlaneWorker
from lakesoul_tpu.service.flight import LakeSoulFlightServer

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("f", pa.float32())])


def _make_table(tmp_path, *, rows=12_000, commits=3, name="t"):
    catalog = LakeSoulCatalog(
        str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
    )
    t = catalog.create_table(
        name, SCHEMA, primary_keys=["id"], hash_bucket_num=2
    )
    rng = np.random.default_rng(7)
    per = rows // commits
    for _ in range(commits):
        ids = np.sort(rng.choice(rows * 2, per, replace=False)).astype(np.int64)
        t.upsert(pa.table({
            "id": ids,
            "v": rng.normal(size=per),
            "f": rng.normal(size=per).astype(np.float32),
        }, schema=SCHEMA))
    return catalog, t


class _Plane:
    """In-process fleet: spool delivery gateway + worker threads, with the
    object-store spill rung armed under ``tmp_path/spill_store``."""

    def __init__(self, catalog, tmp_path, *, workers=1, wait_s=30.0,
                 start_workers=True, spill=True):
        self.spool = str(tmp_path / "spool")
        os.makedirs(self.spool, exist_ok=True)
        self.spill_prefix = str(tmp_path / "spill_store") if spill else None
        self.delivery = ScanPlaneDelivery(
            catalog, self.spool, wait_s=wait_s,
            spill_prefix=self.spill_prefix or "",
        )
        self.server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", scanplane=self.delivery
        )
        threading.Thread(target=self.server.serve, daemon=True).start()
        self.location = f"grpc://127.0.0.1:{self.server.port}"
        self._stops = []
        self.workers = [
            ScanPlaneWorker(
                catalog, self.spool, lease_ttl_s=10.0,
                poll_interval_s=0.02, worker_id=f"w{i}",
            )
            for i in range(workers)
        ]
        if start_workers:
            for w in self.workers:
                stop = threading.Event()
                self._stops.append(stop)
                threading.Thread(
                    target=w.run_forever, kwargs={"stop_event": stop},
                    daemon=True,
                ).start()

    def close(self):
        for s in self._stops:
            s.set()
        self.server.shutdown()


def _counter(snapshot, family, **labels):
    key = family
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        key = f"{family}{{{inner}}}"
    return snapshot.get(key, 0)


# -------------------------------------------------------- transport seam


class TestTransportConfig:
    def test_forced_transport_resolution(self, monkeypatch):
        monkeypatch.delenv(transport.ENV_TRANSPORT, raising=False)
        assert transport.forced_transport() is None
        assert transport.forced_transport("auto") is None
        assert transport.forced_transport("") is None
        assert transport.forced_transport("spill") == "spill"
        monkeypatch.setenv(transport.ENV_TRANSPORT, "stream")
        assert transport.forced_transport() == "stream"
        # the explicit value (client kwarg) beats the env
        assert transport.forced_transport("shm") == "shm"
        with pytest.raises(ConfigError, match="unknown fleet transport"):
            transport.forced_transport("carrier-pigeon")

    def test_typoed_env_fails_at_client_construction(self, monkeypatch):
        monkeypatch.setenv(transport.ENV_TRANSPORT, "hsm")
        with pytest.raises(ConfigError, match="unknown fleet transport"):
            ScanPlaneClient("grpc://127.0.0.1:0")

    def test_spill_publication_crc_and_prune(self, tmp_path):
        prefix = str(tmp_path / "store")
        offer = transport.write_spill_probe(prefix, "sess-a")
        assert transport.spill_probe_matches(offer)
        assert not transport.spill_probe_matches(None)
        assert not transport.spill_probe_matches(
            {**offer, "token": "some-other-session"}
        )
        # publish one sealed segment and pull it back, CRC-verified
        from lakesoul_tpu.scanplane import spool as spool_mod

        sdir = str(tmp_path / "spool-sess")
        os.makedirs(sdir)
        t = pa.table({"x": np.arange(512, dtype=np.int64)})
        spool_mod.write_range(
            sdir, 0, t.schema, iter(t.to_batches(max_chunksize=128)),
            holder="w0",
        )
        doc = transport.spill_range(prefix, "sess-a", sdir, 0)
        assert doc["nbytes"] > 0
        # idempotent: the CRC sidecar short-circuits the re-publish
        assert transport.spill_range(prefix, "sess-a", sdir, 0) == doc
        nbytes, batches = transport.fetch_spilled(doc)
        assert nbytes == doc["nbytes"]
        assert pa.Table.from_batches(batches).equals(t)
        # a torn object must fail loudly, never decode silently wrong
        with open(doc["path"], "r+b") as f:
            f.write(b"\x00\x00torn")
        from lakesoul_tpu.errors import IOError_

        with pytest.raises(IOError_, match="failed verification"):
            transport.fetch_spilled(doc)
        # pruning follows the session manifest lifecycle
        assert transport.prune_spill(prefix, {"sess-a"}) == 0
        assert transport.prune_spill(prefix, set()) == 1
        assert not os.path.exists(doc["path"])
        assert not os.path.exists(
            transport.spill_probe_path(prefix, "sess-a")
        )


class TestTransportMatrix:
    @pytest.fixture()
    def plane(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        p = _Plane(catalog, tmp_path, workers=1)
        yield catalog, t, p
        p.close()

    @pytest.mark.parametrize("rung", ["shm", "spill", "stream"])
    def test_forced_rung_sha_identical_and_metered(self, plane, rung):
        _, t, p = plane
        want = list(t.scan().batch_size(4096).to_batches())
        before = registry().snapshot()
        client = ScanPlaneClient(p.location, transport=rung)
        got = list(client.iter_batches({"table": "t", "batch_size": 4096}))
        assert len(got) == len(want)
        for a, b in zip(got, want):
            assert a.equals(b)
        after = registry().snapshot()
        # the negotiated rung and its per-range delivery were metered
        fam = "lakesoul_fleet_transport_negotiated_total"
        assert _counter(after, fam, transport=rung) \
            > _counter(before, fam, transport=rung)
        fam = "lakesoul_fleet_transport_ranges_total"
        moved = _counter(after, fam, transport=rung) \
            - _counter(before, fam, transport=rung)
        assert moved > 0
        fam = "lakesoul_fleet_transport_bytes_total"
        assert _counter(after, fam, transport=rung) \
            > _counter(before, fam, transport=rung)
        mode = {"shm": "shm", "spill": "spill", "stream": "socket"}[rung]
        fam = "lakesoul_scanplane_client_ranges_total"
        assert _counter(after, fam, mode=mode) \
            - _counter(before, fam, mode=mode) == moved

    def test_env_forced_spill_rank_stream(self, plane, monkeypatch):
        # the env knob (not the kwarg) forces the rung, on a sharded scan
        _, t, p = plane
        monkeypatch.setenv(transport.ENV_TRANSPORT, "spill")
        client = ScanPlaneClient(p.location)
        want = list(t.scan().batch_size(4096).shard(1, 2).to_batches())
        got = list(client.iter_batches(
            {"table": "t", "batch_size": 4096}, rank=1, world=2
        ))
        assert len(got) == len(want)
        assert all(a.equals(b) for a, b in zip(got, want))
        # the spill store now mirrors this session's served ranges
        sessions = os.listdir(p.spill_prefix)
        assert any(s.startswith("probe-") for s in sessions)
        assert any(not s.startswith("probe-") for s in sessions)

    def test_auto_negotiation_prefers_shm_then_spill(self, plane):
        _, t, p = plane
        # same host: the spool probe passes → shm wins the ladder
        before = registry().snapshot()
        client = ScanPlaneClient(p.location)
        list(client.iter_batches({"table": "t", "batch_size": 8192}))
        after = registry().snapshot()
        fam = "lakesoul_fleet_transport_negotiated_total"
        assert _counter(after, fam, transport="shm") \
            > _counter(before, fam, transport="shm")
        # shm=False drops the mapping rung: spill is the next rung down
        before = after
        client = ScanPlaneClient(p.location, shm=False)
        list(client.iter_batches({"table": "t", "batch_size": 8192}))
        after = registry().snapshot()
        assert _counter(after, fam, transport="spill") \
            > _counter(before, fam, transport="spill")

    def test_forced_rung_without_offer_raises(self, tmp_path):
        catalog, _ = _make_table(tmp_path)
        p = _Plane(catalog, tmp_path, workers=1, spill=False)
        try:
            p.delivery.offer_shm = False  # emulate a cross-host gateway
            client = ScanPlaneClient(p.location, transport="shm")
            with pytest.raises(ConfigError, match="shm transport required"):
                list(client.iter_batches({"table": "t"}))
            client = ScanPlaneClient(p.location, transport="spill")
            with pytest.raises(ConfigError, match="spill transport required"):
                list(client.iter_batches({"table": "t"}))
        finally:
            p.close()


# ------------------------------------------------------ typed wait timeout


class TestWaitTimeout:
    def test_from_message_round_trip(self):
        e = ScanPlaneWaitTimeout("sess-42", 7, 1.5)
        assert "session=sess-42" in str(e) and "range=7" in str(e)
        typed = ScanPlaneWaitTimeout.from_message(
            f"gateway said: {e}"
        )
        assert isinstance(typed, ScanPlaneWaitTimeout)
        assert "sess-42" in str(typed) and "range=7" in str(typed)
        assert ScanPlaneWaitTimeout.from_message("range timed out") is None

    def test_client_raises_typed_and_meters(self, tmp_path):
        catalog, _ = _make_table(tmp_path, rows=4000)
        # no workers: every range waits until the gateway's budget burns
        p = _Plane(catalog, tmp_path, workers=0, start_workers=False,
                   wait_s=0.3)
        try:
            before = registry().snapshot()
            client = ScanPlaneClient(p.location, transport="stream")
            with pytest.raises(ScanPlaneWaitTimeout) as ei:
                list(client.iter_batches({"table": "t", "batch_size": 4096}))
            # the typed error names the session and range — the operator's
            # first question ("which scan, how far in") answered inline
            assert "session=" in str(ei.value)
            assert "range=0" in str(ei.value)
            assert "workers running" in str(ei.value)
            after = registry().snapshot()
            fam = "lakesoul_scanplane_wait_exhausted_total"
            # both sides meter: the gateway when its wait burns, the
            # client when the typed marker crosses the wire
            assert _counter(after, fam) - _counter(before, fam) >= 2
        finally:
            p.close()


# ----------------------------------------------------------- autoscaler


class _FakeSpawner:
    """A spawner whose children are list entries, not processes."""

    def __init__(self):
        self._children = []
        self._dead = []
        self._seq = 0
        self.stopped = 0

    @property
    def count(self):
        return len(self._children)

    def spawn(self):
        self._seq += 1
        child = {"worker_id": f"fake-{self._seq}", "pid": 40_000 + self._seq}
        self._children.append(child)
        return child

    def retire(self):
        if not self._children:
            return None
        return {"pid": self._children.pop()["pid"]}

    def kill_one(self):
        self._dead.append(self._children.pop(0))

    def reap(self):
        dead = [{"pid": c["pid"], "returncode": -9} for c in self._dead]
        self._dead = []
        return dead

    def stop_all(self, timeout=10.0):
        self.stopped += 1
        self._children = []


class TestAutoscalePolicy:
    def test_backlog_maps_to_workers(self):
        p = AutoscalePolicy(1, 8, ranges_per_worker=4)
        assert p.target(AutoscaleSignals(backlog=1), current=0) == 1
        assert p.target(AutoscaleSignals(backlog=9), current=1) == 3
        assert p.target(AutoscaleSignals(backlog=100), current=1) == 8

    def test_slo_breach_with_backlog_jumps_to_max(self):
        p = AutoscalePolicy(1, 6)
        sig = AutoscaleSignals(backlog=2, slo_breached=True)
        assert p.target(sig, current=1) == 6

    def test_never_shrinks_under_live_backlog(self):
        p = AutoscalePolicy(1, 8, ranges_per_worker=4)
        # 5 workers mid-drain, tail backlog of 2 ranges: hold, don't churn
        assert p.target(AutoscaleSignals(backlog=2), current=5) == 5

    def test_scale_down_waits_out_idle_polls(self):
        p = AutoscalePolicy(1, 8, idle_polls_to_scale_down=3)
        idle = AutoscaleSignals(backlog=0)
        assert p.target(idle, current=4) == 4
        assert p.target(idle, current=4) == 4
        assert p.target(idle, current=4) == 1  # third consecutive idle poll
        # any backlog resets the idle streak
        p2 = AutoscalePolicy(1, 8, idle_polls_to_scale_down=2)
        assert p2.target(idle, current=3) == 3
        assert p2.target(AutoscaleSignals(backlog=4), current=3) == 3
        assert p2.target(idle, current=3) == 3  # streak restarted at 1
        assert p2.target(idle, current=3) == 1

    def test_invalid_bounds_rejected(self):
        with pytest.raises(ConfigError, match="invalid autoscale bounds"):
            AutoscalePolicy(5, 2)
        with pytest.raises(ConfigError, match="invalid autoscale bounds"):
            AutoscalePolicy(-1, 2)


class TestAutoscalerSignals:
    def test_spool_backlog_counts_unproduced_ranges(self, tmp_path):
        catalog, _ = _make_table(tmp_path, rows=6000)
        spool_dir = str(tmp_path / "spool")
        assert spool_backlog(spool_dir) == (0, 0)
        session = ScanSession.plan(catalog, {"table": "t"})
        session.publish(spool_dir)
        backlog, sessions = spool_backlog(spool_dir)
        assert backlog == len(session.ranges) and sessions == 1
        # a worker drains it: backlog falls to zero
        ScanPlaneWorker(catalog, spool_dir, lease_ttl_s=10).poll_once()
        assert spool_backlog(spool_dir) == (0, 0)

    def test_collect_signals_without_obs_spool(self, tmp_path, monkeypatch):
        monkeypatch.delenv(obs_fleet.ENV_SPOOL, raising=False)
        sig = collect_signals(str(tmp_path / "nope"))
        assert sig.backlog == 0 and not sig.slo_breached


class TestWorkerAutoscaler:
    def _controller(self, store, spool_dir, *, cid, min_w=1, max_w=4,
                    ttl_s=10.0):
        return WorkerAutoscaler(
            store, _FakeSpawner(), spool_dir=spool_dir,
            min_workers=min_w, max_workers=max_w, controller_id=cid,
            lease_ttl_s=ttl_s, heartbeat=False,
        )

    def test_leader_scales_to_backlog_then_down(self, tmp_path, monkeypatch):
        monkeypatch.delenv(obs_fleet.ENV_SPOOL, raising=False)
        catalog, _ = _make_table(tmp_path, rows=6000)
        spool_dir = str(tmp_path / "spool")
        session = ScanSession.plan(catalog, {"table": "t"})
        session.publish(spool_dir)
        backlog = len(session.ranges)
        ctl = self._controller(
            catalog.client.store, spool_dir, cid="A", min_w=1, max_w=4
        )
        ctl.policy.idle_polls_to_scale_down = 2
        now = 1_000_000
        events = ctl.step(now_ms=now)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "leader" and not events[0]["takeover"]
        assert ctl.state == "leader" and ctl.fencing_token == 1
        want = min(4, max(1, math.ceil(backlog / 4)))
        assert kinds.count("spawn") == want
        assert events[-1]["backlog"] == backlog
        # the backlog drains (a worker produced everything): after the
        # idle-poll debounce the fleet returns to min
        ScanPlaneWorker(catalog, spool_dir, lease_ttl_s=10).poll_once()
        ctl.step(now_ms=now + 1000)
        events = ctl.step(now_ms=now + 2000)
        assert ctl.spawner.count == 1
        if want > 1:
            assert any(e["event"] == "retire" for e in events)
        ctl.stop()

    def test_sigkilled_worker_backfilled_next_tick(self, tmp_path, monkeypatch):
        monkeypatch.delenv(obs_fleet.ENV_SPOOL, raising=False)
        catalog, _ = _make_table(tmp_path, rows=6000)
        spool_dir = str(tmp_path / "spool")
        ScanSession.plan(catalog, {"table": "t"}).publish(spool_dir)
        ctl = self._controller(
            catalog.client.store, spool_dir, cid="A", min_w=2, max_w=4
        )
        now = 1_000_000
        ctl.step(now_ms=now)
        had = ctl.spawner.count
        assert had >= 2
        ctl.spawner.kill_one()  # SIGKILL from outside
        events = ctl.step(now_ms=now + 1000)
        kinds = [e["event"] for e in events]
        assert "worker_exit" in kinds and "spawn" in kinds
        assert ctl.spawner.count == had
        ctl.stop()

    def test_fenced_takeover_bumps_token_and_demotes_zombie(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.delenv(obs_fleet.ENV_SPOOL, raising=False)
        catalog, _ = _make_table(tmp_path, rows=4000)
        spool_dir = str(tmp_path / "spool")
        store = catalog.client.store
        a = self._controller(store, spool_dir, cid="A", ttl_s=10.0)
        b = self._controller(store, spool_dir, cid="B", ttl_s=10.0)
        assert a.key == b.key == lease_key(spool_dir)
        t0 = 1_000_000
        assert a.step(now_ms=t0)[0]["event"] == "leader"
        # B contends while A's lease is live: standby, nothing spawned
        events = b.step(now_ms=t0 + 500)
        assert events == [{"event": "standby", "controller": "B"}]
        assert b.spawner.count == 0
        # A goes silent (SIGKILL emulated: no renewals); one TTL later B
        # takes the lease over with a BUMPED fencing token
        events = b.step(now_ms=t0 + 10_001)
        assert events[0]["event"] == "leader"
        assert events[0]["takeover"] is True and events[0]["fence"] == 2
        assert b.state == "leader" and b.spawner.count >= 1
        # the zombie wakes: its renewal fails against the bumped token —
        # it demotes itself and retires its own children
        a_children = a.spawner.count
        assert a_children >= 1
        events = a.step(now_ms=t0 + 10_500)
        assert events == [{"event": "fenced", "controller": "A"}]
        assert a.state == "standby" and a.fencing_token is None
        assert a.spawner.count == 0 and a.spawner.stopped >= 1
        # B keeps leading undisturbed
        assert b.step(now_ms=t0 + 11_000)[-1]["state"] == "leader"
        b.stop()
        a.stop()

    def test_stop_releases_lease_for_immediate_successor(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.delenv(obs_fleet.ENV_SPOOL, raising=False)
        catalog, _ = _make_table(tmp_path, rows=4000)
        spool_dir = str(tmp_path / "spool")
        store = catalog.client.store
        a = self._controller(store, spool_dir, cid="A")
        t0 = 1_000_000
        a.step(now_ms=t0)
        a.stop()  # clean shutdown: release, don't wait out the TTL
        b = self._controller(store, spool_dir, cid="B")
        events = b.step(now_ms=t0 + 100)
        assert events[0]["event"] == "leader"
        b.stop()


class TestWorkerSpawner:
    def test_worker_argv_is_the_real_entry(self, tmp_path):
        sp = WorkerSpawner(
            str(tmp_path / "wh"), str(tmp_path / "spool"),
            db_path=str(tmp_path / "meta.db"), lease_ttl_s=2.0, poll_s=0.05,
        )
        argv = sp.worker_argv("fleet-1-1")
        assert argv[1:4] == ["-m", "lakesoul_tpu.scanplane", "worker"]
        assert "--worker-id" in argv and "fleet-1-1" in argv
        assert "--lease-ttl-s" in argv and "2.0" in argv
        assert "--db-path" in argv

    def test_retire_parks_child_for_reap_not_zombie(self, tmp_path):
        """PIN (boundedness pack): retire() must not drop the terminated
        handle — the child goes to _retiring, reap() collects the exit
        status (no zombie), and a retired exit is never a deficit."""
        import sys as _sys
        import time as _time

        sp = WorkerSpawner(str(tmp_path / "wh"), str(tmp_path / "spool"))
        sp.worker_argv = lambda worker_id: [
            _sys.executable, "-c", "import time; time.sleep(60)",
        ]
        sp.spawn()
        child = sp._children[0]
        assert sp.retire() == {"pid": child.pid}
        assert sp._retiring == [child] and sp.count == 0
        deadline = _time.monotonic() + 10.0
        deficit: list = []
        while sp._retiring and _time.monotonic() < deadline:
            deficit += sp.reap()
            _time.sleep(0.05)
        assert sp._retiring == [] and child.returncode is not None
        assert deficit == []  # the controller asked it to leave


# ------------------------------------------------------------- multihost


class TestProcessAxis:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv(multihost.ENV_INDEX, "1")
        monkeypatch.setenv(multihost.ENV_COUNT, "3")
        assert multihost.process_axis() == (1, 3)

    def test_env_vars_required_together(self, monkeypatch):
        monkeypatch.setenv(multihost.ENV_INDEX, "1")
        monkeypatch.delenv(multihost.ENV_COUNT, raising=False)
        with pytest.raises(ConfigError, match="must be set together"):
            multihost.process_axis()

    @pytest.mark.parametrize("idx,cnt", [("x", "2"), ("0", "y")])
    def test_non_integer_rejected(self, monkeypatch, idx, cnt):
        monkeypatch.setenv(multihost.ENV_INDEX, idx)
        monkeypatch.setenv(multihost.ENV_COUNT, cnt)
        with pytest.raises(ConfigError, match="non-integer"):
            multihost.process_axis()

    @pytest.mark.parametrize("idx,cnt", [("3", "3"), ("-1", "2"), ("0", "0")])
    def test_out_of_range_rejected(self, monkeypatch, idx, cnt):
        monkeypatch.setenv(multihost.ENV_INDEX, idx)
        monkeypatch.setenv(multihost.ENV_COUNT, cnt)
        with pytest.raises(ConfigError, match="invalid process axis"):
            multihost.process_axis()

    def test_single_host_default(self, monkeypatch):
        monkeypatch.delenv(multihost.ENV_INDEX, raising=False)
        monkeypatch.delenv(multihost.ENV_COUNT, raising=False)
        assert multihost.process_axis() == (0, 1)


class TestShardScan:
    def test_applies_axis_and_passes_through(self, tmp_path, monkeypatch):
        _, t = _make_table(tmp_path, rows=4000)
        monkeypatch.setenv(multihost.ENV_INDEX, "1")
        monkeypatch.setenv(multihost.ENV_COUNT, "3")
        sharded = multihost.shard_scan(t.scan())
        assert (sharded._rank, sharded._world) == (1, 3)
        # a scan already sharded CONSISTENTLY passes through untouched
        pre = t.scan().shard(1, 3)
        assert multihost.shard_scan(pre) is pre
        # an inconsistent explicit shard is a loud configuration conflict
        with pytest.raises(ConfigError, match="already sharded"):
            multihost.shard_scan(t.scan().shard(0, 3))

    def test_single_host_is_identity(self, tmp_path, monkeypatch):
        _, t = _make_table(tmp_path, rows=4000)
        monkeypatch.delenv(multihost.ENV_INDEX, raising=False)
        monkeypatch.delenv(multihost.ENV_COUNT, raising=False)
        scan = t.scan()
        assert multihost.shard_scan(scan) is scan


class TestMultihostIter:
    def test_ranks_disjoint_and_union_complete(self, tmp_path, monkeypatch):
        _, t = _make_table(tmp_path, rows=8000)
        all_ids = set()
        for b in t.scan().to_batches():
            all_ids.update(b.column("id").to_pylist())
        world = 3
        per_rank = []
        for rank in range(world):
            monkeypatch.setenv(multihost.ENV_INDEX, str(rank))
            monkeypatch.setenv(multihost.ENV_COUNT, str(world))
            ids = []
            it = t.scan().batch_size(2048).to_jax_iter(
                multihost=True, drop_remainder=False
            )
            for batch in it:
                ids.extend(np.asarray(batch["id"]).tolist())
            # the emulated rank matches a plain single-process shard scan
            want = []
            for b in t.scan().batch_size(2048).shard(rank, world).to_batches():
                want.extend(b.column("id").to_pylist())
            assert ids == want
            per_rank.append(set(ids))
        union = set().union(*per_rank)
        assert union == all_ids
        for i in range(world):
            for j in range(i + 1, world):
                assert per_rank[i].isdisjoint(per_rank[j])

    def test_conflicting_explicit_shard_raises(self, tmp_path, monkeypatch):
        _, t = _make_table(tmp_path, rows=4000)
        monkeypatch.setenv(multihost.ENV_INDEX, "0")
        monkeypatch.setenv(multihost.ENV_COUNT, "2")
        with pytest.raises(ConfigError, match="already sharded"):
            t.scan().shard(1, 2).to_jax_iter(multihost=True)
