"""Process-level chaos for the fleet plane (slow tier; the deterministic
unit machine of the same controller runs in test_fleet.py).

Two emulated hosts front one warehouse + spool fabric: each host is a
real gateway process (``python -m lakesoul_tpu.scanplane service``) plus
one trainer rank (``python -m lakesoul_tpu.fleet train`` under
``LAKESOUL_FLEET_PROCESS_INDEX/_COUNT``); the worker fleet is owned by a
real autoscaler process (``python -m lakesoul_tpu.fleet autoscale``)
emitting JSON-line events.  The acceptance contract, proven by SIGKILL:

- kill one host's gateway AND one autoscaler-owned worker mid-run → the
  surviving rank completes with **exactly-once** delivery (sha-identical
  to the single-process shard scan), the autoscaler notices the dead
  worker and backfills it within ~one controller poll + worker boot;
- the orphaned rank relaunched against the surviving gateway completes
  the SAME session exactly-once — the spool fabric, not the gateway,
  owns delivered state.

Everything killed here is the REAL deployed entry point — what is
tested is what deploys."""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("p", pa.string())])
TTL_S = 2.0
WORLD = 2
BATCH = 4096

pytestmark = pytest.mark.slow


def _child_env(**extra) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "LAKESOUL_RETRY_SEED": "7",
        "LAKESOUL_RETRY_CAP_S": "0.5",
    })
    env.update(extra)
    return env


def _spawn(argv, **extra_env) -> subprocess.Popen:
    return subprocess.Popen(
        argv, env=_child_env(**extra_env), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _gateway(wh, db, spool) -> "tuple[subprocess.Popen, str]":
    proc = _spawn([
        sys.executable, "-m", "lakesoul_tpu.scanplane", "service",
        "--warehouse", wh, "--db-path", db, "--spool", spool,
        "--workers", "0",  # serve only: the autoscaler owns the fleet
    ])
    handle = proc.stdout.readline()
    if not handle:
        _, err = proc.communicate(timeout=10.0)
        pytest.fail(f"gateway died before printing its handle: {err[-2000:]}")
    return proc, json.loads(handle)["location"]


def _trainer(wh, db, location, rank) -> subprocess.Popen:
    return _spawn(
        [
            sys.executable, "-m", "lakesoul_tpu.fleet", "train",
            "--warehouse", wh, "--db-path", db, "--table", "t",
            "--batch-size", str(BATCH), "--location", location,
        ],
        LAKESOUL_FLEET_PROCESS_INDEX=str(rank),
        LAKESOUL_FLEET_PROCESS_COUNT=str(WORLD),
    )


def _expected_sha(catalog, rank) -> "tuple[str, int]":
    """The trainer role's collated-host-array hash, computed in-process
    over a plain ``scan.shard(rank, world)`` — the exactly-once oracle."""
    from lakesoul_tpu.fleet.multihost import digest_batch

    digest = hashlib.sha256()
    rows = 0
    it = catalog.scan("t").batch_size(BATCH).shard(rank, WORLD).to_jax_iter(
        device_put=False, drop_remainder=False
    )
    for batch in it:
        rows += digest_batch(digest, batch)
    return digest.hexdigest(), rows


class TestKillAHost:
    def test_surviving_rank_exactly_once_and_backfill(self, tmp_path):
        wh, db = str(tmp_path / "wh"), str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", SCHEMA, primary_keys=["id"], range_partitions=["p"],
            hash_bucket_num=2,
        )
        rng = np.random.default_rng(3)
        for _ in range(3):
            for part, base in (("a", 0.0), ("b", 1000.0)):
                ids = np.sort(
                    rng.choice(40_000, 12_000, replace=False)
                ).astype(np.int64)
                t.upsert(pa.table({
                    "id": ids,
                    "v": base + rng.normal(size=len(ids)),
                    "p": np.repeat(part, len(ids)),
                }, schema=SCHEMA))
        spool = str(tmp_path / "spool")
        os.makedirs(spool)

        events: list[dict] = []
        procs: list[subprocess.Popen] = []
        worker_pids: set[int] = set()
        try:
            gw_a, loc_a = _gateway(wh, db, spool)
            procs.append(gw_a)
            gw_b, loc_b = _gateway(wh, db, spool)
            procs.append(gw_b)

            scaler = _spawn([
                sys.executable, "-m", "lakesoul_tpu.fleet", "autoscale",
                "--warehouse", wh, "--db-path", db, "--spool", spool,
                "--min-workers", "2", "--max-workers", "4",
                "--lease-ttl-s", str(TTL_S), "--poll-s", "0.1",
                "--worker-lease-ttl-s", str(TTL_S), "--worker-poll-s", "0.05",
            ])
            procs.append(scaler)

            def pump():
                for line in scaler.stdout:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "spawn":
                        worker_pids.add(ev["pid"])
                    events.append(ev)

            threading.Thread(target=pump, daemon=True).start()

            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and len(worker_pids) < 2:
                if scaler.poll() is not None:
                    _, err = scaler.communicate(timeout=10.0)
                    pytest.fail(f"autoscaler exited early: {err[-2000:]}")
                time.sleep(0.05)
            assert len(worker_pids) >= 2, "autoscaler never reached min fleet"

            # one trainer rank per emulated host, each on its own gateway
            rank0 = _trainer(wh, db, loc_a, 0)
            procs.append(rank0)
            rank1 = _trainer(wh, db, loc_b, 1)
            procs.append(rank1)

            # let the run get properly underway (session published, some
            # ranges in flight), then KILL host B: its gateway and one
            # autoscaler-owned worker, the most destructive pair
            time.sleep(1.0)
            victim_pid = sorted(worker_pids)[0]
            gw_b.send_signal(signal.SIGKILL)
            os.kill(victim_pid, signal.SIGKILL)
            killed_at = time.monotonic()

            # the autoscaler notices the SIGKILLed child and backfills:
            # a worker_exit for the victim followed by a fresh spawn
            deadline = time.monotonic() + TTL_S + 30.0
            backfilled_at = None
            while time.monotonic() < deadline and backfilled_at is None:
                snap = list(events)
                for i, ev in enumerate(snap):
                    if ev.get("event") == "worker_exit" \
                            and ev.get("pid") == victim_pid:
                        if any(e.get("event") == "spawn" for e in snap[i + 1:]):
                            backfilled_at = time.monotonic()
                            break
                time.sleep(0.05)
            assert backfilled_at is not None, (
                "autoscaler never backfilled the SIGKILLed worker:"
                f" {events[-10:]}"
            )
            # reap-and-respawn is one control tick; the TTL bounds even a
            # worst-case controller that was itself mid-failover
            assert backfilled_at - killed_at < TTL_S + 10.0

            # the surviving rank completes exactly-once
            out0, err0 = rank0.communicate(timeout=180.0)
            assert rank0.returncode == 0, err0[-2000:]
            doc0 = json.loads(out0.strip().splitlines()[-1])
            want_sha0, want_rows0 = _expected_sha(catalog, 0)
            assert doc0["rows"] == want_rows0
            assert doc0["sha256"] == want_sha0
            assert doc0["process_index"] == 0
            assert doc0["process_count"] == WORLD

            # the orphaned rank: its gateway is gone.  Whether it died
            # mid-stream or never connected, relaunching it against the
            # SURVIVING gateway must complete the same session
            # exactly-once — delivered state lives in the spool fabric
            try:
                out1, _ = rank1.communicate(timeout=60.0)
            except subprocess.TimeoutExpired:
                rank1.kill()
                rank1.communicate(timeout=10.0)
                out1 = ""
            doc1 = None
            if rank1.returncode == 0 and out1.strip():
                doc1 = json.loads(out1.strip().splitlines()[-1])
            if doc1 is None:
                relaunched = _trainer(wh, db, loc_a, 1)
                procs.append(relaunched)
                out1, err1 = relaunched.communicate(timeout=180.0)
                assert relaunched.returncode == 0, err1[-2000:]
                doc1 = json.loads(out1.strip().splitlines()[-1])
            want_sha1, want_rows1 = _expected_sha(catalog, 1)
            assert doc1["rows"] == want_rows1
            assert doc1["sha256"] == want_sha1
            assert want_rows0 + want_rows1 == t.scan().count_rows()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(10.0)
                except subprocess.TimeoutExpired:
                    p.kill()
            # the autoscaler's SIGTERM death skips stop_all: sweep its
            # orphaned worker children directly
            for pid in worker_pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
