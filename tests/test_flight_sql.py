"""Arrow Flight SQL protocol tests (VERDICT r3 missing #1).

No ADBC driver ships in the image, so parity is proven at the protocol
level: the client half of these tests builds the exact Any-wrapped protobuf
messages a conformant ADBC/JDBC driver puts on the wire
(arrow.flight.protocol.sql package, public Apache Arrow spec) and drives the
standard Flight RPCs — GetFlightInfo/DoGet for queries, DoPut for
updates/ingest/bind, DoAction for prepared statements.
"""

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service import _flight_sql_pb2 as pb
from lakesoul_tpu.service.flight_sql import (
    FlightSqlClient,
    LakeSoulFlightSqlServer,
    bind_parameters,
    _pack,
)

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def server(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("orders", SCHEMA, primary_keys=["id"])
    t.write_arrow(pa.table({"id": np.arange(10), "v": np.arange(10) * 1.0}))
    srv = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0")
    yield srv, catalog
    srv.shutdown()


@pytest.fixture()
def client(server):
    srv, _ = server
    c = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}")
    yield c
    c.close()


class TestStatementQuery:
    def test_select_round_trip(self, client):
        out = client.execute("SELECT id, v FROM orders WHERE id < 3")
        assert out.num_rows == 3
        assert sorted(out.column("id").to_pylist()) == [0, 1, 2]

    def test_aggregate(self, client):
        out = client.execute("SELECT sum(v) AS s FROM orders")
        assert out.column("s").to_pylist() == [45.0]

    def test_ticket_is_one_shot(self, server):
        srv, _ = server
        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        desc = flight.FlightDescriptor.for_command(
            _pack(pb.CommandStatementQuery(query="SELECT count(*) AS c FROM orders"))
        )
        info = raw.get_flight_info(desc)
        ticket = info.endpoints[0].ticket
        assert raw.do_get(ticket).read_all().column("c").to_pylist() == [10]
        with pytest.raises(flight.FlightError, match="expired"):
            raw.do_get(ticket).read_all()
        raw.close()

    def test_command_as_ticket_direct(self, server):
        """Liberal server: DoGet accepts the command itself as a ticket."""
        srv, _ = server
        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        t = raw.do_get(
            flight.Ticket(
                _pack(pb.CommandStatementQuery(query="SELECT count(*) AS c FROM orders"))
            )
        ).read_all()
        assert t.column("c").to_pylist() == [10]
        raw.close()

    def test_flight_info_reports_schema_and_rows(self, server):
        srv, _ = server
        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        desc = flight.FlightDescriptor.for_command(
            _pack(pb.CommandStatementQuery(query="SELECT id FROM orders"))
        )
        info = raw.get_flight_info(desc)
        assert info.schema.names == ["id"]
        assert info.total_records == 10
        schema_result = raw.get_schema(
            flight.FlightDescriptor.for_command(
                _pack(pb.CommandStatementQuery(query="SELECT v FROM orders"))
            )
        )
        assert schema_result.schema.names == ["v"]
        raw.close()

    def test_json_dialect_still_served(self, server):
        from lakesoul_tpu.service.flight import LakeSoulFlightClient

        srv, _ = server
        c = LakeSoulFlightClient(f"grpc://127.0.0.1:{srv.port}")
        out = c.scan("orders")
        assert out.num_rows == 10


class TestStatementUpdate:
    def test_insert_reports_count(self, client):
        n = client.execute_update("INSERT INTO orders VALUES (100, 1.5), (101, 2.5)")
        assert n == 2
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [12]

    def test_update_and_delete_counts(self, client):
        assert client.execute_update("UPDATE orders SET v = 0 WHERE id < 4") == 4
        assert client.execute_update("DELETE FROM orders WHERE id >= 8") == 2
        out = client.execute("SELECT sum(v) AS s FROM orders")
        assert out.column("s").to_pylist() == [4.0 + 5 + 6 + 7]


class TestIngest:
    def test_ingest_append_existing(self, client):
        data = pa.table({"id": np.arange(20, 25), "v": np.ones(5)})
        assert client.ingest("orders", data) == 5
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [15]

    def test_ingest_creates_missing_table(self, client):
        data = pa.table({"a": [1, 2, 3]})
        assert client.ingest("fresh", data, primary_keys=["a"]) == 3
        out = client.execute("SELECT count(*) AS c FROM fresh")
        assert out.column("c").to_pylist() == [3]

    def test_ingest_transaction_id_exactly_once(self, client):
        data = pa.table({"id": np.arange(30, 33), "v": np.zeros(3)})
        txn = b"job-7:epoch-3"
        assert client.ingest("orders", data, transaction_id=txn) == 3
        # replay with the same transaction id must not duplicate rows
        client.ingest("orders", data, transaction_id=txn)
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [13]

    def test_ingest_replace(self, client):
        data = pa.table({"id": np.arange(3), "v": np.zeros(3)})
        client.ingest("scratch", data)
        assert client.ingest("scratch", data, mode="replace") == 3
        out = client.execute("SELECT count(*) AS c FROM scratch")
        assert out.column("c").to_pylist() == [3]

    def test_ingest_replace_preserves_structure(self, client, server):
        """REPLACE swaps the data, not the table's nature: primary keys and
        bucketing survive, so post-replace upserts still merge-on-read."""
        _, catalog = server
        data = pa.table({"id": np.arange(3), "v": np.zeros(3)})
        client.ingest("orders", data, mode="replace")
        info = catalog.table("orders").info
        assert info.primary_keys == ["id"]
        # upsert the same keys: merge-on-read dedups instead of duplicating
        client.ingest("orders", pa.table({"id": np.arange(3), "v": np.ones(3)}))
        out = client.execute("SELECT count(*) AS c, sum(v) AS s FROM orders")
        assert out.column("c").to_pylist() == [3]
        assert out.column("s").to_pylist() == [3.0]

    def test_ingest_fail_mode(self, client):
        data = pa.table({"id": np.arange(3), "v": np.zeros(3)})
        with pytest.raises(flight.FlightError, match="already exists"):
            client.ingest("orders", data, mode="fail")


class TestPreparedStatements:
    def test_prepare_execute_close(self, client):
        handle = client.prepare("SELECT id, v FROM orders WHERE id < 5")
        out = client.execute_prepared(handle)
        assert out.num_rows == 5
        # repeat execution sees fresh data
        client.execute_update("DELETE FROM orders WHERE id = 0")
        out = client.execute_prepared(handle)
        assert out.num_rows == 4
        client.close_prepared(handle)
        with pytest.raises(flight.FlightError, match="unknown prepared"):
            client.execute_prepared(handle)

    def test_parameter_binding(self, client):
        handle = client.prepare("SELECT v FROM orders WHERE id = ?")
        out = client.execute_prepared(handle, params=[7])
        assert out.column("v").to_pylist() == [7.0]
        out = client.execute_prepared(handle, params=[3])
        assert out.column("v").to_pylist() == [3.0]
        client.close_prepared(handle)

    def test_create_returns_dataset_schema(self, server):
        srv, _ = server
        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        action = flight.Action(
            "CreatePreparedStatement",
            _pack(pb.ActionCreatePreparedStatementRequest(query="SELECT id FROM orders")),
        )
        body = list(raw.do_action(action))[0].body.to_pybytes()
        from lakesoul_tpu.service.flight_sql import _unpack

        name, msg = _unpack(body)
        assert name == "ActionCreatePreparedStatementResult"
        schema = pa.ipc.read_schema(pa.py_buffer(msg.dataset_schema))
        assert schema.names == ["id"]
        raw.close()


class TestMetadataCommands:
    def test_catalogs_schemas_table_types(self, client):
        assert client.get_catalogs().column("catalog_name").to_pylist() == ["lakesoul"]
        schemas = client.get_db_schemas()
        assert "default" in schemas.column("db_schema_name").to_pylist()
        assert client.get_table_types().column("table_type").to_pylist() == ["TABLE"]

    def test_get_tables_with_pattern_and_schema(self, client):
        t = client.get_tables(table_pattern="ord%")
        assert t.column("table_name").to_pylist() == ["orders"]
        t = client.get_tables(include_schema=True)
        row = t.column("table_name").to_pylist().index("orders")
        schema = pa.ipc.read_schema(
            pa.py_buffer(t.column("table_schema").to_pylist()[row])
        )
        assert schema.names == ["id", "v"]

    def test_primary_keys(self, client):
        pk = client.get_primary_keys("orders")
        assert pk.column("column_name").to_pylist() == ["id"]
        assert pk.column("key_sequence").to_pylist() == [1]

    def test_sql_info(self, client):
        info = client.get_sql_info()
        names = info.column("info_name").to_pylist()
        assert 0 in names  # FLIGHT_SQL_SERVER_NAME
        values = info.column("value")
        idx = names.index(0)
        assert values[idx].as_py() == "lakesoul_tpu"
        ro = values[names.index(3)].as_py()
        assert ro is False


class TestAuth:
    def test_jwt_enforced_on_flight_sql_paths(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("sec", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        srv = LakeSoulFlightSqlServer(
            catalog, "grpc://127.0.0.1:0", jwt_secret="s3cr3t"
        )
        try:
            anon = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}")
            with pytest.raises(flight.FlightError, match="[Uu]nauthenticated|authorization"):
                anon.execute("SELECT * FROM sec")
            anon.close()
            token = srv.jwt_server.create_token(
                __import__("lakesoul_tpu.service.jwt", fromlist=["Claims"]).Claims(
                    sub="alice", group="public"
                )
            )
            ok = FlightSqlClient(f"grpc://127.0.0.1:{srv.port}", token=token)
            assert ok.execute("SELECT count(*) AS c FROM sec").column("c").to_pylist() == [1]
            ok.close()
        finally:
            srv.shutdown()


class TestBindParameters:
    def test_placeholders_outside_strings_only(self):
        q = bind_parameters("SELECT * FROM t WHERE a = ? AND b = 'x?y' AND c = ?", None,
                            [1, "it's"])
        assert q == "SELECT * FROM t WHERE a = 1 AND b = 'x?y' AND c = 'it''s'"

    def test_too_few_params(self):
        with pytest.raises(flight.FlightError, match="1 parameter"):
            bind_parameters("SELECT ?", None, [])


class TestTransactions:
    """BeginTransaction / EndTransaction actions (VERDICT r4 item 3): the
    flow an ADBC driver with autocommit=False puts on the wire — begin →
    ingest (staged) → commit publishes; rollback leaves no committed rows.
    Reference: flight_sql_service.rs:1044-1082."""

    def test_begin_ingest_commit(self, client):
        txn = client.begin_transaction()
        assert isinstance(txn, bytes) and len(txn) == 16
        data = pa.table({"id": np.arange(50, 55), "v": np.ones(5)})
        assert client.ingest("orders", data, transaction_id=txn) == 5
        # staged, not visible before commit
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [10]
        client.commit(txn)
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [15]

    def test_rollback_leaves_no_rows(self, client, server):
        import os

        _, catalog = server
        root = catalog.table("orders").info.table_path
        before = {
            f for _, _, files in os.walk(root) for f in files
        }
        txn = client.begin_transaction()
        data = pa.table({"id": np.arange(60, 70), "v": np.zeros(10)})
        client.ingest("orders", data, transaction_id=txn)
        client.rollback(txn)
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [10]
        # staged files are deleted, not orphaned
        after = {
            f for _, _, files in os.walk(root) for f in files
        }
        assert after == before

    def test_multi_table_transaction(self, client):
        txn = client.begin_transaction()
        client.ingest("orders", pa.table({"id": [90], "v": [1.0]}),
                      transaction_id=txn)
        client.ingest("fresh_tx", pa.table({"a": [1, 2]}), transaction_id=txn)
        client.commit(txn)
        assert client.execute("SELECT count(*) AS c FROM orders") \
            .column("c").to_pylist() == [11]
        assert client.execute("SELECT count(*) AS c FROM fresh_tx") \
            .column("c").to_pylist() == [2]

    def test_commit_unknown_transaction(self, client):
        with pytest.raises(flight.FlightError, match="unknown or expired"):
            client.commit(b"nope-nope-nope!!")

    def test_transaction_gone_after_end(self, client):
        txn = client.begin_transaction()
        client.commit(txn)
        with pytest.raises(flight.FlightError, match="unknown or expired"):
            client.rollback(txn)

    def test_non_minted_transaction_id_keeps_idempotent_path(self, client):
        """A transaction_id NOT minted by BeginTransaction keeps its
        pre-existing meaning: per-statement commit with replay dedup."""
        data = pa.table({"id": np.arange(70, 73), "v": np.zeros(3)})
        assert client.ingest("orders", data, transaction_id=b"ext:epoch9") == 3
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [13]  # committed immediately

    def test_replace_within_transaction(self, client, server):
        _, catalog = server
        before = catalog.table("orders").info.table_id
        txn = client.begin_transaction()
        client.ingest("orders", pa.table({"id": [1], "v": [9.0]}),
                      mode="replace", transaction_id=txn)
        # old content visible until commit
        assert client.execute("SELECT count(*) AS c FROM orders") \
            .column("c").to_pylist() == [10]
        client.commit(txn)
        out = client.execute("SELECT id, v FROM orders")
        assert out.column("id").to_pylist() == [1]
        assert out.column("v").to_pylist() == [9.0]
        assert catalog.table("orders").info.table_id == before

    def test_listed_actions(self, server):
        srv, _ = server
        raw = flight.FlightClient(f"grpc://127.0.0.1:{srv.port}")
        kinds = {a.type for a in raw.list_actions()}
        assert {"BeginTransaction", "EndTransaction"} <= kinds
        raw.close()

    def test_ingest_on_ended_transaction_rejected(self, client):
        """An ingest replaying an ENDED minted transaction id must error,
        not silently fall through to the autocommit path."""
        txn = client.begin_transaction()
        client.commit(txn)
        with pytest.raises(flight.FlightError, match="already ended"):
            client.ingest("orders", pa.table({"id": [1], "v": [0.0]}),
                          transaction_id=txn)
        out = client.execute("SELECT count(*) AS c FROM orders")
        assert out.column("c").to_pylist() == [10]

    def test_open_transaction_cap_rejects_new_begins(self, client):
        """At the cap the server refuses NEW transactions instead of
        evicting (and destroying) someone else's live staged data."""
        from lakesoul_tpu.service import flight_sql as mod

        old = mod._TXN_CAP
        mod._TXN_CAP = 3
        try:
            txns = [client.begin_transaction() for _ in range(3)]
            with pytest.raises(flight.FlightError, match="too many open"):
                client.begin_transaction()
            client.rollback(txns[0])
            client.begin_transaction()  # capacity freed
        finally:
            mod._TXN_CAP = old

    def test_closed_transaction_ingest_creates_no_table(self, client, server):
        """Replaying a CLOSED minted id must error BEFORE any side effect —
        no table creation (high-review r5)."""
        _, catalog = server
        txn = client.begin_transaction()
        client.commit(txn)
        with pytest.raises(flight.FlightError, match="already ended"):
            client.ingest("ghost_tbl", pa.table({"a": [1]}), transaction_id=txn)
        assert "ghost_tbl" not in catalog.list_tables("default")
