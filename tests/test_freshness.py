"""Freshness layer: SLO evaluation math, the retry-hardened follower's
exactly-once resume contract (including across a compaction that rewrites
the files the recorded units point at), the notifier's failure isolation,
and the ``to_jax_iter(follow=...)`` training-source seam."""

from __future__ import annotations

import os
import threading
import time

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError
from lakesoul_tpu.freshness import (
    FollowBatchSource,
    FollowerState,
    FreshFollower,
    SloMonitor,
    ThroughputSlo,
)
from lakesoul_tpu.meta.entity import now_millis
from lakesoul_tpu.runtime import faults
from lakesoul_tpu.runtime.resilience import RetryPolicy

SCHEMA = pa.schema([("id", pa.int64()), ("seq", pa.int64()), ("v", pa.float64())])


@pytest.fixture
def catalog(tmp_path):
    return LakeSoulCatalog(
        str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
    )


def _commit(table, base: int, n: int) -> None:
    table.upsert(pa.table({
        "id": list(range(base, base + n)),
        "seq": list(range(base, base + n)),
        "v": [float(base + i) for i in range(n)],
    }, schema=SCHEMA))


def _rows(batches) -> list[int]:
    return [s for b in batches for s in b.column("seq").to_pylist()]


def _drain(follower) -> list[int]:
    return _rows(follower.iter_batches())


def _fast_policy(attempts: int = 10) -> RetryPolicy:
    return RetryPolicy(
        max_attempts=attempts, base_delay_s=0.001, max_delay_s=0.01, seed=7
    )


# ------------------------------------------------------------------- slo


class TestSloMonitor:
    def test_target_and_budget_accounting(self):
        m = SloMonitor(target_s=1.0, budget_fraction=0.5, slo="t1")
        for lat in (0.1, 0.2, 2.0, 0.3):
            m.observe(lat)
        snap = m.snapshot()
        assert snap["count"] == 4 and snap["violations"] == 1
        assert snap["allowed_violations"] == 2 and snap["in_budget"]
        m.observe(3.0)
        m.observe(4.0)
        # floor semantics: 6 observations x 0.5 = 3 allowed, 3 violations
        assert m.snapshot()["budget_remaining"] == 0 and m.in_budget()
        m.observe(9.0)  # 4 violations > floor(7 x 0.5) = 3: budget burned
        assert not m.in_budget()

    def test_violations_hit_the_labeled_counter(self):
        from lakesoul_tpu.obs import registry

        before = registry().counter(
            "lakesoul_slo_violations_total", slo="t2"
        ).value
        m = SloMonitor(target_s=0.5, slo="t2")
        m.observe(0.1)
        m.observe(1.5)
        after = registry().counter(
            "lakesoul_slo_violations_total", slo="t2"
        ).value
        assert after - before == 1

    def test_percentiles_are_exact_over_reservoir(self):
        m = SloMonitor(target_s=100.0, slo="t3")
        for i in range(100):
            m.observe(i / 100.0)
        snap = m.snapshot()
        assert snap["p50_s"] == pytest.approx(0.50, abs=0.02)
        assert snap["p99_s"] == pytest.approx(0.98, abs=0.02)
        assert snap["max_s"] == pytest.approx(0.99)

    def test_observe_commit_skips_unknown_timestamps(self):
        m = SloMonitor(target_s=1.0, slo="t4")
        assert m.observe_commit(0) == -1.0
        assert m.snapshot()["count"] == 0
        lat = m.observe_commit(now_millis() - 250)
        assert 0.2 <= lat <= 5.0
        assert m.snapshot()["count"] == 1

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("LAKESOUL_FRESHNESS_SLO_S", "3.5")
        monkeypatch.setenv("LAKESOUL_FRESHNESS_BUDGET", "0.25")
        m = SloMonitor(slo="t5")
        assert m.target_s == 3.5 and m.budget_fraction == 0.25

    def test_throughput_slo(self):
        s = ThroughputSlo(1.0, slo="tp1")
        s.start()
        s.add_rows(10_000)
        out = s.evaluate()
        assert out["ok"] and out["rows"] == 10_000
        slow = ThroughputSlo(1e12, slo="tp2")
        slow.start()
        slow.add_rows(1)
        time.sleep(0.01)
        assert not slow.evaluate()["ok"]

    def test_histogram_quantile_estimate(self):
        from lakesoul_tpu.obs.metrics import Histogram

        h = Histogram("lakesoul_test_q_seconds", buckets=(0.1, 1.0, 10.0))
        assert h.quantile(0.5) == 0.0  # empty
        for _ in range(90):
            h.observe(0.05)
        for _ in range(10):
            h.observe(5.0)
        assert h.quantile(0.5) <= 0.1
        assert 1.0 <= h.quantile(0.99) <= 10.0
        with pytest.raises(ValueError):
            h.quantile(1.5)


# -------------------------------------------------------------- follower


class TestFollowerExactlyOnce:
    def test_state_resume_is_row_identical(self, catalog):
        """Kill a follower mid-stream, restart from persisted state:
        concatenated delivery == an uninterrupted follow — no dup, no gap."""
        t = catalog.create_table("f1", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        start = now_millis() - 1
        for c in range(4):
            _commit(t, c * 10, 10)

        oracle = _drain(FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        ))
        assert len(oracle) == 40

        f1 = FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        )
        got: list[int] = []
        it = f1.iter_batches()
        for i, b in enumerate(it):
            got.extend(b.column("seq").to_pylist())
            if i == 1:
                state = f1.state_json()  # persisted next to the checkpoint
                break
        it.close()  # the "kill"
        f2 = FreshFollower(
            t.scan().batch_size(7),
            state=FollowerState.from_json(state),
            poll_interval=0.01, max_polls=3,
        )
        got += _drain(f2)
        assert got == oracle

    def test_resume_survives_compaction_rewriting_files(self, catalog):
        """The recorded pending units reference pre-compaction files; a
        compaction between kill and restart rewrites the table but the old
        files stay on disk until the cleaner runs — the resumed delivery
        is still row-identical, and the post-compaction commit arrives
        exactly once."""
        t = catalog.create_table("f2", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        start = now_millis() - 1
        for c in range(4):
            _commit(t, c * 10, 10)

        f1 = FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        )
        got: list[int] = []
        it = f1.iter_batches()
        for i, b in enumerate(it):
            got.extend(b.column("seq").to_pylist())
            if i == 1:
                state = f1.state_json()
                break
        it.close()

        # between kill and restart: a compaction rewrites every file the
        # cursors/pending units point at, then one more commit lands
        assert t.compact() == 1
        _commit(t, 40, 10)

        f2 = FreshFollower(
            t.scan().batch_size(7),
            state=FollowerState.from_json(state),
            poll_interval=0.01, max_polls=3,
        )
        got += _drain(f2)
        # no dup, no gap: every written row exactly once (delivery order
        # across polls may group differently; the multiset must not)
        assert sorted(got) == list(range(50))
        assert len(got) == 50

    def test_lagged_consumer_resume_state(self, catalog):
        """resume_state(k) reconstructs the position of a consumer k rows
        in — the loader-pipeline shape where prefetch buffers run ahead."""
        t = catalog.create_table("f3", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        start = now_millis() - 1
        for c in range(3):
            _commit(t, c * 10, 10)
        oracle = _drain(FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        ))

        f = FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        )
        it = f.iter_batches()
        b1, b2 = next(it), next(it)
        next(it)  # the source ran ahead; consumer only finished 3 rows of b2
        consumed = len(b1) + 3
        rs = f.resume_state(consumed)
        it.close()
        got = (
            b1.column("seq").to_pylist()
            + b2.column("seq").to_pylist()[:3]
            + _drain(FreshFollower(
                t.scan().batch_size(7), state=rs,
                poll_interval=0.01, max_polls=3,
            ))
        )
        assert got == oracle

    def test_cursor_dict_compat_mutated_in_place(self, catalog):
        """The legacy coarse-grained resume: follow(cursors=dict) advances
        the caller's dict in place (follow_cursors_to_json round-trip)."""
        from lakesoul_tpu.meta.client import (
            follow_cursors_from_json,
            follow_cursors_to_json,
        )

        t = catalog.create_table("f4", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _commit(t, 0, 5)
        cursors = catalog.client.init_follow_cursors(t.info.table_name, now_millis())
        _commit(t, 10, 5)
        f = FreshFollower(
            t.scan(), cursors=cursors, poll_interval=0.01, max_polls=2
        )
        assert sorted(_drain(f)) == list(range(10, 15))
        restored = follow_cursors_from_json(follow_cursors_to_json(cursors))
        _commit(t, 20, 5)
        f2 = FreshFollower(
            t.scan(), cursors=restored, poll_interval=0.01, max_polls=2
        )
        assert sorted(_drain(f2)) == list(range(20, 25))


class TestFollowerResilience:
    def test_transient_faults_absorbed_with_seeded_schedule(self, catalog):
        """p=0.4 flaky faults on the poll + store reads: the stream
        retries on the shared policy and delivers byte-identically."""
        t = catalog.create_table("f5", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        start = now_millis() - 1
        for c in range(3):
            _commit(t, c * 10, 10)
        oracle = _drain(FreshFollower(
            t.scan().batch_size(7), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3,
        ))
        from lakesoul_tpu.obs import registry

        attempts_before = registry().counter(
            "lakesoul_retry_attempts_total", op="follow.poll"
        ).value
        faults.clear()
        faults.install("follow.poll:0.4:flaky")
        faults.install("object_store.cat_file:0.2:flaky")
        faults.install("object_store.open:0.2:flaky")
        try:
            got = _drain(FreshFollower(
                t.scan().batch_size(7), start_timestamp_ms=start,
                poll_interval=0.01, max_polls=6,
                retry_policy=_fast_policy(),
            ))
        finally:
            faults.clear()
        assert got == oracle
        attempts_after = registry().counter(
            "lakesoul_retry_attempts_total", op="follow.poll"
        ).value
        assert attempts_after > attempts_before  # the retry path really ran

    def test_decode_fault_mid_unit_does_not_duplicate(self, catalog):
        """A fault between batches of one unit re-opens the unit at the
        delivered offset: no replayed rows."""
        t = catalog.create_table("f6", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        start = now_millis() - 1
        _commit(t, 0, 50)  # one unit, several 7-row batches
        faults.clear()
        faults.install("object_store.open:0.5:flaky")
        faults.install("object_store.cat_file:0.5:flaky")
        try:
            got = _drain(FreshFollower(
                t.scan().batch_size(7), start_timestamp_ms=start,
                poll_interval=0.01, max_polls=3,
                retry_policy=_fast_policy(20),
            ))
        finally:
            faults.clear()
        assert sorted(got) == list(range(50)) and len(got) == 50

    def test_permanent_failure_raises_typed(self, catalog, monkeypatch):
        t = catalog.create_table("f7", SCHEMA)
        _commit_plain(t)

        def boom(*a, **k):
            raise ConfigError("permanent")

        monkeypatch.setattr(catalog.client, "poll_scan_plan", boom)
        f = FreshFollower(t.scan(), poll_interval=0.01, max_polls=2)
        with pytest.raises(ConfigError):
            list(f.iter_batches())

    def test_retry_exhaustion_raises_last_native_error(self, catalog):
        t = catalog.create_table("f8", SCHEMA)
        faults.clear()
        faults.install("follow.poll:1.0:flaky")  # every attempt fails
        try:
            f = FreshFollower(
                t.scan(), poll_interval=0.01, max_polls=2,
                retry_policy=_fast_policy(3),
            )
            with pytest.raises(ConnectionError):
                list(f.iter_batches())
        finally:
            faults.clear()


def _commit_plain(t):
    t.write_arrow(pa.table({
        "id": [1], "seq": [1], "v": [1.0]
    }, schema=SCHEMA))


class TestFollowerFreshnessMeasurement:
    def test_commit_to_visible_lands_in_histogram_and_budget(self, catalog):
        t = catalog.create_table("f9", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        start = now_millis() - 1
        slo = SloMonitor(target_s=30.0, slo="test-follow")
        _commit(t, 0, 10)
        _commit(t, 10, 10)
        f = FreshFollower(
            t.scan(), start_timestamp_ms=start,
            poll_interval=0.01, max_polls=3, slo=slo,
        )
        assert len(_drain(f)) == 20
        snap = slo.snapshot()
        # one observation per delivered unit (a poll groups the new commits
        # of a bucket into one unit, stamped with the EARLIEST commit's
        # instant), all fresh (sub-target)
        assert snap["count"] >= 1
        assert snap["violations"] == 0 and snap["in_budget"]
        assert 0.0 <= snap["p99_s"] < 30.0

    def test_scan_follow_surface_passes_slo_through(self, catalog):
        t = catalog.create_table("f10", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        slo = SloMonitor(target_s=30.0, slo="test-follow-2")
        stop = threading.Event()
        _commit(t, 0, 5)
        start = catalog.client.store.get_latest_partition_info(
            t.info.table_id, "-5"
        ).timestamp - 1
        seen = []
        for b in t.scan().follow(
            start, poll_interval=0.01, stop_event=stop, slo=slo
        ):
            seen.extend(b.column("seq").to_pylist())
            if len(seen) >= 5:
                stop.set()
        assert slo.snapshot()["count"] >= 1


class TestFollowDeprecationsAndShutdown:
    def test_settle_ms_deprecated_noop(self, catalog):
        t = catalog.create_table("f11", SCHEMA)
        stop = threading.Event()
        stop.set()
        with pytest.deprecated_call():
            assert list(t.scan().follow(stop_event=stop, settle_ms=250)) == []

    def test_stop_within_one_tick_even_on_long_poll_interval(self, catalog):
        """The satellite contract: the idle wait rides stop_event.wait, so
        a parked follower exits in ~0 s, not one poll_interval."""
        t = catalog.create_table("f12", SCHEMA)
        stop = threading.Event()
        done = threading.Event()

        def run():
            list(t.scan().follow(stop_event=stop, poll_interval=30.0))
            done.set()

        th = threading.Thread(target=run, daemon=True)
        th.start()
        time.sleep(0.3)  # park it on the 30 s wait
        t0 = time.monotonic()
        stop.set()
        assert done.wait(timeout=5.0)
        assert time.monotonic() - t0 < 2.0


# ------------------------------------------------------ notifier isolation


class TestNotifierIsolation:
    def _table_with_gap(self, catalog):
        t = catalog.create_table(
            "n1", SCHEMA, primary_keys=["id"], hash_bucket_num=1
        )
        for c in range(4):  # enough committed versions to open a gap
            _commit(t, c * 5, 5)
        return t

    def test_raising_listener_does_not_starve_others(self, catalog):
        from lakesoul_tpu.compaction.events import PollingWatermarkNotifier
        from lakesoul_tpu.obs import registry

        self._table_with_gap(catalog)
        n = PollingWatermarkNotifier(catalog.client.store, version_gap=2)
        seen: list = []

        def bad(ev):
            raise RuntimeError("listener bug")

        n.listen(bad)
        n.listen(seen.append)
        errors_before = registry().counter(
            "lakesoul_notifier_listener_errors_total"
        ).value
        delivered = n.poll()
        assert delivered >= 1
        assert len(seen) == delivered  # the good listener saw EVERY event
        errors_after = registry().counter(
            "lakesoul_notifier_listener_errors_total"
        ).value
        assert errors_after - errors_before == delivered  # one per bad call

    def test_store_errors_retried_then_survive_the_poll(self, catalog):
        """Transient candidate-derivation faults retry through the shared
        policy; exhaustion fails THIS poll only (returns 0) instead of
        propagating into the owning service loop."""
        from lakesoul_tpu.compaction.events import PollingWatermarkNotifier

        self._table_with_gap(catalog)
        store = catalog.client.store
        calls = {"n": 0}
        real = store.get_compaction_candidates

        class FlakyStore:
            def __getattr__(self, name):
                return getattr(store, name)

            def get_compaction_candidates(self, *a, **k):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise ConnectionError("transient store blip")
                return real(*a, **k)

        n = PollingWatermarkNotifier(
            FlakyStore(), version_gap=2, retry_policy=_fast_policy()
        )
        seen: list = []
        n.listen(seen.append)
        assert n.poll() >= 1  # first attempt blipped, retry delivered
        assert calls["n"] >= 2

        class DeadStore:
            def get_compaction_candidates(self, *a, **k):
                raise ConnectionError("store down")

        dead = PollingWatermarkNotifier(
            DeadStore(), version_gap=2, retry_policy=_fast_policy(2)
        )
        dead.listen(seen.append)
        assert dead.poll() == 0  # exhaustion: logged + counted, never raised


# -------------------------------------------------- loader follow source


class TestJaxIterFollow:
    def _table(self, catalog, name="j1", commits=4, per=32):
        t = catalog.create_table(
            name, SCHEMA, primary_keys=["id"], hash_bucket_num=2
        )
        start = now_millis() - 1
        for c in range(commits):
            _commit(t, c * per, per)
        return t, start, commits * per

    def test_follow_is_a_continuous_training_source(self, catalog):
        t, start, total = self._table(catalog)
        stop = threading.Event()
        it = t.scan().batch_size(16).to_jax_iter(
            follow={
                "start_timestamp_ms": start,
                "poll_interval": 0.02,
                "stop_event": stop,
            },
            device_put=False,
        )
        seen: list[int] = []
        for batch in it:
            seen.extend(batch["seq"].tolist())
            if len(seen) >= total:
                stop.set()
                break
        assert sorted(seen) == list(range(total))

    def test_follow_state_json_resumes_exactly(self, catalog):
        t, start, total = self._table(catalog, name="j2")
        stop1 = threading.Event()
        it1 = t.scan().batch_size(16).to_jax_iter(
            follow={
                "start_timestamp_ms": start,
                "poll_interval": 0.02,
                "stop_event": stop1,
            },
            device_put=False,
        )
        seen: list[int] = []
        for i, batch in enumerate(it1):
            seen.extend(batch["seq"].tolist())
            if i == 3:
                saved = it1.follow_state_json()  # next to the model ckpt
                stop1.set()
                break
        stop2 = threading.Event()
        it2 = t.scan().batch_size(16).to_jax_iter(
            follow={
                "state": saved,
                "poll_interval": 0.02,
                "stop_event": stop2,
            },
            device_put=False,
        )
        for batch in it2:
            seen.extend(batch["seq"].tolist())
            if len(seen) >= total:
                stop2.set()
                break
        # rows prefetched-but-undelivered at the save point replayed, none
        # skipped, none doubled
        assert sorted(seen) == list(range(total))
        assert len(seen) == total

    def test_follow_rejects_checkpoint_and_device_cache(self, catalog):
        from lakesoul_tpu.data.jax_iter import LoaderCheckpoint

        t, start, _ = self._table(catalog, name="j3", commits=1)
        with pytest.raises(ConfigError):
            t.scan().to_jax_iter(follow=True, checkpoint=LoaderCheckpoint())
        with pytest.raises(ConfigError):
            t.scan().to_jax_iter(follow=True, cache="device")
        with pytest.raises(ConfigError):
            t.scan().to_jax_iter(device_put=False).follow_state_json()

    def test_batch_source_seam_resolution(self, catalog):
        from lakesoul_tpu.data.batch_source import (
            ScanBatchSource,
            batch_source_for,
        )

        t, start, _ = self._table(catalog, name="j4", commits=1)
        scan = t.scan()
        assert isinstance(batch_source_for(scan), ScanBatchSource)
        src = batch_source_for(scan, follow={"start_timestamp_ms": start})
        assert isinstance(src, FollowBatchSource)
        assert batch_source_for(scan, follow=src) is src
        # a persisted position (state JSON or FollowerState) resumes from
        # it — never silently degrades to follow-from-now
        state = FollowerState()
        for value in (state, state.to_json()):
            resumed = batch_source_for(scan, follow=value)
            assert isinstance(resumed, FollowBatchSource)
            assert resumed.resume_state(0) is not None
        with pytest.raises(ConfigError):
            batch_source_for(scan, follow=42)

    def test_follow_iterator_is_single_pass(self, catalog):
        """Re-iterating would rebuild the follower from the INITIAL state
        while the delivered-row counter kept growing — duplicated rows and
        a corrupt follow_state_json position.  It raises instead."""
        t, start, total = self._table(catalog, name="j5", commits=1)
        stop = threading.Event()
        it = t.scan().batch_size(16).to_jax_iter(
            follow={"start_timestamp_ms": start, "poll_interval": 0.02,
                    "stop_event": stop},
            device_put=False,
        )
        seen = 0
        for batch in it:
            seen += len(batch["seq"])
            if seen >= total:
                stop.set()
                break
        with pytest.raises(ConfigError):
            iter(it).__next__()


# -------------------------------------------------------- writer oracle


class TestWriterRole:
    def test_oracle_sha_is_order_invariant(self):
        from lakesoul_tpu.freshness.__main__ import oracle_sha

        rows = [(2, 0, 1.5), (1, 1, 2.5), (3, 0, 0.5)]
        assert oracle_sha(rows) == oracle_sha(list(reversed(rows)))
        assert oracle_sha(rows) != oracle_sha(rows[:2])

    def test_writer_rejects_in_commit_duplicate_pks(self, tmp_path):
        from lakesoul_tpu.freshness.__main__ import main

        with pytest.raises(SystemExit):
            main([
                "writer", "--warehouse", str(tmp_path / "wh"),
                "--rows-per-commit", "10", "--keyspace", "5",
            ])
