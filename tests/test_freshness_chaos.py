"""The ingest-to-train freshness SLO, held under fire.

Three roles run CONCURRENTLY against one warehouse — a CDC writer
streaming checkpointed upserts, the leased compaction service keeping the
table compacted, and a follower trainer observing bounded staleness —
while chaos is injected: flaky-store faults on the follower's read path
and (in the slow leg) a SIGKILL of the real ``python -m
lakesoul_tpu.compaction`` process mid-leased-job with a peer taking over.
The run must hold BOTH declared SLOs — freshness (p99 commit-to-visible
seconds) and sustained throughput (rows/s) — and the follower's delivered
rows must exactly match the writer's oracle (no dup, no gap).  No other
lakehouse repro proves its MOR/compaction loop under concurrent ingest +
compaction + training with faults injected; this is ROADMAP item 4's
"heavy traffic" claim as a measured test."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.freshness import FreshFollower, SloMonitor, ThroughputSlo
from lakesoul_tpu.freshness.__main__ import oracle_sha
from lakesoul_tpu.meta.entity import CommitOp, now_millis
from lakesoul_tpu.runtime import faults
from lakesoul_tpu.runtime.resilience import RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = pa.schema([("id", pa.int64()), ("seq", pa.int64()), ("v", pa.float64())])

# declared SLOs for the chaos runs: generous enough for a loaded CI box,
# tight enough that a broken follower (stuck retry loop, lost poll) fails
FRESHNESS_TARGET_S = 10.0
FRESHNESS_BUDGET = 0.05
THROUGHPUT_FLOOR_ROWS_S = 100.0


def _retry_env(monkeypatch) -> None:
    monkeypatch.setenv("LAKESOUL_RETRY_MAX_ATTEMPTS", "10")
    monkeypatch.setenv("LAKESOUL_RETRY_BASE_S", "0.002")
    monkeypatch.setenv("LAKESOUL_RETRY_CAP_S", "0.02")
    monkeypatch.setenv("LAKESOUL_RETRY_SEED", "7")


def _follower_policy() -> RetryPolicy:
    return RetryPolicy(
        max_attempts=12, base_delay_s=0.002, max_delay_s=0.05, seed=7
    )


def _drain_until(follower, expected_rows: int, deadline_s: float, stop):
    """Consume the follower until ``expected_rows`` rows arrived (or the
    deadline passes); returns the delivered (seq, id, v) tuples."""
    rows: list[tuple[int, int, float]] = []
    deadline = time.monotonic() + deadline_s

    def consume():
        for b in follower.iter_batches():
            seqs = b.column("seq").to_pylist()
            ids = b.column("id").to_pylist()
            vs = b.column("v").to_pylist()
            rows.extend(zip(seqs, ids, vs))
            if len(rows) >= expected_rows:
                stop.set()

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    while th.is_alive() and time.monotonic() < deadline:
        th.join(timeout=0.2)
    stop.set()
    th.join(timeout=10.0)
    return rows


def _write_commits(table, *, commits: int, per: int, interval_s: float,
                   keyspace: int = 4096):
    """In-process writer role: checkpointed CDC upserts + oracle rows."""
    from lakesoul_tpu.streaming.cdc import CheckpointedWriter

    cdc_col = table.info.cdc_column
    w = CheckpointedWriter(table)
    oracle: list[tuple[int, int, float]] = []
    seq = 0
    for ckpt in range(commits):
        ids, seqs, vals, kinds = [], [], [], []
        for _ in range(per):
            id_ = seq % keyspace
            v = float(seq % 1009) / 7.0
            ids.append(id_)
            seqs.append(seq)
            vals.append(v)
            kinds.append("insert" if seq < keyspace else "update")
            oracle.append((seq, id_, v))
            seq += 1
        w.write(pa.table(
            {"id": ids, "seq": seqs, "v": vals, cdc_col: kinds},
            schema=table.schema,
        ))
        w.checkpoint(ckpt)
        if interval_s > 0:
            time.sleep(interval_s)
    return oracle


class TestThreeRolesInProcess:
    """Tier-1 leg: all three roles in one process (writer thread, leased
    compaction service thread, follower main thread) under p=0.3
    flaky-store + flaky-poll faults.  Fast enough for every CI run; the
    real-process SIGKILL variant below is the slow capstone."""

    def test_freshness_and_throughput_slos_hold_under_faults(
        self, tmp_path, monkeypatch
    ):
        _retry_env(monkeypatch)
        catalog = LakeSoulCatalog(
            str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
        )
        t = catalog.create_table(
            "fresh", SCHEMA, primary_keys=["id"], hash_bucket_num=2, cdc=True
        )
        start_ts = now_millis() - 1
        commits, per = 10, 400
        expected = commits * per

        # role 2: the leased compaction service (own catalog handle, as a
        # separate process would hold)
        from lakesoul_tpu.compaction.service import LeasedCompactionService

        svc = LeasedCompactionService(
            LakeSoulCatalog(str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")),
            service_id="inproc-compactor",
            lease_ttl_s=5.0,
            poll_interval_s=0.05,
            version_gap=3,
        )
        svc_thread = threading.Thread(target=svc.run_forever, daemon=True)

        # role 1: the writer
        oracle: list = []
        writer_done = threading.Event()

        def write_role():
            try:
                oracle.extend(_write_commits(
                    t, commits=commits, per=per, interval_s=0.05
                ))
            finally:
                writer_done.set()

        writer = threading.Thread(target=write_role, daemon=True)

        # role 3: the follower trainer, under chaos
        slo = SloMonitor(
            target_s=FRESHNESS_TARGET_S,
            budget_fraction=FRESHNESS_BUDGET,
            slo="chaos-inproc",
        )
        tput = ThroughputSlo(THROUGHPUT_FLOOR_ROWS_S, slo="chaos-inproc-tput")
        stop = threading.Event()
        follower = FreshFollower(
            catalog.table("fresh").scan().batch_size(2048),
            start_timestamp_ms=start_ts,
            poll_interval=0.05,
            stop_event=stop,
            retry_policy=_follower_policy(),
            slo=slo,
        )

        faults.clear()
        faults.install("follow.poll:0.3:flaky")
        faults.install("object_store.cat_file:0.3:flaky")
        faults.install("object_store.open:0.3:flaky")
        try:
            tput.start()
            svc_thread.start()
            writer.start()
            rows = _drain_until(follower, expected, deadline_s=90.0, stop=stop)
            tput.add_rows(len(rows))
        finally:
            faults.clear()
            svc.stop()
            stop.set()
        writer.join(timeout=30.0)
        svc_thread.join(timeout=10.0)

        # exactly-once under fire: delivered rows == the writer's oracle
        assert len(rows) == expected, f"delivered {len(rows)} of {expected}"
        assert oracle_sha(rows) == oracle_sha(oracle)

        # both SLOs held
        snap = slo.snapshot()
        assert snap["count"] >= 1
        assert snap["in_budget"], snap
        assert snap["p99_s"] <= FRESHNESS_TARGET_S, snap
        out = tput.evaluate()
        assert out["ok"], out

        # the compaction loop really ran against the live table
        versions = catalog.client.store.get_partition_versions(
            t.info.table_id, "-5"
        )
        assert any(v.commit_op == CommitOp.COMPACTION for v in versions), (
            "compaction never committed during the run"
        )


@pytest.mark.slow
class TestThreeProcessSigkillChaos:
    """The capstone: real processes for every role — ``python -m
    lakesoul_tpu.freshness writer`` streaming upserts, the real ``python
    -m lakesoul_tpu.compaction`` leased service SIGKILLed mid-leased-job
    (hung on the ``compaction.leased_job`` fault point while HOLDING its
    lease), a peer taking over with the fencing trail, and the follower
    trainer in this process under p=0.3 flaky faults — all while both
    SLOs must hold and delivery must match the writer's oracle."""

    def test_sigkill_compactor_mid_run_slos_hold(self, tmp_path, monkeypatch):
        _retry_env(monkeypatch)
        wh, db = str(tmp_path / "wh"), str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "fresh", SCHEMA, primary_keys=["id"], hash_bucket_num=2, cdc=True
        )
        start_ts = now_millis() - 1
        commits, per = 15, 400
        expected = commits * per
        ttl_s = 2.0

        base_env = dict(os.environ)
        base_env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "LAKESOUL_RETRY_SEED": "7",
        })
        victim_env = dict(base_env)
        # the victim hangs INSIDE its leased job, holding the lease — the
        # deterministic SIGKILL window the topology suite established
        victim_env["LAKESOUL_FAULTS"] = "compaction.leased_job:1:hang:300"

        def compactor(service_id: str, env: dict) -> subprocess.Popen:
            return subprocess.Popen(
                [sys.executable, "-m", "lakesoul_tpu.compaction",
                 "--warehouse", wh, "--db-path", db,
                 "--lease-ttl-s", str(ttl_s), "--poll-s", "0.1",
                 "--version-gap", "3", "--service-id", service_id],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )

        victim = compactor("victim", victim_env)
        writer = subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.freshness", "writer",
             "--warehouse", wh, "--db-path", db, "--table", "fresh",
             "--commits", str(commits), "--rows-per-commit", str(per),
             "--interval-s", "0.15"],
            env=base_env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )

        # watcher: SIGKILL the victim the moment it holds the lease, then
        # start the peer that must take over within ~one TTL
        store = catalog.client.store
        lease_key = f"compaction/{t.info.table_id}/-5"
        peer_box: dict = {}
        killed = threading.Event()

        def kill_and_replace():
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and not killed.is_set():
                if store.get_lease(lease_key) is not None:
                    victim.send_signal(signal.SIGKILL)
                    victim.wait(10.0)
                    peer_box["peer"] = compactor("peer", base_env)
                    killed.set()
                    return
                time.sleep(0.05)

        watcher = threading.Thread(target=kill_and_replace, daemon=True)

        slo = SloMonitor(
            target_s=FRESHNESS_TARGET_S,
            budget_fraction=FRESHNESS_BUDGET,
            slo="chaos-sigkill",
        )
        tput = ThroughputSlo(THROUGHPUT_FLOOR_ROWS_S, slo="chaos-sigkill-tput")
        stop = threading.Event()
        follower = FreshFollower(
            catalog.table("fresh").scan().batch_size(2048),
            start_timestamp_ms=start_ts,
            poll_interval=0.05,
            stop_event=stop,
            retry_policy=_follower_policy(),
            slo=slo,
        )

        faults.clear()
        faults.install("follow.poll:0.3:flaky")
        faults.install("object_store.cat_file:0.3:flaky")
        faults.install("object_store.open:0.3:flaky")
        try:
            try:
                tput.start()
                watcher.start()
                rows = _drain_until(
                    follower, expected, deadline_s=120.0, stop=stop
                )
                tput.add_rows(len(rows))
            finally:
                faults.clear()
                stop.set()
                out, err = writer.communicate(timeout=60.0)
                if victim.poll() is None:
                    victim.send_signal(signal.SIGKILL)

            assert writer.returncode == 0, err[-1000:]
            oracle = json.loads(out.strip().splitlines()[-1])
            assert oracle["rows"] == expected

            # the kill really happened mid-run
            assert killed.is_set(), "victim compactor never held a lease"

            # exactly-once through the SIGKILL + faults
            assert len(rows) == expected, f"delivered {len(rows)} of {expected}"
            assert oracle_sha(rows) == oracle["sha256"]

            # both SLOs held through the chaos
            snap = slo.snapshot()
            assert snap["in_budget"], snap
            assert snap["p99_s"] <= FRESHNESS_TARGET_S, snap
            assert tput.evaluate()["ok"]

            # the (still running) peer completes the compaction with the
            # fencing trail: token 2 proves a TAKEOVER commit, never the
            # victim's
            deadline = time.monotonic() + 60.0
            fenced = []
            while time.monotonic() < deadline:
                versions = store.get_partition_versions(t.info.table_id, "-5")
                fenced = [
                    v for v in versions
                    if v.commit_op == CommitOp.COMPACTION
                    and v.expression.startswith("fence=")
                ]
                if fenced:
                    break
                time.sleep(0.2)
            assert fenced, "no fenced CompactionCommit after takeover"
            assert any(
                int(v.expression.split("=", 1)[1]) >= 2 for v in fenced
            ), [v.expression for v in fenced]
        finally:
            for p in (victim, peer_box.get("peer")):
                if p is not None and p.poll() is None:
                    p.send_signal(signal.SIGKILL)
                    p.wait(10.0)
