"""Crash-prefix replay (analysis/fscheck): the real publication protocols
— spool range write, session manifest, obs fleet docs, the spill rung,
the plane manifest store + ``AnnPlane.open`` — must replay torn-state
free at EVERY op prefix, while seeded bad publications (in-place writes,
unfsynced renames, CRC barriers before their data) are caught with the
publishing stack and the offending prefix.  Also pins the opt-in
``LAKESOUL_FSYNC_DIR`` parent-dir fsync and the detector's control
surface (env gate, enable/disable restore, watch scoping)."""

import builtins
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.analysis import fscheck
from lakesoul_tpu.runtime import atomicio

SCHEMA = pa.schema([("x", pa.int64())])


def one_batch(values=(1, 2, 3)):
    return pa.record_batch([pa.array(list(values))], schema=SCHEMA)


@pytest.fixture(autouse=True)
def _pristine_detector():
    """Every test starts and ends with the real filesystem surface."""
    assert not fscheck.enabled()
    yield
    fscheck.disable()
    fscheck.reset()


# ------------------------------------------------------------ control plane


def test_env_gate(monkeypatch):
    monkeypatch.delenv("LAKESOUL_FSCHECK", raising=False)
    assert not fscheck.env_requested()
    monkeypatch.setenv("LAKESOUL_FSCHECK", "1")
    assert fscheck.env_requested()
    monkeypatch.setenv("LAKESOUL_FSCHECK", "0")
    assert not fscheck.env_requested()


def test_enable_disable_restores_surface():
    real_open, real_replace, real_fsync = builtins.open, os.replace, os.fsync
    fscheck.enable()
    fscheck.enable()  # idempotent
    assert builtins.open is not real_open
    assert os.replace is not real_replace
    fscheck.disable()
    fscheck.disable()
    assert builtins.open is real_open
    assert os.replace is real_replace
    assert os.fsync is real_fsync


def test_unrelated_paths_stay_untraced(tmp_path):
    with fscheck.watch():
        with open(tmp_path / "notes.txt", "w") as f:
            f.write("scratch")
        os.replace(tmp_path / "notes.txt", tmp_path / "notes2.txt")
    assert fscheck.ops() == []
    assert fscheck.replay() == []


# ------------------------------------------------- real protocols stay clean


def test_spool_session_obs_replay_clean(tmp_path):
    from lakesoul_tpu.scanplane import spool

    sess = tmp_path / "sess"
    sess.mkdir()
    with fscheck.watch() as w:
        spool.write_range(str(sess), 0, SCHEMA, [one_batch()], holder="w1")
        atomicio.publish_atomic(
            str(sess / "manifest.json"),
            json.dumps(
                {
                    "session": "s",
                    "request": {},
                    "version_digest": "v",
                    "ranges": [],
                    "created_ms": 1,
                }
            ),
        )
        atomicio.publish_atomic(
            str(tmp_path / "member-abc.json"),
            json.dumps({"service": "x", "heartbeat_ms": 1}),
        )
        fscheck.replay()
    # the protocol stages, fsyncs, then renames — every prefix is
    # old-complete or new-complete under every torn variant
    assert w.violations == [], "\n\n".join(v.render() for v in w.violations)
    kinds = [op.kind for op in fscheck.ops()]
    assert "fsync" in kinds and "replace" in kinds


def test_spill_rung_replay_clean(tmp_path):
    from lakesoul_tpu.fleet import transport
    from lakesoul_tpu.scanplane import spool

    sess = tmp_path / "sess"
    sess.mkdir()
    spool.write_range(str(sess), 0, SCHEMA, [one_batch()], holder="w1")
    with fscheck.watch() as w:
        spill = transport.spill_range(
            str(tmp_path / "spill"), "sessA", str(sess), 0
        )
        transport.write_spill_probe(str(tmp_path / "spill"), "sessA")
        fscheck.replay()
    assert w.violations == [], "\n\n".join(v.render() for v in w.violations)
    # the round-trip still verifies after replay (nothing was mutated)
    nbytes, batches = transport.fetch_spilled(spill)
    assert nbytes == spill["nbytes"] and batches[0].num_rows == 3


def test_plane_store_replay_clean(tmp_path):
    from lakesoul_tpu.annplane import AnnPlane, AnnPlaneConfig, ShardedAnnBuilder
    from lakesoul_tpu.vector.config import VectorIndexConfig

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(600, 16)).astype(np.float32)
    ids = np.arange(600, dtype=np.uint64)
    index = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=4)
    probe = AnnPlaneConfig(
        index=index, shard_budget_bytes=1 << 30, keep_raw=True
    )
    cfg = AnnPlaneConfig(
        index=index,
        shard_budget_bytes=300 * probe.bytes_per_vector(),
        keep_raw=True,
    )
    root = str(tmp_path / "p")

    def stream():
        for lo in range(0, 600, 200):
            yield vecs[lo : lo + 200], ids[lo : lo + 200]

    with fscheck.watch() as w:
        ShardedAnnBuilder(root, cfg).build(stream())
        AnnPlane.open(root, use_pallas=False)
        fscheck.replay()
    # every PLANE pointer swing replays old-or-new: AnnPlane.open at each
    # prefix sees the previous complete record, a mid-build record (a
    # loud, typed refusal), or the finished plane — never a CRC error
    assert w.violations == [], "\n\n".join(v.render() for v in w.violations)
    assert any(
        op.kind == "replace" and os.path.basename(op.dst) == "PLANE"
        for op in fscheck.ops()
    )


# -------------------------------------------------- seeded torn publications


def test_in_place_write_caught(tmp_path):
    with fscheck.watch() as w:
        with open(tmp_path / "member-bad.json", "w") as f:
            f.write(json.dumps({"service": "y"}))
        found = fscheck.replay()
    assert found and all(v.kind == "torn-state" for v in found)
    v = found[0]
    assert v.prefix >= 1
    assert "neither old-complete nor new-complete" in v.message
    rendered = v.render()
    assert "publishing op:" in rendered and "reader:" in rendered
    assert "test_fscheck" in rendered  # the producing stack names this test
    assert w.violations == found


def test_unfsynced_rename_caught_online(tmp_path):
    tmp = tmp_path / "recorder-bad.json.tmp-1"
    with fscheck.watch() as w:
        with open(tmp, "w") as f:
            f.write("{}")
        os.replace(tmp, tmp_path / "recorder-bad.json")
    kinds = {v.kind for v in w.violations}
    assert "unfsynced-rename" in kinds
    (v,) = [v for v in w.violations if v.kind == "unfsynced-rename"]
    assert "never" in v.message and "fsync" in v.message


def test_crc_barrier_before_data_caught(tmp_path):
    crc = tmp_path / "range-00007.arrow.crc"
    tmp = str(crc) + ".tmp-x"
    with fscheck.watch() as w:
        with open(tmp, "w") as f:
            f.write(
                json.dumps(
                    {
                        "path": str(tmp_path / "range-00007.arrow"),
                        "crc32": 0,
                        "nbytes": 3,
                    }
                )
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, crc)
    assert "barrier-before-data" in {v.kind for v in w.violations}


def test_data_then_crc_is_clean_online(tmp_path):
    # the sanctioned spill ordering: segment durable first, CRC doc last
    seg = tmp_path / "range-00008.arrow"
    with fscheck.watch() as w:
        for path, payload in (
            (seg, b"segment-bytes"),
            (str(seg) + ".crc", json.dumps({"path": str(seg)}).encode()),
        ):
            t = str(path) + ".tmp-x"
            with open(t, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(t, path)
    assert [v.kind for v in w.violations] == []


# ----------------------------------------------------- LAKESOUL_FSYNC_DIR


def test_fsync_dir_opt_in_records_fsyncdir(tmp_path, monkeypatch):
    doc = str(tmp_path / "member-dir.json")
    monkeypatch.delenv(atomicio.ENV_FSYNC_DIR, raising=False)
    with fscheck.watch():
        atomicio.publish_atomic(doc, "{}")
    assert not any(op.kind == "fsyncdir" for op in fscheck.ops())
    fscheck.reset()
    monkeypatch.setenv(atomicio.ENV_FSYNC_DIR, "1")
    with fscheck.watch() as w:
        atomicio.publish_atomic(doc, "{}")
        fscheck.replay()
    ops = fscheck.ops()
    kinds = [op.kind for op in ops]
    assert "fsyncdir" in kinds, kinds
    # the directory fsync lands AFTER the publication rename: it makes the
    # new NAME durable, so it must follow the replace
    assert kinds.index("fsyncdir") > kinds.index("replace")
    assert ops[kinds.index("fsyncdir")].path == str(tmp_path)
    assert w.violations == []
