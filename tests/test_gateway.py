"""Flight gateway, JWT, RBAC, and console tests."""

import json
import time

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import RBACError
from lakesoul_tpu.service.console import Console
from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer
from lakesoul_tpu.service.jwt import Claims, JwtServer
from lakesoul_tpu.service.rbac import RbacVerifier


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


class TestJwt:
    def test_round_trip(self):
        srv = JwtServer("secret")
        token = srv.create_token(Claims(sub="alice", group="team1"))
        claims = srv.decode_token(token)
        assert claims.sub == "alice" and claims.group == "team1"

    def test_tampered_token_rejected(self):
        srv = JwtServer("secret")
        token = srv.create_token(Claims(sub="alice"))
        head, payload, sig = token.split(".")
        with pytest.raises(RBACError, match="signature"):
            srv.decode_token(f"{head}.{payload}x.{sig}")
        with pytest.raises(RBACError):
            JwtServer("other-secret").decode_token(token)

    def test_expired_token(self):
        srv = JwtServer("secret")
        token = srv.create_token(Claims(sub="a", exp=int(time.time()) - 10))
        with pytest.raises(RBACError, match="expired"):
            srv.decode_token(token)


class TestRbac:
    def test_domain_rules(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        cat.create_table("pub", SCHEMA)
        info = cat.client.create_table(
            "priv", f"{tmp_warehouse}/priv", SCHEMA, domain="team1"
        )
        rbac = RbacVerifier(cat.client)
        assert rbac.verify_permission_by_table_name("u", "whatever", "default", "pub")
        assert rbac.verify_permission_by_table_name("u", "team1", "default", "priv")
        assert not rbac.verify_permission_by_table_name("u", "team2", "default", "priv")
        with pytest.raises(RBACError):
            rbac.check("u", "team2", "default", "priv")
        # cache answers without hitting the store
        cat.client.store.delete_table(info.table_id)
        assert rbac.verify_permission_by_table_name("u", "team1", "default", "priv")


@pytest.fixture()
def gateway(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("events", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    t.write_arrow(pa.table({"id": np.arange(100), "v": np.arange(100, dtype=np.float64)}))
    server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0", jwt_secret="s3cr3t")
    token = server.jwt_server.create_token(Claims(sub="alice", group="public"))
    yield server, f"grpc://127.0.0.1:{server.port}", token, catalog
    server.shutdown()


class TestFlightGateway:
    def test_do_get_scan(self, gateway):
        server, loc, token, _ = gateway
        client = LakeSoulFlightClient(loc, token=token)
        table = client.scan("events")
        assert table.num_rows == 100
        proj = client.scan("events", columns=["id"], filter={"op": "ge", "col": "id", "value": 95})
        assert proj.column_names == ["id"]
        assert sorted(proj.column("id").to_pylist()) == [95, 96, 97, 98, 99]

    def test_do_put_ingest_and_exactly_once(self, gateway):
        server, loc, token, catalog = gateway
        client = LakeSoulFlightClient(loc, token=token)
        new = pa.table({"id": np.arange(100, 120), "v": np.zeros(20)})
        client.write("events", new, checkpoint_id=1)
        client.write("events", new, checkpoint_id=1)  # replay → no-op
        assert client.scan("events").num_rows == 120
        metrics = json.loads(client.action("metrics")[0])
        assert metrics["total_put_streams"] == 2
        assert metrics["rows_in"] == 40  # both streams counted, one committed

    def test_unauthenticated_rejected(self, gateway):
        _, loc, _, _ = gateway
        client = LakeSoulFlightClient(loc)  # no token
        with pytest.raises(flight.FlightUnauthenticatedError):
            client.scan("events")
        bad = LakeSoulFlightClient(loc, token="garbage.token.sig")
        with pytest.raises(flight.FlightUnauthenticatedError):
            bad.scan("events")

    def test_actions_create_compact_drop(self, gateway):
        _, loc, token, catalog = gateway
        client = LakeSoulFlightClient(loc, token=token)
        schema_hex = SCHEMA.serialize().to_pybytes().hex()
        client.action("create_table", {"table": "t2", "schema_ipc_hex": schema_hex,
                                       "primary_keys": ["id"]})
        assert "default.t2" in client.list_tables()
        client.write("t2", pa.table({"id": [1], "v": [1.0]}))
        client.write("t2", pa.table({"id": [2], "v": [2.0]}))
        out = json.loads(client.action("compact", {"table": "t2"})[0])
        assert out["compacted"] == 1
        client.action("drop_table", {"table": "t2"})
        assert "default.t2" not in client.list_tables()

    def test_incremental_scan_over_flight(self, gateway):
        server, loc, token, catalog = gateway
        client = LakeSoulFlightClient(loc, token=token)
        t = catalog.table("events")
        ts0 = max(
            p.timestamp
            for p in catalog.client.store.get_all_latest_partition_info(t.info.table_id)
        )
        time.sleep(0.002)
        client.write("events", pa.table({"id": [999], "v": [9.0]}))
        inc = client.scan("events", incremental_start_ms=ts0)
        assert inc.column("id").to_pylist() == [999]


class TestConsole:
    def test_console_commands(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        t = cat.create_table("t", SCHEMA, primary_keys=["id"])
        t.write_arrow(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        c = Console(cat)
        assert "default.t" in c.execute("tables")
        assert "primary keys: ['id']" in c.execute("show t")
        assert c.execute("count t") == "2"
        assert "v0" in c.execute("versions t")
        assert "unknown command" in c.execute("bogus")
        assert "error:" in c.execute("show nope")
        c.execute("drop t")
        assert c.execute("tables") == "(no tables)"


class TestFlightLimit:
    def test_limit_in_ticket(self, tmp_warehouse):
        import numpy as np

        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table(
            "fl", pa.schema([("id", pa.int64()), ("v", pa.float64())])
        )
        t.write_arrow(pa.table({"id": np.arange(100), "v": np.zeros(100)}))
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            got = client.scan("fl", limit=7)
            assert got.num_rows == 7
        finally:
            server.shutdown()


class TestLoginHandshake:
    """Token-service role: basic credentials → login action → bearer token
    (reference: the JWT token gRPC service beside the Flight server)."""

    def test_basic_auth_login_then_bearer(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.flight import (
            LakeSoulFlightClient,
            LakeSoulFlightServer,
        )
        from lakesoul_tpu.service.jwt import UserRegistry

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("lg", pa.schema([("id", pa.int64())]))
        t.write_arrow(pa.table({"id": [1, 2]}))
        UserRegistry(catalog.client).register("alice", "s3cret", group="public")

        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0", jwt_secret="k")
        try:
            port = server.port
            # basic credentials authenticate the login call
            client = LakeSoulFlightClient(
                f"grpc://127.0.0.1:{port}", basic_auth=("alice", "s3cret")
            )
            token = client.login()
            assert token.count(".") == 2
            # the minted bearer token works on its own
            fresh = LakeSoulFlightClient(f"grpc://127.0.0.1:{port}", token=token)
            assert fresh.scan("lg").num_rows == 2
        finally:
            server.shutdown()

    def test_bad_credentials_rejected(self, tmp_warehouse):
        import pyarrow.flight as flight

        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.flight import (
            LakeSoulFlightClient,
            LakeSoulFlightServer,
        )
        from lakesoul_tpu.service.jwt import UserRegistry

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        UserRegistry(catalog.client).register("bob", "pw")
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0", jwt_secret="k")
        try:
            client = LakeSoulFlightClient(
                f"grpc://127.0.0.1:{server.port}", basic_auth=("bob", "WRONG")
            )
            with pytest.raises(flight.FlightUnauthenticatedError):
                client.login()
        finally:
            server.shutdown()


class TestVectorSearchAction:
    def test_vector_search_over_flight(self, tmp_warehouse):
        rng = np.random.default_rng(0)
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("emb", pa.list_(pa.float32(), 16))])
        t = catalog.create_table("docs", schema, primary_keys=["id"])
        vecs = rng.normal(size=(400, 16)).astype(np.float32)
        t.write_arrow(
            pa.table({"id": np.arange(400),
                      "emb": pa.array(list(vecs), type=pa.list_(pa.float32(), 16))})
        )
        t.build_vector_index("emb", nlist=4)
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0", jwt_secret="s3cr3t")
        try:
            token = server.jwt_server.create_token(Claims(sub="alice", group="public"))
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}", token=token)
            out = json.loads(client.action(
                "vector_search",
                {"table": "docs", "column": "emb", "query": vecs[7].tolist(),
                 "top_k": 3, "nprobe": 4},
            )[0])
            assert out["ids"][0] == 7  # self-NN through the gateway
            assert len(out["ids"]) == 3 and len(out["distances"]) == 3
            assert out["distances"][0] <= out["distances"][1]
            # results match the local surface
            ids_local, _ = t.vector_search("emb", vecs[7], top_k=3, nprobe=4)
            assert [int(i) for i in ids_local] == out["ids"]
        finally:
            server.shutdown()


class TestCallCleanGate:
    """CALL clean() is warehouse-wide destructive: its empty
    referenced_tables set must NOT skip RBAC — the gateway requires the
    caller's domain to reach EVERY table (wildcard/admin shape)."""

    def _server(self, tmp_warehouse, private: bool):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("pub", SCHEMA)
        t.write_arrow(pa.table({"id": np.arange(5), "v": np.zeros(5)}))
        if private:
            catalog.client.create_table(
                "priv", f"{tmp_warehouse}/default/priv", SCHEMA, domain="team1"
            )
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0", jwt_secret="k")
        token = server.jwt_server.create_token(Claims(sub="alice", group="public"))
        return server, f"grpc://127.0.0.1:{server.port}", token

    def test_clean_denied_without_wildcard_access(self, tmp_warehouse):
        server, loc, token = self._server(tmp_warehouse, private=True)
        try:
            client = LakeSoulFlightClient(loc, token=token)
            with pytest.raises(flight.FlightError, match="warehouse-wide"):
                client.action("sql", {"statement": "CALL clean()"})
            # per-table ops on accessible tables still work
            assert client.scan("pub").num_rows == 5
        finally:
            server.shutdown()

    def test_clean_allowed_with_access_to_every_table(self, tmp_warehouse):
        server, loc, token = self._server(tmp_warehouse, private=False)
        try:
            client = LakeSoulFlightClient(loc, token=token)
            client.action("sql", {"statement": "CALL clean()"})  # no raise
        finally:
            server.shutdown()
