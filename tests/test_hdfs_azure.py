"""HDFS pass-through and the Azure storage-proxy upstream (VERDICT r3 #8).

The image has no libhdfs and no Azure account, so both legs run against
wire-faithful fakes:

- HDFS: a mocked ``hdfs://`` fsspec implementation (captures host/port/user
  exactly as the pyarrow HadoopFileSystem wrapper would receive them, backed
  by a local dir) proves the full catalog write→commit→MOR-scan path works
  over hdfs:// table paths, including protocol-scoped option plumbing.
- Azure: the proxy's AzureUpstream signs requests with the account Shared
  Key; a local fake Blob endpoint re-derives the canonicalized
  string-to-sign from the spec and cryptographically verifies every
  forwarded request (same stance as the fake-S3 SigV4 leg in
  test_proxy_upstream.py).
"""

import base64
import hashlib
import hmac
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pyarrow as pa
import pytest

import fsspec
from fsspec.implementations.dirfs import DirFileSystem
from fsspec.utils import infer_storage_options

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service.azure import (
    API_VERSION,
    AzureUpstream,
    AzureUpstreamConfig,
    sign_shared_key,
    string_to_sign,
)

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])

_MOCK_ROOTS: dict = {}
_MOCK_INSTANCES: list = []


class MockHdfsFileSystem(DirFileSystem):
    """What fsspec's arrow wrapper over pyarrow.fs.HadoopFileSystem looks
    like on the wire: protocol 'hdfs', host/port from the URL, extra kwargs
    (user, kerb_ticket, replication) from storage options — backed here by
    a local directory per namenode host."""

    protocol = "hdfs"

    def __init__(self, host=None, port=None, user=None, kerb_ticket=None, **kw):
        kw.pop("path", None)
        kw.pop("fs", None)
        super().__init__(path=_MOCK_ROOTS[host], fs=fsspec.filesystem("file"), **kw)
        self.host = host
        self.port = port
        self.user = user
        self.kerb_ticket = kerb_ticket
        _MOCK_INSTANCES.append(self)

    @classmethod
    def _strip_protocol(cls, path):
        return infer_storage_options(str(path))["path"]

    @staticmethod
    def _get_kwargs_from_urls(path):
        o = infer_storage_options(str(path))
        out = {"host": o.get("host")}
        if o.get("port") is not None:
            out["port"] = o["port"]
        return out


@pytest.fixture()
def mock_hdfs(tmp_path):
    from fsspec.registry import _registry

    root = tmp_path / "hdfs-root"
    root.mkdir()
    _MOCK_ROOTS["namenode"] = str(root)
    _MOCK_INSTANCES.clear()
    saved = _registry.pop("hdfs", None)
    fsspec.register_implementation("hdfs", MockHdfsFileSystem, clobber=True)
    MockHdfsFileSystem.clear_instance_cache()
    yield root
    MockHdfsFileSystem.clear_instance_cache()
    # restore the registry so later hdfs:// users get the arrow wrapper back
    _registry.pop("hdfs", None)
    if saved is not None:
        _registry["hdfs"] = saved


class TestHdfsPassThrough:
    def test_catalog_end_to_end_over_hdfs(self, mock_hdfs, tmp_path):
        cat = LakeSoulCatalog(
            "hdfs://namenode:9000/wh",
            db_path=str(tmp_path / "meta.db"),
            storage_options={"hdfs.user": "etl"},
        )
        t = cat.create_table("ht", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        t.write_arrow(pa.table({"id": np.arange(20), "v": np.arange(20) * 1.0}))
        # upsert to force a merge-on-read scan through hdfs://
        t.write_arrow(pa.table({"id": np.arange(5), "v": np.full(5, -1.0)}))
        out = t.to_arrow()
        got = dict(zip(out.column("id").to_pylist(), out.column("v").to_pylist()))
        assert len(got) == 20 and got[3] == -1.0 and got[10] == 10.0
        # the data physically landed under the mocked namenode root
        files = list(mock_hdfs.rglob("*.parquet")) + list(mock_hdfs.rglob("*.lsf"))
        assert files, "no data files written through the hdfs protocol"
        # URL kwargs and protocol-scoped options reached the filesystem
        inst = _MOCK_INSTANCES[0]
        assert inst.host == "namenode" and inst.port == 9000
        assert inst.user == "etl"

    def test_protocol_scoped_options_do_not_leak(self, mock_hdfs, tmp_path):
        from lakesoul_tpu.io.object_store import filesystem_for

        fs, _ = filesystem_for(
            "hdfs://namenode:9000/wh/x",
            {"hdfs.user": "etl", "s3.endpoint_url": "http://other"},
        )
        assert fs.user == "etl"
        assert not hasattr(fs, "endpoint_url")

    def test_scope_aliases_are_symmetric(self):
        from lakesoul_tpu.io.object_store import _scope_options

        # either spelling of an aliased scheme reaches either path form
        assert _scope_options({"gcs.token": "anon"}, "gs") == {"token": "anon"}
        assert _scope_options({"gs.token": "anon"}, "gcs") == {"token": "anon"}
        assert _scope_options({"s3a.key": "k"}, "s3") == {"key": "k"}
        assert _scope_options({"s3.key": "k"}, "s3a") == {"key": "k"}
        # unscoped keys pass through; foreign scopes drop
        assert _scope_options({"timeout": 3, "az.key": "x"}, "s3") == {"timeout": 3}


ACCOUNT, KEY = "testacct", base64.b64encode(b"super-secret-key-32-bytes!!!!!!!").decode()


def _verify_shared_key(handler: BaseHTTPRequestHandler) -> bool:
    """Independent spec-derived verification in the fake Blob server."""
    auth = handler.headers.get("Authorization", "")
    if not auth.startswith(f"SharedKey {ACCOUNT}:"):
        return False
    got_sig = auth.split(":", 1)[1]
    # rebuild the string-to-sign from the received request
    headers = {k: v for k, v in handler.headers.items()}
    sts = string_to_sign("GET" if handler.command == "GET" else handler.command,
                         ACCOUNT, handler.path, {}, headers)
    want = base64.b64encode(
        hmac.new(base64.b64decode(KEY), sts.encode(), hashlib.sha256).digest()
    ).decode()
    return hmac.compare_digest(got_sig, want)


class _FakeBlobServer:
    def __init__(self):
        store: dict[str, bytes] = {}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _check(self):
                if self.headers.get("x-ms-version") != API_VERSION:
                    self.send_error(400, "missing x-ms-version")
                    return False
                if "x-ms-date" not in self.headers:
                    self.send_error(400, "missing x-ms-date")
                    return False
                if not _verify_shared_key(self):
                    self.send_error(403, "signature mismatch")
                    return False
                return True

            def do_PUT(self):
                if not self._check():
                    return
                if self.headers.get("x-ms-blob-type") != "BlockBlob":
                    self.send_error(400, "missing x-ms-blob-type")
                    return
                n = int(self.headers.get("Content-Length", 0))
                store[self.path] = self.rfile.read(n)
                self.send_response(201)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if not self._check():
                    return
                blob = store.get(self.path)
                if blob is None:
                    self.send_error(404)
                    return
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    a, _, b = rng[6:].partition("-")
                    start = int(a)
                    end = int(b) + 1 if b else len(blob)
                    piece = blob[start:end]
                    self.send_response(206)
                    self.send_header(
                        "Content-Range", f"bytes {start}-{end-1}/{len(blob)}"
                    )
                else:
                    piece = blob
                    self.send_response(200)
                self.send_header("Content-Length", str(len(piece)))
                self.end_headers()
                self.wfile.write(piece)

            def do_HEAD(self):
                if not self._check():
                    return
                blob = store.get(self.path)
                if blob is None:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()

        self.store = store
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def port(self):
        return self.server.server_address[1]

    def stop(self):
        self.server.shutdown()


@pytest.fixture()
def blob_server():
    s = _FakeBlobServer()
    yield s
    s.stop()


def _upstream(port) -> AzureUpstream:
    cfg = AzureUpstreamConfig(
        account=ACCOUNT, key_b64=KEY, container="lake",
        endpoint=f"http://127.0.0.1:{port}",
    )
    return AzureUpstream(
        cfg,
        resolver=lambda host, p: ["127.0.0.1"],
        health_check=lambda ip, p: True,
    )


class TestAzureSharedKey:
    def test_string_to_sign_shape(self):
        sts = string_to_sign(
            "GET", ACCOUNT, "/lake/a b.parquet", {"comp": "list"},
            {
                "x-ms-date": "Mon, 27 Jul 2026 10:00:00 GMT",
                "x-ms-version": API_VERSION,
                "Content-Length": "0",
                "Range": "bytes=0-9",
            },
        )
        lines = sts.split("\n")
        assert lines[0] == "GET"
        assert lines[3] == ""  # zero Content-Length signs as empty
        assert lines[6] == ""  # Date empty: x-ms-date supplied
        assert lines[11] == "bytes=0-9"
        assert "x-ms-date:Mon, 27 Jul 2026 10:00:00 GMT" in sts
        assert sts.endswith(f"/{ACCOUNT}/lake/a b.parquet\ncomp:list")

    def test_signature_is_deterministic_and_keyed(self):
        h = {"x-ms-date": "Mon, 27 Jul 2026 10:00:00 GMT", "x-ms-version": API_VERSION}
        s1 = sign_shared_key("GET", ACCOUNT, KEY, "/lake/x", {}, h)
        s2 = sign_shared_key("GET", ACCOUNT, KEY, "/lake/x", {}, h)
        assert s1 == s2 and s1.startswith(f"SharedKey {ACCOUNT}:")
        other = base64.b64encode(b"another-key").decode()
        assert sign_shared_key("GET", ACCOUNT, other, "/lake/x", {}, h) != s1

    def test_put_get_head_range_verified(self, blob_server):
        up = _upstream(blob_server.port)
        body = b"0123456789abcdef" * 100
        status, _, resp = up.request("PUT", "wh/t/part-x_0000.parquet", body=body)
        resp.read()
        assert status == 201
        status, headers, resp = up.request("GET", "wh/t/part-x_0000.parquet")
        assert status == 200 and resp.read() == body
        status, _, resp = up.request(
            "GET", "wh/t/part-x_0000.parquet", range_header="bytes=16-31"
        )
        assert status == 206 and resp.read() == b"0123456789abcdef"
        status, headers, resp = up.request("HEAD", "wh/t/part-x_0000.parquet")
        resp.read()
        assert status == 200 and headers["Content-Length"] == str(len(body))

    def test_tampered_key_rejected(self, blob_server):
        cfg = AzureUpstreamConfig(
            account=ACCOUNT,
            key_b64=base64.b64encode(b"wrong-key").decode(),
            container="lake",
            endpoint=f"http://127.0.0.1:{blob_server.port}",
        )
        up = AzureUpstream(
            cfg, resolver=lambda h, p: ["127.0.0.1"], health_check=lambda i, p: True
        )
        status, _, resp = up.request("GET", "wh/x")
        resp.read()
        assert status == 403

    def test_streamed_put_through_proxy(self, blob_server, tmp_path):
        """Full path: HTTP client → RBAC proxy → Azure upstream → verified
        fake Blob endpoint (the azure.rs role end to end)."""
        from lakesoul_tpu.service.storage_proxy import StorageProxy

        cat = LakeSoulCatalog(str(tmp_path / "wh"), db_path=str(tmp_path / "m.db"))
        cat.create_table("az", SCHEMA)
        proxy = StorageProxy(cat, upstream=_upstream(blob_server.port))
        proxy.start()
        try:
            url = f"http://127.0.0.1:{proxy.port}/default/az/f.bin"
            body = b"zz" * 4096
            req = urllib.request.Request(url, data=body, method="PUT")
            assert urllib.request.urlopen(req).status == 201
            got = urllib.request.urlopen(url).read()
            assert got == body
            req = urllib.request.Request(url, headers={"Range": "bytes=0-1"})
            assert urllib.request.urlopen(req).read() == b"zz"
        finally:
            proxy.stop()
