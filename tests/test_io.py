"""IO layer tests: partitioned writer, merge-on-read, merge operators,
filters, CDC, schema evolution."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lakesoul_tpu.io import IOConfig, TableWriter, read_scan_unit
from lakesoul_tpu.io.filters import Filter, col, extract_pk_equalities
from lakesoul_tpu.io.merge import apply_cdc_filter, merge_sorted_tables, uniform_table
from lakesoul_tpu.meta.client import extract_hash_bucket_id
from lakesoul_tpu.utils import spark_hash


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("name", pa.string())])


def make_writer(tmp_path, **cfg_kwargs):
    cfg = IOConfig(schema=SCHEMA, **cfg_kwargs)
    return TableWriter(cfg, str(tmp_path / "tbl")), cfg


class TestWriter:
    def test_plain_write(self, tmp_path):
        w, _ = make_writer(tmp_path)
        w.write_batch(pa.table({"id": [1, 2], "v": [1.0, 2.0], "name": ["a", "b"]}))
        outs = w.close()
        assert len(outs) == 1
        t = pq.read_table(outs[0].path)
        assert t.num_rows == 2
        assert outs[0].row_count == 2 and outs[0].size > 0

    def test_hash_bucketing_matches_scalar_hash(self, tmp_path):
        w, cfg = make_writer(tmp_path, primary_keys=["id"], hash_bucket_num=4)
        ids = list(range(100))
        w.write_batch(pa.table({"id": ids, "v": [float(i) for i in ids], "name": ["x"] * 100}))
        outs = w.close()
        assert len(outs) >= 2  # multiple buckets hit
        for out in outs:
            bucket = extract_hash_bucket_id(out.path)
            assert bucket == out.bucket_id
            t = pq.read_table(out.path)
            for v in t.column("id").to_pylist():
                assert spark_hash.bucket_id_for_scalar(v, 4, pa.int64()) == bucket
            # PK cells are written sorted
            vals = t.column("id").to_pylist()
            assert vals == sorted(vals)

    def test_range_partitioning_drops_partition_cols(self, tmp_path):
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())])
        cfg = IOConfig(schema=schema, range_partitions=["date"])
        w = TableWriter(cfg, str(tmp_path / "tbl"))
        w.write_batch(
            pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0], "date": ["d1", "d1", "d2"]})
        )
        outs = w.close()
        descs = sorted(o.partition_desc for o in outs)
        assert descs == ["date=d1", "date=d2"]
        t = pq.read_table(
            [o for o in outs if o.partition_desc == "date=d1"][0].path,
            partitioning=None,  # single data file; no hive path inference
        )
        assert "date" not in t.column_names  # directory-encoded
        assert t.num_rows == 2
        assert "date=d1" in outs[0].path

    def test_abort_deletes_staged_files(self, tmp_path):
        import os

        w, _ = make_writer(tmp_path)
        w.write_batch(pa.table({"id": [1], "v": [1.0], "name": ["a"]}))
        outs = w.flush()
        assert os.path.exists(outs[0].path)
        w.abort()
        assert not os.path.exists(outs[0].path)


class TestMerge:
    def test_use_last_wins(self):
        t1 = pa.table({"id": [1, 2, 3], "v": [10.0, 20.0, 30.0]})
        t2 = pa.table({"id": [2, 4], "v": [99.0, 40.0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("id").to_pylist() == [1, 2, 3, 4]
        assert m.column("v").to_pylist() == [10.0, 99.0, 30.0, 40.0]

    def test_use_last_includes_null(self):
        t1 = pa.table({"id": [1], "v": [10.0]})
        t2 = pa.table({"id": [1], "v": pa.array([None], type=pa.float64())})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("v").to_pylist() == [None]
        m2 = merge_sorted_tables([t1, t2], ["id"], merge_operators={"v": "UseLastNotNull"})
        assert m2.column("v").to_pylist() == [10.0]

    def test_sum_all_and_sum_last(self):
        t1 = pa.table({"id": [1, 1, 2], "v": [1, 2, 5]})
        t2 = pa.table({"id": [1, 2], "v": [10, 7]})
        m = merge_sorted_tables([t1, t2], ["id"], merge_operators={"v": "SumAll"})
        assert m.column("v").to_pylist() == [13, 12]
        m2 = merge_sorted_tables([t1, t2], ["id"], merge_operators={"v": "SumLast"})
        # SumLast sums only rows from the newest file present in each group
        assert m2.column("v").to_pylist() == [10, 7]

    def test_joined_operators(self):
        t1 = pa.table({"id": [1, 1], "s": ["a", "b"]})
        t2 = pa.table({"id": [1], "s": ["c"]})
        m = merge_sorted_tables([t1, t2], ["id"], merge_operators={"s": "JoinedAllByComma"})
        assert m.column("s").to_pylist() == ["a,b,c"]
        m2 = merge_sorted_tables(
            [t1, t2], ["id"], merge_operators={"s": "JoinedLastBySemicolon"}
        )
        assert m2.column("s").to_pylist() == ["c"]

    def test_multi_pk_and_string_keys(self):
        t1 = pa.table({"k1": ["a", "a", "b"], "k2": [1, 2, 1], "v": [1, 2, 3]})
        t2 = pa.table({"k1": ["a", "b"], "k2": [2, 1], "v": [20, 30]})
        m = merge_sorted_tables([t1, t2], ["k1", "k2"])
        assert m.column("v").to_pylist() == [1, 20, 30]

    def test_unsorted_input_ok(self):
        # vectorized merge does its own stable sort
        t1 = pa.table({"id": [3, 1, 2], "v": [3.0, 1.0, 2.0]})
        m = merge_sorted_tables([t1], ["id"])
        assert m.column("id").to_pylist() == [1, 2, 3]

    def test_schema_evolution_fill(self):
        target = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("extra", pa.string())])
        t_old = pa.table({"id": [1], "v": [1.0]})
        u = uniform_table(t_old, target)
        assert u.column("extra").to_pylist() == [None]
        u2 = uniform_table(t_old, target, defaults={"extra": "dflt"})
        assert u2.column("extra").to_pylist() == ["dflt"]

    def test_cdc_delete_filter(self):
        t1 = pa.table({"id": [1, 2], "rowKinds": ["insert", "insert"], "v": [1, 2]})
        t2 = pa.table({"id": [1], "rowKinds": ["delete"], "v": [0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        filtered = apply_cdc_filter(m, "rowKinds")
        assert filtered.column("id").to_pylist() == [2]


class TestReader:
    def test_round_trip_with_merge(self, tmp_path):
        w, cfg = make_writer(tmp_path, primary_keys=["id"], hash_bucket_num=2)
        w.write_batch(pa.table({"id": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0], "name": ["a", "b", "c", "d"]}))
        out1 = w.flush()
        w.write_batch(pa.table({"id": [2, 3], "v": [20.0, 30.0], "name": ["B", "C"]}))
        out2 = w.flush()
        # per-bucket merge: bucket files from both flushes, older first
        rows = {}
        for bucket in {o.bucket_id for o in out1 + out2}:
            files = [o.path for o in out1 if o.bucket_id == bucket] + [
                o.path for o in out2 if o.bucket_id == bucket
            ]
            t = read_scan_unit(files, ["id"], schema=SCHEMA)
            for r in t.to_pylist():
                rows[r["id"]] = r
        assert rows[1]["v"] == 1.0 and rows[2]["v"] == 20.0 and rows[3]["name"] == "C"
        assert len(rows) == 4

    def test_filter_pushdown_and_projection(self, tmp_path):
        w, _ = make_writer(tmp_path)
        w.write_batch(pa.table({"id": list(range(10)), "v": [float(i) for i in range(10)], "name": ["n"] * 10}))
        outs = w.close()
        t = read_scan_unit(
            [outs[0].path], [], schema=SCHEMA, filter=col("v") > 5.0, columns=["id"]
        )
        assert t.column_names == ["id"]
        assert t.column("id").to_pylist() == [6, 7, 8, 9]

    def test_non_pk_filter_not_pushed_premerge(self, tmp_path):
        # filter on v must not resurrect the stale version of id=1
        w, cfg = make_writer(tmp_path, primary_keys=["id"], hash_bucket_num=1)
        w.write_batch(pa.table({"id": [1], "v": [10.0], "name": ["old"]}))
        o1 = w.flush()
        w.write_batch(pa.table({"id": [1], "v": [3.0], "name": ["new"]}))
        o2 = w.flush()
        t = read_scan_unit(
            [o1[0].path, o2[0].path], ["id"], schema=SCHEMA, filter=col("v") > 5.0
        )
        assert t.num_rows == 0  # newest version (v=3) excluded; old must NOT appear

    def test_partition_value_fill(self, tmp_path):
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("date", pa.string())])
        cfg = IOConfig(schema=schema, range_partitions=["date"])
        w = TableWriter(cfg, str(tmp_path / "tbl"))
        w.write_batch(pa.table({"id": [1], "v": [1.0], "date": ["2024-01-01"]}))
        outs = w.close()
        t = read_scan_unit(
            [outs[0].path],
            [],
            schema=schema,
            partition_values={"date": "2024-01-01"},
        )
        assert t.column("date").to_pylist() == ["2024-01-01"]


class TestFilters:
    def test_json_round_trip(self):
        f = (col("id") == 5) | (col("name") != "x") & (col("v") > 1.5)
        f2 = Filter.from_json(f.to_json())
        assert f2 == f

    def test_extract_pk_equalities(self):
        f = (col("id") == 1) | (col("id") == 2)
        assert extract_pk_equalities(f, ["id"]) == [("id", 1), ("id", 2)]
        assert extract_pk_equalities(col("id").is_in([3, 4]), ["id"]) == [("id", 3), ("id", 4)]
        # non-PK column breaks pruning
        assert extract_pk_equalities((col("id") == 1) | (col("v") == 2), ["id"]) == []
        assert extract_pk_equalities(col("id") > 5, ["id"]) == []


class TestWriterByteBudget:
    def test_byte_budget_triggers_flush(self, tmp_path):
        """The writer's byte budget is the spill mechanism (mem/pool.rs +
        spill.rs roles): crossing it stages sorted runs to disk mid-stream."""
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu.io.config import IOConfig
        from lakesoul_tpu.io.writer import TableWriter

        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        cfg = IOConfig(schema=schema, primary_keys=["id"], hash_bucket_num=1)
        cfg.memory_budget_bytes = 64 << 10  # tiny budget → frequent spills
        w = TableWriter(cfg, str(tmp_path / "t"))
        rng = np.random.default_rng(0)
        for wave in range(4):
            n = 4096  # ~64KB per batch ≥ budget
            w.write_batch(pa.table({
                "id": rng.permutation(n).astype(np.int64),
                "v": rng.normal(size=n),
            }))
        # spills happened before close: multiple sorted runs already staged
        assert len(w._staged) >= 3
        outs = w.close()
        assert sum(o.row_count for o in outs) == 4 * 4096
        # every staged run is internally sorted (they're the spill runs the
        # streaming merger recombines)
        import pyarrow.parquet as pq

        for o in outs:
            ids = pq.read_table(o.path).column("id").to_numpy()
            assert (ids[1:] >= ids[:-1]).all()
