"""leakcheck: the runtime resource-leak detector must catch seeded
thread/child/debris/fd/heap leaks (each with its creation stack), stay
silent on well-behaved lifecycles and sanctioned pool threads,
instrument/restore the creation seams cleanly, and record-never-raise —
plus regression pins for the three leaks the boundedness pack surfaced
and this PR fixed at source (exporter serve-thread join, autoscaler
retire reaping, stale-spool pruning)."""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from lakesoul_tpu.analysis import leakcheck


@pytest.fixture()
def armed():
    leakcheck.reset()
    leakcheck.enable()
    yield
    leakcheck.disable()
    leakcheck.reset()


# ----------------------------------------------------------- control surface


def test_env_gate(monkeypatch):
    monkeypatch.delenv("LAKESOUL_LEAKCHECK", raising=False)
    assert not leakcheck.env_requested()
    monkeypatch.setenv("LAKESOUL_LEAKCHECK", "1")
    assert leakcheck.env_requested()
    monkeypatch.setenv("LAKESOUL_LEAKCHECK", "0")
    assert not leakcheck.env_requested()


def test_instrument_and_restore():
    """enable() swaps the four creation seams; disable() puts the real
    callables back — no wrapper may survive, other suites patch the same
    seams."""
    from lakesoul_tpu.runtime import atomicio

    real_start = threading.Thread.start
    real_init = subprocess.Popen.__init__
    real_stage = atomicio.stage_stream
    real_mkdtemp = tempfile.mkdtemp
    leakcheck.reset()
    leakcheck.enable()
    try:
        assert leakcheck.enabled()
        assert threading.Thread.start is not real_start
        assert subprocess.Popen.__init__ is not real_init
        assert atomicio.stage_stream is not real_stage
        assert tempfile.mkdtemp is not real_mkdtemp
        leakcheck.enable()  # idempotent: no double wrap
    finally:
        leakcheck.disable()
        leakcheck.reset()
    assert not leakcheck.enabled()
    assert threading.Thread.start is real_start
    assert subprocess.Popen.__init__ is real_init
    assert atomicio.stage_stream is real_stage
    assert tempfile.mkdtemp is real_mkdtemp


# ------------------------------------------------------------- seeded leaks


def test_seeded_thread_leak_with_creation_stack(armed):
    stop = threading.Event()
    leaked = threading.Thread(target=stop.wait, name="seeded-leak", daemon=True)
    try:
        with leakcheck.scope("seeded") as s:
            leaked.start()
        kinds = [v.kind for v in s.leaks]
        assert kinds == ["thread-leak"]
        v = s.leaks[0]
        assert "seeded-leak" in v.message
        # the creation stack rides on the report — it names THIS file
        assert v.stacks and "test_leakcheck" in v.stacks[0]
        # recorded, never raised: the scope exits normally and the
        # violation sits in the module registry for the fixture to assert
        assert v in leakcheck.violations()
    finally:
        stop.set()
        leaked.join(timeout=5.0)


def test_joined_thread_and_sanctioned_pool_thread_silent(armed):
    stop = threading.Event()
    with leakcheck.scope("clean") as s:
        # joined before scope end — not a leak
        t = threading.Thread(target=stop.wait, daemon=True)
        t.start()
        stop.set()
        t.join(timeout=5.0)
        # the process-wide pool singleton's threads outlive scopes by
        # design; the sanctioned prefix exempts them
        hold = threading.Event()
        pool_t = threading.Thread(
            target=hold.wait, name="lakesoul-rt-sanctioned", daemon=True
        )
        pool_t.start()
    try:
        assert s.leaks == [], "\n".join(v.render() for v in s.leaks)
    finally:
        hold.set()
        pool_t.join(timeout=5.0)


def test_seeded_child_leak_then_reaped_clean(armed):
    with leakcheck.scope("spawned") as s:
        child = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
    try:
        assert [v.kind for v in s.leaks] == ["child-leak"]
        assert str(child.pid) in s.leaks[0].message
        assert s.leaks[0].stacks and "test_leakcheck" in s.leaks[0].stacks[0]
    finally:
        child.kill()
        child.wait(timeout=10.0)
    # a reaped child is not a leak
    leakcheck.reset()
    with leakcheck.scope("reaped") as s2:
        done = subprocess.Popen([sys.executable, "-c", "pass"])
        done.wait(timeout=30.0)
    assert s2.leaks == [], "\n".join(v.render() for v in s2.leaks)


def test_staged_tmp_debris_vs_committed(armed, tmp_path):
    from lakesoul_tpu.runtime import atomicio

    with leakcheck.scope("staged") as s:
        staged = atomicio.stage_stream(
            str(tmp_path / "doc.json"), lambda f: f.write(b"{}")
        )
        # ... and nothing ever commits or aborts it
    assert [v.kind for v in s.leaks] == ["debris"]
    assert staged.tmp in s.leaks[0].message
    staged.abort()
    leakcheck.reset()
    with leakcheck.scope("committed") as s2:
        ok = atomicio.stage_stream(
            str(tmp_path / "ok.json"), lambda f: f.write(b"{}")
        )
        ok.commit()
    assert s2.leaks == [], "\n".join(v.render() for v in s2.leaks)
    assert (tmp_path / "ok.json").read_bytes() == b"{}"


def test_mkdtemp_debris_vs_pruned(armed):
    import shutil

    with leakcheck.scope("scratch") as s:
        d = tempfile.mkdtemp(prefix="leakcheck-seed-")
    try:
        assert [v.kind for v in s.leaks] == ["debris"]
        assert d in s.leaks[0].message
    finally:
        shutil.rmtree(d, ignore_errors=True)
    leakcheck.reset()
    with leakcheck.scope("pruned") as s2:
        d2 = tempfile.mkdtemp(prefix="leakcheck-seed-")
        shutil.rmtree(d2)
    assert s2.leaks == [], "\n".join(v.render() for v in s2.leaks)


def test_fd_leak_only_for_scratch_targets(armed, tmp_path):
    scratch = tmp_path / "spool.tmp-seed"
    scratch.write_bytes(b"x")
    plain = tmp_path / "warehouse.bin"
    plain.write_bytes(b"y")
    with leakcheck.scope("fds") as s:
        held_scratch = open(scratch, "rb")
        held_plain = open(plain, "rb")  # legitimate cache shape: silent
    try:
        assert [v.kind for v in s.leaks] == ["fd-leak"]
        assert ".tmp-" in s.leaks[0].message
    finally:
        held_scratch.close()
        held_plain.close()


def test_heap_budget_gate(armed):
    import tracemalloc

    tracemalloc.start()
    try:
        with leakcheck.scope("heap", heap_budget=1_000_000) as s:
            ballast = bytearray(8_000_000)
        assert [v.kind for v in s.leaks] == ["heap-growth"]
        assert "budget 1000000" in s.leaks[0].message
        del ballast
        leakcheck.reset()
        with leakcheck.scope("flat", heap_budget=1_000_000) as s2:
            small = bytearray(1024)
            del small
        assert s2.leaks == []
    finally:
        tracemalloc.stop()


def test_disabled_records_nothing():
    leakcheck.reset()
    assert not leakcheck.enabled()
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, daemon=True)
    with leakcheck.scope("dark") as s:
        t.start()
        d = tempfile.mkdtemp(prefix="leakcheck-dark-")
    try:
        # untracked artifacts can't be reported; the un-instrumented
        # thread IS visible via threading.enumerate, but carries no stack
        assert all(v.kind == "thread-leak" for v in s.leaks)
        for v in s.leaks:
            assert v.stacks == ()
    finally:
        stop.set()
        t.join(timeout=5.0)
        os.rmdir(d)
        leakcheck.reset()


# ------------------------------------------- regression pins (fixed leaks)


def test_exporter_shutdown_joins_serve_thread(armed):
    """PIN: serve_prometheus used to start an anonymous un-joinable
    thread; shutdown() must now join it — under leakcheck the serve scope
    ends thread-clean."""
    from lakesoul_tpu.obs.exporter import serve_prometheus

    with leakcheck.scope("exporter") as s:
        srv = serve_prometheus(port=0, host="127.0.0.1")
        thread = srv._serve_thread
        assert thread.name == "lakesoul-metrics-exporter"
        srv.shutdown()
        srv.server_close()
        assert not thread.is_alive()
    assert s.leaks == [], "\n".join(v.render() for v in s.leaks)


def test_autoscaler_retire_reaps_terminated_child(armed, tmp_path):
    """PIN: retire() used to pop+terminate and drop the handle — a zombie
    until interpreter exit.  It must now park the child on a retiring
    list that reap()/stop_all() waits, collecting the exit status."""
    from lakesoul_tpu.fleet.autoscale import WorkerSpawner

    spawner = WorkerSpawner(str(tmp_path), str(tmp_path))
    spawner.worker_argv = lambda worker_id: [
        sys.executable, "-c", "import time; time.sleep(60)",
    ]
    with leakcheck.scope("retire") as s:
        spawner.spawn()
        child = spawner._children[0]
        spawner.retire()
        deadline = time.monotonic() + 10.0
        while child.poll() is None and time.monotonic() < deadline:
            spawner.reap()
            time.sleep(0.05)
        spawner.stop_all()
        # the exit status was collected — not a zombie, not a leak
        assert child.returncode is not None
        assert spawner._retiring == [] and spawner._children == []
    assert s.leaks == [], "\n".join(v.render() for v in s.leaks)


def test_prune_stale_spools_sweeps_dead_owner(tmp_path):
    """PIN: spool dirs are pid-stamped at creation; a dir whose owner died
    without atexit (SIGKILL) must be swept by the next process's prune,
    while live-owner and markerless dirs are spared."""
    from lakesoul_tpu.runtime import atomicio
    from lakesoul_tpu.scanplane.delivery import (
        _OWNER_MARKER,
        _SPOOL_PREFIX,
        prune_stale_spools,
    )

    base = tmp_path / "shm"
    base.mkdir()
    dead = base / (_SPOOL_PREFIX + "dead")
    dead.mkdir()
    # a pid that cannot exist: max_pid is bounded well below 2**22 + 7
    atomicio.publish_atomic(str(dead / _OWNER_MARKER), str(2**22 + 7))
    live = base / (_SPOOL_PREFIX + "live")
    live.mkdir()
    atomicio.publish_atomic(str(live / _OWNER_MARKER), str(os.getpid()))
    foreign = base / (_SPOOL_PREFIX + "markerless")
    foreign.mkdir()
    unrelated = base / "not-a-spool"
    unrelated.mkdir()

    removed = prune_stale_spools(str(base))
    assert str(dead) in removed and not dead.exists()
    assert live.exists() and foreign.exists() and unrelated.exists()


def test_default_spool_dir_is_owned_and_sweepable(tmp_path, monkeypatch):
    """PIN: default_spool_dir stamps the owner pid so a successor can
    tell live scratch from debris."""
    import lakesoul_tpu.scanplane.delivery as delivery

    monkeypatch.setattr(delivery, "_spool_base", lambda: str(tmp_path))
    d = delivery.default_spool_dir()
    assert os.path.isdir(d)
    marker = os.path.join(d, delivery._OWNER_MARKER)
    with open(marker) as f:
        assert int(f.read()) == os.getpid()
    # own live spool survives a prune pass
    assert d not in delivery.prune_stale_spools(str(tmp_path))
    import shutil

    shutil.rmtree(d, ignore_errors=True)
