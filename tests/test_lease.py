"""Lease-machine edges (PR 7): expiry during renew, fencing-token
rejection of a zombie holder's commit, crash-recovery interacting with a
dead leaseholder's debris, and the polling watermark notifier's
crash-safety — all on injectable clocks (``now_ms=``), no sleeps except
the one real-TTL zombie test."""

from __future__ import annotations

import threading

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.compaction.events import PollingWatermarkNotifier
from lakesoul_tpu.compaction.service import LeasedCompactionService
from lakesoul_tpu.errors import LeaseFencedError
from lakesoul_tpu.meta.entity import CommitOp

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def catalog(tmp_path):
    return LakeSoulCatalog(str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db"))


def _stack_versions(t, n=12, rows=6):
    for i in range(n):
        t.upsert(pa.table({
            "id": np.arange(rows, dtype=np.int64),
            "v": np.full(rows, float(i)),
        }))


class TestLeasePrimitives:
    def test_acquire_free_then_held_then_reentrant(self, catalog):
        store = catalog.client.store
        a = store.acquire_lease("k", "alice", 1000, now_ms=100)
        assert a.fencing_token == 1 and a.expires_at_ms == 1100 and not a.taken_over
        assert store.acquire_lease("k", "bob", 1000, now_ms=200) is None
        again = store.acquire_lease("k", "alice", 1000, now_ms=500)
        assert again.fencing_token == 1 and again.expires_at_ms == 1500

    def test_expiry_during_renew(self, catalog):
        """THE renew edge: once the TTL passes, renew fails even if NOBODY
        re-acquired — an expired lease must go back through acquire (where
        a takeover would bump the token), never be silently revived, because
        the renewal gap is exactly where a peer may have slipped in."""
        store = catalog.client.store
        lease = store.acquire_lease("k", "alice", 1000, now_ms=0)
        ok = store.renew_lease("k", "alice", lease.fencing_token, 1000, now_ms=900)
        assert ok is not None and ok.expires_at_ms == 1900
        assert store.renew_lease("k", "alice", ok.fencing_token, 1000, now_ms=1900) is None
        # re-acquire by the SAME holder after expiry still bumps the token:
        # the gap is indistinguishable from a takeover window
        back = store.acquire_lease("k", "alice", 1000, now_ms=2000)
        assert back.fencing_token == lease.fencing_token + 1

    def test_takeover_bumps_token_and_fences_renewal(self, catalog):
        store = catalog.client.store
        store.acquire_lease("k", "alice", 1000, now_ms=0)
        taken = store.acquire_lease("k", "bob", 1000, now_ms=1500)
        assert taken.taken_over and taken.fencing_token == 2
        # the zombie's renew and release are both dead ends
        assert store.renew_lease("k", "alice", 1, 1000, now_ms=1600) is None
        assert not store.release_lease("k", "alice", 1)
        assert store.get_lease("k").holder == "bob"

    def test_release_clears_only_matching_token(self, catalog):
        store = catalog.client.store
        lease = store.acquire_lease("k", "alice", 1000, now_ms=0)
        assert store.release_lease("k", "alice", lease.fencing_token)
        assert store.get_lease("k") is None
        fresh = store.acquire_lease("k", "bob", 1000, now_ms=10)
        # release tombstones the row instead of deleting it, so tokens stay
        # monotonic per key — and acquiring a cleanly-released lease is not
        # a "takeover" (no dead peer was displaced)
        assert fresh.fencing_token == lease.fencing_token + 1
        assert not fresh.taken_over

    def test_tokens_stay_monotonic_across_release_cycles(self, catalog):
        """THE zombie-rebirth edge: alice (token 1) hangs past TTL, bob
        takes over (token 2), compacts and releases.  If release deleted
        the row, the next acquisition would mint token 1 again and the
        still-alive alice process would pass the commit guard with her
        stale token.  Tombstoning keeps every later token strictly higher,
        so alice's token 1 can never match again."""
        store = catalog.client.store
        store.acquire_lease("k", "alice", 1000, now_ms=0)  # hangs
        bob = store.acquire_lease("k", "bob", 1000, now_ms=2000)
        assert bob.taken_over and bob.fencing_token == 2
        assert store.release_lease("k", "bob", bob.fencing_token)
        # a RESTARTED service reusing the id "alice" acquires next
        fresh = store.acquire_lease("k", "alice", 1000, now_ms=2500)
        assert fresh.fencing_token == 3
        # the original hung alice still holds token 1 — renew, release and
        # (via the commit guard's token match) commit are all dead ends
        assert store.renew_lease("k", "alice", 1, 1000, now_ms=2600) is None
        assert not store.release_lease("k", "alice", 1)
        assert store.get_lease("k").fencing_token == 3

    def test_concurrent_acquirers_one_winner(self, catalog):
        store = catalog.client.store
        wins: list[str] = []
        barrier = threading.Barrier(6)

        def race(name):
            barrier.wait()
            if store.acquire_lease("hot", name, 60_000) is not None:
                wins.append(name)

        threads = [threading.Thread(target=race, args=(f"s{i}",)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1
        assert store.get_lease("hot").holder == wins[0]


class TestFencingAtCommit:
    def test_zombie_compaction_commit_is_fenced(self, catalog):
        """A compactor that stalls past its TTL and is replaced must NOT be
        able to land its commit: the lease guard runs inside the commit
        transaction, so the zombie's work vanishes atomically."""
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        zombie = store.acquire_lease("compaction/x", "zombie", ttl_ms=1)
        import time

        time.sleep(0.01)  # let the 1 ms TTL lapse
        peer = store.acquire_lease("compaction/x", "peer", ttl_ms=60_000)
        assert peer.taken_over and peer.fencing_token == 2
        before = t.to_arrow().sort_by("id")
        with pytest.raises(LeaseFencedError):
            t.compact(lease=zombie)
        # nothing landed: no CompactionCommit, identical table state
        head = store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op != CommitOp.COMPACTION
        assert t.refresh().to_arrow().sort_by("id").equals(before)
        # ... and the peer's commit (valid token) goes through, stamped
        assert t.compact(lease=peer) == 1
        head = store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op == CommitOp.COMPACTION
        assert head.expression == "fence=2"

    def test_fenced_commit_cleans_its_own_debris(self, catalog):
        """A fenced commit is dead for good — the client deletes its
        phase-1 rows immediately instead of leaving committed=0 debris for
        a recovery sweep (the two-services-race chaos test caught exactly
        that leak before this cleanup existed)."""
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        dead = store.acquire_lease("compaction/t", "dead", ttl_ms=1)
        import time

        time.sleep(0.01)
        store.acquire_lease("compaction/t", "somebody", ttl_ms=60_000)
        with pytest.raises(LeaseFencedError):
            t.compact(lease=dead)
        assert store.list_uncommitted_commits() == []

    def test_recovery_rolls_back_killed_leaseholders_debris(self, catalog, tmp_path):
        """A compactor SIGKILLed between commit phases (no chance to clean
        up) leaves committed=0 COMPACTION rows + staged files, while its
        lease quietly expires.  recover_incomplete_commits must roll that
        back — snapshot-replacing ops are never rolled forward, their
        read-version validation died with the holder — and the partition's
        still-open gap is then compacted by a healthy peer."""
        from lakesoul_tpu.meta.entity import DataCommitInfo, DataFileOp

        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        # the dead holder's trail: an expired lease and phase-1 debris
        store.acquire_lease(f"compaction/{t.info.table_id}/-5", "dead", ttl_ms=1)
        staged = tmp_path / "part-deadbeef_0000.parquet"
        staged.write_bytes(b"never-committed compaction output")
        store.insert_data_commit_info([
            DataCommitInfo(
                table_id=t.info.table_id,
                partition_desc="-5",
                commit_id=DataCommitInfo.new_commit_id(),
                file_ops=[DataFileOp(path=str(staged), size=staged.stat().st_size)],
                commit_op=CommitOp.COMPACTION,
                committed=False,
            )
        ])
        import time

        time.sleep(0.01)  # the 1 ms lease lapses; nobody renews it
        counts = catalog.client.recover_incomplete_commits(min_age_ms=0)
        assert counts == {"flag_repaired": 0, "rolled_forward": 0, "rolled_back": 1}
        assert store.list_uncommitted_commits() == []
        assert not staged.exists()  # the orphaned output was reclaimed
        # recovery never touches the lease table — expiry is the mechanism
        lease = store.get_lease(f"compaction/{t.info.table_id}/-5")
        assert lease is not None and lease.holder == "dead"
        # the gap is still open; a healthy service takes over from here
        svc = LeasedCompactionService(catalog, lease_ttl_s=30, poll_interval_s=0.01)
        assert svc.poll_once()["compacted"] == 1
        head = store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op == CommitOp.COMPACTION
        assert head.expression == "fence=2"  # takeover of the dead holder's lease
        assert t.refresh().to_arrow().num_rows == 6


class TestPollingWatermark:
    def test_candidates_derive_from_committed_state(self, catalog):
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        store = catalog.client.store
        assert store.get_compaction_candidates() == []
        _stack_versions(t, n=12)
        cands = store.get_compaction_candidates()
        assert [c.partition_desc for c in cands] == ["-5"]
        assert cands[0].table_path == t.info.table_path

    def test_killed_consumer_loses_no_events(self, catalog):
        """Crash-safety of the watermark design: a consumer that polled and
        died delivers nothing — a FRESH consumer (new process, empty
        memory) re-derives the same candidate, because the watermark is the
        committed compaction version, not consumer state."""
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        seen_a: list = []
        a = PollingWatermarkNotifier(store)
        a.listen(seen_a.append)
        assert a.poll() == 1 and len(seen_a) == 1
        del a  # consumer dies without acting
        seen_b: list = []
        b = PollingWatermarkNotifier(store)
        b.listen(seen_b.append)
        assert b.poll() == 1
        assert seen_b[0].partition_desc == seen_a[0].partition_desc
        # once compaction commits, the candidate disappears for EVERYONE
        t.compact()
        assert b.poll() == 0

    def test_open_gap_redelivered_every_poll(self, catalog):
        """At-least-once is the contract: an open gap re-emits on every
        poll until a CompactionCommit closes it — repeat suppression is
        the consumer's job (see LeasedCompactionService._skipped_heads)."""
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        seen: list = []
        n = PollingWatermarkNotifier(store)
        n.listen(seen.append)
        assert n.poll() == 1
        assert n.poll() == 1  # still open → delivered again
        assert seen[0].partition_desc == seen[1].partition_desc
        t.compact()
        assert n.poll() == 0  # gap closed for everyone


class TestLeasedServiceUnits:
    def test_poll_once_compacts_and_releases(self, catalog):
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        svc = LeasedCompactionService(catalog, lease_ttl_s=30, poll_interval_s=0.01)
        counts = svc.poll_once()
        assert counts["candidates"] == 1 and counts["compacted"] == 1
        store = catalog.client.store
        head = store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op == CommitOp.COMPACTION
        assert head.expression == "fence=1"
        # lease released; nothing left to do
        assert store.get_lease(svc._lease_key(
            type("E", (), {"table_id": t.info.table_id, "partition_desc": "-5"})()
        )) is None
        assert svc.poll_once()["candidates"] == 0

    def test_job_longer_than_ttl_completes_via_heartbeat(self, catalog):
        """A compaction that outlives one TTL must still commit: the
        heartbeat renews the store row at TTL/3, so the commit-time lease
        guard sees a live lease and the original fencing token.  Without
        renewal this livelocks — every pass fences at commit, a peer
        re-runs the same doomed job, and the partition never compacts."""
        from lakesoul_tpu.runtime import faults

        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        svc = LeasedCompactionService(catalog, lease_ttl_s=0.3, poll_interval_s=0.01)
        # stall inside the leased window for 3× the TTL before compacting
        faults.install("compaction.leased_job:1.0:delay:0.9")
        try:
            counts = svc.poll_once()
        finally:
            faults.clear()
        assert counts["compacted"] == 1 and counts["fenced"] == 0, counts
        store = catalog.client.store
        head = store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op == CommitOp.COMPACTION
        assert head.expression == "fence=1"  # the ORIGINAL token, renewed alive

    def test_peer_with_held_lease_skips(self, catalog):
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        _stack_versions(t)
        store = catalog.client.store
        key = f"compaction/{t.info.table_id}/-5"
        store.acquire_lease(key, "other-process", ttl_ms=60_000)
        svc = LeasedCompactionService(catalog, lease_ttl_s=1, poll_interval_s=0.01)
        counts = svc.poll_once()
        assert counts == {
            "candidates": 1, "compacted": 0, "skipped": 0,
            "lease_held": 1, "fenced": 0, "conflicts": 0, "errors": 0,
        }
        # the partition was NOT compacted and stays a candidate
        assert store.get_compaction_candidates() != []
