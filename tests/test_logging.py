"""Data-plane observability (VERDICT r1 #9): commit retries, scan/flush
timings, and cache hits are visible in captured logs — the role of the
reference's `tracing` instrumentation (reader.rs:116,147, pyo3-log) — and
the structured JSON formatter stamps the active span's trace id."""

import io
import json
import logging

import fsspec
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


class TestCommitLogging:
    def test_conflict_retry_is_logged(self, catalog, caplog):
        from lakesoul_tpu.errors import CommitConflictError

        t = catalog.create_table("lg", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        # deterministic conflict: first insert attempt loses the version race
        store = catalog.client.store
        real_insert = store.transaction_insert_partition_info
        failed = {"n": 0}

        def flaky_insert(parts, **kwargs):
            if failed["n"] == 0:
                failed["n"] = 1
                raise CommitConflictError("version taken by a concurrent committer")
            return real_insert(parts, **kwargs)

        store.transaction_insert_partition_info = flaky_insert
        try:
            with caplog.at_level(logging.WARNING, logger="lakesoul_tpu.meta.client"):
                t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        finally:
            store.transaction_insert_partition_info = real_insert
        msgs = [r.getMessage() for r in caplog.records]
        assert any("conflict" in m and "retrying" in m for m in msgs), msgs
        assert t.to_arrow().num_rows == 2  # retry succeeded

    def test_commit_timing_at_debug(self, catalog, caplog):
        t = catalog.create_table("lg2", SCHEMA)
        with caplog.at_level(logging.DEBUG, logger="lakesoul_tpu.meta.client"):
            t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        assert any(r.getMessage().startswith("commit AppendCommit") for r in caplog.records)


class TestScanLogging:
    def test_unit_read_timing_at_debug(self, catalog, caplog):
        t = catalog.create_table("lg3", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        with caplog.at_level(logging.DEBUG, logger="lakesoul_tpu.io.reader"):
            t.to_arrow()
        assert any("scan unit materialized" in r.getMessage() for r in caplog.records)

    def test_flush_logged_at_debug(self, catalog, caplog):
        t = catalog.create_table("lg4", SCHEMA)
        with caplog.at_level(logging.DEBUG, logger="lakesoul_tpu.io.writer"):
            t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        assert any(r.getMessage().startswith("flush staged") for r in caplog.records)


class TestCacheLogging:
    def test_cache_hit_is_logged(self, tmp_path, caplog):
        from lakesoul_tpu.io.page_cache import DiskPageCache

        fs = fsspec.filesystem("memory")
        fs.pipe_file("/lg/blob", b"a" * 65536)
        cache = DiskPageCache(str(tmp_path / "c"), page_bytes=16 << 10)
        with caplog.at_level(logging.DEBUG, logger="lakesoul_tpu.io.page_cache"):
            cache.read_range(fs, "/lg/blob", 0, 65536)  # miss
            cache.read_range(fs, "/lg/blob", 0, 65536)  # hit
        hits = [r for r in caplog.records if "hit" in r.getMessage()]
        assert any("4 hit / 0 miss" in r.getMessage() for r in hits)
        fs.rm("/lg", recursive=True)


class TestJsonLogFormat:
    """LAKESOUL_LOG_FORMAT=json: one JSON object per line, trace_id stamped
    whenever a span is active (obs satellite)."""

    def test_formatter_stamps_trace_id_inside_span(self):
        from lakesoul_tpu.obs import span
        from lakesoul_tpu.obs.logging import JsonLogFormatter

        logger = logging.getLogger("lakesoul_tpu.tests.jsonfmt")
        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        handler.setFormatter(JsonLogFormatter())
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
        try:
            with span("json-fmt-test", trace_id="tid-json-1"):
                logger.info("inside %d", 1)
            logger.warning("outside")
        finally:
            logger.removeHandler(handler)
            logger.propagate = True
        lines = [json.loads(line) for line in buf.getvalue().splitlines()]
        assert lines[0]["msg"] == "inside 1"
        assert lines[0]["trace_id"] == "tid-json-1"
        assert lines[0]["level"] == "INFO"
        assert lines[0]["logger"] == "lakesoul_tpu.tests.jsonfmt"
        assert "ts" in lines[0]
        # no active span → no trace_id key at all (not a null)
        assert lines[1]["level"] == "WARNING"
        assert "trace_id" not in lines[1]

    def test_exception_serialized(self):
        from lakesoul_tpu.obs.logging import JsonLogFormatter

        logger = logging.getLogger("lakesoul_tpu.tests.jsonexc")
        buf = io.StringIO()
        handler = logging.StreamHandler(buf)
        handler.setFormatter(JsonLogFormatter())
        logger.addHandler(handler)
        logger.propagate = False
        try:
            try:
                raise ValueError("boom")
            except ValueError:
                logger.exception("failed")
        finally:
            logger.removeHandler(handler)
            logger.propagate = True
        rec = json.loads(buf.getvalue())
        assert rec["msg"] == "failed"
        assert "ValueError: boom" in rec["exc"]

    def test_env_var_selects_json(self, monkeypatch):
        from lakesoul_tpu.obs.logging import JsonLogFormatter, configure_logging

        monkeypatch.setenv("LAKESOUL_LOG_FORMAT", "json")
        root = logging.getLogger("lakesoul_tpu")
        handler = configure_logging(stream=io.StringIO())
        try:
            assert isinstance(handler.formatter, JsonLogFormatter)
            # idempotent: reconfiguring replaces, never stacks
            monkeypatch.setenv("LAKESOUL_LOG_FORMAT", "text")
            handler2 = configure_logging(stream=io.StringIO())
            configured = [
                h for h in root.handlers
                if getattr(h, "_lakesoul_configured", False)
            ]
            assert configured == [handler2]
            assert not isinstance(handler2.formatter, JsonLogFormatter)
        finally:
            for h in list(root.handlers):
                if getattr(h, "_lakesoul_configured", False):
                    root.removeHandler(h)
