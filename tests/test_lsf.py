"""LSF native columnar format: round-trips, encodings, registry + e2e.

The third physical format (the Vortex role, file_format/vortex.rs): these
tests pin the encoding decisions (FOR / delta-FOR / dict / raw / ipc
fallback), exact schema + data round-trips incl. nulls, the bounded
streaming iterator, and the catalog-level ``lakesoul.file_format=lsf``
table property end to end (mixed-format partitions included).
"""

import datetime
import os

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.io.config import IOConfig
from lakesoul_tpu.io.formats import format_by_name, format_for
from lakesoul_tpu.io.lsf import LsfFile, write_lsf_table


def _roundtrip(table: pa.Table, tmp_path, config=None, columns=None) -> pa.Table:
    path = str(tmp_path / "t.lsf")
    write_lsf_table(table, path, config=config)
    return LsfFile(path).read(columns)


def _assert_tables_equal(a: pa.Table, b: pa.Table):
    assert a.schema.equals(b.schema), f"{a.schema} != {b.schema}"
    assert a.num_rows == b.num_rows
    for name in a.column_names:
        ca, cb = a.column(name), b.column(name)
        assert ca.combine_chunks().equals(cb.combine_chunks()), name


class TestRoundTrips:
    def test_int_types_with_nulls(self, tmp_path):
        rng = np.random.default_rng(0)
        cols = {}
        for name, dt, lo, hi in [
            ("i8", pa.int8(), -100, 100),
            ("i16", pa.int16(), -30000, 30000),
            ("i32", pa.int32(), -2**31, 2**31 - 1),
            ("i64", pa.int64(), -2**62, 2**62),
            ("u8", pa.uint8(), 0, 255),
            ("u32", pa.uint32(), 0, 2**32 - 1),
        ]:
            vals = rng.integers(lo, hi, 1000)
            arr = pa.array(vals, type=dt)
            mask = rng.random(1000) < 0.1
            cols[name] = pa.array(
                [None if m else int(v) for v, m in zip(vals, mask)], type=dt
            )
        t = pa.table(cols)
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_uint64_extremes(self, tmp_path):
        t = pa.table({"u": pa.array([0, 2**64 - 1, 2**63, 5], type=pa.uint64())})
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_int64_full_range(self, tmp_path):
        # span >= 2^63: FOR impossible, must fall back to raw
        t = pa.table({"i": pa.array([-2**63, 2**63 - 1, 0], type=pa.int64())})
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_floats_and_bool(self, tmp_path):
        rng = np.random.default_rng(1)
        t = pa.table({
            "f32": pa.array(rng.normal(size=500).astype(np.float32)),
            "f64": pa.array(
                [None if i % 7 == 0 else float(i) for i in range(500)],
                type=pa.float64(),
            ),
            "b": pa.array([None if i % 11 == 0 else i % 2 == 0 for i in range(500)]),
        })
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_sorted_ids_use_dfor(self, tmp_path):
        ids = pa.array(np.arange(100_000, dtype=np.int64) * 3 + 7)
        t = pa.table({"id": ids})
        path = str(tmp_path / "t.lsf")
        size = write_lsf_table(t, path)
        f = LsfFile(path)
        meta = f._footer["chunks"][0]["columns"][0]
        assert meta["enc"] == "dfor"
        # constant stride of 3 → 0-bit deltas; file is ~just the footer
        assert meta["width"] == 0
        assert size < 4096
        _assert_tables_equal(t, f.read())

    def test_constant_column_zero_bytes(self, tmp_path):
        t = pa.table({"c": pa.array([42] * 10_000, type=pa.int32())})
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        meta = f._footer["chunks"][0]["columns"][0]
        assert meta["enc"] == "for" and meta["width"] == 0 and meta["bufs"] == []
        _assert_tables_equal(t, f.read())

    def test_strings_high_cardinality(self, tmp_path):
        t = pa.table({
            "s": pa.array(
                [None if i % 13 == 0 else f"value-{i}-{'x' * (i % 17)}" for i in range(5000)]
            ),
        })
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        assert f._footer["chunks"][0]["columns"][0]["enc"] == "bytes"
        _assert_tables_equal(t, f.read())

    def test_strings_low_cardinality_dict(self, tmp_path):
        vals = [None if i % 31 == 0 else ["alpha", "beta", "gamma"][i % 3] for i in range(5000)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        meta = f._footer["chunks"][0]["columns"][0]
        assert meta["enc"] == "dict"
        assert meta["n_values"] == 4  # alpha/beta/gamma + the "" null fill
        got = f.read()
        assert got.column("s").type == pa.string()
        _assert_tables_equal(t, got)

    def test_binary_and_large_types(self, tmp_path):
        t = pa.table({
            "bin": pa.array([b"ab", None, b"", b"\x00\xff"], type=pa.binary()),
            "ls": pa.array(["x", "yy", None, "zzz"], type=pa.large_string()),
            "lb": pa.array([b"1", b"22", b"", None], type=pa.large_binary()),
        })
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_temporal_types(self, tmp_path):
        t = pa.table({
            "ts": pa.array(
                [datetime.datetime(2026, 1, 1, 12), None, datetime.datetime(1970, 1, 1)],
                type=pa.timestamp("us"),
            ),
            "d32": pa.array([datetime.date(2026, 7, 29), None, datetime.date(2000, 1, 1)]),
        })
        _assert_tables_equal(t, _roundtrip(t, tmp_path))

    def test_embedding_fsl_zero_copy(self, tmp_path):
        rng = np.random.default_rng(2)
        vecs = rng.normal(size=(300, 8)).astype(np.float32)
        arr = pa.FixedSizeListArray.from_arrays(pa.array(vecs.reshape(-1)), 8)
        t = pa.table({"emb": arr})
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        assert f._footer["chunks"][0]["columns"][0]["enc"] == "fsl"
        _assert_tables_equal(t, f.read())

    def test_ipc_fallback_types(self, tmp_path):
        t = pa.table({
            "lst": pa.array([[1, 2], None, [], [3]], type=pa.list_(pa.int64())),
            "dec": pa.array([None, 1, 2, 3], type=pa.decimal128(10, 2)),
            "st": pa.array([{"a": 1}, None, {"a": 3}, {"a": 4}],
                           type=pa.struct([("a", pa.int32())])),
        })
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        for col in f._footer["chunks"][0]["columns"]:
            assert col["enc"] == "ipc"
        _assert_tables_equal(t, f.read())

    def test_empty_table_and_single_row(self, tmp_path):
        schema = pa.schema([("a", pa.int64()), ("s", pa.string())])
        empty = schema.empty_table()
        got = _roundtrip(empty, tmp_path)
        assert got.num_rows == 0 and got.schema.equals(schema)
        one = pa.table({"a": [7], "s": ["x"]}, schema=schema)
        path = str(tmp_path / "one.lsf")
        write_lsf_table(one, path)
        _assert_tables_equal(one, LsfFile(path).read())

    def test_all_null_column(self, tmp_path):
        t = pa.table({"x": pa.array([None] * 100, type=pa.int32()),
                      "s": pa.array([None] * 100, type=pa.string())})
        _assert_tables_equal(t, _roundtrip(t, tmp_path))


class TestChunkingAndProjection:
    def _big(self, n=600_000):
        rng = np.random.default_rng(3)
        return pa.table({
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "v": pa.array(rng.normal(size=n).astype(np.float32)),
            "tag": pa.array([f"t{i % 5}" for i in range(n)]),
        })

    def test_multi_chunk_roundtrip_and_order(self, tmp_path):
        t = self._big()
        cfg = IOConfig(max_row_group_size=100_000)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path, config=cfg)
        f = LsfFile(path)
        assert len(f._footer["chunks"]) == 6
        got = f.read()
        _assert_tables_equal(t, got)

    def test_iter_batches_bounded(self, tmp_path):
        t = self._big(250_000)
        cfg = IOConfig(max_row_group_size=50_000)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path, config=cfg)
        fmt = format_for(path)
        sizes, ids = [], []
        for b in fmt.iter_batches(path, batch_size=8192):
            sizes.append(len(b))
            ids.append(b.column("id").to_numpy())
        assert max(sizes) <= 8192
        np.testing.assert_array_equal(np.concatenate(ids), np.arange(250_000))

    def test_projection_and_missing_columns(self, tmp_path):
        t = self._big(10_000)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        fmt = format_for(path)
        got = fmt.read_table(path, columns=["v", "ghost"])
        assert got.column_names == ["v"]  # caller null-fills missing, like parquet
        assert got.num_rows == 10_000

    def test_zero_stored_columns_keep_row_count(self, tmp_path):
        """Projection to only-missing columns must preserve num_rows (the
        caller null-fills schema-evolution columns from it)."""
        t = self._big(5000)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path, config=IOConfig(max_row_group_size=2000))
        got = LsfFile(path).read(columns=["ghost"])
        assert got.num_columns == 0 and got.num_rows == 5000
        streamed = sum(
            b.num_rows for b in format_for(path).iter_batches(path, columns=["ghost"])
        )
        assert streamed == 5000

    def test_remote_footer_only_metadata(self, tmp_path):
        """count_rows/read_schema on a remote store must not GET the body."""
        import fsspec

        t = self._big(7000)
        mem = fsspec.filesystem("memory")
        local = str(tmp_path / "t.lsf")
        write_lsf_table(t, local)
        with open(local, "rb") as f:
            mem.pipe_file("/lsf_meta/t.lsf", f.read())
        calls = []
        orig = type(mem).cat_file

        def spy(self, path, start=None, end=None, **kw):
            calls.append((start, end))
            return orig(self, path, start=start, end=end, **kw)

        fmt = format_by_name("lsf")
        try:
            type(mem).cat_file = spy
            assert fmt.count_rows("memory://lsf_meta/t.lsf") == 7000
            assert fmt.read_schema("memory://lsf_meta/t.lsf").equals(t.schema)
        finally:
            type(mem).cat_file = orig
        assert calls and all(s is not None for s, _ in calls)  # ranged only
        size = mem.size("/lsf_meta/t.lsf")
        assert all((e - s) < size // 2 for s, e in calls)

    def test_count_rows_and_schema(self, tmp_path):
        t = self._big(12_345)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        fmt = format_for(path)
        assert fmt.count_rows(path) == 12_345
        assert fmt.read_schema(path).equals(t.schema)

    def test_filter_best_effort(self, tmp_path):
        import pyarrow.dataset as pads

        t = self._big(10_000)
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        fmt = format_for(path)
        got = fmt.read_table(path, arrow_filter=(pads.field("id") < 100))
        assert got.num_rows == 100
        # filter on a column the file doesn't have: ignored, not an error
        got = fmt.read_table(path, arrow_filter=(pads.field("ghost") < 1))
        assert got.num_rows == 10_000


class TestZoneMaps:
    """Chunk min/max statistics skip whole chunks on refuting predicates
    (the role of parquet's row-group statistics pruning)."""

    def _file(self, tmp_path, n=100_000, chunk=10_000):
        t = pa.table({
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "v": pa.array(np.random.default_rng(0).normal(size=n).astype(np.float32)),
        })
        path = str(tmp_path / "z.lsf")
        write_lsf_table(t, path, config=IOConfig(max_row_group_size=chunk))
        return path, t

    def test_skip_chunks_by_stats(self, tmp_path):
        path, t = self._file(tmp_path)
        f = LsfFile(path)
        got = f.read(zone_predicates=[("id", "lt", 15_000)])
        assert f.chunks_decoded == 2  # chunks [0,10k) and [10k,20k) only
        assert got.num_rows == 20_000  # stats skip is chunk-granular
        f = LsfFile(path)
        got = f.read(zone_predicates=[("id", "ge", 95_000)])
        assert f.chunks_decoded == 1 and got.num_rows == 10_000
        f = LsfFile(path)
        got = f.read(zone_predicates=[("id", "eq", 55_555)])
        assert f.chunks_decoded == 1
        f = LsfFile(path)
        got = f.read(zone_predicates=[("id", "in", [5, 99_999])])
        assert f.chunks_decoded == 2
        f = LsfFile(path)
        got = f.read(zone_predicates=[("id", "lt", -1)])
        assert f.chunks_decoded == 0 and got.num_rows == 0
        # float columns carry min/max stats too: v ~ N(0,1), so every chunk
        # refutes v < -100 and none refutes v < 0
        f = LsfFile(path)
        got = f.read(zone_predicates=[("v", "lt", -100.0)])
        assert f.chunks_decoded == 0 and got.num_rows == 0
        f = LsfFile(path)
        f.read(zone_predicates=[("v", "lt", 0.0)])
        assert f.chunks_decoded == 10

    def test_float_stats_skip_nan_and_null_fill_is_sound(self, tmp_path):
        # a NaN anywhere in the chunk poisons min/max → that chunk keeps no
        # stats and never refutes; null fill (0.0) only widens the range
        t = pa.table({
            "a": pa.array([1.0, float("nan"), 3.0], type=pa.float64()),
            "b": pa.array([5.0, None, 9.0], type=pa.float64()),
        })
        path = str(tmp_path / "nan.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        f.read(zone_predicates=[("a", "gt", 100.0)])
        assert f.chunks_decoded == 1  # NaN column: no stats, no refutation
        f = LsfFile(path)
        got = f.read(zone_predicates=[("b", "lt", -1.0)])
        assert f.chunks_decoded == 0 and got.num_rows == 0  # [0, 9] refutes
        f = LsfFile(path)
        f.read(zone_predicates=[("b", "lt", 2.0)])
        assert f.chunks_decoded == 1  # fill-0 widened the range: kept (sound)

    def test_raw_int_chunks_carry_stats(self, tmp_path):
        # full-range int64 falls back to raw encoding but still has stats
        t = pa.table({"i": pa.array([-2**63, 0, 2**63 - 1] * 100, type=pa.int64()),
                      "j": pa.array(np.arange(300, dtype=np.int64))})
        path = str(tmp_path / "raw.lsf")
        write_lsf_table(t, path)
        f = LsfFile(path)
        meta = f._footer["chunks"][0]["columns"][0]
        assert meta["enc"] == "raw" and meta["stats"] == [-2**63, 2**63 - 1]

    def test_e2e_scan_filter_skips_chunks(self, tmp_warehouse, monkeypatch):
        """A PK-only filter pushes down through the catalog scan and the zone
        maps skip chunks; results stay exact."""
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.io.filters import col
        import lakesoul_tpu.io.lsf as lsf_mod

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table(
            "zm", schema, primary_keys=["id"], hash_bucket_num=1,
            properties={"lakesoul.file_format": "lsf",
                        "lakesoul.max_row_group_size": "1000"},
        )
        n = 20_000
        t.write_arrow(pa.table({
            "id": np.arange(n, dtype=np.int64), "v": np.zeros(n),
        }, schema=schema))
        decoded = []
        orig = lsf_mod.LsfFile._chunk_table

        def spy(self, chunk, columns):
            decoded.append(chunk["n_rows"])
            return orig(self, chunk, columns)

        monkeypatch.setattr(lsf_mod.LsfFile, "_chunk_table", spy)
        got = t.scan().filter(col("id") < 1500).to_arrow()
        assert got.num_rows == 1500
        assert sum(decoded) <= 2000  # 2 of 20 chunks decoded

    def test_streaming_merge_respects_zone_maps(self, tmp_warehouse):
        """Zone predicates flow into the bounded-memory streaming path; the
        merged result equals the materialized one."""
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.io.filters import col

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table(
            "zs", schema, primary_keys=["id"], hash_bucket_num=1,
            properties={"lakesoul.file_format": "lsf",
                        "lakesoul.max_row_group_size": "500",
                        "lakesoul.memory_budget_bytes": str(1 << 20)},
        )
        n = 30_000
        t.write_arrow(pa.table({"id": np.arange(n), "v": np.zeros(n)}, schema=schema))
        t.upsert(pa.table({"id": np.arange(0, n, 7), "v": np.ones(n // 7 + (1 if n % 7 else 0))}, schema=schema))
        flt = (col("id") >= 100) & (col("id") < 700)
        streamed = pa.Table.from_batches(
            list(t.scan().filter(flt).batch_size(128).to_batches())
        ).sort_by("id")
        assert streamed.column("id").to_pylist() == list(range(100, 700))
        assert streamed.column("v").to_pylist()[5] == 1.0  # id=105 upserted


class TestRegistryDispatch:
    def test_extension_dispatch(self):
        assert format_for("a/b/part-x_0000.lsf").name == "lsf"
        assert format_by_name("lsf").extensions == (".lsf",)

    def test_numpy_fallback_decodes_native_file(self, tmp_path, monkeypatch):
        t = pa.table({
            "id": pa.array(np.arange(5000, dtype=np.int64) * 2),
            "k": pa.array(np.random.default_rng(0).integers(0, 1000, 5000), type=pa.int32()),
            "s": pa.array([f"s{i % 4}" for i in range(5000)]),
        })
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)  # native pack (when available)
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        _assert_tables_equal(t, LsfFile(path).read())

    def test_native_file_written_by_fallback(self, tmp_path, monkeypatch):
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        t = pa.table({"id": pa.array([5, 1, 9, 1 << 40], type=pa.int64())})
        path = str(tmp_path / "t.lsf")
        write_lsf_table(t, path)
        monkeypatch.delenv("LAKESOUL_TPU_DISABLE_NATIVE")
        _assert_tables_equal(t, LsfFile(path).read())


class TestCatalogE2E:
    def test_lsf_table_property_mor(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("s", pa.string())])
        t = catalog.create_table(
            "lsf_t", schema, primary_keys=["id"], hash_bucket_num=2,
            properties={"lakesoul.file_format": "lsf"},
        )
        t.write_arrow(pa.table({
            "id": list(range(100)), "v": [float(i) for i in range(100)],
            "s": [f"a{i}" for i in range(100)],
        }, schema=schema))
        t.upsert(pa.table({
            "id": [3, 7], "v": [30.0, 70.0], "s": ["b3", "b7"],
        }, schema=schema))
        files = [u for unit in t.scan().scan_plan() for u in unit.data_files]
        assert files and all(f.endswith(".lsf") for f in files)
        got = t.scan().to_arrow().sort_by("id")
        assert got.num_rows == 100
        assert got.column("v").to_pylist()[3] == 30.0
        assert got.column("s").to_pylist()[7] == "b7"
        # compaction rewrites through the same format property
        t.compact()
        files = [u for unit in t.scan().scan_plan() for u in unit.data_files]
        assert files and all(f.endswith(".lsf") for f in files)
        got2 = t.scan().to_arrow().sort_by("id")
        assert got2.equals(got)

    def test_mixed_format_partition(self, tmp_warehouse):
        """A partition holding parquet + lsf files reads transparently."""
        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.int32())])
        t = catalog.create_table("mix", schema, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1, 2, 3], "v": [10, 20, 30]}, schema=schema))
        t.set_properties({"lakesoul.file_format": "lsf"})
        t = catalog.table("mix")
        t.upsert(pa.table({"id": [2, 4], "v": [99, 40]}, schema=schema))
        exts = {os.path.splitext(u)[1]
                for unit in t.scan().scan_plan() for u in unit.data_files}
        assert exts == {".parquet", ".lsf"}
        got = t.scan().to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3, 4]
        assert got.column("v").to_pylist() == [10, 99, 30, 40]
