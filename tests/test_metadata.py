"""Metadata layer tests: DDL, commit protocol, optimistic concurrency,
scan-plan construction, time travel, incremental reads."""

import threading

import pyarrow as pa
import pytest

from lakesoul_tpu.errors import CommitConflictError, MetadataError, TableNotFoundError
from lakesoul_tpu.meta import (
    CommitOp,
    DataCommitInfo,
    DataFileOp,
    MetaDataClient,
    MetaInfo,
    PartitionInfo,
)
from lakesoul_tpu.meta.client import extract_hash_bucket_id, partition_desc_to_dict
from lakesoul_tpu.meta.store import SqliteMetadataStore


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float32()), ("date", pa.string())])


@pytest.fixture(params=["sqlite", "pglike", "pg-real"])
def client(tmp_path, request, monkeypatch):
    """The full metadata suite runs against THREE backends: sqlite,
    PostgresMetadataStore driven by a wire-faithful psycopg2 fake (format
    paramstyle, autocommit switching, psycopg2 error classes, real
    cross-connection transactions — VERDICT r1 weak #5), and — when a real
    server is reachable — LIVE PostgreSQL (the reference CI's postgres:14.5
    shape, .github/workflows/rust-ci.yml:27-56).  The live leg needs
    ``LAKESOUL_TEST_PG_DSN`` (e.g. postgresql://user:pw@host/db) and the
    psycopg2 driver; this image ships neither, so it shows as SKIPPED here
    and runs wherever they exist.  tests/test_pg_dialect.py statically
    checks every emitted statement for PG-dialect safety in the meantime."""
    if request.param == "sqlite":
        yield MetaDataClient(db_path=str(tmp_path / "meta.db"))
        return
    if request.param == "pg-real":
        import os

        dsn = os.environ.get("LAKESOUL_TEST_PG_DSN")
        if not dsn:
            pytest.skip("no live PostgreSQL (set LAKESOUL_TEST_PG_DSN)")
        pytest.importorskip("psycopg2")
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        store = PostgresMetadataStore(dsn)

        def wipe():
            # the DSN must point at a DEDICATED throwaway database: the
            # suite uses fixed table names, so the metadata tables are
            # truncated — before (residue from a crashed prior run) AND
            # after each test
            conn = store._conn()
            with conn:
                cur = conn.cursor()
                for tbl in ("namespace", "table_info", "table_name_id",
                            "table_path_id", "data_commit_info",
                            "partition_info", "global_config",
                            "discard_compressed_file_info"):
                    cur.execute(f"DELETE FROM {tbl}")

        wipe()
        yield MetaDataClient(store=store)
        wipe()
        return
    import sys

    import fake_psycopg2

    monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
    from lakesoul_tpu.meta.store import PostgresMetadataStore

    store = PostgresMetadataStore(f"postgresql://fake/{tmp_path.name}")
    yield MetaDataClient(store=store)
    fake_psycopg2.reset(f"postgresql://fake/{tmp_path.name}")


def make_table(client, name="t1", pks=("id",), ranges=()):
    return client.create_table(
        name,
        f"/tmp/wh/{name}",
        SCHEMA,
        primary_keys=list(pks),
        range_partitions=list(ranges),
    )


def append_files(client, info, desc, paths, op=CommitOp.APPEND):
    return client.commit_data_files(
        info, {desc: [DataFileOp(path=p, size=100) for p in paths]}, op
    )


class TestDDL:
    def test_create_get_drop(self, client):
        info = make_table(client)
        got = client.get_table_info_by_name("t1")
        assert got.table_id == info.table_id
        assert got.primary_keys == ["id"]
        assert got.hash_bucket_num == 4  # default when PKs present
        assert got.arrow_schema == SCHEMA
        client.drop_table("t1")
        with pytest.raises(TableNotFoundError):
            client.get_table_info_by_name("t1")

    def test_duplicate_name_rejected(self, client):
        make_table(client)
        with pytest.raises(MetadataError):
            make_table(client)

    def test_partitions_field_round_trip(self, client):
        info = make_table(client, name="t2", pks=("id",), ranges=("date",))
        assert info.partitions == "date;id"
        assert info.range_partition_columns == ["date"]
        assert info.primary_keys == ["id"]

    def test_namespaces(self, client):
        assert "default" in client.list_namespaces()
        client.create_namespace("ns1")
        assert "ns1" in client.list_namespaces()


class TestCommitProtocol:
    def test_append_versions_accumulate(self, client):
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        append_files(client, info, "-5", ["/f/part-b_0000.parquet"])
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert head.version == 1
        assert len(head.snapshot) == 2  # append extends the snapshot

    def test_compaction_replaces_snapshot(self, client):
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        append_files(client, info, "-5", ["/f/part-b_0000.parquet"])
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        client.commit_data_files(
            info,
            {"-5": [DataFileOp(path="/f/part-compact_0000.parquet")]},
            CommitOp.COMPACTION,
            read_partition_info=[head],
        )
        new_head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert new_head.version == 2
        assert len(new_head.snapshot) == 1
        assert new_head.commit_op == CommitOp.COMPACTION

    def test_compaction_conflict_detected(self, client):
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        stale = client.store.get_latest_partition_info(info.table_id, "-5")
        # concurrent append advances the partition
        append_files(client, info, "-5", ["/f/part-b_0000.parquet"])
        with pytest.raises(CommitConflictError):
            client.commit_data_files(
                info,
                {"-5": [DataFileOp(path="/f/part-compact_0000.parquet")]},
                CommitOp.COMPACTION,
                read_partition_info=[stale],
            )

    def test_conflicted_update_cleanup_follows_staged_file_fate(self, client):
        """A conflicted UPDATE whose caller deletes its staged files
        (``staged_deleted_on_conflict=True``, the partition-rewrite DML
        path) must not leave committed=0 rows pointing at nothing; the
        default keeps the rows because cdc replay reuses the same staged
        files and recovery needs them to reclaim the files on give-up."""
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        stale = client.store.get_latest_partition_info(info.table_id, "-5")
        append_files(client, info, "-5", ["/f/part-b_0000.parquet"])
        for flag, rows_left in ((True, 0), (False, 1)):
            with pytest.raises(CommitConflictError):
                client.commit_data_files(
                    info,
                    {"-5": [DataFileOp(path=f"/f/part-up{flag}_0000.parquet")]},
                    CommitOp.UPDATE,
                    read_partition_info=[stale],
                    staged_deleted_on_conflict=flag,
                )
            debris = [
                c for c in client.store.list_uncommitted_commits()
                if c.table_id == info.table_id
            ]
            assert len(debris) == rows_left, (flag, debris)
            for c in debris:
                client.store.delete_data_commit_info(
                    c.table_id, c.partition_desc, [c.commit_id]
                )

    def test_delete_clears_snapshot(self, client):
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        client.commit_data(
            MetaInfo(
                table_info=info,
                list_partition=[PartitionInfo(info.table_id, "-5")],
            ),
            CommitOp.DELETE,
        )
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert head.snapshot == []

    def test_idempotent_commit_replay(self, client):
        info = make_table(client)
        cid = DataCommitInfo.new_commit_id()
        c1 = client.commit_data_files(
            info,
            {"-5": [DataFileOp(path="/f/part-a_0000.parquet")]},
            CommitOp.APPEND,
            commit_id_by_partition={"-5": cid},
        )
        c2 = client.commit_data_files(
            info,
            {"-5": [DataFileOp(path="/f/part-a_0000.parquet")]},
            CommitOp.APPEND,
            commit_id_by_partition={"-5": cid},
        )
        assert len(c1) == 1 and c2 == []  # replay is a no-op
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert head.version == 0

    def test_concurrent_appends_all_land(self, tmp_path):
        # many writers on one store: optimistic retry must serialize them
        store = SqliteMetadataStore(str(tmp_path / "meta.db"))
        client = MetaDataClient(store=store)
        info = make_table(client)
        errs = []

        def writer(i):
            try:
                c = MetaDataClient(store=SqliteMetadataStore(str(tmp_path / "meta.db")))
                append_files(c, info, "-5", [f"/f/part-w{i}_0000.parquet"])
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert head.version == 7
        assert len(head.snapshot) == 8


class TestScanPlan:
    def test_bucket_grouping_and_pks(self, client):
        info = make_table(client)
        append_files(
            client, info, "-5", ["/f/part-a_0000.parquet", "/f/part-b_0001.parquet"]
        )
        append_files(client, info, "-5", ["/f/part-c_0000.parquet"])
        plan = client.get_scan_plan_partitions("t1")
        by_bucket = {p.bucket_id: p for p in plan}
        assert set(by_bucket) == {0, 1}
        assert by_bucket[0].data_files == [
            "/f/part-a_0000.parquet",
            "/f/part-c_0000.parquet",
        ]
        assert by_bucket[0].primary_keys == ["id"]

    def test_pks_dropped_after_compaction(self, client):
        info = make_table(client)
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        client.commit_data_files(
            info,
            {"-5": [DataFileOp(path="/f/part-comp_0000.parquet")]},
            CommitOp.COMPACTION,
            read_partition_info=[head],
        )
        plan = client.get_scan_plan_partitions("t1")
        assert len(plan) == 1
        assert plan[0].primary_keys == []  # merge skipped on compacted head

    def test_del_file_ops_drop_files(self, client):
        info = make_table(client, name="nopk", pks=())
        append_files(client, info, "-5", ["/f/a.parquet", "/f/b.parquet"])
        client.commit_data_files(
            info,
            {"-5": [DataFileOp(path="/f/a.parquet", file_op="del")]},
            CommitOp.APPEND,
        )
        plan = client.get_scan_plan_partitions("nopk")
        assert plan[0].data_files == ["/f/b.parquet"]

    def test_range_partition_filter(self, client):
        info = make_table(client, name="t3", pks=("id",), ranges=("date",))
        append_files(client, info, "date=2024-01-01", ["/f/part-a_0000.parquet"])
        append_files(client, info, "date=2024-01-02", ["/f/part-b_0000.parquet"])
        plan = client.get_scan_plan_partitions("t3", partitions={"date": "2024-01-01"})
        assert len(plan) == 1
        assert plan[0].partition_values == {"date": "2024-01-01"}

    def test_filter_fast_paths_multi_column(self, client):
        """Point-lookup, prefix-range, and unindexed paths all agree — and
        descs committed with k=v pairs in the wrong order are canonicalized
        on entry so every filter shape still finds them."""
        schema = pa.schema(
            [("id", pa.int64()), ("a", pa.string()), ("b", pa.string())]
        )
        info = client.create_table(
            "t4", "/tmp/wh/t4", schema, primary_keys=["id"],
            range_partitions=["a", "b"],
        )
        append_files(client, info, "a=1,b=2", ["/f/p1_0000.parquet"])
        append_files(client, info, "b=4,a=3", ["/f/p2_0000.parquet"])  # wrong order
        # fully specified → indexed point lookup
        for f in ({"a": "1", "b": "2"}, {"b": "4", "a": "3"}):
            plan = client.get_scan_plan_partitions("t4", partitions=f)
            assert len(plan) == 1, f
        # leading-prefix → indexed desc range; d1 must not match d10-style descs
        append_files(client, info, "a=11,b=2", ["/f/p3_0000.parquet"])
        plan = client.get_scan_plan_partitions("t4", partitions={"a": "1"})
        assert {p.partition_desc for p in plan} == {"a=1,b=2"}
        # non-leading column → full-scan filter path
        plan = client.get_scan_plan_partitions("t4", partitions={"b": "2"})
        assert {p.partition_desc for p in plan} == {"a=1,b=2", "a=11,b=2"}
        # stored desc is the canonical form even for the out-of-order commit
        assert client.store.get_latest_partition_info(info.table_id, "a=3,b=4")
        # fully-specified miss is still just empty, not an error
        assert client.get_scan_plan_partitions("t4", partitions={"a": "9", "b": "9"}) == []


class TestTimeTravel:
    def test_snapshot_and_incremental(self, client):
        info = make_table(client)
        import time

        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        t0 = client.store.get_latest_partition_info(info.table_id, "-5").timestamp
        time.sleep(0.002)
        append_files(client, info, "-5", ["/f/part-b_0000.parquet"])

        snap = client.get_snapshot_at_timestamp("t1", t0)
        assert len(snap) == 1 and snap[0].version == 0

        inc = client.get_incremental_partitions("t1", t0)
        assert len(inc) == 1
        head, commits = inc[0]
        assert len(commits) == 1  # only the second commit is in the window
        plan = client.incremental_scan_plan("t1", t0)
        assert plan[0].data_files == ["/f/part-b_0000.parquet"]


def test_extract_hash_bucket_id():
    assert extract_hash_bucket_id("/p/part-AbC_0042.parquet") == 42
    assert extract_hash_bucket_id("part-x_7") == 7
    assert extract_hash_bucket_id("no-bucket.parquet") is None


def test_partition_desc_to_dict():
    assert partition_desc_to_dict("-5") == {}
    assert partition_desc_to_dict("a=1,b=x") == {"a": "1", "b": "x"}


class TestReplayIdempotence:
    def test_crash_between_phase2_and_mark_committed(self, client):
        info = make_table(client, name="replay_t")
        cid = DataCommitInfo.new_commit_id()
        # full phase 1 + phase 2, but "crash" before mark_committed
        client.store.insert_data_commit_info(
            [DataCommitInfo(info.table_id, "-5", cid, [DataFileOp("/f/part-a_0000.parquet")], CommitOp.APPEND)]
        )
        client.commit_data(
            MetaInfo(
                table_info=info,
                list_partition=[PartitionInfo(info.table_id, "-5", snapshot=[cid])],
            ),
            CommitOp.APPEND,
        )
        # replay must not double-append the commit id or bump the version
        client.commit_data_files(
            info,
            {"-5": [DataFileOp("/f/part-a_0000.parquet")]},
            CommitOp.APPEND,
            commit_id_by_partition={"-5": cid},
        )
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert head.version == 0
        assert head.snapshot == [cid]
        assert client.store.commit_state(info.table_id, "-5", cid) is True

    def test_empty_commit_id_lists_are_noops(self, client):
        info = make_table(client, name="noop_t")
        client.store.mark_committed(info.table_id, "-5", [])
        client.store.delete_data_commit_info(info.table_id, "-5", [])

    def test_concurrent_appends_memory_store(self):
        # the shared-connection :memory: store must serialize transactions
        store = SqliteMetadataStore(":memory:")
        client = MetaDataClient(store=store)
        info = make_table(client)
        errs = []

        def writer(i):
            try:
                append_files(client, info, "-5", [f"/f/part-m{i}_0000.parquet"])
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        head = client.store.get_latest_partition_info(info.table_id, "-5")
        assert len(head.snapshot) == 8

    def test_incremental_end_zero_is_empty_window(self, client):
        info = make_table(client, name="w0")
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        assert client.get_incremental_partitions("w0", 0, 0) == []


class TestGenericStoreLayer:
    def test_translate_sql_qmark_passthrough(self):
        from lakesoul_tpu.meta.store import translate_sql

        sql = "SELECT a FROM t WHERE x=? AND y=?"
        assert translate_sql(sql, "qmark") == sql

    def test_translate_sql_postgres_format(self):
        from lakesoul_tpu.meta.store import translate_sql

        assert translate_sql("SELECT a FROM t WHERE x=?", "format") == (
            "SELECT a FROM t WHERE x=%s"
        )
        out = translate_sql(
            "INSERT OR IGNORE INTO ns(namespace) VALUES (?)", "format"
        )
        assert out == "INSERT INTO ns(namespace) VALUES (%s) ON CONFLICT DO NOTHING"

    def test_postgres_store_gated_without_driver(self):
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        with pytest.raises(ImportError, match="psycopg2"):
            PostgresMetadataStore("postgresql://localhost/lakesoul")

    def test_format_paramstyle_dao_layer(self, tmp_path):
        """Prove the generic DAO layer works with format paramstyle by driving
        it through a DB-API shim that translates %s back to qmark (stands in
        for psycopg2, which is not in the image)."""
        import sqlite3

        from lakesoul_tpu.meta.store import SqliteMetadataStore

        class FormatShimStore(SqliteMetadataStore):
            PARAMSTYLE = "format"

            def _exec(self, conn, sql, params=()):
                from lakesoul_tpu.meta.store import translate_sql

                sql = translate_sql(sql, "format")
                # shim: sqlite only understands qmark
                return conn.execute(sql.replace("%s", "?"), params)

        store = FormatShimStore(str(tmp_path / "fmt.db"))
        client = MetaDataClient(store=store)
        info = make_table(client, name="fmt_t")
        append_files(client, info, "-5", ["/f/part-a_0000.parquet"])
        plan = client.get_scan_plan_partitions("fmt_t")
        assert plan[0].data_files == ["/f/part-a_0000.parquet"]


class TestPgLikeConcurrency:
    """Concurrent committers through SEPARATE connections of the pg-like
    backend: version races must surface as conflicts and resolve by retry —
    the contention path the single-connection sqlite shim could never
    exercise."""

    def test_concurrent_appends_all_land(self, tmp_path, monkeypatch):
        import sys

        import fake_psycopg2

        monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        dsn = f"postgresql://fake/{tmp_path.name}-conc"
        store = PostgresMetadataStore(dsn)
        client = MetaDataClient(store=store)
        info = make_table(client, name="conc")
        n_threads, per_thread = 4, 5
        errors: list = []

        def worker(w):
            # per-thread connection (threading.local in the store) → real
            # cross-connection commit races
            try:
                for i in range(per_thread):
                    append_files(
                        client, info, "-5", [f"/f/part-w{w}i{i}_0000.parquet"]
                    )
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        head = store.get_latest_partition_info(info.table_id, "-5")
        assert head.version == n_threads * per_thread - 1
        assert len(head.snapshot) == n_threads * per_thread
        fake_psycopg2.reset(dsn)

    def test_integrity_error_is_fake_pg_class(self, tmp_path, monkeypatch):
        import sys

        import fake_psycopg2

        monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        dsn = f"postgresql://fake/{tmp_path.name}-ie"
        store = PostgresMetadataStore(dsn)
        client = MetaDataClient(store=store)
        make_table(client, name="dup")
        with pytest.raises(MetadataError):
            make_table(client, name="dup")  # psycopg2.IntegrityError mapped
        fake_psycopg2.reset(dsn)


class TestDropNamespace:
    def test_drop_empty_namespace(self, client):
        client.create_namespace("tmp_ns")
        assert "tmp_ns" in client.list_namespaces()
        client.drop_namespace("tmp_ns")
        assert "tmp_ns" not in client.list_namespaces()

    def test_drop_guards(self, client):
        with pytest.raises(MetadataError, match="default"):
            client.drop_namespace("default")
        with pytest.raises(MetadataError, match="does not exist"):
            client.drop_namespace("ghost")
        client.create_namespace("busy")
        client.create_table("t_in_ns", "/tmp/wh/busy/t", SCHEMA, namespace="busy")
        with pytest.raises(MetadataError, match="not empty"):
            client.drop_namespace("busy")


class TestCasHelpers:
    """The CAS/merge helpers the isolation lint pack retired the blind
    read-modify-write shapes onto — and the :memory: eager-cursor rowcount
    the lease CAS consumers depend on."""

    def test_memory_store_lease_cas_rowcount_paths(self):
        # the shared-connection :memory: store fetches eagerly through
        # _EagerCursor, which must still expose the CAS .rowcount — the
        # whole lease protocol reads it on every refresh/renew/release
        store = SqliteMetadataStore(":memory:")
        got = store.acquire_lease("p", "a", ttl_ms=10_000, now_ms=1_000)
        assert got is not None and got.fencing_token == 1
        # holder refresh: the CAS UPDATE path with rowcount consumed
        again = store.acquire_lease("p", "a", ttl_ms=10_000, now_ms=2_000)
        assert again is not None and again.fencing_token == 1
        assert store.renew_lease("p", "a", 1, ttl_ms=10_000, now_ms=3_000)
        assert store.release_lease("p", "a", 1)
        # tombstone re-acquire bumps the token (expired row takeover CAS)
        fresh = store.acquire_lease("p", "b", ttl_ms=10_000, now_ms=4_000)
        assert fresh is not None and fresh.fencing_token == 2

    def test_merge_table_properties_concurrent_merges_all_land(self, tmp_path):
        store = SqliteMetadataStore(str(tmp_path / "merge.db"))
        client = MetaDataClient(store=store)
        info = make_table(client, name="merge_t")
        errs: list = []

        def merger(i):
            try:
                store.merge_table_properties(
                    info.table_id, lambda cur: {**cur, f"k{i}": str(i)}
                )
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=merger, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        merged = store.get_table_info_by_id(info.table_id).properties
        # every merger's key survived: the row-locked transaction means no
        # update was lost to a concurrent read-merge-write
        assert {f"k{i}": str(i) for i in range(8)}.items() <= merged.items()
        with pytest.raises(MetadataError, match="no such table"):
            store.merge_table_properties("ghost-id", lambda cur: cur)

    def test_set_descs_verified_cas_rejects_stale_epoch(self, tmp_path):
        from lakesoul_tpu.meta.store import DESC_EPOCH_KEY, DESCS_VERIFIED_KEY

        store = SqliteMetadataStore(str(tmp_path / "cas.db"))
        tid = "tbl-1"
        store.set_global_config(DESC_EPOCH_KEY + tid, "3")
        # stale epoch: the re-read under the row lock no longer matches
        assert store.set_descs_verified(tid, "2") is False
        assert store.get_global_config(DESCS_VERIFIED_KEY + tid) is None
        # current epoch: the flag lands at exactly that epoch
        assert store.set_descs_verified(tid, "3") is True
        assert store.get_global_config(DESCS_VERIFIED_KEY + tid) == "3"

    def test_update_global_config_concurrent_increments_serialize(self, tmp_path):
        store = SqliteMetadataStore(str(tmp_path / "rmw.db"))
        store.set_global_config("counter", "0")

        def bump():
            for _ in range(5):
                store.update_global_config(
                    "counter", lambda old: str(int(old or "0") + 1)
                )

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 4 threads x 5 increments: a lost update would leave a lower count
        assert store.get_global_config("counter") == "20"
