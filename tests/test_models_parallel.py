"""Model + parallelism tests on the virtual 8-device CPU mesh: ring attention
correctness vs full attention, sharded BERT train step, ResNet/MLP steps,
and the driver entry points."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lakesoul_tpu.models.bert import BertConfig, bert_forward, bert_mlm_loss, init_bert_params
from lakesoul_tpu.models.train import (
    make_bert_train_state,
    make_bert_train_step,
    make_mlp_train_step,
    make_resnet_train_step,
)
from lakesoul_tpu.parallel.mesh import make_mesh
from lakesoul_tpu.parallel.ring_attention import make_ring_attention, ring_attention


class TestMesh:
    def test_factorization(self):
        plan = make_mesh(jax.devices())
        assert plan.dp * plan.tp * plan.sp == 8
        assert plan.mesh.axis_names == ("dp", "tp", "sp")

    def test_explicit_axes(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        assert (plan.dp, plan.tp, plan.sp) == (2, 2, 2)
        with pytest.raises(ValueError):
            make_mesh(jax.devices(), dp=3, tp=1, sp=1)


class TestRingAttention:
    def test_matches_full_attention(self):
        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 2, 4, 64, 16
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        mask[:, -7:] = False  # padding on the tail
        mask = jnp.asarray(mask)

        # reference: plain softmax attention with masking
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        expected = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

        ring = make_ring_attention(plan.mesh)
        got = jax.jit(ring)(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_ring_respects_mask_fully_padded_shard(self):
        # one whole sequence shard masked out must not poison the softmax
        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 1, 2, 32, 8
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        mask[:, T // 2 :] = False  # entire later shards padded
        ring = make_ring_attention(plan.mesh)
        got = np.asarray(jax.jit(ring)(q, k, v, jnp.asarray(mask)))
        assert np.isfinite(got).all()


class TestUlyssesAttention:
    def _qkvm(self, B, H, T, D, seed=0, pad=7):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        if pad:
            mask[:, -pad:] = False
        return q, k, v, jnp.asarray(mask)

    def test_matches_full_attention(self):
        from lakesoul_tpu.parallel.ulysses import make_ulysses_attention

        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 2, 8, 64, 16  # heads divisible by sp=8
        q, k, v, mask = self._qkvm(B, H, T, D)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        expected = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        uly = make_ulysses_attention(plan.mesh)
        got = jax.jit(uly)(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_matches_ring(self):
        from lakesoul_tpu.parallel.ulysses import make_ulysses_attention

        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=4)
        B, H, T, D = 2, 4, 32, 8
        q, k, v, mask = self._qkvm(B, H, T, D, seed=2, pad=3)
        ring = jax.jit(make_ring_attention(plan.mesh))(q, k, v, mask)
        uly = jax.jit(make_ulysses_attention(plan.mesh))(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=2e-5)

    def test_bert_trains_with_ulysses(self):
        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=4)
        cfg = BertConfig(vocab_size=128, hidden=64, layers=1, heads=4, ff=128, max_len=32)
        params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=5e-3)
        step = make_bert_train_step(cfg, plan, tx, shardings, sequence_parallel="ulysses")
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 32)), dtype=jnp.int32)
        labels = jnp.where(ids % 5 == 0, ids, -100).astype(jnp.int32)
        mask = jnp.ones((4, 32), dtype=jnp.int32)
        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestBert:
    def test_forward_shapes_and_loss(self):
        cfg = BertConfig.tiny()
        params = init_bert_params(cfg, jax.random.key(0))
        ids = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = jax.jit(lambda p, i: bert_forward(p, i, cfg=cfg))(params, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        labels = jnp.full((2, 16), -100, dtype=jnp.int32)
        labels = labels.at[0, 3].set(7)
        loss = bert_mlm_loss(params, ids, labels, cfg=cfg)
        assert np.isfinite(float(loss))

    def test_sharded_train_step_runs_and_improves(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        cfg = BertConfig(vocab_size=128, hidden=64, layers=2, heads=4, ff=128, max_len=32)
        params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=5e-3)
        step = make_bert_train_step(cfg, plan, tx, shardings)
        rng = np.random.default_rng(0)
        B, T = 4, 32
        sharding = NamedSharding(plan.mesh, P("dp", "sp"))
        ids = jax.device_put(rng.integers(0, 128, (B, T)).astype(np.int32), sharding)
        labels_np = np.full((B, T), -100, np.int32)
        labels_np[:, ::4] = rng.integers(0, 128, labels_np[:, ::4].shape)
        labels = jax.device_put(labels_np, sharding)
        mask = jax.device_put(np.ones((B, T), bool), sharding)
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # optimizing

    def test_tp_params_actually_sharded(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        cfg = BertConfig(vocab_size=64, hidden=64, layers=2, heads=4, ff=128, max_len=16)
        params, *_ = make_bert_train_state(cfg, plan)
        w1_sharding = params["layers"]["w1"].sharding
        assert w1_sharding.spec == P(None, None, "tp")


class TestOtherModels:
    def test_mlp_step(self):
        from lakesoul_tpu.models.mlp import init_mlp_params

        params = init_mlp_params(jax.random.key(0), 4)
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        step, _ = make_mlp_train_step(tx)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)), dtype=jnp.float32)
        y = jnp.asarray(np.random.default_rng(1).integers(0, 2, 32), dtype=jnp.int32)
        params, opt_state, loss = step(params, opt_state, x, y)
        assert np.isfinite(float(loss))

    def test_resnet_tiny_step(self):
        from lakesoul_tpu.models.resnet import ResNetConfig, init_resnet_params

        cfg = ResNetConfig(num_classes=10, width=8, dtype="float32")
        params = init_resnet_params(cfg, jax.random.key(0))
        tx = optax.sgd(0.1)
        opt_state = tx.init(params)
        plan = make_mesh(jax.devices())
        step = make_resnet_train_step(cfg, tx, plan)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
            NamedSharding(plan.mesh, P("dp")),
        )
        labels = jax.device_put(
            rng.integers(0, 10, 8).astype(np.int32), NamedSharding(plan.mesh, P("dp"))
        )
        params, opt_state, loss = step(params, opt_state, images, labels)
        assert np.isfinite(float(loss))


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_entry_compiles_tiny(self):
        # full BERT-base compile on CPU is slow; check the traced shapes only
        import __graft_entry__ as ge

        fn, args = ge.entry()
        shape = jax.eval_shape(fn, *args)
        assert shape.shape == (8, 128, 30522)


class TestTrainCheckpoint:
    def test_save_restore_round_trip(self, tmp_path):
        import optax

        from lakesoul_tpu.models.checkpoint import TrainCheckpointer
        from lakesoul_tpu.models.mlp import init_mlp_params

        params = init_mlp_params(jax.random.key(0), 4)
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)
        ckpt = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
        try:
            ckpt.save(1, params, opt_state)
            bumped = jax.tree.map(lambda x: x + 1.0, params)
            ckpt.save(2, bumped, opt_state)
            assert ckpt.latest_step() == 2
            p2, o2, step = ckpt.restore_latest(like=(params, opt_state))
            assert step == 2
            np.testing.assert_allclose(
                np.asarray(p2[0]["w"]), np.asarray(bumped[0]["w"])
            )
        finally:
            ckpt.close()

    def test_restore_empty_raises(self, tmp_path):
        from lakesoul_tpu.models.checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(str(tmp_path / "empty"))
        try:
            with pytest.raises(FileNotFoundError):
                ckpt.restore_latest()
        finally:
            ckpt.close()


class TestMultiHostDataPlane:
    """Multi-host read rehearsal (the reference fakes multi-node with many
    clients on one PG — SURVEY §4 takeaway): N simulated processes with
    independent catalogs over ONE shared metadata db + warehouse must
    partition the scan exactly and train to identical parameters."""

    def _mk_table(self, wh, rows=4000):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(wh))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float32())])
        t = catalog.create_table("mh", schema, primary_keys=["id"], hash_bucket_num=8)
        rng = np.random.default_rng(0)
        t.write_arrow(pa.table({
            "id": np.arange(rows, dtype=np.int64),
            "v": rng.normal(size=rows).astype(np.float32),
        }))
        t.upsert(pa.table({
            "id": rng.choice(rows, rows // 10, replace=False).astype(np.int64),
            "v": rng.normal(size=rows // 10).astype(np.float32),
        }))
        return t

    def test_auto_shard_partitions_exactly(self, tmp_warehouse, monkeypatch):
        import jax

        from lakesoul_tpu import LakeSoulCatalog

        t = self._mk_table(tmp_warehouse)
        world = 4
        all_ids = []
        per_rank_units = []
        for rank in range(world):
            # each "process" opens its own catalog against the shared store,
            # like separate TPU hosts would
            cat = LakeSoulCatalog(str(tmp_warehouse))
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            monkeypatch.setattr(jax, "process_count", lambda w=world: w)
            scan = cat.table("mh").scan().auto_shard()
            units = scan.scan_plan()
            per_rank_units.append({(u.partition_desc, u.bucket_id) for u in units})
            got = scan.to_arrow()
            all_ids.extend(got.column("id").to_pylist())
        # exact partition: no unit on two ranks, every row delivered once
        for a in range(world):
            for b in range(a + 1, world):
                assert not (per_rank_units[a] & per_rank_units[b])
        assert sorted(all_ids) == list(range(4000))

    def test_dp_training_consistent_across_hosts(self, tmp_warehouse, monkeypatch):
        """Each simulated host trains on its shard; psum-style averaging of
        grads (here: summing per-host losses) must see every row exactly
        once — the input-pipeline half of data parallelism."""
        import jax

        from lakesoul_tpu import LakeSoulCatalog

        t = self._mk_table(tmp_warehouse, rows=1000)
        world = 2
        total = 0.0
        rows_seen = 0
        for rank in range(world):
            cat = LakeSoulCatalog(str(tmp_warehouse))
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            monkeypatch.setattr(jax, "process_count", lambda w=world: w)
            for b in cat.table("mh").scan().auto_shard().batch_size(128).to_jax_iter(
                transform=lambda x: x, device_put=False, drop_remainder=False
            ):
                total += float(b["v"].sum())
                rows_seen += len(b["v"])
        assert rows_seen == 1000
        # equals the single-host sum over the same (merged) table
        expected = float(
            LakeSoulCatalog(str(tmp_warehouse)).table("mh").to_arrow().column("v").to_numpy().sum()
        )
        assert abs(total - expected) < 1e-2
