"""Model + parallelism tests on the virtual 8-device CPU mesh: ring attention
correctness vs full attention, sharded BERT train step, ResNet/MLP steps,
and the driver entry points."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lakesoul_tpu.models.bert import BertConfig, bert_forward, bert_mlm_loss, init_bert_params
from lakesoul_tpu.models.train import (
    make_bert_train_state,
    make_bert_train_step,
    make_mlp_train_step,
    make_resnet_train_step,
)
from lakesoul_tpu.parallel.mesh import make_mesh
from lakesoul_tpu.parallel.ring_attention import make_ring_attention, ring_attention


class TestMesh:
    def test_factorization(self):
        plan = make_mesh(jax.devices())
        assert plan.dp * plan.tp * plan.sp == 8
        assert plan.mesh.axis_names == ("dp", "tp", "sp", "pp", "ep")

    def test_explicit_axes(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        assert (plan.dp, plan.tp, plan.sp) == (2, 2, 2)
        with pytest.raises(ValueError):
            make_mesh(jax.devices(), dp=3, tp=1, sp=1)


class TestRingAttention:
    def test_matches_full_attention(self):
        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 2, 4, 64, 16
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        mask[:, -7:] = False  # padding on the tail
        mask = jnp.asarray(mask)

        # reference: plain softmax attention with masking
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        expected = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

        ring = make_ring_attention(plan.mesh)
        got = jax.jit(ring)(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_ring_respects_mask_fully_padded_shard(self):
        # one whole sequence shard masked out must not poison the softmax
        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 1, 2, 32, 8
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        mask[:, T // 2 :] = False  # entire later shards padded
        ring = make_ring_attention(plan.mesh)
        got = np.asarray(jax.jit(ring)(q, k, v, jnp.asarray(mask)))
        assert np.isfinite(got).all()


class TestUlyssesAttention:
    def _qkvm(self, B, H, T, D, seed=0, pad=7):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), dtype=jnp.float32)
        mask = np.ones((B, T), dtype=bool)
        if pad:
            mask[:, -pad:] = False
        return q, k, v, jnp.asarray(mask)

    def test_matches_full_attention(self):
        from lakesoul_tpu.parallel.ulysses import make_ulysses_attention

        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=8)
        B, H, T, D = 2, 8, 64, 16  # heads divisible by sp=8
        q, k, v, mask = self._qkvm(B, H, T, D)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        expected = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        uly = make_ulysses_attention(plan.mesh)
        got = jax.jit(uly)(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_matches_ring(self):
        from lakesoul_tpu.parallel.ulysses import make_ulysses_attention

        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=4)
        B, H, T, D = 2, 4, 32, 8
        q, k, v, mask = self._qkvm(B, H, T, D, seed=2, pad=3)
        ring = jax.jit(make_ring_attention(plan.mesh))(q, k, v, mask)
        uly = jax.jit(make_ulysses_attention(plan.mesh))(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), atol=2e-5)

    def test_bert_trains_with_ulysses(self):
        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=4)
        cfg = BertConfig(vocab_size=128, hidden=64, layers=1, heads=4, ff=128, max_len=32)
        params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=5e-3)
        step = make_bert_train_step(cfg, plan, tx, shardings, sequence_parallel="ulysses")
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 32)), dtype=jnp.int32)
        labels = jnp.where(ids % 5 == 0, ids, -100).astype(jnp.int32)
        mask = jnp.ones((4, 32), dtype=jnp.int32)
        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestBert:
    def test_forward_shapes_and_loss(self):
        cfg = BertConfig.tiny()
        params = init_bert_params(cfg, jax.random.key(0))
        ids = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = jax.jit(lambda p, i: bert_forward(p, i, cfg=cfg))(params, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        labels = jnp.full((2, 16), -100, dtype=jnp.int32)
        labels = labels.at[0, 3].set(7)
        loss = bert_mlm_loss(params, ids, labels, cfg=cfg)
        assert np.isfinite(float(loss))

    def test_sharded_train_step_runs_and_improves(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        cfg = BertConfig(vocab_size=128, hidden=64, layers=2, heads=4, ff=128, max_len=32)
        params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=5e-3)
        step = make_bert_train_step(cfg, plan, tx, shardings)
        rng = np.random.default_rng(0)
        B, T = 4, 32
        sharding = NamedSharding(plan.mesh, P("dp", "sp"))
        ids = jax.device_put(rng.integers(0, 128, (B, T)).astype(np.int32), sharding)
        labels_np = np.full((B, T), -100, np.int32)
        labels_np[:, ::4] = rng.integers(0, 128, labels_np[:, ::4].shape)
        labels = jax.device_put(labels_np, sharding)
        mask = jax.device_put(np.ones((B, T), bool), sharding)
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # optimizing

    def test_tp_params_actually_sharded(self):
        plan = make_mesh(jax.devices(), dp=2, tp=2, sp=2)
        cfg = BertConfig(vocab_size=64, hidden=64, layers=2, heads=4, ff=128, max_len=16)
        params, *_ = make_bert_train_state(cfg, plan)
        w1_sharding = params["layers"]["w1"].sharding
        assert w1_sharding.spec == P(None, None, "tp")


class TestMoE:
    def test_moe_matches_per_token_dense_reference(self):
        # top-1 routing with generous capacity: every token goes through its
        # argmax expert — identical to looping experts token by token
        from lakesoul_tpu.parallel.moe import moe_ffn

        rng = np.random.default_rng(0)
        N, h, f, E = 64, 16, 32, 4
        x = jnp.asarray(rng.normal(size=(N, h)), dtype=jnp.float32)
        gate_w = jnp.asarray(rng.normal(size=(h, E)), dtype=jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(E, h, f)) * 0.1, dtype=jnp.float32)
        b1 = jnp.zeros((E, f))
        w2 = jnp.asarray(rng.normal(size=(E, f, h)) * 0.1, dtype=jnp.float32)
        b2 = jnp.zeros((E, h))
        out, aux = moe_ffn(x, gate_w, w1, b1, w2, b2,
                           capacity_factor=float(E), ep_sharding=None)
        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        expert = np.argmax(np.asarray(probs), axis=-1)
        gate = np.max(np.asarray(probs), axis=-1)
        expected = np.zeros((N, h), np.float32)
        for n in range(N):
            e = expert[n]
            hdn = jax.nn.gelu(x[n] @ w1[e] + b1[e])
            expected[n] = gate[n] * np.asarray(hdn @ w2[e] + b2[e])
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-4)
        assert float(aux) >= 1.0 - 1e-5  # E·Σ f_e·p_e minimized at 1

    def test_moe_capacity_drops_overflow(self):
        from lakesoul_tpu.parallel.moe import moe_ffn

        # all tokens route to one expert; capacity 1/E forces drops → the
        # dropped tokens contribute exactly zero (residual passthrough)
        N, h, E = 16, 8, 4
        x = jnp.ones((N, h), dtype=jnp.float32)
        gate_w = jnp.zeros((h, E)).at[:, 2].set(1.0)
        w1 = jnp.ones((E, h, h)) * 0.1
        w2 = jnp.ones((E, h, h)) * 0.1
        out, _ = moe_ffn(x, gate_w, w1, jnp.zeros((E, h)), w2, jnp.zeros((E, h)),
                         capacity_factor=1.0, ep_sharding=None)
        out = np.asarray(out)
        kept = np.abs(out).sum(axis=1) > 0
        assert kept.sum() == N // E  # capacity = N/E tokens on that expert
        assert (kept[: N // E]).all()  # deterministic: first-come keeps

    def test_moe_bert_trains_expert_parallel(self):
        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=1, ep=4)
        cfg = BertConfig(vocab_size=128, hidden=32, layers=2, heads=4, ff=64,
                         max_len=16, n_experts=4, dtype="float32")
        params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=5e-3)
        # expert weights actually live on the ep axis
        assert params["layers"]["moe"]["w1"].sharding.spec == P(None, "ep", None, None)
        step = make_bert_train_step(cfg, plan, tx, shardings)
        rng = np.random.default_rng(0)
        B, T = 4, 16
        sharding = NamedSharding(plan.mesh, P("dp", "sp"))
        ids = jax.device_put(rng.integers(0, 128, (B, T)).astype(np.int32), sharding)
        labels_np = np.full((B, T), -100, np.int32)
        labels_np[:, ::2] = rng.integers(0, 128, labels_np[:, ::2].shape)
        labels = jax.device_put(labels_np, sharding)
        mask = jax.device_put(np.ones((B, T), bool), sharding)
        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_dense_parity_single_expert(self):
        # E=1, ample capacity → MoE degenerates to (gated) dense FFN; the
        # router's softmax over one expert gates at exactly 1.0
        from lakesoul_tpu.parallel.moe import moe_ffn

        rng = np.random.default_rng(3)
        N, h, f = 32, 8, 16
        x = jnp.asarray(rng.normal(size=(N, h)), dtype=jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(1, h, f)) * 0.1, dtype=jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(1, f, h)) * 0.1, dtype=jnp.float32)
        out, _ = moe_ffn(x, jnp.zeros((h, 1)), w1, jnp.zeros((1, f)), w2,
                         jnp.zeros((1, h)), capacity_factor=2.0, ep_sharding=None)
        dense = jax.nn.gelu(x @ w1[0]) @ w2[0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5)


class TestPipeline:
    def test_pipeline_primitive_stages_compose(self):
        # stage i adds 10^i: pipelined result must see every stage once, in
        # stage order, for every microbatch
        from lakesoul_tpu.parallel.pipeline import make_pipeline

        plan = make_mesh(jax.devices(), dp=1, tp=1, sp=1, pp=8)
        adds = jnp.asarray([[10.0**i] for i in range(8)])  # [pp, 1]

        def stage_fn(stage_params, inp):
            return {"x": inp["x"] + stage_params[0]}

        pipe = make_pipeline(plan.mesh, stage_fn)
        micro = {"x": jnp.zeros((5, 4))}  # 5 microbatches of 4
        out = jax.jit(lambda p, m: pipe(p, m))({"a": adds}["a"], micro)
        expected = np.full((5, 4), float(sum(10.0**i for i in range(8))))
        np.testing.assert_allclose(np.asarray(out["x"]), expected)

    def test_pipelined_bert_matches_dense_loss_and_trains(self):
        from lakesoul_tpu.models.train import (
            make_bert_pipeline_train_state,
            make_bert_pipeline_train_step,
        )

        plan = make_mesh(jax.devices(), dp=2, tp=1, sp=1, pp=4)
        cfg = BertConfig(vocab_size=128, hidden=32, layers=4, heads=4, ff=64,
                         max_len=16, dtype="float32")
        params, opt_state, tx, shardings = make_bert_pipeline_train_state(cfg, plan, lr=5e-3)
        # each stage's layer slice is sharded over pp
        assert params["layers"]["wq"].sharding.spec[0] == "pp"
        step = make_bert_pipeline_train_step(cfg, plan, tx, shardings, n_micro=4)
        rng = np.random.default_rng(0)
        B, T = 8, 16
        sharding = NamedSharding(plan.mesh, P("dp"))
        ids = jax.device_put(rng.integers(0, 128, (B, T)).astype(np.int32), sharding)
        labels_np = np.full((B, T), -100, np.int32)
        labels_np[:, ::2] = rng.integers(0, 128, labels_np[:, ::2].shape)
        labels = jax.device_put(labels_np, sharding)
        mask = jax.device_put(np.ones((B, T), np.int32), sharding)

        # the pipelined loss must equal the plain scan-encoder loss on the
        # SAME parameters (pipelining is an execution schedule, not a model)
        host_params = jax.device_get(params)
        ref = float(bert_mlm_loss(
            host_params, jax.device_get(ids), jax.device_get(labels),
            jax.device_get(mask).astype(bool), cfg=cfg, moe_ep_sharding=None,
        ))
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, ids, labels, mask)
            losses.append(float(loss))
        np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
        assert losses[-1] < losses[0]


class TestOtherModels:
    def test_mlp_step(self):
        from lakesoul_tpu.models.mlp import init_mlp_params

        params = init_mlp_params(jax.random.key(0), 4)
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        step, _ = make_mlp_train_step(tx)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)), dtype=jnp.float32)
        y = jnp.asarray(np.random.default_rng(1).integers(0, 2, 32), dtype=jnp.int32)
        params, opt_state, loss = step(params, opt_state, x, y)
        assert np.isfinite(float(loss))

    def test_resnet_tiny_step(self):
        from lakesoul_tpu.models.resnet import ResNetConfig, init_resnet_params

        cfg = ResNetConfig(num_classes=10, width=8, dtype="float32")
        params = init_resnet_params(cfg, jax.random.key(0))
        tx = optax.sgd(0.1)
        opt_state = tx.init(params)
        plan = make_mesh(jax.devices())
        step = make_resnet_train_step(cfg, tx, plan)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
            NamedSharding(plan.mesh, P("dp")),
        )
        labels = jax.device_put(
            rng.integers(0, 10, 8).astype(np.int32), NamedSharding(plan.mesh, P("dp"))
        )
        params, opt_state, loss = step(params, opt_state, images, labels)
        assert np.isfinite(float(loss))


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_entry_compiles_tiny(self):
        # full BERT-base compile on CPU is slow; check the traced shapes only
        import __graft_entry__ as ge

        fn, args = ge.entry()
        shape = jax.eval_shape(fn, *args)
        assert shape.shape == (8, 128, 30522)


class TestTrainCheckpoint:
    def test_save_restore_round_trip(self, tmp_path):
        import optax

        from lakesoul_tpu.models.checkpoint import TrainCheckpointer
        from lakesoul_tpu.models.mlp import init_mlp_params

        params = init_mlp_params(jax.random.key(0), 4)
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)
        ckpt = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
        try:
            ckpt.save(1, params, opt_state)
            bumped = jax.tree.map(lambda x: x + 1.0, params)
            ckpt.save(2, bumped, opt_state)
            assert ckpt.latest_step() == 2
            p2, o2, step = ckpt.restore_latest(like=(params, opt_state))
            assert step == 2
            np.testing.assert_allclose(
                np.asarray(p2[0]["w"]), np.asarray(bumped[0]["w"])
            )
        finally:
            ckpt.close()

    def test_restore_empty_raises(self, tmp_path):
        from lakesoul_tpu.models.checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(str(tmp_path / "empty"))
        try:
            with pytest.raises(FileNotFoundError):
                ckpt.restore_latest()
        finally:
            ckpt.close()


class TestMultiHostDataPlane:
    """Multi-host read rehearsal (the reference fakes multi-node with many
    clients on one PG — SURVEY §4 takeaway): N simulated processes with
    independent catalogs over ONE shared metadata db + warehouse must
    partition the scan exactly and train to identical parameters."""

    def _mk_table(self, wh, rows=4000):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(wh))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float32())])
        t = catalog.create_table("mh", schema, primary_keys=["id"], hash_bucket_num=8)
        rng = np.random.default_rng(0)
        t.write_arrow(pa.table({
            "id": np.arange(rows, dtype=np.int64),
            "v": rng.normal(size=rows).astype(np.float32),
        }))
        t.upsert(pa.table({
            "id": rng.choice(rows, rows // 10, replace=False).astype(np.int64),
            "v": rng.normal(size=rows // 10).astype(np.float32),
        }))
        return t

    def test_auto_shard_partitions_exactly(self, tmp_warehouse, monkeypatch):
        import jax

        from lakesoul_tpu import LakeSoulCatalog

        t = self._mk_table(tmp_warehouse)
        world = 4
        all_ids = []
        per_rank_units = []
        for rank in range(world):
            # each "process" opens its own catalog against the shared store,
            # like separate TPU hosts would
            cat = LakeSoulCatalog(str(tmp_warehouse))
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            monkeypatch.setattr(jax, "process_count", lambda w=world: w)
            scan = cat.table("mh").scan().auto_shard()
            units = scan.scan_plan()
            per_rank_units.append({(u.partition_desc, u.bucket_id) for u in units})
            got = scan.to_arrow()
            all_ids.extend(got.column("id").to_pylist())
        # exact partition: no unit on two ranks, every row delivered once
        for a in range(world):
            for b in range(a + 1, world):
                assert not (per_rank_units[a] & per_rank_units[b])
        assert sorted(all_ids) == list(range(4000))

    def test_dp_training_consistent_across_hosts(self, tmp_warehouse, monkeypatch):
        """Each simulated host trains on its shard; psum-style averaging of
        grads (here: summing per-host losses) must see every row exactly
        once — the input-pipeline half of data parallelism."""
        import jax

        from lakesoul_tpu import LakeSoulCatalog

        t = self._mk_table(tmp_warehouse, rows=1000)
        world = 2
        total = 0.0
        rows_seen = 0
        for rank in range(world):
            cat = LakeSoulCatalog(str(tmp_warehouse))
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            monkeypatch.setattr(jax, "process_count", lambda w=world: w)
            for b in cat.table("mh").scan().auto_shard().batch_size(128).to_jax_iter(
                transform=lambda x: x, device_put=False, drop_remainder=False
            ):
                total += float(b["v"].sum())
                rows_seen += len(b["v"])
        assert rows_seen == 1000
        # equals the single-host sum over the same (merged) table
        expected = float(
            LakeSoulCatalog(str(tmp_warehouse)).table("mh").to_arrow().column("v").to_numpy().sum()
        )
        assert abs(total - expected) < 1e-2
