"""True multi-process tests: separate OS processes share one warehouse
(SQLite metadata + files), exercising the real optimistic-concurrency path
the way multiple TPU hosts would share a PG instance."""

import pathlib
import subprocess
import sys
import textwrap

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


def run_worker(code: str, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code), *args, REPO],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )


class TestMultiProcess:
    def test_concurrent_writer_processes(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=2)

        worker = """
        import sys
        sys.path.insert(0, sys.argv[-1])
        import numpy as np, pyarrow as pa
        from lakesoul_tpu import LakeSoulCatalog

        wh, start = sys.argv[1], int(sys.argv[2])
        t = LakeSoulCatalog(wh).table("t")
        for i in range(5):
            base = start + i * 10
            t.upsert(pa.table({"id": np.arange(base, base + 10),
                               "v": np.full(10, float(start))}))
        print("done", start)
        """
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", textwrap.dedent(worker), str(tmp_warehouse), str(s), REPO],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=REPO,
            )
            for s in (0, 1000, 2000)
        ]
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, err[-1500:]
        t = catalog.table("t")
        got = t.to_arrow()
        assert got.num_rows == 150  # 3 workers x 5 commits x 10 rows
        head = catalog.client.store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.version == 14  # all 15 commits serialized

    def test_reader_process_sees_writer_process_commits(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("r", SCHEMA, primary_keys=["id"])
        t.write_arrow(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        reader = """
        import sys
        sys.path.insert(0, sys.argv[-1])
        from lakesoul_tpu import LakeSoulCatalog
        t = LakeSoulCatalog(sys.argv[1]).table("r")
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2], got
        print("rows:", got.num_rows)
        """
        out = run_worker(reader, str(tmp_warehouse))
        assert out.returncode == 0, out.stderr[-1500:]
        assert "rows: 2" in out.stdout

    def test_sharded_readers_partition_disjointly(self, tmp_warehouse):
        import numpy as np

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("s", SCHEMA, primary_keys=["id"], hash_bucket_num=4)
        t.write_arrow(pa.table({"id": np.arange(100), "v": np.zeros(100)}))
        shard_reader = """
        import sys
        sys.path.insert(0, sys.argv[-1])
        from lakesoul_tpu import LakeSoulCatalog
        wh, rank, world = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        t = LakeSoulCatalog(wh).table("s")
        ids = t.scan().shard(rank, world).to_arrow().column("id").to_pylist()
        print(",".join(map(str, sorted(ids))))
        """
        seen = []
        for rank in range(2):
            out = run_worker(shard_reader, str(tmp_warehouse), str(rank), "2")
            assert out.returncode == 0, out.stderr[-1000:]
            seen.append(set(int(x) for x in out.stdout.strip().split(",") if x))
        assert seen[0] & seen[1] == set()
        assert seen[0] | seen[1] == set(range(100))


class TestParallelReaders:
    def test_threaded_to_batches_matches_sequential(self, tmp_warehouse):
        import numpy as np

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("p", SCHEMA, primary_keys=["id"], hash_bucket_num=8)
        t.write_arrow(pa.table({"id": np.arange(5000), "v": np.arange(5000, dtype=np.float64)}))
        t.upsert(pa.table({"id": np.arange(0, 5000, 7), "v": np.zeros(len(range(0, 5000, 7)))}))
        seq = pa.Table.from_batches(list(t.scan().to_batches())).sort_by("id")
        par = pa.Table.from_batches(list(t.scan().to_batches(num_threads=4))).sort_by("id")
        assert seq.equals(par)
        # and through the jax iterator
        rows = 0
        for b in t.scan().batch_size(512).to_jax_iter(device_put=False, io_threads=4,
                                                      drop_remainder=False):
            rows += len(b["id"])
        assert rows == 5000
